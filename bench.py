#!/usr/bin/env python
"""Benchmark: SGNS training words/sec on the flagship config (BASELINE.json:
skip-gram, negative=5, dim=300, window=5, text8-scale corpus).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
— ALWAYS, even when the TPU backend is unreachable (the axon tunnel can hang
indefinitely on backend init, so availability is probed in a subprocess with a
timeout and the bench falls back to CPU with an explicit marker) or when the
run itself fails (the line then carries an "error" field instead of rc=1).

Extra fields: "platform"/"device_kind" (where it actually ran), "mfu" and
"model_tflops_per_sec" (model-FLOPs utilisation: algorithmically useful FLOPs
from the trained-pair count over the chip's peak — executed FLOPs may be
higher, e.g. band-kernel masking, so this is the honest denominator-side
number), and "tpu_fallback_reason" when the TPU was requested but unusable.

Corpus: ./text8 if present (streamed through the native ingest — no Python
token lists), else a synthetic Zipf stream with text8's vocab size and skew
(utils/synthetic.py) — the perf-relevant properties match, so words/sec
transfers.

Baseline: benchmarks/reference_baseline.json holds the measured words/sec of
the compiled C++ reference on this machine (see benchmarks/reference_harness/
for how it is produced). vs_baseline = ours / reference.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

# bf16 MXU peak per chip, by jax device_kind prefix. Model-FLOPs MFU is only
# reported when the chip is recognised; CPU runs report mfu=null.
PEAK_FLOPS_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def emit(record: dict) -> None:
    print(json.dumps(record))


def probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Check in a SUBPROCESS whether the default jax backend initialises.

    The axon TPU tunnel fails by hanging, not by raising, so an in-process
    check could wedge the bench forever. Returns (ok, platform_or_reason).
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init hang (> {timeout_s:.0f}s)"
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()
        return False, "backend init error: " + (tail[-1] if tail else "unknown")
    return True, out.stdout.strip()


def best_banked_tpu(key: str) -> dict | None:
    """Scan benchmarks/TPU_R*/ for banked on-chip bench records matching this
    config key and return the best (highest words/sec) with provenance.

    Attached to the emitted record whenever the live probe fails: the tunnel
    can be down for hours at round end, and the round's official artifact
    should carry the freshest on-chip evidence rather than reporting CPU-only
    while banked TPU measurements exist (the BENCH_r02 failure mode)."""
    import glob

    base = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"
    )
    # round-1/2 records used the pre-multi-config key spelling
    legacy = {"sg+ns-dim300-w5-k5": "sgns-dim300-w5-k5"}
    names = {key, legacy.get(key, key)}
    best = None
    for path in sorted(glob.glob(os.path.join(base, "TPU_R*", "*"))):
        if not path.endswith((".json", ".txt", ".out")):
            continue
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("platform") != "tpu":
                continue
            if not isinstance(rec.get("value"), (int, float)):
                continue
            # exact key match (substring would let '...-k5' claim '...-k50')
            metric = rec.get("metric", "")
            if not any(metric.startswith(n + " words/sec") for n in names):
                continue
            if best is None or rec["value"] > best["value"]:
                best = {
                    "value": rec["value"],
                    "vs_baseline": rec.get("vs_baseline"),
                    "metric": rec["metric"],
                    "source": os.path.relpath(path, base),
                    "banked_utc": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ",
                        time.gmtime(os.path.getmtime(path)),
                    ),
                }
    return best


def config_key(model: str, method: str, dim: int, window: int, k: int) -> str:
    """The shape key shared by the baseline writer
    (benchmarks/reference_harness/measure_baseline.py --multi) and every
    vs_baseline lookup/metric label here — one definition so a key-format
    change cannot silently break the match."""
    return f"{model}+{method}-dim{dim}-w{window}-k{k}"


def model_flops_per_target(dim: int) -> float:
    """Algorithmic FLOPs for one sigmoid target: a d-dot logit + d-axpy
    hidden-grad + d-axpy row update (Word2Vec.cpp:262-268) ~= 3 * 2d FLOPs.
    The kernels' "pairs" metric counts TARGETS (positives and negatives
    alike: train_step.py sums tmask over all K+1; band_step.py adds
    sum(w_neg)), so no extra (K+1) factor belongs here."""
    return 6.0 * dim


def run(args: argparse.Namespace, platform_note: str | None) -> dict:
    import jax
    import jax.numpy as jnp

    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import (
        BatchIterator, PackedCorpus, chunk_batches, placed_prefetch,
    )
    from word2vec_tpu.data.vocab import Vocab
    from word2vec_tpu.models.params import init_params
    from word2vec_tpu.ops.tables import DeviceTables
    from word2vec_tpu.ops.train_step import jit_chunk_runner
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model=args.model,
        train_method=args.train_method,
        negative=args.negative if args.train_method == "ns" else 0,
        word_dim=args.dim,
        window=args.window,
        subsample_threshold=1e-4,
        batch_rows=args.batch_rows,
        max_sentence_len=args.max_len,
        chunk_cap=args.chunk_cap,
        slab_scatter=bool(args.slab_scatter),
        fused_tables=bool(args.fused) and args.train_method == "ns",
        table_layout=args.table_layout,  # config raises on hs+unified: a
                                         # misconfigured item must fail
                                         # loudly, not bank mislabeled
        shared_negatives=args.kp,
        negative_scope=args.neg_scope,
        band_chunk=args.band_chunk,
        band_backend=args.band_backend,
        hs_dense_top=args.hs_dense_top,  # config raises on ns+dense-top:
                                         # a misconfigured item must fail
                                         # loudly, not bank mislabeled
        hs_tail_slots=args.hs_tail_slots,
        prng_impl=args.prng,
        dtype=args.table_dtype,
        stochastic_rounding=bool(args.sr),
        corpus_mode=args.corpus_mode,  # a plan-cache dimension (tune/)
        # --health 1 banks the full on-device health counters (grad-norm,
        # per-table update magnitudes) in the record; default off because
        # they cost an extra table read per step and this is a throughput
        # measurement. The free non-finite tripwire counter is always on.
        health_metrics=bool(args.health),
    )

    if os.path.exists(args.text8):
        from word2vec_tpu import native

        counts, _total = native.count_file(args.text8)
        vocab = Vocab.from_counter(counts, min_count=cfg.min_count)
        flat = native.encode_file(args.text8, vocab, native.MODE_STREAM)
        corpus = PackedCorpus.from_flat(flat, cfg.max_sentence_len)
        corpus_name = "text8"
    else:
        vocab = zipf_vocab(args.vocab, 17_000_000)
        # flat-stream cache: sweep scripts invoke bench many times and the
        # 17M-token weighted draw costs ~20s host time per run
        cache = f"/tmp/w2v_zipf_{args.vocab}_{args.tokens}_s0.npy"
        if os.path.exists(cache):
            flat = np.load(cache)
        else:
            flat = np.concatenate(zipf_corpus_ids(vocab, args.tokens, seed=0))
            try:
                np.save(cache, flat)
            except OSError:
                pass
        # re-slice into the generator's 1000-token pseudo-sentences
        # (main.cpp:66 chunking) so the cached and fresh workloads are
        # identical row-for-row
        ids = [flat[i:i + 1000] for i in range(0, len(flat), 1000)]
        corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
        corpus_name = f"zipf-synthetic-{args.tokens // 1_000_000}M"

    # Autotuned execution planner (tune/): resolve the step-shape plan
    # against THIS corpus + device before anything shape-dependent is built.
    # "cached" starts from the persisted (device, kernel, vocab, dim) plan
    # with zero probe cost; "probe" searches (cost-model-pruned grid, short
    # compile-separated probes) and persists the winner for next time.
    plan_res = None
    if args.autotune != "off":
        from word2vec_tpu.tune import resolve_plan

        plan_res = resolve_plan(
            cfg, vocab, corpus=corpus, mode=args.autotune,
            cache_path=args.plan_cache or None,
        )
        cfg = cfg.apply_plan(plan_res.plan)
        print(
            f"autotune: {'cache hit' if plan_res.source == 'cache' else 'probed'}"
            f" key={plan_res.key} plan={plan_res.plan.to_json()}",
            file=sys.stderr,
        )

    tables = DeviceTables.build(vocab, cfg)
    params = init_params(cfg, len(vocab), jax.random.key(0, impl=cfg.jax_prng_impl))
    batcher = BatchIterator(corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1)
    base_key = jax.random.key(7, impl=cfg.jax_prng_impl)

    # Phase-timing breakdown (obs/phases.py) feeding a flight-recorder ring
    # (obs/flight.py): where the measured epoch's wall time went (input wait
    # vs dispatch vs device backpressure), banked both as aggregate p50/p90
    # AND as a span timeline — `trace_summary` (per-span p50 + the top
    # step-time contributors) in every record, with --trace DIR exporting
    # the full Chrome-trace artifact for Perfetto / tracediff. Span
    # overhead is two clock reads + one ring append.
    from word2vec_tpu.obs.flight import FlightRecorder
    from word2vec_tpu.obs.phases import PhaseRecorder

    flight = FlightRecorder()
    phases = PhaseRecorder(tracer=flight.ring)

    # In-training quality probe (obs/quality.py): at --quality-every chunk
    # boundaries the live table is scored (planted golds when the corpus
    # has them — the zipf stream doesn't, so this is stats-only: row norms,
    # neighbor drift, effective rank) and the row sequence banks as
    # `quality_curve`. Each probe adds one device fetch mid-measurement, so
    # it is off by default on throughput runs.
    qprobe = None
    if args.quality_every:
        from word2vec_tpu.obs.quality import ProbeSet, QualityProbe

        qprobe = QualityProbe(
            vocab, ProbeSet.synthesize(vocab), every=args.quality_every,
            flight=flight,
        )

    # Chunked dispatch (ops/train_step.make_chunk_runner): S optimizer steps
    # per device program, so per-dispatch overhead — which through the remote
    # tunnel costs ~4-5x the 8 ms device step — amortizes to noise. The
    # trajectory is identical to per-step dispatch (tests/test_chunk_runner.py).
    S, _ = cfg.chunk_geometry(batcher.steps_per_epoch(), cap=cfg.chunk_cap)
    alphas = jnp.full((S,), cfg.init_alpha, jnp.float32)

    # Derived-signal plane (obs/signals.py): the same windowed engine the
    # CLI wires, fed at chunk boundaries — the record banks `signals`
    # (windowed throughput/step-time stats) and, with --slo, the rule
    # states under `slo`. Window auto = one chunk, so every dispatch is a
    # window (the bench's natural cadence).
    from word2vec_tpu.obs.signals import SignalEngine
    from word2vec_tpu.obs.slo import SloEvaluator, parse_slo

    slo_rules = parse_slo(args.slo)
    signals = SignalEngine(
        window=args.signal_window or S,
        phases=phases,
        flight=flight,
        slo=SloEvaluator(slo_rules) if slo_rules else None,
    )

    # Device-truth observability (obs/devmem.py + obs/harvest.py): the HBM
    # memory ledger (per-phase watermarks — init / table placement / the
    # measured epoch — banked as `device_memory`; statless CPU backends
    # degrade to available=false, never a crash) and the compiled-program
    # cost harvest (XLA's own FLOPs/bytes/temp/code-size per executable,
    # banked as `cost_harvest` and fed to the anchor-drift gate below).
    from word2vec_tpu.obs.devmem import MemoryLedger, table_row_bytes
    from word2vec_tpu.obs.harvest import CostHarvest

    mem_ledger = MemoryLedger(
        sample_every=max(1, S), flight=flight,
        row_bytes=table_row_bytes(cfg),
    )
    mem_ledger.sample("init")
    harvest = CostHarvest()

    # Bounded profiler window over the measured epoch (--profile-steps A:B;
    # obs/profiler.py): the capture manifest lands in --profile-dir next to
    # the banked record's trace artifacts.
    prof_capture = None
    if args.profile_steps:
        from word2vec_tpu.obs.profiler import ProfilerCapture

        a_s, _, b_s = args.profile_steps.partition(":")
        prof_capture = ProfilerCapture(
            args.profile_dir or "bench_profile", flight=flight,
        )
        prof_capture.schedule(int(a_s), int(b_s))

    from word2vec_tpu.ops import resident as res

    streaming = args.corpus_mode == "streaming"
    use_resident = (
        bool(args.resident) and not streaming and res.corpus_fits(corpus)
    )
    if use_resident:
        # Device-resident corpus (ops/resident.py): batches assembled on
        # device; a dispatch carries only scalars. One [R] order upload.
        chunk_fn = res.jit_resident_chunk_runner(cfg, tables)
        order = res.epoch_order(1, 0, corpus.num_rows)
        step_words = res.epoch_step_words(corpus, order, cfg.batch_rows)
        corpus_dev = res.device_corpus(corpus)
        order_dev = jnp.asarray(order.astype(np.int32))
        spe = len(step_words)

        harvest.capture(
            "resident_chunk", chunk_fn,
            (params, corpus_dev, order_dev, base_key, 0, spe, alphas),
        )
        params, m = chunk_fn(  # warmup / compile (no-op pad steps)
            params, corpus_dev, order_dev, base_key, 0, spe, alphas
        )
        jax.block_until_ready(params)

        def dispatches():
            for c in range(0, spe, S):
                yield int(step_words[c:c + S].sum()), (
                    lambda p, s, c=c: chunk_fn(
                        p, corpus_dev, order_dev, base_key, s, c, alphas
                    )
                )
    else:
        chunk_fn = jit_chunk_runner(cfg, tables)

        # warmup / compile on a throwaway chunk
        warm = next(chunk_batches(batcher.epoch(), S))
        warm_dev = jnp.asarray(warm[0])
        harvest.capture(
            "train_chunk", chunk_fn, (params, warm_dev, base_key, 0, alphas)
        )
        params, m = chunk_fn(params, warm_dev, base_key, 0, alphas)
        jax.block_until_ready(params)

        def place(np_chunk):
            with phases.span("h2d"):  # producer thread: overlapped time
                return jax.device_put(np_chunk)

        if streaming:
            # The streaming data plane (stream/): the SAME chunk_fn and
            # prefetch pipeline, but the id stream arrives in bounded
            # segments that are read and packed per segment — the measured
            # delta vs resident/host-streamed is pure data-plane cost.
            from word2vec_tpu.stream import ArraySource
            from word2vec_tpu.stream.driver import DEFAULT_SEGMENT_TOKENS

            seg_tokens = args.segment_tokens or DEFAULT_SEGMENT_TOKENS

            def dispatches():
                src = ArraySource(flat, segment_tokens=seg_tokens)
                idx = shard = ofs = 0
                while True:
                    raw = src.read_segment(idx, shard, ofs)
                    if raw.raw_tokens == 0:
                        return
                    with phases.span("segment_pack"):
                        seg_corpus = PackedCorpus.from_flat(
                            raw.flat, cfg.max_sentence_len
                        )
                        it = BatchIterator(
                            seg_corpus, cfg.batch_rows,
                            cfg.max_sentence_len, seed=1 + idx,
                        )
                    for dev_chunk, wlist in placed_prefetch(
                        chunk_batches(it.epoch(0), S), place,
                        depth=cfg.prefetch_depth,
                    ):
                        yield sum(wlist), (
                            lambda p, s, t=dev_chunk: chunk_fn(
                                p, t, base_key, s, alphas
                            )
                        )
                    if raw.exhausted:
                        return
                    idx += 1
                    shard, ofs = raw.shard1, raw.offset1
        else:
            def dispatches():
                # chunk transfers overlap compute (batcher.placed_prefetch)
                for dev_chunk, wlist in placed_prefetch(
                    chunk_batches(batcher.epoch(), S), place,
                    depth=cfg.prefetch_depth,
                ):
                    yield sum(wlist), (
                        lambda p, s, t=dev_chunk: chunk_fn(
                            p, t, base_key, s, alphas
                        )
                    )

    # timed steady-state over one full epoch; metrics stay on device until
    # the end (no per-chunk sync)
    words = 0
    steps = 0
    chunk_metrics = []
    dropped_metrics = []
    health_chunks = []  # per-chunk health counters (obs/health.py)
    # 1-minute load average at measurement start: on the 1-core bench host
    # a CPU-fallback number is only comparable across rounds at similar
    # host load (the r4 CPU artifact dropped 24% vs r3 with the queue
    # supervisors probing all round — VERDICT r4 weak item 1; this field
    # lets the artifact distinguish contention from regression)
    load_start = os.getloadavg()[0] if hasattr(os, "getloadavg") else None
    t0 = time.perf_counter()
    t_chunk = t0
    # prime the window clock at the measurement start so even a one-chunk
    # --smoke epoch closes a window (the trainers' first boundary opens)
    signals.on_boundary(0, 0)
    # tables + warmup buffers are placed: the table-placement watermark
    mem_ledger.sample("table_place")
    for chunk_words, dispatch in phases.timed_iter(dispatches(), "batcher_wait"):
        with phases.span("dispatch"):
            params, m = dispatch(params, steps)
        chunk_metrics.append(m["pairs"])
        if "hs_tail_dropped" in m:
            dropped_metrics.append(m["hs_tail_dropped"])
        health_chunks.append(
            {k: m[k] for k in ("nonfinite_loss", "grad_sq") if k in m}
        )
        words += chunk_words
        steps += S
        now = time.perf_counter()
        flight.note_step(steps, t_chunk, now - t_chunk, kind="chunk", steps=S)
        t_chunk = now
        signals.on_boundary(steps, words)
        mem_ledger.on_boundary(steps)
        if prof_capture is not None:
            prof_capture.on_boundary(steps)
        if qprobe is not None and qprobe.due(steps):
            with phases.span("quality_probe"):
                qprobe.probe(params, steps)
        if args.measure_steps and steps >= args.measure_steps:
            break
    with phases.span("device_wait"):
        jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    wps = words / dt
    signals.finish(steps, words)
    if prof_capture is not None:
        prof_capture.finish(steps)
    harvest_report = harvest.finalize()
    def sum_device(xs):
        return float(sum(float(np.sum(jax.device_get(x))) for x in xs))

    pairs = sum_device(chunk_metrics)

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "reference_baseline.json",
    )
    # vs_baseline compares against the measured reference on the SAME config:
    # the flagship single-record file, or the multi-config table keyed by
    # shape (benchmarks/reference_harness/measure_baseline.py --multi)
    flagship = (
        args.model == "sg" and args.train_method == "ns"
        and args.dim == 300 and args.window == 5 and args.negative == 5
    )
    key = config_key(
        args.model, args.train_method, args.dim, args.window, cfg.negative
    )
    vs = None
    ref_wps = None
    if flagship and os.path.exists(baseline_path):
        with open(baseline_path) as f:
            ref_wps = json.load(f).get("words_per_sec")
    if ref_wps is None:
        multi_path = os.path.join(
            os.path.dirname(baseline_path), "reference_baselines.json"
        )
        if os.path.exists(multi_path):
            with open(multi_path) as f:
                ref_wps = json.load(f).get(key, {}).get("words_per_sec")
    if ref_wps:
        vs = wps / float(ref_wps)

    dev = jax.devices()[0]
    model_fps = pairs * model_flops_per_target(args.dim) / dt
    peak = next(
        (v for k, v in PEAK_FLOPS_BF16.items() if dev.device_kind.startswith(k)),
        None,
    )
    # Predicted-vs-measured cost (tune/cost_model.py; the cost model and
    # this record share the utils/profiling counters). measured_cost is the
    # whole-pipeline truth the model is judged against — banked side by
    # side so the model's error stays observable round over round.
    from word2vec_tpu.tune import cost_model as _cm

    predicted_est = _cm.predict(cfg, len(vocab), dev.device_kind, dev.platform)
    predicted = predicted_est.to_json()
    measured = {
        "step_ms": round(1e3 * dt / max(1, steps), 4),
        "words_per_sec": round(wps, 1),
    }
    # Trace summary (obs/tracediff.summarize over the flight ring): per-span
    # p50 + the top step-time contributors, and the measured-vs-predicted
    # cost rows it feeds (tune/cost_model.attribution_rows) — the record
    # attributes its own step time without an xprof rerun.
    from word2vec_tpu.obs import tracediff as _tracediff

    trace_summary = _tracediff.summarize(flight.ring.events())
    cost_attribution = _cm.attribution_rows(predicted_est, trace_summary)
    # Anchor-drift gate (tune/cost_model.cost_calibrate): the measured
    # device step inverted against the three hand anchors, each banked with
    # an ok|drift|stale verdict — and any DRIFTED anchor's attribution rows
    # refused (apply_calibration), so a stale constant cannot bank a
    # silently-wrong attribution as evidence.
    cost_calibration = _cm.cost_calibrate(
        predicted_est, _cm.measured_device_ms(trace_summary)
    )
    cost_attribution = _cm.apply_calibration(
        cost_attribution, cost_calibration
    )
    if args.trace:
        from word2vec_tpu.obs.trace import chrome_trace_doc, write_trace

        write_trace(
            os.path.join(args.trace, "trace.json"),
            chrome_trace_doc(flight.ring.events()),
        )
    # Telemetry (obs/): the phase breakdown + health counters make the
    # predicted-vs-measured audit self-contained — an off-model number can
    # be attributed (input-bound? divergence?) from the record alone — and
    # the manifest slice pins provenance (device, versions, git sha).
    from word2vec_tpu.obs import manifest as obs_manifest
    from word2vec_tpu.obs.health import health_record

    health = {"nonfinite_loss_steps": 0.0}
    if health_chunks:
        fetched = [jax.device_get(h) for h in health_chunks]
        merged = {
            k: np.concatenate([np.atleast_1d(np.asarray(h[k])) for h in fetched])
            for k in fetched[0]
        }
        health = health_record(merged) or health

    record = {
        "metric": f"{key} words/sec ({corpus_name}, {dev.platform})",
        "value": round(wps, 1),
        "unit": "words/sec",
        "vs_baseline": round(vs, 2) if vs is not None else None,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "steps": steps,
        "words": words,
        "model_tflops_per_sec": round(model_fps / 1e12, 4),
        "mfu": round(model_fps / peak, 5) if peak else None,
        "resident_corpus": use_resident,
        "corpus_mode": args.corpus_mode,
        "segment_tokens": (
            (args.segment_tokens or 4_000_000) if streaming else None
        ),
        # data-plane attribution: host time blocked waiting on the input
        # pipeline (p50 per chunk) and the input-vs-compute verdict — the
        # fields the streaming-vs-resident A/B is judged on
        "batcher_wait_p50_ms": (
            ((phases.report() or {}).get("phases") or {})
            .get("batcher_wait", {}).get("p50_ms")
        ),
        "input_bound_ratio": (phases.report() or {}).get("input_fraction"),
        "plan": cfg.current_plan().to_json(),
        "plan_source": plan_res.source if plan_res else "flags",
        "predicted_cost": predicted,
        "measured_cost": measured,
        "phases": phases.report(),
        "trace_summary": trace_summary,
        "cost_attribution": cost_attribution,
        "cost_calibrate": cost_calibration,
        # device truth (obs/devmem.py + obs/harvest.py): the measured
        # epoch's HBM watermarks and XLA's own per-executable costs, in the
        # same record as the analytic prediction they audit
        "device_memory": mem_ledger.summary(),
        "cost_harvest": harvest_report,
        "health": health,
        # the signal plane's windowed view of the measured epoch (and the
        # SLO rule states when --slo was set): fleet-aggregatable evidence
        # in the same record as the raw number
        "signals": signals.report(),
        "slo": signals.slo.summary() if signals.slo else None,
        "manifest": obs_manifest.manifest_dict(
            cfg, vocab_size=len(vocab), plan_resolution=plan_res,
            include_config=False,
        ),
    }
    if qprobe is not None:
        # the probe-row sequence over the measured epoch: how the table's
        # health statistics (and planted scores, when the corpus has golds)
        # moved while the throughput number was being taken
        record["quality_curve"] = [dict(r) for r in qprobe.history]
    if plan_res is not None:
        record["plan_cache_hit"] = plan_res.source == "cache"
        if plan_res.probes:
            record["plan_probes"] = plan_res.probes
    if load_start is not None:
        record["host_load_1m"] = [
            round(load_start, 2), round(os.getloadavg()[0], 2),
        ]
    if platform_note:
        record["tpu_fallback_reason"] = platform_note
    if args.smoke:
        # smoke contract: the banked record must carry a non-empty span
        # timeline (CI's trace job additionally schema-validates the export)
        assert trace_summary["spans"] and trace_summary["steps"] > 0, (
            f"--smoke: empty trace_summary {trace_summary!r}"
        )
        # device-truth contract (CI devmem job): the ledger and harvest
        # fields must bank even on statless CPU (available=false, but the
        # phases and at least one analyzed program are real), and every
        # anchor must carry a verdict
        dm = record["device_memory"]
        assert dm and dm["samples"] > 0 and "train_step" in dm["phases"], (
            f"--smoke: empty device_memory {dm!r}"
        )
        ch = record["cost_harvest"]
        assert ch and ch["programs_ok"] >= 1, (
            f"--smoke: cost_harvest analyzed no program: {ch!r}"
        )
        cal = record["cost_calibrate"]
        assert cal and len(cal["anchors"]) == 3 and all(
            a["verdict"] in ("ok", "drift", "stale") for a in cal["anchors"]
        ), f"--smoke: bad cost_calibrate {cal!r}"
    if tables.hs_msig is not None:
        # two-tier hs observability: the banked record shows what share of
        # token-weighted path entries the measured dense tier covered, and
        # whether the tail-compaction bound dropped ANY updates during the
        # timed epoch — a throughput number must not hide dropped work
        record["hs_dense_top"] = int(tables.hs_msig.shape[1])
        record["hs_dense_coverage"] = round(tables.hs_dense_coverage, 4)
        record["hs_tail_dropped"] = sum_device(dropped_metrics)
    return record


def run_fault_drill(args: argparse.Namespace, platform_note: str | None) -> dict:
    """`--faults`: measure RECOVERY OVERHEAD instead of raw throughput.

    Two supervised end-to-end Trainer runs on the same synthetic corpus —
    one clean, one with the fault plan active (NaN injection, checkpoint
    OSError, stalls; resilience/faults.py) under auto-recovery
    (resilience/supervisor.py). The emitted record carries both walls and
    their difference: what a divergence-rollback-retry actually costs at
    this shape, as a number that can be banked and compared round over
    round. The clean run is preceded by an untimed warmup pass so compile
    time doesn't masquerade as (negative) fault overhead.
    """
    import tempfile

    import jax

    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import BatchIterator, PackedCorpus
    from word2vec_tpu.io.checkpoint import save_checkpoint
    from word2vec_tpu.resilience import faults as faults_mod
    from word2vec_tpu.resilience.faults import FaultPlan
    from word2vec_tpu.resilience.shutdown import ShutdownHandler
    from word2vec_tpu.resilience.supervisor import Supervisor
    from word2vec_tpu.train import Trainer
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    # the drill runs the full pipeline 3x (warmup, clean, faulted): keep the
    # corpus smoke-sized unless the caller explicitly sized it down further
    tokens = min(args.tokens, 300_000)
    cfg = Word2VecConfig(
        model=args.model,
        train_method=args.train_method,
        negative=args.negative if args.train_method == "ns" else 0,
        word_dim=args.dim,
        window=args.window,
        batch_rows=args.batch_rows,
        max_sentence_len=args.max_len,
        chunk_cap=args.chunk_cap,
        band_backend=args.band_backend,
        table_layout=args.table_layout,
        prng_impl=args.prng,
        divergence_budget=4,
        seed=0,
    )
    vocab = zipf_vocab(71000, 17_000_000)
    flat = np.concatenate(zipf_corpus_ids(vocab, tokens, seed=0))
    ids = [flat[i:i + 1000] for i in range(0, len(flat), 1000)]
    corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)

    spe = BatchIterator(
        corpus, cfg.batch_rows, cfg.max_sentence_len
    ).steps_per_epoch()
    spec = args.faults
    if spec == "default":
        # one NaN divergence past the mid-epoch checkpoint: the canonical
        # rollback-and-retry scenario
        spec = f"nan@{max(1, (spe * 3) // 5)}"
    checkpoint_every = max(2, spe // 4)

    trainer = Trainer(cfg, vocab, corpus)
    handler = ShutdownHandler().install()  # sigterm faults stop cooperatively
    trainer.install_shutdown(handler)
    base = tempfile.mkdtemp(prefix="w2v_fault_drill_")

    def timed_run(name: str, plan: FaultPlan | None):
        ck = os.path.join(base, f"ck_{name}")

        def cb(s):
            save_checkpoint(ck, s, trainer.config, vocab, keep=2)

        trainer.fault_plan = plan
        prev = faults_mod.activate(plan) if plan is not None else None
        t0 = time.perf_counter()
        try:
            if plan is not None:
                sup = Supervisor(
                    trainer, checkpoint_dir=ck, max_retries=2,
                    alpha_scale=0.5,
                )
                _, rep = sup.run(
                    state=trainer.init_state(), log_every=0,
                    checkpoint_cb=cb, checkpoint_every=checkpoint_every,
                )
            else:
                _, rep = trainer.train(
                    state=trainer.init_state(), log_every=0,
                    checkpoint_cb=cb, checkpoint_every=checkpoint_every,
                )
        finally:
            if plan is not None:
                faults_mod.activate(prev)
            trainer.fault_plan = None
        return time.perf_counter() - t0, rep

    try:
        timed_run("warmup", None)  # compile + checkpoint paths warm
        clean_wall, clean_rep = timed_run("clean", None)
        plan = FaultPlan.parse(spec)
        fault_wall, fault_rep = timed_run("faulted", plan)
    finally:
        handler.uninstall()

    dev = jax.devices()[0]
    key = config_key(
        args.model, args.train_method, args.dim, args.window, cfg.negative
    )
    overhead = fault_wall - clean_wall
    record = {
        "metric": f"{key} recovery overhead ({tokens // 1000}k zipf, "
                  f"{dev.platform})",
        "value": round(overhead, 3),
        "unit": "s",
        "vs_baseline": None,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "faults": spec,
        "fault_log": plan.log,
        "clean_wall_s": round(clean_wall, 3),
        "faulted_wall_s": round(fault_wall, 3),
        "overhead_pct": round(100.0 * overhead / max(clean_wall, 1e-9), 1),
        "clean_words_per_sec": round(clean_rep.words_per_sec, 1),
        # effective: the CLEAN run's useful words over the FAULTED wall —
        # the last retry's own words_per_sec would count resumed progress
        # it never retrained and flatter the faulted run
        "faulted_effective_words_per_sec": round(
            clean_rep.total_words / max(fault_wall, 1e-9), 1
        ),
        "recoveries": fault_rep.recoveries or [],
        "interrupted": fault_rep.interrupted,
        "divergence_budget": cfg.divergence_budget,
        "checkpoint_every_steps": checkpoint_every,
        "steps_per_epoch": spe,
    }
    if platform_note:
        record["tpu_fallback_reason"] = platform_note
    return record


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    # text8 is ~17M tokens; the synthetic default matches it so the headline
    # number is steady-state (at 2M tokens the epoch is ~48 steps and compile-
    # adjacent fixed costs dominate: 1.5M w/s there vs 3.6M at 20M, measured)
    ap.add_argument("--tokens", type=int, default=17_000_000)
    ap.add_argument("--vocab", type=int, default=71000,
                    help="synthetic zipf vocabulary size (the flagship "
                    "71k unless shrunk; interpret-mode pallas_fused "
                    "smokes shrink it — the interpreter materializes the "
                    "HBM-resident [V, 2, d] slab per grid step, so CPU "
                    "canary cost scales with V)")
    ap.add_argument("--dim", type=int, default=300)
    ap.add_argument("--model", choices=["sg", "cbow"], default="sg")
    ap.add_argument("--train-method", choices=["ns", "hs"], default="ns",
                    help="hs benches the positional Huffman kernel "
                    "(BASELINE config 3)")
    ap.add_argument("--window", type=int, default=5)
    ap.add_argument("--negative", type=int, default=5)
    ap.add_argument("--batch-rows", type=int, default=256)
    ap.add_argument("--max-len", type=int, default=192)
    ap.add_argument("--chunk-cap", type=int, default=32,
                    help="max optimizer steps fused per dispatch")
    ap.add_argument("--slab-scatter", type=int, default=0, choices=[0, 1],
                    help="band kernel slab-space context scatter (A/B knob)")
    ap.add_argument("--kp", type=int, default=64,
                    help="shared negative draws per row (accuracy holds to "
                    "KP=8 on the parity harness; PERF.md)")
    ap.add_argument("--neg-scope", choices=["row", "batch"], default="row",
                    help="negative pool scope: per row, or one pool per "
                    "batch (single dense matmul, KP-row update scatter)")
    ap.add_argument("--band-chunk", type=int, default=0,
                    help="band slab row-chunk S (0 = auto; ops/banded.py)")
    ap.add_argument("--hs-dense-top", type=int, default=0,
                    help="two-tier hs: top-P dense tier (config.hs_dense_top)")
    ap.add_argument("--hs-tail-slots", type=int, default=-1,
                    help="two-tier hs tail compaction bound "
                         "(config.hs_tail_slots)")
    ap.add_argument("--band-backend",
                    choices=["xla", "pallas", "pallas_oa", "pallas_fused"],
                    default="xla",
                    help="band step compute: XLA chain, the fused Pallas "
                    "kernel (ops/pallas_band.py), the XLA chain with "
                    "the Pallas overlap-add kernel replacing the "
                    "layout-copy chain (pallas_oa, ops/pallas_overlap.py; "
                    "composes with --fused/--table-dtype/--sr/--neg-scope), "
                    "or the fully-fused step over the unified slab "
                    "(pallas_fused, ops/pallas_step.py; requires "
                    "--table-layout unified, row negative scope; composes "
                    "with --table-dtype/--sr)")
    ap.add_argument("--table-dtype", choices=["float32", "bfloat16"],
                    default="float32",
                    help="storage dtype of the [V, d] tables (A/B lever: "
                    "halves table gather/scatter bytes)")
    ap.add_argument("--sr", type=int, default=0, choices=[0, 1],
                    help="stochastic rounding of table updates (bf16 tables)")
    ap.add_argument("--quality-every", type=int, default=0, metavar="STEPS",
                    help="bank an in-training quality_curve: probe the "
                    "live table every STEPS optimizer steps "
                    "(obs/quality.py — planted scores when the corpus has "
                    "golds, else row-norm/drift/effective-rank stats). "
                    "Each probe adds one device fetch mid-measurement, so "
                    "0 (off) is the throughput default")
    ap.add_argument("--health", type=int, default=0, choices=[0, 1],
                    help="bank the full on-device health counters "
                    "(grad-norm, per-table update magnitudes) in the "
                    "record; off by default — they cost an extra table "
                    "read per step (config.health_metrics)")
    ap.add_argument("--prng", choices=["threefry", "rbg"], default="threefry",
                    help="jax PRNG impl for the device draw streams; rbg is "
                    "cheaper on TPU (different stream, statistically "
                    "equivalent draws)")
    ap.add_argument("--fused", type=int, default=0, choices=[0, 1],
                    help="fused-table scatter inside chunks "
                    "(config.fused_tables; band ns only)")
    ap.add_argument("--table-layout", choices=["split", "unified"],
                    default="split",
                    help="table storage layout (config.table_layout): "
                    "unified = one persistent [V, 2, d] slab, ONE sorted "
                    "scatter per step at doubled width (~half the "
                    "table-update tail; trajectory bitwise identical). The "
                    "banked record's plan carries the realized layout — "
                    "queue items grep it (forwarding audit)")
    ap.add_argument("--corpus-mode", choices=["resident", "streaming"],
                    default="resident",
                    help="data plane A/B (stream/): resident packs the "
                         "whole id stream once; streaming consumes it in "
                         "--segment-tokens segments through the segment "
                         "read/pack/prefetch pipeline — the SAME chunked "
                         "dispatch measures both, so the delta is pure "
                         "data-plane cost (batcher_wait / "
                         "input_bound_ratio attribution in the record)")
    ap.add_argument("--segment-tokens", type=int, default=0,
                    help="streaming segment size in tokens (0 = auto: 4M)")
    ap.add_argument("--resident", type=int, default=1, choices=[0, 1],
                    help="device-resident corpus (ops/resident.py); falls "
                    "back to host streaming when the corpus exceeds HBM "
                    "budget")
    ap.add_argument("--autotune", choices=["off", "probe", "cached"],
                    default="off",
                    help="autotuned execution planner (word2vec_tpu/tune): "
                    "probe = cost-model-pruned grid + timed probes, winner "
                    "persisted; cached = start from the persisted plan with "
                    "zero probe cost (miss falls back to probe)")
    ap.add_argument("--plan-cache", default="",
                    help="plan-cache JSON path (default: $W2V_PLAN_CACHE or "
                    "~/.cache/word2vec_tpu/plan_cache.json)")
    ap.add_argument("--faults", nargs="?", const="default", default="",
                    metavar="SPEC",
                    help="recovery-overhead drill instead of the throughput "
                    "bench: run clean vs fault-injected+auto-recovered and "
                    "emit the measured overhead (resilience/faults.py spec, "
                    "incl. the hang kinds — 'hang@K:secs=S' measures an "
                    "S-second main-loop wedge as overhead; bare --faults = "
                    "one NaN divergence past the mid-epoch checkpoint; the "
                    "idle-watchdog cost itself is banked by "
                    "benchmarks/watchdog_overhead.py)")
    ap.add_argument("--slo", default="", metavar="RULES",
                    help="SLO rules evaluated over the measured epoch's "
                    "derived-signal windows (obs/slo.py grammar, e.g. "
                    "'throughput_wps<0.8*baseline:for=3'); the banked "
                    "record carries the rule states under 'slo'. The "
                    "derived signals themselves bank under 'signals' "
                    "regardless")
    ap.add_argument("--signal-window", type=int, default=0, metavar="STEPS",
                    help="optimizer steps per derived-signal window "
                    "(0 = auto: one chunk)")
    ap.add_argument("--trace", default="", metavar="DIR",
                    help="export the measured epoch's span timeline as "
                    "Chrome-trace JSON to DIR/trace.json (obs/trace.py; "
                    "diff two plans with python -m "
                    "word2vec_tpu.obs.tracediff). The in-record "
                    "trace_summary is banked regardless")
    ap.add_argument("--profile-steps", default="", metavar="A:B",
                    help="bounded jax.profiler window over the measured "
                    "epoch (obs/profiler.py): arm at step A, stop at step "
                    "B, capture manifest (capture_<n>.json) into "
                    "--profile-dir. The in-record device_memory / "
                    "cost_harvest fields bank regardless")
    ap.add_argument("--profile-dir", default="bench_profile", metavar="DIR",
                    help="where --profile-steps writes its trace + "
                    "capture manifest")
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke preset: shrink the synthetic corpus to "
                    "~60s of CPU wall time (still the real pipeline at the "
                    "flagship dim/vocab — catches throughput regressions "
                    "and crashes, not absolute-number drift)")
    ap.add_argument("--measure-steps", type=int, default=0,
                    help="0 = one full epoch (rounded up to whole chunks)")
    ap.add_argument("--text8", default="text8")
    ap.add_argument("--probe-timeout", type=float, default=90.0,
                    help="seconds to wait for backend init before CPU fallback")
    ap.add_argument("--probe-retries", type=int, default=3,
                    help="backend probe attempts (the tunnel flaps; a hang "
                    "now does not mean a hang in two minutes)")
    ap.add_argument("--probe-retry-wait", type=float, default=60.0,
                    help="seconds between probe attempts")
    ap.add_argument("--run-timeout", type=float, default=3600.0,
                    help="watchdog for the measured run itself (the tunnel "
                    "can hang MID-run, after a successful probe)")
    ap.add_argument("--cpu", action="store_true", help="skip probe, run on CPU")
    ap.add_argument("--inner", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--fallback-reason", default=None, help=argparse.SUPPRESS)
    return ap


def apply_smoke(args: argparse.Namespace) -> None:
    """--smoke preset, applied identically in the outer shell and the inner
    child (both parse argv): a ~300k-token synthetic epoch at the flagship
    shape. Explicit --tokens/--probe flags still win where smaller."""
    if not args.smoke:
        return
    args.tokens = min(args.tokens, 300_000)
    args.probe_timeout = min(args.probe_timeout, 20.0)
    args.probe_retries = 1
    args.run_timeout = min(args.run_timeout, 600.0)


def error_record(args: argparse.Namespace, err: str, note: str | None) -> dict:
    return {
        "metric": config_key(
            args.model, args.train_method, args.dim, args.window,
            args.negative if args.train_method == "ns" else 0,
        ) + " words/sec",
        "value": None,
        "unit": "words/sec",
        "vs_baseline": None,
        "error": err,
        "tpu_fallback_reason": note,
    }


def inner_main(args: argparse.Namespace) -> None:
    """The measured run. Any failure still emits the one JSON line, with a
    traceback tail for post-hoc diagnosis."""
    try:
        import jax

        if args.cpu:
            # JAX_PLATFORMS env is overridden by the axon sitecustomize's
            # jax.config call; config.update after import wins over both.
            jax.config.update("jax_platforms", "cpu")
        # --prng flows through cfg.prng_impl into explicit key impls (run())
        if args.faults:
            emit(run_fault_drill(args, args.fallback_reason))
        else:
            emit(run(args, args.fallback_reason))
    except Exception as e:  # noqa: BLE001 — the contract is one JSON line, always
        import traceback

        tb = traceback.format_exc().strip().splitlines()
        emit(
            error_record(
                args,
                f"{type(e).__name__}: {e}",
                args.fallback_reason,
            )
            | {"traceback_tail": tb[-12:]}
        )
        sys.exit(0)


def acquire_chip_lock(timeout_s: float = 900.0):
    """Cooperate with the measurement queue (benchmarks/tpu_queue_lib.sh):
    its run_item holds benchmarks/.chip.lock around each on-chip item, and
    its probes block while someone else holds it. Acquiring the same lock
    here means a driver-invoked bench waits for the current queue item to
    finish instead of racing it — two clients on the one chip would bank
    contention-degraded numbers as official evidence. The wait is BOUNDED:
    after timeout_s the bench proceeds anyway (a wedged queue item must
    never starve the round's official artifact), and the lock is held
    until process exit so queue probes stay blocked for the whole
    measured run. No-ops inside the queue itself (W2V_CHIP_LOCK_HELD) and
    on --cpu runs."""
    if os.environ.get("W2V_CHIP_LOCK_HELD"):
        return None
    try:
        import fcntl
    except ImportError:
        return None
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        ".chip.lock",
    )
    try:
        f = open(path, "w")
    except OSError:
        return None
    deadline = time.time() + timeout_s
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if time.time() >= deadline:
                return f  # proceed unlocked; keep the fd open harmlessly
            time.sleep(5)


def main() -> None:
    args = build_parser().parse_args()
    apply_smoke(args)
    if args.inner:
        inner_main(args)
        return

    # Outer shell: probe the backend, then run the bench in a watchdogged
    # child — a tunnel hang mid-run (post-probe) would otherwise wedge with
    # no output at all, which is exactly the BENCH_r01 failure mode.
    platform_note = None
    force_cpu = args.cpu
    chip_lock = None if force_cpu else acquire_chip_lock()
    if not force_cpu:
        for attempt in range(max(1, args.probe_retries)):
            if attempt:
                time.sleep(args.probe_retry_wait)
            ok, info = probe_backend(args.probe_timeout)
            if ok:
                platform_note = None
                break
            platform_note = f"{info} (attempt {attempt + 1})"
        else:
            force_cpu = True
    if force_cpu and chip_lock is not None:
        # the run will never touch the chip — don't block the queue's
        # probes/items behind a CPU fallback (closing releases the flock)
        chip_lock.close()
        chip_lock = None

    child_cmd = [sys.executable, os.path.abspath(__file__), "--inner"]
    child_cmd += ["--cpu"] if force_cpu else []
    child_cmd += ["--fallback-reason", platform_note] if platform_note else []
    for flag, val in [
        ("--tokens", args.tokens), ("--vocab", args.vocab),
        ("--dim", args.dim),
        ("--model", args.model), ("--train-method", args.train_method),
        ("--window", args.window), ("--negative", args.negative),
        ("--batch-rows", args.batch_rows), ("--max-len", args.max_len),
        ("--chunk-cap", args.chunk_cap), ("--slab-scatter", args.slab_scatter),
        ("--kp", args.kp), ("--neg-scope", args.neg_scope),
        ("--band-chunk", args.band_chunk),
        ("--band-backend", args.band_backend),
        ("--hs-dense-top", args.hs_dense_top),
        ("--hs-tail-slots", args.hs_tail_slots),
        ("--resident", args.resident), ("--fused", args.fused),
        ("--corpus-mode", args.corpus_mode),
        ("--segment-tokens", args.segment_tokens),
        ("--table-layout", args.table_layout),
        ("--prng", args.prng), ("--table-dtype", args.table_dtype),
        ("--sr", args.sr), ("--health", args.health),
        ("--quality-every", args.quality_every),
        ("--autotune", args.autotune), ("--plan-cache", args.plan_cache),
        ("--measure-steps", args.measure_steps), ("--text8", args.text8),
        ("--signal-window", args.signal_window),
    ]:
        child_cmd += [flag, str(val)]
    if args.slo:
        child_cmd += ["--slo", args.slo]
    if args.faults:
        child_cmd += ["--faults", args.faults]
    if args.trace:
        child_cmd += ["--trace", args.trace]
    if args.profile_steps:
        # forwarded outer->inner like every measurement flag (the r4
        # lesson): the inner child is the process that actually profiles
        child_cmd += ["--profile-steps", args.profile_steps,
                      "--profile-dir", args.profile_dir]
    try:
        out = subprocess.run(
            child_cmd, capture_output=True, text=True, timeout=args.run_timeout
        )
    except subprocess.TimeoutExpired:
        rec = error_record(
            args, f"bench run hang (> {args.run_timeout:.0f}s)", platform_note
        )
        banked = best_banked_tpu(rec["metric"].removesuffix(" words/sec"))
        if banked:
            rec["best_banked_tpu"] = banked
        emit(rec)
        return
    lines = [l for l in (out.stdout or "").strip().splitlines() if l.startswith("{")]
    if lines:
        try:
            rec = json.loads(lines[-1])
        except json.JSONDecodeError:
            # a brace-prefixed non-JSON last line (child died mid-write):
            # preserve the one-line contract by printing it verbatim
            print(lines[-1])
            return
        if force_cpu and not args.cpu:
            banked = best_banked_tpu(config_key(
                args.model, args.train_method, args.dim, args.window,
                args.negative if args.train_method == "ns" else 0,
            ))
            if banked:
                rec["best_banked_tpu"] = banked
        print(json.dumps(rec))
        return
    tail = (out.stderr or "").strip().splitlines()[-12:]
    rec = error_record(
        args, f"bench child died rc={out.returncode} with no JSON", platform_note
    ) | {"traceback_tail": tail}
    banked = best_banked_tpu(rec["metric"].removesuffix(" words/sec"))
    if banked:
        rec["best_banked_tpu"] = banked
    emit(rec)


if __name__ == "__main__":
    main()
