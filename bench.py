#!/usr/bin/env python
"""Benchmark: SGNS training words/sec on the flagship config (BASELINE.json:
skip-gram, negative=5, dim=300, window=5, text8-scale corpus).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Corpus: ./text8 if present, else a synthetic Zipf stream with text8's vocab
size and skew (utils/synthetic.py) — the perf-relevant properties match, so
words/sec transfers.

Baseline: benchmarks/reference_baseline.json holds the measured words/sec of
the compiled C++ reference on this machine (see benchmarks/reference_harness/
for how it is produced). vs_baseline = ours / reference.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=2_000_000)
    ap.add_argument("--dim", type=int, default=300)
    ap.add_argument("--window", type=int, default=5)
    ap.add_argument("--negative", type=int, default=5)
    ap.add_argument("--batch-rows", type=int, default=256)
    ap.add_argument("--max-len", type=int, default=192)
    ap.add_argument("--warmup-steps", type=int, default=3)
    ap.add_argument("--measure-steps", type=int, default=0,
                    help="0 = one full epoch")
    ap.add_argument("--text8", default="text8")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import BatchIterator, PackedCorpus, prefetch
    from word2vec_tpu.data.vocab import Vocab
    from word2vec_tpu.models.params import init_params
    from word2vec_tpu.ops.tables import DeviceTables
    from word2vec_tpu.ops.train_step import jit_train_step
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model="sg",
        train_method="ns",
        negative=args.negative,
        word_dim=args.dim,
        window=args.window,
        subsample_threshold=1e-4,
        batch_rows=args.batch_rows,
        max_sentence_len=args.max_len,
    )

    if os.path.exists(args.text8):
        from word2vec_tpu.data.corpus import text8_corpus

        sents = list(text8_corpus(args.text8))
        vocab = Vocab.build(sents, min_count=cfg.min_count)
        corpus = PackedCorpus.pack(vocab.encode_corpus(sents), cfg.max_sentence_len)
        corpus_name = "text8"
    else:
        vocab = zipf_vocab(71000, 17_000_000)
        ids = zipf_corpus_ids(vocab, args.tokens, seed=0)
        corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
        corpus_name = f"zipf-synthetic-{args.tokens // 1_000_000}M"

    tables = DeviceTables.build(vocab, cfg)
    step = jit_train_step(cfg, tables)
    params = init_params(cfg, len(vocab), jax.random.key(0))
    batcher = BatchIterator(corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1)
    alpha = jnp.float32(cfg.init_alpha)
    base_key = jax.random.key(7)

    # warmup / compile
    it = batcher.epoch()
    for _ in range(args.warmup_steps):
        tokens, _ = next(it)
        params, m = step(params, jnp.asarray(tokens), base_key, alpha)
    jax.block_until_ready(params)

    # timed steady-state
    words = 0
    steps = 0
    t0 = time.perf_counter()
    for tokens, w in prefetch(it):
        key = jax.random.fold_in(base_key, steps)
        params, m = step(params, jnp.asarray(tokens), key, alpha)
        words += w
        steps += 1
        if args.measure_steps and steps >= args.measure_steps:
            break
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    wps = words / dt

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "reference_baseline.json",
    )
    vs = None
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            ref = json.load(f)
        if ref.get("words_per_sec"):
            vs = wps / float(ref["words_per_sec"])

    dev = jax.devices()[0]
    print(
        json.dumps(
            {
                "metric": f"sgns-dim{args.dim}-w{args.window}-k{args.negative} "
                f"words/sec ({corpus_name}, {dev.platform})",
                "value": round(wps, 1),
                "unit": "words/sec",
                "vs_baseline": round(vs, 2) if vs is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main()
