"""Persistent JSON plan cache for the autotuned execution planner.

One file, one JSON object: {schema, plans: {key: entry}}. Keys are the
planner's identity tuple

    (device_kind, backend, kernel_route, vocab_size, word_dim,
     table_layout, shared_negatives)

rendered as a string (plan_key) — the dimensions along which a tuned step
shape transfers: the chip generation, where the program runs (cpu/tpu), which
kernel family realizes the objective, the two sizes that set every
matmul/scatter shape, and the CONFIGURED table layout + negative-pool width.
The last two are plan dimensions the grid also searches, but they belong in
the key as the search's STARTING POINT: before schema 2 a plan probed under
the split layout could be served to a run configured unified (and a KP=8
quality run could silently inherit a KP=64 plan) because the key could not
tell the two problems apart. Anything else that could invalidate a plan
(window, sentence length, micro-step block, model/objective) goes into the
entry's FINGERPRINT: a lookup whose fingerprint disagrees is a miss, so a
stale plan can never be silently applied to a different problem.

Entries carry provenance (probe throughput, predicted cost, creation time)
so a banked bench artifact can say where its shapes came from.

Writes are atomic (tmp + os.replace) and lock-free: last writer wins, which
is fine for a cache whose entries are independently recomputable. A corrupt
or unreadable file reads as empty — the planner then re-probes, it never
crashes the run.

The packaged seed file (tune/seed_plans.json) backs every lookup: shapes
hand-tuned in benchmarks/tpu_queue5.sh-era sweeps (e.g. the banked
TPU v5 lite default, TPU_R4/default.json) are available with zero probe cost
on a fresh machine. User-cache entries shadow seeds on key collision.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, Optional

# 2: plan_key gained (table_layout, shared_negatives); fingerprints dropped
#    dtype/stochastic_rounding (now TunePlan dimensions the grid searches)
# 3: plan_key gained the CONFIGURED band_backend — a plan probed under the
#    xla/pallas_oa chain could otherwise be silently applied to a
#    band_backend='pallas_fused' run (the PR 7 plan-key lesson, again:
#    the fused step's optimal chunk/cap shapes have no reason to match
#    the chain's, and a mislabeled cached plan poisons every A/B)
SCHEMA = 3

_SEED_PATH = os.path.join(os.path.dirname(__file__), "seed_plans.json")


def default_cache_path() -> str:
    env = os.environ.get("W2V_PLAN_CACHE")
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "word2vec_tpu", "plan_cache.json"
    )


def plan_key(
    device_kind: str, backend: str, kernel_route: str, vocab_size: int,
    dim: int, table_layout: str, shared_negatives: int, band_backend: str,
) -> str:
    """The cache key: (device_kind, backend, kernel, vocab_size, dim,
    table_layout, shared_negatives, band_backend).

    vocab_size is bucketed to 2 significant figures — step shapes do not
    change between a 71,290- and a 71,000-word vocabulary, and an exact
    count would make every corpus re-probe.

    table_layout, shared_negatives and band_backend are the CONFIGURED
    values (the problem identity), deliberately required arguments: a
    default would re-open the schema-1 bug where a cached split-layout
    plan was silently applied to a unified-layout run (or a pinned-KP
    quality run inherited another width's plan). Schema 3 added
    band_backend for the same reason: a plan probed under the xla or
    pallas_oa chain must never be silently applied to a
    band_backend='pallas_fused' run. The plan stored under the key may
    still realize a different layout/width/backend — that is the
    planner's arbitration, recorded in the entry, not an identity
    mismatch.
    """
    v = int(vocab_size)
    if v >= 100:
        mag = 10 ** (len(str(v)) - 2)
        v = (v // mag) * mag
    return (
        f"{device_kind or 'unknown'}|{backend}|{kernel_route}|V{v}|d{dim}"
        f"|{table_layout}|kp{int(shared_negatives)}|{band_backend}"
    )


def _read(path: str) -> Dict:
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            return {"schema": SCHEMA, "plans": {}}
        if not isinstance(doc.get("plans"), dict):
            return {"schema": SCHEMA, "plans": {}}
        return doc
    except (OSError, json.JSONDecodeError, ValueError):
        return {"schema": SCHEMA, "plans": {}}


def lookup(
    key: str, fingerprint: Dict, path: Optional[str] = None
) -> Optional[Dict]:
    """The cached entry for `key`, or None. Fingerprint mismatch is a miss
    (invalidation: the key matched but the problem changed underneath it).
    User cache first, packaged seeds second."""
    for p in (path or default_cache_path(), _SEED_PATH):
        entry = _read(p)["plans"].get(key)
        if entry is None:
            continue
        if entry.get("fingerprint") != fingerprint:
            continue
        return entry
    return None


def store(key: str, entry: Dict, path: Optional[str] = None) -> str:
    """Atomically merge {key: entry} into the cache file; returns the path."""
    path = path or default_cache_path()
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    doc = _read(path)
    doc["plans"][key] = dict(
        entry,
        created_utc=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        schema=SCHEMA,
    )
    fd, tmp = tempfile.mkstemp(dir=parent, prefix=".plan_cache_")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
