"""Analytic step-cost model: the planner's pruning stage.

Ranks candidate plans WITHOUT running anything: per-step FLOPs and HBM
traffic come from the shared counters in utils/profiling.py (the same
numbers bench.py reports as predicted_cost), and a two-term roofline turns
them into milliseconds:

    step_ms     = max(flops / peak_flops, bytes / hbm_bw) + copy_ms
    dispatch_ms = per-dispatch overhead / chunk_cap        (amortized share)
    total_ms    = step_ms + dispatch_ms

The layout-copy term is the one place the model leans on a measurement
instead of first principles: the r2 on-chip trace put the overlap-add's
layout copies at 2.14 ms = 27% of the 7.97 ms step at the flagship shape
(PERF.md), ~7x what their raw bytes would cost at streaming HBM bandwidth —
layout transposes are strided, not streaming. LAYOUT_COPY_INEFFICIENCY is
calibrated so the model reproduces that anchor exactly at the traced shape
(pinned by tests/test_tune.py); every other shape scales analytically from
it. The term is attributed PER BACKEND by utils/profiling.step_hbm_bytes:
it prices only the XLA overlap-add chain — the 'pallas' backend keeps the
whole plane in VMEM, and 'pallas_oa' replaces exactly that chain with the
VMEM overlap-add kernel (ops/pallas_overlap.py), paying one sequential
slab-plane read + token-plane write instead. That contrast is what lets
the planner rank pallas_oa above xla precisely when the copy term
dominates (tests/test_tune.py ordering tests).

The model's job is ORDERING (which few candidates deserve a timed probe),
not absolute truth — probes decide the winner. Both numbers are banked side
by side in bench.py's output (predicted_cost vs measured_cost) precisely so
the model's error stays observable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ..utils.profiling import step_flops, step_geometry, step_hbm_bytes

# device_kind prefix -> (peak bf16 FLOP/s, HBM bytes/s, per-dispatch
# overhead ms). TPU peaks mirror bench.PEAK_FLOPS_BF16; bandwidths are the
# public HBM specs; dispatch overhead is the measured per-dispatch cost of
# the remote tunnel (~40 ms/dispatch round-1 async loop, PERF.md) for TPU
# and a sub-ms local jit dispatch for CPU.
DEVICE_SPECS: Dict[str, Tuple[float, float, float]] = {
    "TPU v4": (275e12, 1.2e12, 40.0),
    "TPU v5 lite": (197e12, 0.82e12, 40.0),
    "TPU v5e": (197e12, 0.82e12, 40.0),
    "TPU v5p": (459e12, 2.77e12, 40.0),
    "TPU v5": (459e12, 2.77e12, 40.0),
    "TPU v6 lite": (918e12, 1.64e12, 40.0),
    "TPU v6e": (918e12, 1.64e12, 40.0),
}
# 1-core host fallback: measured ~75k words/sec at the flagship CPU shape
# implies ~15 GFLOP/s effective; bandwidth is not the CPU binding term.
CPU_SPEC: Tuple[float, float, float] = (15e9, 2e10, 0.3)

# Calibration anchor (r2 trace, PERF.md): 2.14 ms of layout copies at
# B=256, L=192, d=300, W=5 on TPU v5 lite, whose raw copy bytes
# (3 x [B, C, S+2W, d] f32 = 236 MB) would stream in ~0.29 ms at 0.82 TB/s.
LAYOUT_COPY_INEFFICIENCY = 7.4

# Second calibration anchor (same r2 trace): the sorted table scatters run
# at ~21 ns/ROW regardless of row width — row machinery, not bytes ("Why
# not a Pallas scatter kernel", PERF.md: 2.08 ms for the two 49,152-row
# table scatters + 0.41 ms for the 16,384 negative rows ≈ 21 ns/row). This
# is the term the table LAYOUT moves (utils/profiling.step_hbm_bytes
# scatter_rows): the unified [V, 2, d] slab halves the token-id scatter
# count, predicting ~1.0 ms off the ~8 ms flagship step — which is exactly
# what lets the planner arbitrate split-vs-unified per device
# (tests/test_tune.py counterfactual-flip pin).
SCATTER_SEC_PER_ROW = 21e-9

# --- fused-step terms (r12 lever, band_backend='pallas_fused') ---
# The step's op chain executes as `programs` separately scheduled device
# programs (utils/profiling.step_hbm_bytes "programs": ~9 for the XLA
# band chain — gathers, four band contractions, the overlap-add, two table
# scatters — vs 3 for the fused step). Each boundary costs a scheduling
# gap the byte roofline cannot see; the r2 trace's step decomposition
# leaves ~1 ms of the 7.97 ms flagship step unattributed to bytes, flops
# or scatter rows, which at the 9-program chain calibrates the gap to
# ~0.12 ms/program. This is the dispatch-tail term the fused step deletes
# (tracediff attributed the kp16 win 100% to dispatch — the motivating
# evidence that the tail, not the bytes, now binds).
PROGRAM_GAP_MS = 0.12
# The fused kernels pay their gathers/scatter as back-to-back in-kernel
# row DMAs (step_hbm_bytes "dma_rows") instead of XLA scatter machinery.
# Priced at a third of SCATTER_SEC_PER_ROW: a descriptor-driven DMA skips
# the scatter's bounds/update machinery and overlaps with compute. The
# fused step's predicted win hinges on this staying well under the 21 ns
# anchor — the r12 counterfactual-flip test pins exactly that sensitivity
# (price DMAs AT the scatter anchor x3 and the fused step must stop
# outranking pallas_oa), and the tpu_queue8.sh A/B banks the ground truth.
DMA_SEC_PER_ROW = 7e-9


def device_spec(
    device_kind: str, platform: str
) -> Tuple[float, float, float]:
    for prefix, spec in DEVICE_SPECS.items():
        if device_kind.startswith(prefix):
            return spec
    if platform == "tpu":
        return DEVICE_SPECS["TPU v5 lite"]  # conservative unknown-TPU guess
    return CPU_SPEC


@dataclasses.dataclass(frozen=True)
class CostEstimate:
    flops: float
    hbm_bytes: float
    copy_bytes: float
    scatter_rows: float  # rows fed to table scatter-adds (a count)
    scatter_ms: float    # scatter_rows * SCATTER_SEC_PER_ROW (per-layout)
    dma_rows: float      # in-kernel per-row DMAs (pallas_fused only)
    dma_ms: float        # dma_rows * DMA_SEC_PER_ROW
    programs: float      # separately scheduled device programs per step
    program_gap_ms: float  # programs * PROGRAM_GAP_MS (the dispatch tail)
    step_ms: float       # compute + traffic + copies + row terms, per step
    dispatch_ms: float   # per-step share of dispatch overhead
    total_ms: float

    def to_json(self) -> Dict:
        return {
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "copy_bytes": self.copy_bytes,
            "scatter_rows": self.scatter_rows,
            "scatter_ms": round(self.scatter_ms, 4),
            "dma_rows": self.dma_rows,
            "dma_ms": round(self.dma_ms, 4),
            "programs": self.programs,
            "program_gap_ms": round(self.program_gap_ms, 4),
            "step_ms": round(self.step_ms, 4),
            "dispatch_ms": round(self.dispatch_ms, 4),
            "total_ms": round(self.total_ms, 4),
        }


def layout_copy_ms(copy_bytes: float, hbm_bw: float) -> float:
    return 1e3 * copy_bytes * LAYOUT_COPY_INEFFICIENCY / hbm_bw


def table_scatter_ms(scatter_rows: float) -> float:
    """The per-layout scatter term: row machinery the byte roofline cannot
    see (~21 ns/row regardless of width — SCATTER_SEC_PER_ROW anchor)."""
    return 1e3 * scatter_rows * SCATTER_SEC_PER_ROW


def kernel_dma_ms(dma_rows: float) -> float:
    """The fused step's in-kernel per-row DMA term (DMA_SEC_PER_ROW)."""
    return 1e3 * dma_rows * DMA_SEC_PER_ROW


def program_gap_ms(programs: float) -> float:
    """Inter-program scheduling gaps in the step's device op chain — the
    dispatch tail the fused step collapses (PROGRAM_GAP_MS each)."""
    return programs * PROGRAM_GAP_MS


def predict(
    config,
    vocab_size: int,
    device_kind: str = "",
    platform: str = "cpu",
    chunk_cap: Optional[int] = None,
) -> CostEstimate:
    """CostEstimate for one optimizer step of `config` on the named device.

    chunk_cap overrides the config's scan megastep cap (the planner sweeps
    it without rebuilding configs).
    """
    peak, bw, overhead = device_spec(device_kind, platform)
    flops = step_flops(config, vocab_size)
    traffic = step_hbm_bytes(config, vocab_size)
    streamed = traffic["total"] - traffic["layout_copies"]
    scatter_rows = traffic.get("scatter_rows", 0.0)
    scatter_ms = table_scatter_ms(scatter_rows)
    dma_rows = traffic.get("dma_rows", 0.0)
    dma_ms = kernel_dma_ms(dma_rows)
    programs = traffic.get("programs", 0.0)
    gap_ms = program_gap_ms(programs)
    step_ms = (
        1e3 * max(flops / peak, streamed / bw)
        + layout_copy_ms(traffic["layout_copies"], bw)
        + scatter_ms
        + dma_ms
        + gap_ms
    )
    cap = chunk_cap if chunk_cap is not None else config.chunk_cap
    dispatch_ms = overhead / max(1, cap)
    return CostEstimate(
        flops=flops,
        hbm_bytes=traffic["total"],
        copy_bytes=traffic["layout_copies"],
        scatter_rows=scatter_rows,
        scatter_ms=scatter_ms,
        dma_rows=dma_rows,
        dma_ms=dma_ms,
        programs=programs,
        program_gap_ms=gap_ms,
        step_ms=step_ms,
        dispatch_ms=dispatch_ms,
        total_ms=step_ms + dispatch_ms,
    )


def predicted_words_per_sec(
    config, vocab_size: int, device_kind: str = "", platform: str = "cpu"
) -> float:
    """The ranking metric: tokens per dispatched step over predicted step
    time. Row-packing fill is a corpus property shared by all candidates, so
    a constant factor drops out of the ordering."""
    est = predict(config, vocab_size, device_kind, platform)
    words_per_step = config.batch_rows * config.max_sentence_len
    return 1e3 * words_per_step / max(est.total_ms, 1e-9)


def geometry(config, vocab_size: int) -> Dict:
    """Re-export of the shared shape resolution (utils/profiling) so planner
    callers need one import."""
    return step_geometry(config, vocab_size)


# ---------------------------------------------------- anchor calibration
# The three hand anchors above were each calibrated from ONE measurement
# (the r2 trace) and cannot detect their own drift: a new jaxlib, a layout
# change, or a different chip silently invalidates them while the model
# keeps ranking plans with stale constants. cost_calibrate inverts the
# prediction against a run's measured device time: assuming the OTHER
# anchors are right, the residual the measurement leaves for anchor `a`'s
# term implies a value for `a`; implied/hand outside DRIFT_FACTOR is
# drift. One scalar measurement cannot separate three anchors — a drifted
# total flags EVERY active anchor whose term could carry the residual, and
# the verdict means "re-measure the anchors", not "this one constant
# moved". Terms contributing less than CALIBRATE_MIN_SHARE of the measured
# step are 'stale': there is not enough signal at this shape to judge them
# (the honest CPU-smoke outcome, where compute dwarfs every anchor term).

#: implied/hand ratio beyond which an anchor reads as drifted (a 3x
#: perturbation lands at ~3 or ~1/3 — well outside; honest measurement
#: noise on anchor-dominated shapes stays well inside)
DRIFT_FACTOR = 2.0
#: minimum fraction of the measured device step an anchor's predicted term
#: must carry before its implied value is meaningful
CALIBRATE_MIN_SHARE = 0.02

#: anchor name -> (module constant name, CostEstimate count field,
#: CostEstimate term-ms field)
ANCHORS = {
    "scatter_sec_per_row": (
        "SCATTER_SEC_PER_ROW", "scatter_rows", "scatter_ms"
    ),
    "program_gap_ms": ("PROGRAM_GAP_MS", "programs", "program_gap_ms"),
    "dma_sec_per_row": ("DMA_SEC_PER_ROW", "dma_rows", "dma_ms"),
}


def measured_device_ms(trace_summary: Dict) -> Optional[float]:
    """The measured device-side step time cost_calibrate inverts against:
    the loop-stalling dispatch + device_wait spans per optimizer step
    (the same mapping attribution_rows' device_step row uses). None when
    the summary carries neither span."""
    spans = (trace_summary or {}).get("spans", {})
    vals = [
        spans.get(n, {}).get("ms_per_step")
        for n in ("dispatch", "device_wait")
    ]
    vals = [float(v) for v in vals if isinstance(v, (int, float))]
    if not vals:
        return None
    return sum(vals)


def _anchor_unit_ms(name: str, value: float) -> float:
    """An anchor's per-count cost in ms (the sec-per-row anchors convert)."""
    return value * (1e3 if name.endswith("_sec_per_row") else 1.0)


def cost_calibrate(
    est: CostEstimate,
    measured_ms: Optional[float],
    anchors: Optional[Dict[str, float]] = None,
    drift_factor: float = DRIFT_FACTOR,
    min_share: float = CALIBRATE_MIN_SHARE,
) -> Dict:
    """Per-anchor drift verdict (ok | drift | stale) for one run.

    `est` is the model's prediction at the run's realized shape (its term
    counts are the inversion's denominators); `measured_ms` is the run's
    measured device step (measured_device_ms over its trace summary, or a
    banked record's value). `anchors` overrides the module constants —
    how tests inject a perturbed anchor and pin the counterfactual flip.

    Verdicts:
      stale — no measurement, zero count for the term, or the term's
              predicted share of the measurement is below `min_share`
              (not enough signal to judge at this shape)
      ok    — implied/hand within [1/drift_factor, drift_factor]
      drift — outside; `attribution_trusted` goes False and
              apply_calibration refuses the affected attribution rows
    """
    hand = {
        name: anchors[name] if anchors and name in anchors else globals()[const]
        for name, (const, _, _) in ANCHORS.items()
    }
    # the predicted total REBUILT on the `hand` anchors: est's own term
    # fields embed the module constants, and an overridden (perturbed)
    # anchor must price its term consistently everywhere or the inversion
    # leaks the true value back in (the counterfactual tests pin this)
    terms = {
        name: float(getattr(est, count_field))
        * _anchor_unit_ms(name, hand[name])
        for name, (_c, count_field, _t) in ANCHORS.items()
    }
    base_ms = (
        est.step_ms + est.dispatch_ms
        - est.scatter_ms - est.program_gap_ms - est.dma_ms
    )
    total_pred = base_ms + sum(terms.values())
    rows = []
    worst = "ok"
    for name, (_const, count_field, _term_field) in ANCHORS.items():
        count = float(getattr(est, count_field))
        unit = _anchor_unit_ms(name, hand[name])
        term_pred = terms[name]
        row: Dict = {
            "anchor": name,
            "hand_value": hand[name],
            "count": count,
            "predicted_term_ms": round(term_pred, 4),
        }
        if measured_ms is None or count <= 0:
            row["verdict"] = "stale"
            row["why"] = (
                "no measured device time" if measured_ms is None
                else "term inactive at this shape (count 0)"
            )
        else:
            share = term_pred / max(measured_ms, 1e-9)
            row["share_of_measured"] = round(share, 4)
            if share < min_share:
                row["verdict"] = "stale"
                row["why"] = (
                    f"term is {share:.2%} of the measured step "
                    f"(< {min_share:.0%}): no signal at this shape"
                )
            else:
                other = total_pred - term_pred
                implied_ms = measured_ms - other
                implied_unit = implied_ms / count
                implied_value = implied_unit / (
                    1e3 if name.endswith("_sec_per_row") else 1.0
                )
                ratio = implied_unit / unit if unit > 0 else float("inf")
                row["implied_value"] = implied_value
                row["ratio"] = round(ratio, 4)
                row["verdict"] = (
                    "drift"
                    if ratio > drift_factor or ratio < 1.0 / drift_factor
                    else "ok"
                )
        if row["verdict"] == "drift":
            worst = "drift"
        elif row["verdict"] == "stale" and worst == "ok":
            worst = "stale"
        rows.append(row)
    return {
        "anchors": rows,
        "measured_device_ms": (
            round(measured_ms, 4) if measured_ms is not None else None
        ),
        "predicted_device_ms": round(total_pred, 4),
        "drift_factor": drift_factor,
        "min_share": min_share,
        "verdict": worst,
        # the refusal gate: attributions built on a drifted anchor are
        # silently wrong — apply_calibration marks them refused
        "attribution_trusted": all(r["verdict"] != "drift" for r in rows),
    }


#: attribution-row term -> the anchor that prices it
_TERM_ANCHOR = {
    "table_scatter": "scatter_sec_per_row",
    "program_gap": "program_gap_ms",
    "kernel_dma": "dma_sec_per_row",
}


def apply_calibration(rows: list, calib: Dict) -> list:
    """Stamp attribution_rows with their anchors' calibration verdicts —
    and REFUSE the prediction of any row whose anchor drifted (the
    predicted number moves to `predicted_ms_uncalibrated`, the row says
    why). A silently-wrong attribution is worse than none: the r7/r12
    counterfactual-flip discipline, now fed by device truth."""
    verdicts = {a["anchor"]: a["verdict"] for a in calib.get("anchors", ())}
    out = []
    for row in rows:
        row = dict(row)
        anchor = _TERM_ANCHOR.get(row.get("term"))
        if anchor is not None and anchor in verdicts:
            row["calibration"] = verdicts[anchor]
            if verdicts[anchor] == "drift":
                row["predicted_ms_uncalibrated"] = row.get("predicted_ms")
                row["predicted_ms"] = None
                row["refused"] = (
                    f"anchor {anchor} drifted (cost_calibrate): this "
                    "attribution would be silently wrong — re-measure the "
                    "anchor before trusting the term"
                )
        out.append(row)
    return out


def attribution_rows(est: CostEstimate, trace_summary: Dict) -> list:
    """Measured-vs-predicted cost rows from a run's trace summary.

    `trace_summary` is obs/tracediff.summarize over the flight ring (the
    per-span ms/step bench.py banks). The mapping onto the model's terms:
    the device-side prediction (step_ms + the amortized dispatch_ms) is
    measured by the loop-stalling dispatch + device_wait spans; batcher_wait
    is input wait the model deliberately prices at zero (the planner assumes
    the input pipeline keeps up — a large measured value there is an
    input-bound verdict, not model error, which is why it gets its own row
    instead of polluting the device term). Banked by bench.py as
    `cost_attribution` so the model's per-term error stays observable from
    the record alone, round over round.
    """
    spans = (trace_summary or {}).get("spans", {})

    def per_step(name: str) -> float:
        return float(spans.get(name, {}).get("ms_per_step") or 0.0)

    rows = [
        {
            "term": "device_step",
            "spans": ["dispatch", "device_wait"],
            "predicted_ms": round(est.step_ms + est.dispatch_ms, 4),
            "measured_ms": round(
                per_step("dispatch") + per_step("device_wait"), 4
            ),
        },
        {
            "term": "input_wait",
            "spans": ["batcher_wait"],
            "predicted_ms": 0.0,
            "measured_ms": round(per_step("batcher_wait"), 4),
        },
    ]
    for r in rows:
        r["delta_ms"] = round(r["measured_ms"] - r["predicted_ms"], 4)
    # Per-layout scatter sub-term (SCATTER_SEC_PER_ROW): a component of
    # device_step, not an extra span — there is no host-visible scatter
    # span to measure it against directly, so it is measured DIFFERENTIALLY
    # via a split-vs-unified tracediff A/B (the delta between the two runs'
    # device_step rows isolates it; PERF.md worked example). Banked so the
    # record names how much of its predicted step the layout is carrying.
    rows.append({
        "term": "table_scatter",
        "spans": [],
        "predicted_ms": round(est.scatter_ms, 4),
        "scatter_rows": est.scatter_rows,
        "measured_ms": None,
        "delta_ms": None,
        "note": "sub-term of device_step; measure via split-vs-unified "
                "tracediff A/B",
    })
    # Fused-step sub-terms (r12): the program-gap tail the fused backend
    # collapses and the in-kernel DMA rows it pays instead. Like
    # table_scatter these have no host-visible span of their own — they
    # are measured DIFFERENTIALLY via a fused-vs-xla tracediff A/B (the
    # dispatch-span delta between the two runs isolates the gap term).
    rows.append({
        "term": "program_gap",
        "spans": [],
        "predicted_ms": round(est.program_gap_ms, 4),
        "programs": est.programs,
        "measured_ms": None,
        "delta_ms": None,
        "note": "sub-term of device_step; measure via fused-vs-xla "
                "tracediff A/B (the dispatch-span delta)",
    })
    rows.append({
        "term": "kernel_dma",
        "spans": [],
        "predicted_ms": round(est.dma_ms, 4),
        "dma_rows": est.dma_rows,
        "measured_ms": None,
        "delta_ms": None,
        "note": "sub-term of device_step; nonzero only for "
                "band_backend='pallas_fused'",
    })
    return rows
