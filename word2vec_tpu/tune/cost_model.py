"""Analytic step-cost model: the planner's pruning stage.

Ranks candidate plans WITHOUT running anything: per-step FLOPs and HBM
traffic come from the shared counters in utils/profiling.py (the same
numbers bench.py reports as predicted_cost), and a two-term roofline turns
them into milliseconds:

    step_ms     = max(flops / peak_flops, bytes / hbm_bw) + copy_ms
    dispatch_ms = per-dispatch overhead / chunk_cap        (amortized share)
    total_ms    = step_ms + dispatch_ms

The layout-copy term is the one place the model leans on a measurement
instead of first principles: the r2 on-chip trace put the overlap-add's
layout copies at 2.14 ms = 27% of the 7.97 ms step at the flagship shape
(PERF.md), ~7x what their raw bytes would cost at streaming HBM bandwidth —
layout transposes are strided, not streaming. LAYOUT_COPY_INEFFICIENCY is
calibrated so the model reproduces that anchor exactly at the traced shape
(pinned by tests/test_tune.py); every other shape scales analytically from
it. The term is attributed PER BACKEND by utils/profiling.step_hbm_bytes:
it prices only the XLA overlap-add chain — the 'pallas' backend keeps the
whole plane in VMEM, and 'pallas_oa' replaces exactly that chain with the
VMEM overlap-add kernel (ops/pallas_overlap.py), paying one sequential
slab-plane read + token-plane write instead. That contrast is what lets
the planner rank pallas_oa above xla precisely when the copy term
dominates (tests/test_tune.py ordering tests).

The model's job is ORDERING (which few candidates deserve a timed probe),
not absolute truth — probes decide the winner. Both numbers are banked side
by side in bench.py's output (predicted_cost vs measured_cost) precisely so
the model's error stays observable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ..utils.profiling import step_flops, step_geometry, step_hbm_bytes

# device_kind prefix -> (peak bf16 FLOP/s, HBM bytes/s, per-dispatch
# overhead ms). TPU peaks mirror bench.PEAK_FLOPS_BF16; bandwidths are the
# public HBM specs; dispatch overhead is the measured per-dispatch cost of
# the remote tunnel (~40 ms/dispatch round-1 async loop, PERF.md) for TPU
# and a sub-ms local jit dispatch for CPU.
DEVICE_SPECS: Dict[str, Tuple[float, float, float]] = {
    "TPU v4": (275e12, 1.2e12, 40.0),
    "TPU v5 lite": (197e12, 0.82e12, 40.0),
    "TPU v5e": (197e12, 0.82e12, 40.0),
    "TPU v5p": (459e12, 2.77e12, 40.0),
    "TPU v5": (459e12, 2.77e12, 40.0),
    "TPU v6 lite": (918e12, 1.64e12, 40.0),
    "TPU v6e": (918e12, 1.64e12, 40.0),
}
# 1-core host fallback: measured ~75k words/sec at the flagship CPU shape
# implies ~15 GFLOP/s effective; bandwidth is not the CPU binding term.
CPU_SPEC: Tuple[float, float, float] = (15e9, 2e10, 0.3)

# Calibration anchor (r2 trace, PERF.md): 2.14 ms of layout copies at
# B=256, L=192, d=300, W=5 on TPU v5 lite, whose raw copy bytes
# (3 x [B, C, S+2W, d] f32 = 236 MB) would stream in ~0.29 ms at 0.82 TB/s.
LAYOUT_COPY_INEFFICIENCY = 7.4

# Second calibration anchor (same r2 trace): the sorted table scatters run
# at ~21 ns/ROW regardless of row width — row machinery, not bytes ("Why
# not a Pallas scatter kernel", PERF.md: 2.08 ms for the two 49,152-row
# table scatters + 0.41 ms for the 16,384 negative rows ≈ 21 ns/row). This
# is the term the table LAYOUT moves (utils/profiling.step_hbm_bytes
# scatter_rows): the unified [V, 2, d] slab halves the token-id scatter
# count, predicting ~1.0 ms off the ~8 ms flagship step — which is exactly
# what lets the planner arbitrate split-vs-unified per device
# (tests/test_tune.py counterfactual-flip pin).
SCATTER_SEC_PER_ROW = 21e-9

# --- fused-step terms (r12 lever, band_backend='pallas_fused') ---
# The step's op chain executes as `programs` separately scheduled device
# programs (utils/profiling.step_hbm_bytes "programs": ~9 for the XLA
# band chain — gathers, four band contractions, the overlap-add, two table
# scatters — vs 3 for the fused step). Each boundary costs a scheduling
# gap the byte roofline cannot see; the r2 trace's step decomposition
# leaves ~1 ms of the 7.97 ms flagship step unattributed to bytes, flops
# or scatter rows, which at the 9-program chain calibrates the gap to
# ~0.12 ms/program. This is the dispatch-tail term the fused step deletes
# (tracediff attributed the kp16 win 100% to dispatch — the motivating
# evidence that the tail, not the bytes, now binds).
PROGRAM_GAP_MS = 0.12
# The fused kernels pay their gathers/scatter as back-to-back in-kernel
# row DMAs (step_hbm_bytes "dma_rows") instead of XLA scatter machinery.
# Priced at a third of SCATTER_SEC_PER_ROW: a descriptor-driven DMA skips
# the scatter's bounds/update machinery and overlaps with compute. The
# fused step's predicted win hinges on this staying well under the 21 ns
# anchor — the r12 counterfactual-flip test pins exactly that sensitivity
# (price DMAs AT the scatter anchor x3 and the fused step must stop
# outranking pallas_oa), and the tpu_queue8.sh A/B banks the ground truth.
DMA_SEC_PER_ROW = 7e-9


def device_spec(
    device_kind: str, platform: str
) -> Tuple[float, float, float]:
    for prefix, spec in DEVICE_SPECS.items():
        if device_kind.startswith(prefix):
            return spec
    if platform == "tpu":
        return DEVICE_SPECS["TPU v5 lite"]  # conservative unknown-TPU guess
    return CPU_SPEC


@dataclasses.dataclass(frozen=True)
class CostEstimate:
    flops: float
    hbm_bytes: float
    copy_bytes: float
    scatter_rows: float  # rows fed to table scatter-adds (a count)
    scatter_ms: float    # scatter_rows * SCATTER_SEC_PER_ROW (per-layout)
    dma_rows: float      # in-kernel per-row DMAs (pallas_fused only)
    dma_ms: float        # dma_rows * DMA_SEC_PER_ROW
    programs: float      # separately scheduled device programs per step
    program_gap_ms: float  # programs * PROGRAM_GAP_MS (the dispatch tail)
    step_ms: float       # compute + traffic + copies + row terms, per step
    dispatch_ms: float   # per-step share of dispatch overhead
    total_ms: float

    def to_json(self) -> Dict:
        return {
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "copy_bytes": self.copy_bytes,
            "scatter_rows": self.scatter_rows,
            "scatter_ms": round(self.scatter_ms, 4),
            "dma_rows": self.dma_rows,
            "dma_ms": round(self.dma_ms, 4),
            "programs": self.programs,
            "program_gap_ms": round(self.program_gap_ms, 4),
            "step_ms": round(self.step_ms, 4),
            "dispatch_ms": round(self.dispatch_ms, 4),
            "total_ms": round(self.total_ms, 4),
        }


def layout_copy_ms(copy_bytes: float, hbm_bw: float) -> float:
    return 1e3 * copy_bytes * LAYOUT_COPY_INEFFICIENCY / hbm_bw


def table_scatter_ms(scatter_rows: float) -> float:
    """The per-layout scatter term: row machinery the byte roofline cannot
    see (~21 ns/row regardless of width — SCATTER_SEC_PER_ROW anchor)."""
    return 1e3 * scatter_rows * SCATTER_SEC_PER_ROW


def kernel_dma_ms(dma_rows: float) -> float:
    """The fused step's in-kernel per-row DMA term (DMA_SEC_PER_ROW)."""
    return 1e3 * dma_rows * DMA_SEC_PER_ROW


def program_gap_ms(programs: float) -> float:
    """Inter-program scheduling gaps in the step's device op chain — the
    dispatch tail the fused step collapses (PROGRAM_GAP_MS each)."""
    return programs * PROGRAM_GAP_MS


def predict(
    config,
    vocab_size: int,
    device_kind: str = "",
    platform: str = "cpu",
    chunk_cap: Optional[int] = None,
) -> CostEstimate:
    """CostEstimate for one optimizer step of `config` on the named device.

    chunk_cap overrides the config's scan megastep cap (the planner sweeps
    it without rebuilding configs).
    """
    peak, bw, overhead = device_spec(device_kind, platform)
    flops = step_flops(config, vocab_size)
    traffic = step_hbm_bytes(config, vocab_size)
    streamed = traffic["total"] - traffic["layout_copies"]
    scatter_rows = traffic.get("scatter_rows", 0.0)
    scatter_ms = table_scatter_ms(scatter_rows)
    dma_rows = traffic.get("dma_rows", 0.0)
    dma_ms = kernel_dma_ms(dma_rows)
    programs = traffic.get("programs", 0.0)
    gap_ms = program_gap_ms(programs)
    step_ms = (
        1e3 * max(flops / peak, streamed / bw)
        + layout_copy_ms(traffic["layout_copies"], bw)
        + scatter_ms
        + dma_ms
        + gap_ms
    )
    cap = chunk_cap if chunk_cap is not None else config.chunk_cap
    dispatch_ms = overhead / max(1, cap)
    return CostEstimate(
        flops=flops,
        hbm_bytes=traffic["total"],
        copy_bytes=traffic["layout_copies"],
        scatter_rows=scatter_rows,
        scatter_ms=scatter_ms,
        dma_rows=dma_rows,
        dma_ms=dma_ms,
        programs=programs,
        program_gap_ms=gap_ms,
        step_ms=step_ms,
        dispatch_ms=dispatch_ms,
        total_ms=step_ms + dispatch_ms,
    )


def predicted_words_per_sec(
    config, vocab_size: int, device_kind: str = "", platform: str = "cpu"
) -> float:
    """The ranking metric: tokens per dispatched step over predicted step
    time. Row-packing fill is a corpus property shared by all candidates, so
    a constant factor drops out of the ordering."""
    est = predict(config, vocab_size, device_kind, platform)
    words_per_step = config.batch_rows * config.max_sentence_len
    return 1e3 * words_per_step / max(est.total_ms, 1e-9)


def geometry(config, vocab_size: int) -> Dict:
    """Re-export of the shared shape resolution (utils/profiling) so planner
    callers need one import."""
    return step_geometry(config, vocab_size)


def attribution_rows(est: CostEstimate, trace_summary: Dict) -> list:
    """Measured-vs-predicted cost rows from a run's trace summary.

    `trace_summary` is obs/tracediff.summarize over the flight ring (the
    per-span ms/step bench.py banks). The mapping onto the model's terms:
    the device-side prediction (step_ms + the amortized dispatch_ms) is
    measured by the loop-stalling dispatch + device_wait spans; batcher_wait
    is input wait the model deliberately prices at zero (the planner assumes
    the input pipeline keeps up — a large measured value there is an
    input-bound verdict, not model error, which is why it gets its own row
    instead of polluting the device term). Banked by bench.py as
    `cost_attribution` so the model's per-term error stays observable from
    the record alone, round over round.
    """
    spans = (trace_summary or {}).get("spans", {})

    def per_step(name: str) -> float:
        return float(spans.get(name, {}).get("ms_per_step") or 0.0)

    rows = [
        {
            "term": "device_step",
            "spans": ["dispatch", "device_wait"],
            "predicted_ms": round(est.step_ms + est.dispatch_ms, 4),
            "measured_ms": round(
                per_step("dispatch") + per_step("device_wait"), 4
            ),
        },
        {
            "term": "input_wait",
            "spans": ["batcher_wait"],
            "predicted_ms": 0.0,
            "measured_ms": round(per_step("batcher_wait"), 4),
        },
    ]
    for r in rows:
        r["delta_ms"] = round(r["measured_ms"] - r["predicted_ms"], 4)
    # Per-layout scatter sub-term (SCATTER_SEC_PER_ROW): a component of
    # device_step, not an extra span — there is no host-visible scatter
    # span to measure it against directly, so it is measured DIFFERENTIALLY
    # via a split-vs-unified tracediff A/B (the delta between the two runs'
    # device_step rows isolates it; PERF.md worked example). Banked so the
    # record names how much of its predicted step the layout is carrying.
    rows.append({
        "term": "table_scatter",
        "spans": [],
        "predicted_ms": round(est.scatter_ms, 4),
        "scatter_rows": est.scatter_rows,
        "measured_ms": None,
        "delta_ms": None,
        "note": "sub-term of device_step; measure via split-vs-unified "
                "tracediff A/B",
    })
    # Fused-step sub-terms (r12): the program-gap tail the fused backend
    # collapses and the in-kernel DMA rows it pays instead. Like
    # table_scatter these have no host-visible span of their own — they
    # are measured DIFFERENTIALLY via a fused-vs-xla tracediff A/B (the
    # dispatch-span delta between the two runs isolates the gap term).
    rows.append({
        "term": "program_gap",
        "spans": [],
        "predicted_ms": round(est.program_gap_ms, 4),
        "programs": est.programs,
        "measured_ms": None,
        "delta_ms": None,
        "note": "sub-term of device_step; measure via fused-vs-xla "
                "tracediff A/B (the dispatch-span delta)",
    })
    rows.append({
        "term": "kernel_dma",
        "spans": [],
        "predicted_ms": round(est.dma_ms, 4),
        "dma_rows": est.dma_rows,
        "measured_ms": None,
        "delta_ms": None,
        "note": "sub-term of device_step; nonzero only for "
                "band_backend='pallas_fused'",
    })
    return rows
