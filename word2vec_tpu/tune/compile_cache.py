"""Warm-restart compile cache: make elastic generation switches stop
paying full recompile.

The elastic remesh blackout (`ELASTIC_DRILL_cpu.json` walls) is dominated
by the next generation recompiling the sharded step/sync programs from
scratch. jax's persistent compilation cache can serve those executables
from disk — but PR 1 root-caused the tier-1 segfaults to exactly that
cache: in a long-lived process, a WARM cache deserializes previously
compiled executables and a later MLIR lowering intermittently dies inside
`mlir.make_ir_context` (tests/conftest.py carries the bisection evidence;
the cache has been off everywhere since).

The fence here is SCOPE, enforced in one place (`enable_warm_cache`):

  * only an exec'd NEXT-GENERATION elastic process (W2V_ELASTIC_GEN > 0)
    may turn the cache on. Such a process is born, compiles one fixed
    program set for one topology, trains, and either finishes or execs
    again — the narrow lifecycle in which the deserialize-then-lower
    interleaving that crashed the long-lived test harness does not recur
    as a suite-wide hazard, and where the win (the remesh blackout) lives.
  * generation 0 — the launch process, every test process, every
    non-elastic run — NEVER gets the cache: `enable_warm_cache` refuses
    (returns None) for gen <= 0. That is the PR 1 regression fence, pinned
    by tests/test_elastic.py.
  * an operator who set JAX_COMPILATION_CACHE_DIR themselves owns the
    decision; we refuse to override it (same contract as conftest).

The cache is keyed per (topology, plan): a generation only ever reads
entries written by a generation of the SAME world/mesh shape and realized
plan, so a shrink that revisits a previously-compiled topology hits, and
plans can never alias across shapes (`topology_key`).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional


def topology_key(world: int, dp: int, tp: int, sp: int, config,
                 plan_key: Optional[str] = None) -> str:
    """One cache subdirectory per (topology, realized plan, jax version):
    the human-readable prefix names the mesh, the hash pins every lever
    that changes the compiled program set."""
    import jax

    parts = [
        f"w{int(world)}", f"dp{int(dp)}", f"tp{int(tp)}", f"sp{int(sp)}",
        config.band_backend, config.table_layout, config.resolved_kernel,
        config.dtype, config.compute_dtype,
        f"b{config.batch_rows}", f"m{config.micro_steps}",
        f"c{config.chunk_steps}", f"L{config.max_sentence_len}",
        f"d{config.word_dim}", f"n{config.negative}",
        f"sn{config.shared_negatives}", config.negative_scope,
        f"sr{int(config.stochastic_rounding)}",
        str(getattr(jax, "__version__", "")),
        plan_key or "",
    ]
    digest = hashlib.sha256("|".join(map(str, parts)).encode()).hexdigest()
    return f"w{int(world)}dp{int(dp)}tp{int(tp)}sp{int(sp)}-{digest[:16]}"


def enable_warm_cache(root: Optional[str], key: str, gen: int,
                      env=os.environ) -> Optional[str]:
    """Point jax's persistent compilation cache at `<root>/<key>` — ONLY
    for an exec'd next-generation elastic process. Returns the enabled
    cache dir, or None when the fence refuses:

      * gen <= 0            — the PR 1 scenario: a long-lived launch/test
                              process must fresh-compile, always
      * no root configured  — the lever is opt-in (--compile-cache)
      * JAX_COMPILATION_CACHE_DIR set — the operator owns the cache
      * the config knob is absent or the dir cannot be created — degrade
        to cold compile, never fail the recovery
    """
    if not root or int(gen) <= 0:
        return None
    if env.get("JAX_COMPILATION_CACHE_DIR"):
        return None
    path = os.path.join(root, key)
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return None
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 — knob absent on this jax: cold compile
        return None
    # CPU-scale programs compile in well under jax's 1 s default write
    # floor; without these the drill's generation switch would never
    # populate the cache it is supposed to warm
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 — best-effort thresholds
            pass
    return path


def disable_cache() -> None:
    """Best-effort reset (tests): point jax back at no persistent cache."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001
        pass
