"""Autotuned execution planner (config.autotune; ISSUE 1 tentpole).

The step-shape space this framework exposes — batch rows, band chunk, scan
megastep length, prefetch depth, negative scope/width, kernel backend — has
until now been searched by humans queuing shell lines at a TPU tunnel
(benchmarks/tpu_queue*.sh). This package turns that search into code:

    cost_model  — analytic HBM-bytes + FLOPs per step (shared counters in
                  utils/profiling.py) -> roofline milliseconds, used to
                  prune the candidate grid without running anything
    planner     — grid -> prune -> short compile-separated timed probes ->
                  winner (resolve_plan, the single entry point)
    cache       — persistent JSON plan cache keyed by (device_kind,
                  backend, kernel, vocab, dim), seeded with the hand-tuned
                  shapes already banked on chip (seed_plans.json)

Consumers: train.Trainer (config.autotune != "off"), cli.py (--autotune),
bench.py (--autotune; banks plan + predicted-vs-measured cost in its JSON).
"""

from .cache import default_cache_path, lookup, plan_key, store
from .cost_model import CostEstimate, predict, predicted_words_per_sec
from .planner import (
    PlanResolution, candidate_grid, config_fingerprint, kernel_route,
    probe_plan, resolve_plan,
)

__all__ = [
    "CostEstimate",
    "PlanResolution",
    "candidate_grid",
    "config_fingerprint",
    "default_cache_path",
    "kernel_route",
    "lookup",
    "plan_key",
    "predict",
    "predicted_words_per_sec",
    "probe_plan",
    "resolve_plan",
    "store",
]
