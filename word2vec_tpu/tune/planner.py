"""The autotuned execution planner: cost-model pruning + timed probes.

Today every platform runs a hand-picked step shape (the benchmarks/ queue
scripts sweep them one shell line at a time, per round, per tunnel window).
This module makes that search code:

  1. GRID     — candidate TunePlans around the configured shape: batch rows,
                band chunk, scan megastep cap, negative-pool width/scope,
                band backend. Candidates that would change training quality
                are excluded up front (hot-row block-token guard; levers
                stay inside their measured quality envelopes — PERF.md).
  2. PRUNE    — rank the grid with the analytic cost model
                (tune/cost_model.py: HBM bytes + FLOPs -> roofline ms) and
                keep the top few plus the configured default.
  3. PROBE    — time the survivors with short, compile-separated probes:
                one warmup dispatch (compile + first-touch, excluded, the
                bench.py protocol), then a few timed dispatches of a short
                scan. The measured step time is combined with the model's
                per-dispatch overhead term so a cheap-to-probe short scan
                still ranks megastep caps correctly.
  4. PERSIST  — the winner goes into the JSON plan cache keyed by
                (device_kind, backend, kernel, vocab, dim); the next run
                starts tuned with zero probe cost (mode="cached").

Probes run the REAL kernels at the REAL shapes on whatever backend is live,
so the whole planner is exercisable on CPU while aiming at the on-chip
>=50x item (ROADMAP). The probe trains on a throwaway copy of the params —
a probed run's training state is never touched.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import TunePlan, Word2VecConfig
from . import cache as plan_cache
from . import cost_model

# fingerprint: everything that invalidates a cached plan but is neither a
# cache-key dimension nor a plan dimension (see cache.py). schema bumps
# force re-probes when the planner's own semantics change.
# Deliberately EXCLUDED: the telemetry fields (health_metrics,
# divergence_budget) — the full health counters add a roughly uniform
# per-step cost that does not reorder step-shape candidates, and keying on
# them would orphan every banked seed plan for an observability overlay.
# Schema 2 moved dtype/stochastic_rounding OUT of the fingerprint (they are
# TunePlan dimensions the grid searches — the bf16+SR-default candidate)
# and table_layout into the KEY (cache.plan_key), not here. Schema 3 moved
# the configured band_backend into the KEY too (a pallas_fused run must
# never inherit a chain-probed plan — cache.py).
FINGERPRINT_FIELDS = (
    "model", "train_method", "negative", "window", "max_sentence_len",
    "compute_dtype", "slab_scatter",
    "fused_tables", "hs_dense_top", "hs_tail_slots", "clip_row_update",
    "scatter_mean", "cbow_mean",
)


def config_fingerprint(config: Word2VecConfig) -> Dict:
    fp = {f: getattr(config, f) for f in FINGERPRINT_FIELDS}
    fp["schema"] = plan_cache.SCHEMA
    return fp


def kernel_route(config: Word2VecConfig) -> str:
    if config.resolved_kernel == "pair":
        return "pair"
    return "band-hs" if config.use_hs else "band-ns"


# ---------------------------------------------------- degeneracy-domain fence
# The measured quality-collapse domain of the shared-negative band kernel
# (benchmarks/BAND_DEGENERACY_r5.md): a tiny closed vocabulary over-trained
# for thousands of occurrences per word. Full collapse at ~860 words /
# 4,600 occ (analogy 0.0 vs pair 0.74); measured degradation up to ~4.4k
# words; onset ~1,000+ occ/word. Realistic corpora (text8: 71k vocab,
# ~240 occ/word) sit 20x outside.
DEGENERACY_VOCAB_MAX = 5000
DEGENERACY_OCC_PER_WORD = 1000


def degeneracy_domain(
    config: Word2VecConfig, vocab_size: int, total_tokens: int
) -> bool:
    """True when (vocab, planned training tokens) sit inside the band
    kernel's measured degeneracy domain — the fence the trainer warning,
    the kernel auto-selection below, and the quality sentinel's alert
    record all share, so the three can never disagree about the domain."""
    return (
        config.use_ns
        and 0 < vocab_size < DEGENERACY_VOCAB_MAX
        and total_tokens * config.iters
        > DEGENERACY_OCC_PER_WORD * vocab_size
    )


def select_kernel(
    config: Word2VecConfig, vocab_size: int, total_tokens: int
) -> Optional[Dict]:
    """Kernel auto-selection (ROADMAP item 5): for kernel='auto' runs whose
    corpus shape sits inside the measured degeneracy domain, choose
    kernel='pair' (per-pair negative draws hold near-reference accuracy on
    the identical stream — BAND_DEGENERACY_r5.md) instead of warning and
    collapsing. Returns the decision record when a change is selected, else
    None. An explicit --kernel band is the override: the trainers only
    consult this for kernel='auto', so a forced band config keeps the fast
    path (and gets the degeneracy warning instead).
    """
    if config.kernel != "auto" or not config.use_ns:
        return None
    # band-only levers are an explicit opt-in to the band machinery (and a
    # pair config would reject them outright — config.__post_init__): the
    # static warning still fires for these, selection stands aside
    if (
        config.fused_tables or config.slab_scatter
        or config.table_layout != "split"
        or config.band_backend != "xla"
        or config.negative_scope != "row"
    ):
        return None
    if not degeneracy_domain(config, vocab_size, total_tokens):
        return None
    occ = total_tokens * config.iters // max(1, vocab_size)
    return {
        "event": "kernel_auto_selection",
        "selected": "pair",
        "instead_of": "band",
        "reason": (
            f"degeneracy domain: {vocab_size}-word vocabulary at ~{occ} "
            f"training occurrences/word (fence: vocab < "
            f"{DEGENERACY_VOCAB_MAX} and occ/word > "
            f"{DEGENERACY_OCC_PER_WORD}; benchmarks/BAND_DEGENERACY_r5.md)"
        ),
        "vocab_size": int(vocab_size),
        "occ_per_word": int(occ),
        "override": "--kernel band forces the band fast path",
    }


@dataclasses.dataclass
class PlanResolution:
    plan: TunePlan
    source: str                 # "cache" | "probe"
    key: str
    predicted: Dict             # CostEstimate.to_json() of the chosen plan
    probes: List[Dict]          # per-candidate records ([] on a cache hit)
    cache_path: Optional[str]

    def to_json(self) -> Dict:
        return {
            "plan": self.plan.to_json(),
            "source": self.source,
            "key": self.key,
            "predicted": self.predicted,
            "probes": self.probes,
        }


def candidate_grid(
    config: Word2VecConfig,
    vocab_size: int,
    constraints: Optional[Dict] = None,
) -> List[TunePlan]:
    """Valid TunePlans around the configured shape.

    Quality fences: the optimizer block may not carry more tokens per vocab
    word than max(8x vocab, the configured block) — tuning must never walk
    a run INTO the hot-row divergence domain the Trainer warns about; KP
    stays >= 16 (accuracy measured holding all the way to KP=8 on the
    parity harness, PERF.md — 16 keeps margin); 'batch' scope is the
    replicated quality-positive lever; the table-layout candidates are
    trajectory-IDENTICAL (tests/test_unified.py) and the bf16+SR candidate
    is margin-neutral at parity budget and at scale
    (PARITY_MATRIX_r3 / QUALITY_FULL_r3). A candidate the config rules
    reject (pallas+hs, batch-scope+pair, unified+pallas, ...) is dropped by
    construction via apply_plan's validation.
    """
    c = constraints or {}
    base = config.current_plan()
    L = config.max_sentence_len
    block = max(1, config.batch_rows // config.micro_steps) * L
    max_block = max(8 * max(1, vocab_size), block)

    rows = sorted({
        base.batch_rows,
        max(config.micro_steps, base.batch_rows // 2),
        base.batch_rows * 2,
    })
    caps = sorted({base.chunk_cap, 32, 96})
    # Band chunk S: the auto rule (ops/banded.resolve_chunk) fills a 128-lane
    # slab — an MXU tiling choice, not a plane-size optimum. Smaller explicit
    # chunks shrink the [B, C, S, S+2W] logit plane (S = L/2 cuts it ~33% at
    # the flagship shape) at the cost of more, narrower matmuls — which side
    # wins is exactly what probes are for.
    W2 = 2 * config.window
    chunks = sorted({
        base.band_chunk,
        max(W2, config.max_sentence_len // 2),
        max(W2, config.max_sentence_len // 3),
    })
    is_band_ns = kernel_route(config) == "band-ns"
    # KP width candidates (ROADMAP lever c): 64 -> 32 -> 16, each ~halving
    # the negative-side einsum width; the accuracy fence measured holding
    # down to KP=8 (Spearman 0.866 / purity 1.0 at KP in {8, 16, 32},
    # benchmarks/parity.py --shared-negatives)
    kps = sorted({base.shared_negatives, 16, 32, 64}) if is_band_ns else [
        base.shared_negatives
    ]
    scopes = ["row", "batch"] if is_band_ns else [base.negative_scope]
    # Table layout (split vs unified [V, 2, d] slab): trajectory-identical,
    # arbitrated by the cost model's per-layout scatter term + probes.
    layouts = (
        sorted({base.table_layout, "split", "unified"})
        if is_band_ns else [base.table_layout]
    )
    # Storage dtype ± SR: the bf16+SR-default lever rides as a sibling
    # candidate (margin-neutral, PARITY_MATRIX_r3/QUALITY_FULL_r3); the
    # configured combo is always present so the incumbent can win.
    dtypes = [(base.table_dtype, base.stochastic_rounding)]
    if is_band_ns and ("bfloat16", True) not in dtypes:
        dtypes.append(("bfloat16", True))
    backends = [base.band_backend]
    if (
        is_band_ns
        and c.get("allow_pallas", True)
        and c.get("platform") == "tpu"
    ):
        # the per-chunk fused kernel cannot gather fused [V, 2, d] tables
        # (chunk-restacked OR unified-layout); the overlap-add kernel
        # composes with both (token-order output shares the center side's
        # sorted index set — ops/pallas_overlap.py); the fully-fused step
        # REQUIRES the unified slab (ops/pallas_step.py). Invalid combos
        # (unified x pallas, split x pallas_fused, batch-scope x
        # pallas_fused, ...) are dropped by apply_plan's validation.
        if not config.fused_tables and "pallas" not in backends:
            backends.append("pallas")
        if "pallas_oa" not in backends:
            backends.append("pallas_oa")
        if "pallas_fused" not in backends:
            backends.append("pallas_fused")

    combos = [
        (b, cap, kp, scope, S, be, lay, dt)
        for b in rows
        for cap in caps
        for kp in kps
        for scope in scopes
        for S in chunks
        for be in backends
        for lay in layouts
        for dt in dtypes
    ]
    out: List[TunePlan] = []
    seen = set()
    for b, cap, kp, scope, S, be, lay, (dt, sr) in combos:
        # batch scope correlates the whole batch on one pool; keep it at
        # the promoted kp=256 width
        eff_kp = max(kp, 256) if scope == "batch" else kp
        plan = TunePlan(
            batch_rows=b,
            band_chunk=S,
            chunk_cap=cap,
            prefetch_depth=base.prefetch_depth,
            shared_negatives=eff_kp,
            negative_scope=scope,
            band_backend=be,
            table_layout=lay,
            table_dtype=dt,
            stochastic_rounding=sr,
        )
        if plan in seen:
            continue
        seen.add(plan)
        try:
            applied = config.apply_plan(plan)
        except ValueError:
            continue
        cand_block = (applied.batch_rows // applied.micro_steps) * L
        if cand_block > max_block:
            continue
        if be in ("pallas", "pallas_oa", "pallas_fused"):
            # all three kernels require the chunked band representation; a
            # candidate whose rows resolve dense would only burn a probe
            # on a guaranteed ValueError
            from ..ops.banded import resolve_chunk

            if resolve_chunk(L, applied.window, applied.band_chunk) == 0:
                continue
        out.append(plan)
    return out


def _synthetic_probe_corpus(vocab, n_tokens: int, max_len: int):
    from ..data.batcher import PackedCorpus
    from ..utils.synthetic import zipf_corpus_ids

    ids = zipf_corpus_ids(vocab, n_tokens, seed=11)
    return PackedCorpus.pack(ids, max_len)


def _probe_chunks(corpus, cfg: Word2VecConfig, s_probe: int, n: int):
    """n [s_probe, B, L] token chunks from the corpus front (no shuffle —
    probes time compute, they don't train)."""
    from ..data.batcher import BatchIterator, chunk_batches

    batcher = BatchIterator(
        corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1, shuffle=False
    )
    chunks: List[Tuple[np.ndarray, List[int]]] = []
    while len(chunks) < n:
        for tok, words in chunk_batches(batcher.epoch(0), s_probe):
            chunks.append((tok, words))
            if len(chunks) == n:
                break
        if not chunks:  # empty corpus cannot happen (PackedCorpus raises)
            break
    return chunks


def probe_plan(
    config: Word2VecConfig,
    plan: TunePlan,
    vocab,
    corpus,
    probe_steps: int = 2,
    probe_dispatches: int = 2,
) -> Dict:
    """Time one candidate: words/sec and ms per optimizer step, compile
    excluded (one warmup dispatch à la bench.py, then timed dispatches of a
    short scan). Raises nothing — a candidate that fails to build/compile
    returns a record with an "error" field and infinite cost."""
    import jax
    import jax.numpy as jnp

    from ..models.params import init_params
    from ..ops.tables import DeviceTables
    from ..ops.train_step import jit_chunk_runner

    rec: Dict = {"plan": plan.to_json()}
    try:
        cfg = config.apply_plan(plan)
        s = max(1, min(probe_steps, cfg.chunk_cap))
        chunks = _probe_chunks(corpus, cfg, s, probe_dispatches + 1)
        tables = DeviceTables.build(vocab, cfg)
        params = init_params(
            cfg, len(vocab), jax.random.key(0, impl=cfg.jax_prng_impl)
        )
        chunk_fn = jit_chunk_runner(cfg, tables)
        base_key = jax.random.key(13, impl=cfg.jax_prng_impl)
        alphas = jnp.full((s,), cfg.init_alpha, jnp.float32)

        warm = jnp.asarray(chunks[0][0])
        params, _ = chunk_fn(params, warm, base_key, 0, alphas)
        jax.block_until_ready(params)

        words = 0
        t0 = time.perf_counter()
        for i in range(probe_dispatches):
            tok, wl = chunks[(i + 1) % len(chunks)]
            params, _ = chunk_fn(
                params, jnp.asarray(tok), base_key, (i + 1) * s, alphas
            )
            words += sum(wl)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        del params, tables, chunk_fn, chunks, warm
        step_ms = 1e3 * dt / (probe_dispatches * s)
        rec["measured_step_ms"] = round(step_ms, 4)
        rec["probe_words_per_sec"] = round(words / max(dt, 1e-9), 1)
        # short scans under-represent dispatch amortization; add the model's
        # per-dispatch overhead share at the candidate's REAL megastep cap
        dev = jax.devices()[0]
        _, _, overhead = cost_model.device_spec(
            dev.device_kind, dev.platform
        )
        total_ms = step_ms + overhead / max(1, plan.chunk_cap)
        wps = 1e3 * words / max(probe_dispatches * s, 1) / max(
            total_ms, 1e-9
        )
        rec["score_words_per_sec"] = round(wps, 1)
    except Exception as e:  # noqa: BLE001 — a candidate must not kill the run
        rec["error"] = f"{type(e).__name__}: {e}"
        rec["score_words_per_sec"] = 0.0
    return rec


def resolve_plan(
    config: Word2VecConfig,
    vocab,
    corpus=None,
    mode: Optional[str] = None,
    cache_path: Optional[str] = None,
    constraints: Optional[Dict] = None,
    max_probes: int = 4,
    probe_steps: int = 2,
    probe_dispatches: int = 2,
    log_fn: Optional[Callable[[Dict], None]] = None,
) -> PlanResolution:
    """The planner entry point (Trainer, cli.py and bench.py all call this).

    mode "cached": cache hit -> zero probe cost; miss -> probe, then cache.
    mode "probe":  always search (and refresh the cache with the winner).
    """
    import jax

    mode = mode or config.autotune
    if mode == "off":
        raise ValueError("resolve_plan called with autotune='off'")
    dev = jax.devices()[0]
    platform = dev.platform
    constraints = dict(constraints or {})
    constraints.setdefault("platform", platform)
    key = plan_cache.plan_key(
        dev.device_kind, platform, kernel_route(config), len(vocab),
        config.word_dim,
        table_layout=config.table_layout,
        shared_negatives=config.shared_negatives,
        band_backend=config.band_backend,
    )
    if config.corpus_mode == "streaming":
        # corpus_mode is a plan dimension: the streaming data plane's host
        # is also reading/tokenizing shards, so prefetch depth and chunk
        # shapes trade differently — streaming runs get their own cached
        # plans. Appended (not a new positional key part) so every banked
        # resident-plan key stays valid.
        key += "+stream"
    fp = config_fingerprint(config)

    if mode == "cached":
        entry = plan_cache.lookup(key, fp, cache_path)
        if entry is not None:
            res = PlanResolution(
                plan=TunePlan.from_json(entry["plan"]),
                source="cache",
                key=key,
                predicted=entry.get("predicted", {}),
                probes=[],
                cache_path=cache_path or plan_cache.default_cache_path(),
            )
            if log_fn:
                log_fn({"event": "autotune", **res.to_json()})
            return res
        # miss: fall through to a probe (then persist, so the NEXT cached
        # run is free)

    grid = candidate_grid(config, len(vocab), constraints)
    base = config.current_plan()
    if base not in grid:
        grid.append(base)

    def predicted_wps(plan: TunePlan) -> float:
        cfg = config.apply_plan(plan)
        return cost_model.predicted_words_per_sec(
            cfg, len(vocab), dev.device_kind, platform
        )

    ranked = sorted(grid, key=predicted_wps, reverse=True)
    survivors = ranked[: max(1, max_probes)]
    if base not in survivors:
        survivors[-1] = base  # the incumbent always gets probed

    if corpus is None:
        need = max(p.batch_rows for p in survivors) * probe_steps * (
            probe_dispatches + 1
        )
        corpus = _synthetic_probe_corpus(
            vocab, need * config.max_sentence_len, config.max_sentence_len
        )

    probes = []
    for plan in survivors:
        rec = probe_plan(
            config, plan, vocab, corpus,
            probe_steps=probe_steps, probe_dispatches=probe_dispatches,
        )
        rec["predicted_total_ms"] = cost_model.predict(
            config.apply_plan(plan), len(vocab), dev.device_kind, platform
        ).to_json()["total_ms"]
        probes.append(rec)
        if log_fn:
            log_fn({"event": "autotune_probe", **rec})

    # Leave no probe residue in the process that is about to train: each
    # candidate compiled its own executables and allocated its own tables,
    # and that residue measurably slows the subsequent run (~10% on the CPU
    # bench's measured epoch). Dropping jit caches + cycles returns the
    # process to a fresh-start allocator state; the caller's own programs
    # have not been built yet (the plan decides their shapes).
    import gc

    gc.collect()
    try:
        jax.clear_caches()
    except Exception:  # noqa: BLE001 — older jax: cache clearing is best-effort
        pass

    best = max(probes, key=lambda r: r.get("score_words_per_sec", 0.0))
    if "error" in best:
        # every survivor failed: keep the configured shape, report why
        best_plan = base
    else:
        best_plan = TunePlan.from_json(best["plan"])
    predicted = cost_model.predict(
        config.apply_plan(best_plan), len(vocab), dev.device_kind, platform
    ).to_json()

    stored_path = None
    try:
        stored_path = plan_cache.store(
            key,
            {
                "plan": best_plan.to_json(),
                "fingerprint": fp,
                "predicted": predicted,
                "measured_words_per_sec": best.get("probe_words_per_sec"),
                "device_kind": dev.device_kind,
                "platform": platform,
            },
            cache_path,
        )
    except OSError:
        pass  # read-only filesystem: the plan still applies, it just won't persist

    res = PlanResolution(
        plan=best_plan,
        source="probe",
        key=key,
        predicted=predicted,
        probes=probes,
        cache_path=stored_path,
    )
    if log_fn:
        log_fn({"event": "autotune", **res.to_json()})
    return res
