"""The continuous-training driver: segments in, trained tables out.

`StreamRun` composes the pieces the platform already has into the data
plane ROADMAP item 3 asked for:

  * SEGMENTS — the source (stream/source.py) is consumed one bounded
    segment at a time; each segment is packed (data/batcher.PackedCorpus)
    and trained through the ordinary Trainer/ShardedTrainer epoch loop, so
    chunked dispatch, placed_prefetch copy overlap, the watchdog, the
    signal plane and the quality probe all apply unchanged. The NEXT
    segment's read/count runs in a prefetch producer thread (the same
    bounded-queue machinery as the batch pipeline, producer-death contract
    included), so shard IO overlaps device compute at segment granularity
    too. The HBM-resident corpus path is off by construction
    (config.corpus_mode validation): segments replace each other.

  * CURSOR — `self.cursor` always names the start of the segment being
    trained plus the run-global counters; every checkpoint written during
    a segment carries it (io/checkpoint.save_checkpoint(stream=...)), so
    SIGTERM at any step resumes by re-reading the same segment from the
    same start and re-entering it mid-epoch (train._resume_skip) —
    byte-for-byte on the uninterrupted trajectory (tests/test_stream.py).

  * GROWTH — at a segment boundary, words the consumed segment saw that
    are not yet in the vocabulary are admitted into reserved table rows
    (config.vocab_reserve; deterministic order: count desc, ties
    lexicographic), the frequency-derived device tables are rebuilt, and
    the vocab generation advances. Existing rows — ids, words, counts, and
    the embedding table rows themselves — are untouched, which is exactly
    what makes a grown vocabulary pass the compatible-superset resume
    guard (data/vocab.Vocab.content_hash(limit=...)). A growth boundary
    sits between two train() calls, i.e. at a sync boundary — the same
    place PR 10's rendezvous parks elastic rejoiners. Segment encoding
    happens AFTER the boundary growth (the producer thread reads and
    counts raw tokens only), so the vocabulary that encodes segment s is
    always "every admission from segments < s" — the property the
    mid-segment resume replay depends on.

  * SWAP — at boundaries, the live input table is exported (one device
    fetch of the logical plane) and atomically swapped into an attached
    serve.QueryEngine — gated by the same planted golds the QualityProbe
    scores: a table scoring under `swap_floor` is REFUSED and the engine
    keeps serving the previous one. Zero requests drop either way
    (QueryEngine.swap_table flips references between batches).

Multi-process caveat: vocab growth is per-process deterministic over the
process's OWN stream; a multi-host fleet where shards differ per rank
would grow divergent vocabularies, so the driver refuses reserve > 0 when
process_count > 1 (streaming itself, with a fixed vocab, shards fine).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..data.batcher import PAD, PackedCorpus, prefetch
from ..train import TrainReport, TrainState
from .source import RawSegment, StreamCursor

#: config.segment_tokens == 0 resolves here
DEFAULT_SEGMENT_TOKENS = 4_000_000


def encode_segment(raw: RawSegment, vocab, fmt: str = "text8") -> np.ndarray:
    """Segment -> flat id stream with the given vocabulary. text8
    semantics: one unbroken stream (PackedCorpus cuts rows at
    max_sentence_len); lines: -1 separators between sentences. OOV drops
    silently, exactly like the resident encode (Word2Vec.cpp:223)."""
    if raw.flat is not None:
        return raw.flat
    lines = fmt == "lines"
    pieces = []
    sep = np.asarray([PAD], dtype=np.int32)
    for s in raw.sentences or []:
        ids = vocab.encode(s)
        if len(ids) == 0:
            continue
        if lines and pieces:
            pieces.append(sep)
        pieces.append(ids)
    if not pieces:
        return np.empty(0, dtype=np.int32)
    return np.concatenate(pieces)


def admission_order(
    counts: Dict[str, int], vocab, min_count: int, cap: int
) -> List[Tuple[str, int]]:
    """The deterministic admission list: candidate words (count >=
    min_count within the consumed segment, not already in the vocabulary)
    ordered by count desc, ties lexicographic — the same comparator the
    initial vocabulary sort uses (data/vocab.Vocab.from_counter) — capped
    to the remaining reserve."""
    eligible = [
        (w, int(c)) for w, c in counts.items()
        if c >= min_count and w not in vocab
    ]
    eligible.sort(key=lambda wc: (-wc[1], wc[0]))
    return eligible[: max(0, int(cap))]


def table_capacity(params: Dict) -> int:
    """Total embedding rows (live vocab + reserved), from the params
    themselves — the one place capacity survives growth and resume."""
    from ..models.params import logical_table

    return int(logical_table(params, "emb_in").shape[0])


def gate_table(
    W: np.ndarray, vocab, probe_set, floor: float
) -> Tuple[bool, Dict]:
    """Score a swap candidate through the SAME planted golds the
    QualityProbe uses (obs/quality.score_table via the serve query
    kernel). The gate watches the planted analogy accuracy when the probe
    set carries analogies, else planted Spearman; with no golds at all the
    swap is ungated (gate='none') — refusing on missing evidence would
    make swaps impossible on unlabelled corpora."""
    from ..obs.quality import score_table

    rec, _ = score_table(W, vocab, probe_set)
    metric = None
    name = "none"
    if "quality_analogy_accuracy" in rec:
        metric, name = rec["quality_analogy_accuracy"], "analogy_accuracy"
    elif "quality_spearman" in rec:
        metric, name = rec["quality_spearman"], "spearman"
    ok = metric is None or float(metric) >= float(floor)
    return ok, {
        "gate": name,
        "score": None if metric is None else float(metric),
        "floor": float(floor),
        **{k: v for k, v in rec.items() if isinstance(v, (int, float))},
    }


class StreamRun:
    """Drive a Trainer continuously over a stream source.

    `train()` matches the Trainer.train signature the CLI already calls
    (state/log_every/checkpoint_cb/checkpoint_every -> (state, report)),
    so the streaming path drops into cli.py where `run_train` is chosen.
    The TrainState it takes/returns carries SEGMENT-LOCAL counters (the
    replay coordinate within the in-progress segment); run-global totals
    live on the cursor and the returned TrainReport.
    """

    def __init__(
        self,
        trainer,
        source,
        *,
        cursor: Optional[StreamCursor] = None,
        min_count: Optional[int] = None,
        swap_engine=None,
        swap_floor: float = 0.0,
        probe_set=None,
        fault_plan=None,
        max_segments: int = 0,
        max_tokens: int = 0,
        log_fn: Optional[Callable[[Dict], None]] = None,
    ):
        self.trainer = trainer
        self.source = source
        self.cursor = cursor or StreamCursor()
        self.min_count = (
            trainer.config.min_count if min_count is None else int(min_count)
        )
        self.swap_engine = swap_engine
        self.swap_floor = float(swap_floor)
        self.probe_set = probe_set
        self.fault_plan = fault_plan
        self.max_segments = int(max_segments)
        self.max_tokens = int(max_tokens)
        self.log_fn = log_fn
        self.swaps = 0
        self.swaps_refused = 0
        self.growths = 0
        self.segments_done = 0
        self._forced_growth = 0
        self._capacity: Optional[int] = None
        import jax

        if jax.process_count() > 1 and trainer.config.vocab_reserve > 0:
            raise ValueError(
                "vocab_reserve > 0 with process_count > 1: per-rank streams "
                "would admit divergent vocabularies (rank-local counts); "
                "online growth is single-process today — run the fleet with "
                "vocab_reserve=0 or stream through one process"
            )

    # ---------------------------------------------------------- chaos hook
    def force_growth(self, n: int) -> None:
        """`vocab_growth@k` fault (resilience/faults.py): admit `n`
        synthetic words at the next boundary even if the corpus brought
        none — the chaos matrix's way of exercising the growth path
        (table rebuild + recompile + generation bump) on any stream."""
        self._forced_growth = max(self._forced_growth, int(n))

    # ------------------------------------------------------------ plumbing
    def cursor_meta(self) -> Dict:
        """The stream.json document every checkpoint of this run carries."""
        doc = self.cursor.to_json()
        doc["schema"] = 1
        doc["source"] = self.source.describe()
        doc["capacity"] = self._capacity
        doc["swaps"] = self.swaps
        doc["growths"] = self.growths
        return doc

    def _log(self, rec: Dict) -> None:
        tr = self.trainer
        if tr.flight is not None and "event" in rec:
            tr.flight.log_record(rec)
        fn = self.log_fn or tr.log_fn
        if fn is not None:
            fn(rec)

    def _emit_stream_record(self) -> None:
        """One 'stream' gauge record (obs/export.GAUGE_EVENTS):
        w2v_vocab_size / w2v_stream_tokens_total / w2v_stream_segment /
        w2v_vocab_generation, present from the run's first boundary. When
        the HBM ledger is live (obs/devmem.py) the record also carries the
        growth-headroom forecast — rows the device could still absorb at
        the realized bytes/row — so a dashboard sees `--vocab-reserve`
        running out of budget segments before it happens."""
        rec = {
            "event": "stream",
            "vocab_size": len(self.trainer.vocab),
            "stream_tokens_total": int(self.cursor.tokens_total),
            "stream_segment": int(self.cursor.segment),
            "vocab_generation": int(self.cursor.vocab_generation),
            "stream_swaps": self.swaps,
            "stream_growths": self.growths,
        }
        ledger = getattr(self.trainer, "devmem", None)
        if ledger is not None:
            fc = ledger.forecast() or {}
            if fc.get("rows_remaining") is not None:
                rec["stream_growth_rows_remaining"] = fc["rows_remaining"]
        self._log(rec)

    # ------------------------------------------------------------- reading
    def _raw_segments(self):
        """Sequential segment reads from the cursor on — runs in the
        prefetch PRODUCER thread, so shard IO/tokenization of segment s+1
        overlaps the device training of segment s. Only reads and counts:
        ENCODING stays on the consumer side, after any boundary growth."""
        index = int(self.cursor.segment)
        shard = int(self.cursor.shard)
        offset = int(self.cursor.offset)
        read = 0
        while True:
            raw = self.source.read_segment(
                index, shard, offset, vocab=self.trainer.vocab
            )
            if raw.raw_tokens == 0:
                return
            yield raw
            if raw.exhausted:
                return
            index += 1
            shard, offset = raw.shard1, raw.offset1
            read += raw.raw_tokens
            if self.max_segments and index - self.cursor.segment >= self.max_segments:
                return
            if self.max_tokens and read >= self.max_tokens:
                return

    def _encode(self, raw: RawSegment) -> np.ndarray:
        """Segment -> flat ids, with the LIVE (post-growth) vocabulary —
        always called AFTER any boundary growth, so the encoding vocab of
        segment s is a pure function of the stream up to s (the resume
        replay invariant)."""
        return encode_segment(
            raw, self.trainer.vocab, getattr(self.source, "fmt", "text8")
        )

    # ------------------------------------------------------------ boundary
    def _advance(self, raw: RawSegment, steps: int, words: int) -> None:
        self.cursor = StreamCursor(
            segment=raw.index + 1,
            shard=raw.shard1,
            offset=raw.offset1,
            vocab_generation=self.cursor.vocab_generation,
            tokens_total=self.cursor.tokens_total + raw.raw_tokens,
            global_steps=int(steps),
            global_words=int(words),
        )

    def _maybe_grow(self, raw: RawSegment) -> int:
        cap = self._capacity or 0
        vocab = self.trainer.vocab
        reserve_left = cap - len(vocab)
        items: List[Tuple[str, int]] = []
        if raw.counts and reserve_left > 0:
            items = admission_order(
                raw.counts, vocab, self.min_count, reserve_left
            )
        if self._forced_growth and reserve_left > len(items):
            gen = self.cursor.vocab_generation
            synth = [
                (f"__chaos_g{gen}_{i}", self.min_count)
                for i in range(self._forced_growth)
            ]
            items = (items + [
                s for s in synth if s[0] not in vocab
            ])[:reserve_left]
        self._forced_growth = 0
        if not items:
            return 0
        ids = vocab.admit(items)
        self.cursor.vocab_generation += 1
        self.growths += 1
        # frequency-derived device tables (keep_probs / alias sampler) now
        # cover the admitted rows; the rebuilt jit step recompiles once at
        # this boundary — growth is rare, and the boundary is already a
        # sync boundary (elastic rejoiners park at the same place)
        self.trainer.refresh_vocab_tables()
        self._log({
            "event": "vocab_growth",
            "segment": raw.index,
            "admitted": len(ids),
            "first_id": int(ids[0]),
            "vocab_size": len(vocab),
            "generation": int(self.cursor.vocab_generation),
            "reserve_left": int(cap - len(vocab)),
        })
        return len(ids)

    def _maybe_swap(self, state: TrainState, segment: int) -> None:
        if self.swap_engine is None:
            return
        import jax

        from ..models.params import logical_table

        vocab = self.trainer.vocab
        W = np.asarray(
            jax.device_get(logical_table(state.params, "emb_in")),
            np.float32,
        )[: len(vocab)]
        probe_set = self.probe_set
        if probe_set is None:
            from ..obs.quality import ProbeSet

            probe_set = self.probe_set = ProbeSet.synthesize(vocab)
        ok, rec = gate_table(W, vocab, probe_set, self.swap_floor)
        if ok:
            # snapshot the vocab: the engine must not see future admits
            # mid-decode (the live object keeps growing)
            from ..data.vocab import Vocab

            snap = Vocab(list(vocab.words), vocab.counts.copy())
            self.swap_engine.swap_table(W, vocab=snap)
            self.swaps += 1
            self._log({
                "event": "table_swap", "segment": segment,
                "vocab_size": len(snap), **rec,
            })
        else:
            self.swaps_refused += 1
            self._log({
                "event": "table_swap_refused", "segment": segment, **rec,
            })

    # ----------------------------------------------------------------- api
    def train(
        self,
        state: Optional[TrainState] = None,
        log_every: int = 50,
        checkpoint_cb: Optional[Callable[[TrainState], None]] = None,
        checkpoint_every: int = 0,
    ) -> Tuple[TrainState, TrainReport]:
        tr = self.trainer
        cfg = tr.config
        t0 = time.perf_counter()
        if state is None:
            state = tr.init_state()
        tr.last_state = state
        self._capacity = table_capacity(state.params)
        self._emit_stream_record()
        interrupted: Optional[str] = None
        loss_hist: List[float] = []
        last_report: Optional[TrainReport] = None
        steps_total = int(self.cursor.global_steps)
        words_total = int(self.cursor.global_words)
        words_entry = words_total  # words trained by PRIOR generations
        gen = prefetch(self._raw_segments(), depth=1)
        try:
            for raw in gen:
                if self.fault_plan is not None:
                    self.fault_plan.on_segment(raw.index, self)
                flat = self._encode(raw)
                trainable = flat.size and bool((flat >= 0).any())
                if trainable:
                    corpus = PackedCorpus.from_flat(
                        flat, cfg.max_sentence_len
                    )
                    tr.set_corpus(corpus)
                    # per-segment draw/shuffle stream: a pure function of
                    # (config.seed, segment index), so segments do not
                    # repeat each other's negative draws and a resumed
                    # segment replays exactly (train.Trainer.seed_offset)
                    tr.seed_offset = raw.index
                    self._log({
                        "event": "stream_segment",
                        "segment": raw.index,
                        "raw_tokens": raw.raw_tokens,
                        "encoded_tokens": int(corpus.num_tokens),
                        "rows": int(corpus.num_rows),
                        "shard": raw.shard0,
                        "offset": raw.offset0,
                    })
                    state, rep = tr.train(
                        state=state, log_every=log_every,
                        checkpoint_cb=checkpoint_cb,
                        checkpoint_every=checkpoint_every,
                    )
                    last_report = rep
                    loss_hist.extend(rep.loss_history)
                    if rep.interrupted:
                        # cursor still names this segment's start; the
                        # seg-local state is the replay coordinate
                        interrupted = rep.interrupted
                        break
                    steps_total += state.step
                    words_total += state.words_done
                self._advance(raw, steps_total, words_total)
                self.segments_done += 1
                self._maybe_grow(raw)
                self._maybe_swap(state, raw.index)
                state = TrainState(params=state.params)  # fresh seg counters
                tr.last_state = state
                self._emit_stream_record()
                if checkpoint_cb is not None and checkpoint_every:
                    # boundary checkpoint: the advanced cursor, any growth,
                    # and the segment's params land together — a preemption
                    # between segments loses nothing
                    checkpoint_cb(state)
        finally:
            gen.close()
        wall = time.perf_counter() - t0
        if interrupted:
            steps_total += state.step
            words_total += state.words_done
        report = TrainReport(
            words_per_sec=(words_total - words_entry) / max(wall, 1e-9),
            total_words=words_total,
            steps=steps_total,
            wall_time=wall,
            final_loss=(
                last_report.final_loss if last_report else float("nan")
            ),
            loss_history=loss_hist,
            resident=None,
            phases=last_report.phases if last_report else None,
            health=last_report.health if last_report else None,
            interrupted=interrupted,
            signals=last_report.signals if last_report else None,
        )
        report.stream = {
            "source": self.source.describe(),
            "segments": self.segments_done,
            "tokens_total": int(self.cursor.tokens_total),
            "vocab_size": len(tr.vocab),
            "vocab_generation": int(self.cursor.vocab_generation),
            "growths": self.growths,
            "swaps": self.swaps,
            "swaps_refused": self.swaps_refused,
            "cursor": self.cursor.to_json(),
        }
        return state, report
