"""Streaming corpus sources: shard sets, directory globs, and pipes.

The continuous-training data plane (ROADMAP item 3) consumes the corpus in
bounded SEGMENTS of raw tokens instead of one resident pack. A source turns
a corpus spec into an ordered shard list and answers one question:

    read_segment(index, shard, offset, vocab=None) -> RawSegment

deterministically — the same (shard, offset) start always yields the same
raw tokens, which is what makes the mid-stream checkpoint cursor a replay
coordinate: SIGTERM at step k of segment s resumes by re-reading segment s
from its recorded start and re-entering it at batch k (train._resume_skip),
byte-for-byte on the uninterrupted trajectory.

Three sources:
  * FileSource  — an explicit file list, comma list, directory, or glob
    (resolve_shards). Offsets count raw TOKENS within a shard ("text8"
    whitespace-stream semantics, main.cpp:63-92) or LINES ("lines",
    Word2Vec.cpp:19-30). Sentences never cross shard boundaries.
  * PipeSource  — an unbounded fd/stdin stream (`-train -`). Bytes are
    SPOOLED to one file per segment before use, so a segment that has been
    read once can be re-read on resume — a pipe cannot seek, the spool can.
  * ArraySource — a pre-encoded id stream (bench/test harnesses; the 100M
    synthetic A/B shape) with zero tokenization cost.

Counting rides the read: a segment reports the words it saw that are NOT in
the current vocabulary (the online-growth admission candidates,
stream/driver.py) — or every word when `vocab` is None (the cold-start
vocabulary bootstrap).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
from collections import Counter
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

#: the pipe spec: `-train -` reads stdin through a PipeSource
PIPE_SPEC = "-"

#: reference pseudo-sentence length for the text8 whitespace stream
#: (main.cpp:66 max_sentence_len)
DEFAULT_CHUNK_WORDS = 1000


@dataclasses.dataclass
class StreamCursor:
    """The mid-stream replay coordinate a streaming checkpoint carries
    (io/checkpoint.save_checkpoint(stream=...) -> stream.json).

    Positional fields name where the IN-PROGRESS segment starts (segment
    index, shard index, consumed units within the shard); bookkeeping
    fields carry what the positional ones cannot re-derive: the vocab
    generation (how many online-growth admissions happened before this
    segment) and the run-global step/word counters (per-segment TrainState
    counters reset at every boundary, so the global totals live here).
    """

    segment: int = 0
    shard: int = 0
    offset: int = 0            # consumed units in shard: tokens (text8) | lines
    vocab_generation: int = 0
    tokens_total: int = 0      # raw tokens consumed before this segment
    global_steps: int = 0      # optimizer steps completed before this segment
    global_words: int = 0      # trained words completed before this segment

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Dict) -> "StreamCursor":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: int(v) for k, v in d.items() if k in known})


@dataclasses.dataclass
class RawSegment:
    """One read segment: raw material plus its positional extent."""

    index: int
    shard0: int
    offset0: int
    shard1: int                # position AFTER the segment (next start)
    offset1: int
    raw_tokens: int
    #: tokenized sentences (FileSource/PipeSource); None for ArraySource
    sentences: Optional[List[List[str]]]
    #: pre-encoded ids (ArraySource); None for token sources
    flat: Optional[np.ndarray]
    #: admission candidates: words seen that are not in `vocab` (all words
    #: when read with vocab=None); None when the source cannot count
    counts: Optional[Counter]
    #: nothing exists after (shard1, offset1) — the stream is drained
    exhausted: bool


def resolve_shards(spec: str) -> List[str]:
    """A corpus spec -> the ordered shard list.

    Comma-separated parts; each part is a glob pattern (expanded, sorted),
    a directory (its regular files, sorted), or a plain file. The order is
    deterministic — it IS the stream order the cursor indexes into."""
    shards: List[str] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if any(ch in part for ch in "*?["):
            hits = sorted(p for p in _glob.glob(part) if os.path.isfile(p))
            if not hits:
                raise FileNotFoundError(
                    f"corpus glob {part!r} matched no files"
                )
            shards.extend(hits)
        elif os.path.isdir(part):
            hits = sorted(
                e.path for e in os.scandir(part) if e.is_file()
            )
            if not hits:
                raise FileNotFoundError(
                    f"corpus directory {part!r} holds no files"
                )
            shards.extend(hits)
        elif os.path.isfile(part):
            shards.append(part)
        else:
            raise FileNotFoundError(f"corpus shard {part!r} does not exist")
    if not shards:
        raise FileNotFoundError(f"corpus spec {spec!r} resolved to no shards")
    return shards


def _iter_shard_units(path: str, fmt: str, skip: int) -> Iterator[List[str]]:
    """Yield the shard's units past `skip`: single tokens (text8) or whole
    tokenized lines (lines). Block-buffered like data/corpus.text8_corpus,
    with the same straddling-token hold-back."""
    if fmt == "lines":
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for n, line in enumerate(f):
                if n < skip:
                    continue
                yield line.split()
        return
    seen = 0
    remainder = ""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            block = remainder + block
            parts = block.split()
            if parts and not block[-1].isspace():
                remainder = parts.pop()
            else:
                remainder = ""
            for tok in parts:
                seen += 1
                if seen > skip:
                    yield [tok]
    if remainder:
        seen += 1
        if seen > skip:
            yield [remainder]


class FileSource:
    """Sharded file-set source (see module docstring)."""

    kind = "files"

    def __init__(
        self,
        shards: Sequence[str],
        fmt: str = "text8",
        segment_tokens: int = 1_000_000,
        chunk_words: int = DEFAULT_CHUNK_WORDS,
    ):
        if fmt not in ("text8", "lines"):
            raise ValueError(f"fmt must be 'text8' or 'lines', got {fmt!r}")
        if segment_tokens < 1:
            raise ValueError("segment_tokens must be >= 1")
        self.shards = list(shards)
        self.fmt = fmt
        self.segment_tokens = int(segment_tokens)
        self.chunk_words = int(chunk_words)
        if not self.shards:
            raise ValueError("FileSource needs at least one shard")

    def describe(self) -> Dict:
        return {
            "kind": self.kind,
            "shards": list(self.shards),
            "fmt": self.fmt,
            "segment_tokens": self.segment_tokens,
        }

    def read_segment(
        self, index: int, shard: int, offset: int, vocab=None
    ) -> RawSegment:
        """Read the next <= segment_tokens raw tokens starting at
        (shard, offset). Deterministic: sentence chunking restarts at the
        segment start, sentences never cross shard boundaries, and the
        segment ends exactly at segment_tokens tokens (text8) or at the
        first line boundary at/after it (lines)."""
        sentences: List[List[str]] = []
        counts: Counter = Counter()
        cur: List[str] = []
        raw = 0
        s, ofs = int(shard), int(offset)
        exhausted = False
        # membership check against a LIVE vocab dict is safe under
        # concurrent admits (CPython: no iteration, only lookups) — the
        # driver's prefetch producer counts while the consumer may grow
        contains = (lambda w: False) if vocab is None else vocab.__contains__
        while s < len(self.shards) and raw < self.segment_tokens:
            for unit in _iter_shard_units(self.shards[s], self.fmt, ofs):
                if self.fmt == "lines":
                    ofs += 1
                    raw += len(unit)
                    for tok in unit:
                        if not contains(tok):
                            counts[tok] += 1
                    if unit:
                        sentences.append(unit)
                else:
                    tok = unit[0]
                    ofs += 1
                    raw += 1
                    if not contains(tok):
                        counts[tok] += 1
                    cur.append(tok)
                    if len(cur) == self.chunk_words:
                        sentences.append(cur)
                        cur = []
                if raw >= self.segment_tokens:
                    break
            else:
                # shard drained: sentence break at the shard boundary
                if cur:
                    sentences.append(cur)
                    cur = []
                s += 1
                ofs = 0
                continue
            break  # segment full mid-shard
        if cur:
            sentences.append(cur)
        if s >= len(self.shards):
            exhausted = True
        elif raw < self.segment_tokens:
            exhausted = True  # ended early: nothing left to read
        return RawSegment(
            index=int(index), shard0=int(shard), offset0=int(offset),
            shard1=s, offset1=ofs, raw_tokens=raw,
            sentences=sentences, flat=None, counts=counts,
            exhausted=exhausted,
        )


class PipeSource:
    """An unbounded fd stream, spooled one file per segment (module doc).

    `spool_dir` must persist as long as resumability is wanted — the spool
    IS the replayable corpus the pipe itself cannot be. Cursor shape:
    shard == segment index (each segment is its own spool file),
    offset == 0 (segments are whole files)."""

    kind = "pipe"

    def __init__(
        self,
        fd: int = 0,
        spool_dir: str = "",
        fmt: str = "text8",
        segment_tokens: int = 1_000_000,
        chunk_words: int = DEFAULT_CHUNK_WORDS,
    ):
        if not spool_dir:
            raise ValueError(
                "PipeSource needs a spool_dir: the pipe cannot be re-read, "
                "so resumability requires spooling segments to disk"
            )
        os.makedirs(spool_dir, exist_ok=True)
        self.fd = int(fd)
        self.spool_dir = spool_dir
        self.fmt = fmt
        self.segment_tokens = int(segment_tokens)
        self.chunk_words = int(chunk_words)
        self._carry = b""
        self._eof = False
        self._spooled = -1  # highest segment index already on disk
        for name in os.listdir(spool_dir):
            if name.startswith("seg_") and name.endswith(".txt"):
                try:
                    self._spooled = max(self._spooled, int(name[4:-4]))
                except ValueError:
                    pass

    def describe(self) -> Dict:
        return {
            "kind": self.kind,
            "spool_dir": self.spool_dir,
            "fmt": self.fmt,
            "segment_tokens": self.segment_tokens,
        }

    def _spool_path(self, index: int) -> str:
        return os.path.join(self.spool_dir, f"seg_{index:06d}.txt")

    def _spool_next(self) -> bool:
        """Spool one more segment file from the fd; False at EOF with
        nothing left to write."""
        if self._eof and not self._carry:
            return False
        chunks = [self._carry]
        total = len(self._carry.split())
        while total < self.segment_tokens and not self._eof:
            block = os.read(self.fd, 1 << 20)
            if not block:
                self._eof = True
                break
            chunks.append(block)
            total += len(block.split())
        data = b"".join(chunks)
        if not data.strip():
            self._carry = b""
            return False
        # cut at a unit boundary: whitespace for text8, newline for lines
        toks = data.split()
        if len(toks) > self.segment_tokens and not self._eof:
            if self.fmt == "lines":
                cut = data.rfind(b"\n")
                if cut < 0:
                    cut = len(data)
                else:
                    cut += 1
            else:
                kept = b" ".join(toks[: self.segment_tokens]) + b" "
                cut = len(kept)
                data = kept + b" ".join(toks[self.segment_tokens:])
            head, self._carry = data[:cut], data[cut:]
        else:
            head, self._carry = data, b""
        self._spooled += 1
        path = self._spool_path(self._spooled)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(head)
        os.replace(tmp, path)  # a torn spool file must never be replayed
        return True

    def read_segment(
        self, index: int, shard: int, offset: int, vocab=None
    ) -> RawSegment:
        del shard, offset  # pipe cursor: shard == index, offset == 0
        while self._spooled < index:
            if not self._spool_next():
                return RawSegment(
                    index=index, shard0=index, offset0=0,
                    shard1=index, offset1=0, raw_tokens=0,
                    sentences=[], flat=None, counts=Counter(),
                    exhausted=True,
                )
        inner = FileSource(
            [self._spool_path(index)], fmt=self.fmt,
            segment_tokens=self.segment_tokens,
            chunk_words=self.chunk_words,
        )
        raw = inner.read_segment(index, 0, 0, vocab=vocab)
        more = (self._spooled > index) or not self._eof or bool(self._carry)
        return RawSegment(
            index=index, shard0=index, offset0=0,
            shard1=index + 1, offset1=0, raw_tokens=raw.raw_tokens,
            sentences=raw.sentences, flat=None, counts=raw.counts,
            exhausted=not more,
        )


class ArraySource:
    """A pre-encoded int32 id stream (bench/test harness; no growth)."""

    kind = "array"

    def __init__(self, flat: np.ndarray, segment_tokens: int = 1_000_000):
        self.flat = np.asarray(flat, dtype=np.int32)
        self.segment_tokens = int(segment_tokens)
        if self.segment_tokens < 1:
            raise ValueError("segment_tokens must be >= 1")

    def describe(self) -> Dict:
        return {
            "kind": self.kind,
            "tokens": int(len(self.flat)),
            "segment_tokens": self.segment_tokens,
        }

    def read_segment(
        self, index: int, shard: int, offset: int, vocab=None
    ) -> RawSegment:
        del vocab
        start = int(offset)
        end = min(len(self.flat), start + self.segment_tokens)
        piece = self.flat[start:end]
        return RawSegment(
            index=int(index), shard0=0, offset0=start,
            shard1=0, offset1=end, raw_tokens=int(end - start),
            sentences=None, flat=piece, counts=None,
            exhausted=end >= len(self.flat),
        )


def make_source(
    spec: str,
    fmt: str = "text8",
    segment_tokens: int = 1_000_000,
    spool_dir: str = "",
    chunk_words: int = DEFAULT_CHUNK_WORDS,
    fd: Optional[int] = None,
):
    """The CLI's source factory: `-` (or an explicit fd) is a pipe, spooled
    under `spool_dir`; anything else resolves through resolve_shards."""
    if spec == PIPE_SPEC or fd is not None:
        return PipeSource(
            fd=0 if fd is None else fd, spool_dir=spool_dir, fmt=fmt,
            segment_tokens=segment_tokens, chunk_words=chunk_words,
        )
    return FileSource(
        resolve_shards(spec), fmt=fmt, segment_tokens=segment_tokens,
        chunk_words=chunk_words,
    )
