"""Continuous-training data plane: streaming corpus segments, online vocab
growth into reserved table rows, mid-stream cursor checkpoints, and hot
table swaps into a live serve engine (ROADMAP item 3).

    from word2vec_tpu.stream import StreamRun, make_source, StreamCursor

    source = make_source("corpus_dir/", segment_tokens=4_000_000)
    run = StreamRun(trainer, source)
    state, report = run.train(checkpoint_cb=..., checkpoint_every=500)

See stream/source.py (shard/pipe/array sources + the StreamCursor replay
coordinate) and stream/driver.py (the segment loop, growth admission, and
the gated swap).
"""

from .driver import (  # noqa: F401
    DEFAULT_SEGMENT_TOKENS, StreamRun, admission_order, gate_table,
    table_capacity,
)
from .source import (  # noqa: F401
    ArraySource, FileSource, PipeSource, RawSegment, StreamCursor,
    make_source, resolve_shards,
)
