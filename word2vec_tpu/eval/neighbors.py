"""Nearest-neighbor queries — the `distance` tool the reference lacks
(SURVEY §3.5: "no nearest-neighbor query ... equivalents from the original
google toolkit").

Since the serving PR these are thin shims over the shared jit'd batched
top-k kernel (serve/query.QueryEngine) — the same code path the async
server and the analogy evaluator use. The engine cache means two
successive queries against the same exported array normalize the table
ONCE instead of recomputing `W / ||W||` per call (pinned by a regression
test), and tied scores come back in deterministic ascending-index order
instead of argpartition's arbitrary one.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..data.vocab import Vocab


def nearest_neighbors(
    W: np.ndarray, vocab: Vocab, word: str, k: int = 10
) -> List[Tuple[str, float]]:
    """Top-k cosine neighbors of `word`, excluding itself."""
    from ..serve.query import get_engine

    return get_engine(W, vocab).neighbors_batch([word], k=k)[0]


def analogy_query(
    W: np.ndarray, vocab: Vocab, a: str, b: str, c: str, k: int = 5
) -> List[Tuple[str, float]]:
    """a:b :: c:? via 3CosAdd (word-analogy tool equivalent)."""
    from ..serve.query import get_engine

    return get_engine(W, vocab).analogy_batch([(a, b, c)], k=k)[0]
