"""Nearest-neighbor queries — the `distance` tool the reference lacks
(SURVEY §3.5: "no nearest-neighbor query ... equivalents from the original
google toolkit").
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..data.vocab import Vocab


def nearest_neighbors(
    W: np.ndarray, vocab: Vocab, word: str, k: int = 10
) -> List[Tuple[str, float]]:
    """Top-k cosine neighbors of `word`, excluding itself."""
    if word not in vocab:
        raise KeyError(f"{word!r} not in vocabulary")
    Wn = W / np.maximum(np.linalg.norm(W, axis=1, keepdims=True), 1e-12)
    sims = Wn @ Wn[vocab[word]]
    sims[vocab[word]] = -np.inf
    top = np.argpartition(-sims, min(k, len(sims) - 1))[:k]
    top = top[np.argsort(-sims[top])]
    return [(vocab.words[i], float(sims[i])) for i in top]


def analogy_query(
    W: np.ndarray, vocab: Vocab, a: str, b: str, c: str, k: int = 5
) -> List[Tuple[str, float]]:
    """a:b :: c:? via 3CosAdd (word-analogy tool equivalent)."""
    for w in (a, b, c):
        if w not in vocab:
            raise KeyError(f"{w!r} not in vocabulary")
    Wn = W / np.maximum(np.linalg.norm(W, axis=1, keepdims=True), 1e-12)
    q = Wn[vocab[b]] - Wn[vocab[a]] + Wn[vocab[c]]
    q /= max(np.linalg.norm(q), 1e-12)
    sims = Wn @ q
    for w in (a, b, c):
        sims[vocab[w]] = -np.inf
    top = np.argpartition(-sims, min(k, len(sims) - 1))[:k]
    top = top[np.argsort(-sims[top])]
    return [(vocab.words[i], float(sims[i])) for i in top]
