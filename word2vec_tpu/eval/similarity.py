"""Word-similarity evaluation (WordSim-353 and compatible datasets).

The reference has no eval tooling at all (SURVEY §3.5); WS-353 Spearman is
half of the BASELINE.json parity gate, so it is a first-class component here.

Dataset format: one pair per line, `word1 word2 score`, separated by commas,
tabs or spaces; an optional header line is skipped. Pairs with OOV words are
dropped (standard protocol) and reported.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..data.vocab import Vocab


@dataclass
class SimilarityResult:
    spearman: float
    pearson: float
    pairs_used: int
    pairs_total: int


def _rankdata(x: np.ndarray) -> np.ndarray:
    """Average ranks with tie handling (scipy-free)."""
    order = np.argsort(x, kind="stable")
    ranks = np.empty(len(x), dtype=np.float64)
    sx = x[order]
    i = 0
    while i < len(sx):
        j = i
        while j + 1 < len(sx) and sx[j + 1] == sx[i]:
            j += 1
        ranks[order[i : j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    return ranks


def spearman(a: np.ndarray, b: np.ndarray) -> float:
    ra, rb = _rankdata(a), _rankdata(b)
    return pearson(ra, rb)


def pearson(a: np.ndarray, b: np.ndarray) -> float:
    a = a - a.mean()
    b = b - b.mean()
    denom = np.sqrt((a * a).sum() * (b * b).sum())
    return float((a * b).sum() / denom) if denom > 0 else 0.0


def _split_pair_line(
    line: str, min_cols: int, delimiter: str | None = None
) -> List[str]:
    """The one delimiter sniff shared by the reader and the converter:
    comma, then tab, then whitespace — first split yielding min_cols
    fields wins. An explicit delimiter skips the sniff; when that
    delimiter is whitespace, consecutive separators count as ONE (the
    split(None) convention), so a MEN-style file padded with runs of
    spaces keeps its columns aligned instead of dying on an empty-string
    "non-numeric score" (ADVICE r5 #3 — `--delimiter ' '` previously
    produced ['w1', '', 'w2', ...])."""
    if delimiter is not None:
        if delimiter.isspace():
            return [p for p in line.split(delimiter) if p != ""]
        return line.split(delimiter)
    for sep in (",", "\t", None):
        parts = line.split(sep)
        if len(parts) >= min_cols:
            break
    return parts


def load_word_pairs(path: str) -> List[Tuple[str, str, float]]:
    pairs: List[Tuple[str, str, float]] = []
    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            parts = _split_pair_line(line, 3)
            try:
                score = float(parts[2])
            except (ValueError, IndexError):
                if ln == 0:
                    continue  # header
                raise
            pairs.append((parts[0].lower(), parts[1].lower(), score))
    return pairs


def convert_pairs_file(
    src: str,
    dst: str,
    cols: Tuple[int, int, int] = (0, 1, 2),
    delimiter: str | None = None,
    lower: bool = True,
) -> int:
    """Normalize any word-pair similarity file into the canonical
    `word1,word2,score` CSV that load_word_pairs (and the --eval-ws353
    training gate) reads.

    Handles the real datasets' quirks without shipping the datasets (the
    build env is offline — BASELINE.md's ±1% gate runs the moment a user
    supplies one):
      - WordSim-353 `combined.csv`: comma-separated with a
        `Word 1,Word 2,Human (mean)` header — default cols work.
      - SimLex-999: tab-separated, header, score in column 3 —
        `--cols 0,1,3`.
      - MEN: space-separated `word1 word2 score`, no header.
    A header line (non-numeric score cell) is skipped; blank lines are
    skipped; returns the number of pairs written. The output is written to
    a temp file and renamed into place only on success, so a malformed row
    mid-file cannot leave a silently truncated dst behind for a later
    eval run to consume.
    """
    import os

    n = 0
    tmp = dst + ".tmp"
    try:
        with open(src, "r", encoding="utf-8") as f, \
                open(tmp, "w", encoding="utf-8") as out:
            for ln, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                parts = _split_pair_line(line, max(cols) + 1, delimiter)
                if len(parts) <= max(cols):
                    raise ValueError(
                        f"{src}:{ln + 1}: expected at least {max(cols) + 1} "
                        f"columns, got {len(parts)}"
                    )
                w1, w2, s = parts[cols[0]], parts[cols[1]], parts[cols[2]]
                try:
                    score = float(s)
                except ValueError:
                    if ln == 0:
                        continue  # header
                    raise ValueError(
                        f"{src}:{ln + 1}: non-numeric score {s!r}"
                    ) from None
                if lower:
                    w1, w2 = w1.lower(), w2.lower()
                out.write(f"{w1},{w2},{score}\n")
                n += 1
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, dst)
    return n


def cosine_rows(W: np.ndarray, i: np.ndarray, j: np.ndarray) -> np.ndarray:
    a, b = W[i], W[j]
    na = np.linalg.norm(a, axis=-1)
    nb = np.linalg.norm(b, axis=-1)
    return (a * b).sum(-1) / np.maximum(na * nb, 1e-12)


def evaluate_pairs(
    W: np.ndarray, vocab: Vocab, pairs: List[Tuple[str, str, float]]
) -> SimilarityResult:
    idx_a, idx_b, gold = [], [], []
    for w1, w2, score in pairs:
        if w1 in vocab and w2 in vocab:
            idx_a.append(vocab[w1])
            idx_b.append(vocab[w2])
            gold.append(score)
    if not gold:
        return SimilarityResult(0.0, 0.0, 0, len(pairs))
    # the serve engine's resident normalized table: one unit_norm pass for
    # every eval/serve query against this array, cosines on device as a
    # pair-dot (rows are unit). cosine_rows stays as the host-side
    # reference implementation (and for callers without a vocab).
    from ..serve.query import get_engine

    eng = get_engine(W, vocab)
    sims = eng.pair_cosines(np.asarray(idx_a), np.asarray(idx_b))
    gold_arr = np.asarray(gold)
    return SimilarityResult(
        spearman=spearman(sims, gold_arr),
        pearson=pearson(sims, gold_arr),
        pairs_used=len(gold),
        pairs_total=len(pairs),
    )


def evaluate_ws353(W: np.ndarray, vocab: Vocab, path: str) -> SimilarityResult:
    return evaluate_pairs(W, vocab, load_word_pairs(path))
