"""Word-similarity evaluation (WordSim-353 and compatible datasets).

The reference has no eval tooling at all (SURVEY §3.5); WS-353 Spearman is
half of the BASELINE.json parity gate, so it is a first-class component here.

Dataset format: one pair per line, `word1 word2 score`, separated by commas,
tabs or spaces; an optional header line is skipped. Pairs with OOV words are
dropped (standard protocol) and reported.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..data.vocab import Vocab


@dataclass
class SimilarityResult:
    spearman: float
    pearson: float
    pairs_used: int
    pairs_total: int


def _rankdata(x: np.ndarray) -> np.ndarray:
    """Average ranks with tie handling (scipy-free)."""
    order = np.argsort(x, kind="stable")
    ranks = np.empty(len(x), dtype=np.float64)
    sx = x[order]
    i = 0
    while i < len(sx):
        j = i
        while j + 1 < len(sx) and sx[j + 1] == sx[i]:
            j += 1
        ranks[order[i : j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    return ranks


def spearman(a: np.ndarray, b: np.ndarray) -> float:
    ra, rb = _rankdata(a), _rankdata(b)
    return pearson(ra, rb)


def pearson(a: np.ndarray, b: np.ndarray) -> float:
    a = a - a.mean()
    b = b - b.mean()
    denom = np.sqrt((a * a).sum() * (b * b).sum())
    return float((a * b).sum() / denom) if denom > 0 else 0.0


def load_word_pairs(path: str) -> List[Tuple[str, str, float]]:
    pairs: List[Tuple[str, str, float]] = []
    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            for sep in (",", "\t", None):
                parts = line.split(sep)
                if len(parts) >= 3:
                    break
            try:
                score = float(parts[2])
            except ValueError:
                if ln == 0:
                    continue  # header
                raise
            pairs.append((parts[0].lower(), parts[1].lower(), score))
    return pairs


def cosine_rows(W: np.ndarray, i: np.ndarray, j: np.ndarray) -> np.ndarray:
    a, b = W[i], W[j]
    na = np.linalg.norm(a, axis=-1)
    nb = np.linalg.norm(b, axis=-1)
    return (a * b).sum(-1) / np.maximum(na * nb, 1e-12)


def evaluate_pairs(
    W: np.ndarray, vocab: Vocab, pairs: List[Tuple[str, str, float]]
) -> SimilarityResult:
    idx_a, idx_b, gold = [], [], []
    for w1, w2, score in pairs:
        if w1 in vocab and w2 in vocab:
            idx_a.append(vocab[w1])
            idx_b.append(vocab[w2])
            gold.append(score)
    if not gold:
        return SimilarityResult(0.0, 0.0, 0, len(pairs))
    sims = cosine_rows(W, np.asarray(idx_a), np.asarray(idx_b))
    gold_arr = np.asarray(gold)
    return SimilarityResult(
        spearman=spearman(sims, gold_arr),
        pearson=pearson(sims, gold_arr),
        pairs_used=len(gold),
        pairs_total=len(pairs),
    )


def evaluate_ws353(W: np.ndarray, vocab: Vocab, path: str) -> SimilarityResult:
    return evaluate_pairs(W, vocab, load_word_pairs(path))
