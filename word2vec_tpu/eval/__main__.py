"""Eval CLI — the `distance` / `compute-accuracy` tools of the original
word2vec toolkit, absent from the reference (SURVEY §3.5).

    python -m word2vec_tpu.eval neighbors vec.txt france [-k 10]
    python -m word2vec_tpu.eval analogy   vec.txt king man woman
    python -m word2vec_tpu.eval ws353     vec.txt wordsim353.csv
    python -m word2vec_tpu.eval analogies vec.txt questions-words.txt
    python -m word2vec_tpu.eval convert   SimLex-999.txt out.csv --cols 0,1,3

Vector files: the trainer's text or binary formats (io/embeddings —
text is auto-detected; pass --binary/--binary-layout otherwise).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..data.vocab import Vocab
from ..io.embeddings import load_embeddings_binary, load_embeddings_text
from .analogy import evaluate_analogies
from .neighbors import analogy_query, nearest_neighbors
from .similarity import evaluate_pairs, load_word_pairs


def _load(args) -> tuple:
    if args.int8:
        from ..io.embeddings import load_embeddings_int8

        words, W = load_embeddings_int8(args.vectors)
    elif args.binary:
        words, W = load_embeddings_binary(args.vectors, layout=args.binary_layout)
    else:
        words, W = load_embeddings_text(args.vectors)
    vocab = Vocab(words, np.ones(len(words), dtype=np.int64))
    return vocab, W


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="word2vec_tpu.eval")
    ap.add_argument("--binary", action="store_true",
                    help="vectors file is binary (default: text)")
    ap.add_argument("--binary-layout", choices=["reference", "google"],
                    default="reference")
    ap.add_argument("--int8", action="store_true",
                    help="vectors file is the int8 symmetric-quantized "
                    "container (io/embeddings; dequantized on load)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("neighbors", help="top-k cosine neighbors (distance.c)")
    p.add_argument("vectors")
    p.add_argument("word")
    p.add_argument("-k", type=int, default=10)

    p = sub.add_parser("analogy", help="a:b :: c:? by 3CosAdd")
    p.add_argument("vectors")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("c")
    p.add_argument("-k", type=int, default=5)

    p = sub.add_parser("ws353", help="Spearman vs a word-pair gold file")
    p.add_argument("vectors")
    p.add_argument("pairs_file")

    p = sub.add_parser("analogies",
                       help="google questions-words accuracy (compute-accuracy)")
    p.add_argument("vectors")
    p.add_argument("questions_file")
    p.add_argument("--method", choices=["3cosadd", "3cosmul"],
                   default="3cosadd",
                   help="scoring objective: compute-accuracy's additive "
                   "3CosAdd (default) or the multiplicative 3CosMul "
                   "(Levy & Goldberg 2014; gensim most_similar_cosmul)")

    p = sub.add_parser(
        "convert",
        help="normalize a similarity dataset (WordSim-353 / SimLex-999 / "
        "MEN / any delimited word-pair file) into the canonical "
        "word1,word2,score CSV the --eval-ws353 gate reads",
    )
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("--cols", default="0,1,2",
                   help="0-based columns of word1,word2,score (SimLex-999: "
                   "0,1,3)")
    p.add_argument("--delimiter", default=None,
                   help="explicit field delimiter (default: sniff , tab "
                   "then whitespace); a whitespace delimiter treats runs "
                   "of it as one separator, like the sniff")
    p.add_argument("--keep-case", action="store_true",
                   help="do not lowercase words")

    args = ap.parse_args(argv)

    if args.cmd == "convert":
        from .similarity import convert_pairs_file

        try:
            cols = tuple(int(c) for c in args.cols.split(","))
        except ValueError:
            print(f"error: --cols must be three integers, got {args.cols!r}",
                  file=sys.stderr)
            return 1
        if len(cols) != 3 or any(c < 0 for c in cols):
            print("error: --cols needs exactly three non-negative indices",
                  file=sys.stderr)
            return 1
        try:
            n = convert_pairs_file(
                args.src, args.dst, cols=cols, delimiter=args.delimiter,
                lower=not args.keep_case,
            )
        except (ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(json.dumps({"pairs_written": n, "dst": args.dst}))
        return 0

    vocab, W = _load(args)

    if args.cmd == "neighbors":
        try:
            for w, s in nearest_neighbors(W, vocab, args.word, k=args.k):
                print(f"{w:<24s} {s:+.4f}")
        except KeyError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    elif args.cmd == "analogy":
        try:
            for w, s in analogy_query(W, vocab, args.a, args.b, args.c, k=args.k):
                print(f"{w:<24s} {s:+.4f}")
        except KeyError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    elif args.cmd == "ws353":
        res = evaluate_pairs(W, vocab, load_word_pairs(args.pairs_file))
        print(json.dumps({
            "spearman": res.spearman, "pearson": res.pearson,
            "pairs_used": res.pairs_used, "pairs_total": res.pairs_total,
        }))
    elif args.cmd == "analogies":
        res = evaluate_analogies(
            W, vocab, args.questions_file, method=args.method
        )
        print(json.dumps({
            "method": args.method,
            "accuracy": res.accuracy,
            "correct": res.correct,
            "total": res.total,
            # previously computed but silently dropped: a question file
            # full of OOV/degenerate rows read as a clean 0-question pass
            "skipped_oov": res.skipped_oov,
            "skipped_degenerate": res.skipped_degenerate,
            "mean_gold_rank": res.mean_gold_rank,
            "by_section": res.by_section,
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
