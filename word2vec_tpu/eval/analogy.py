"""Google analogy evaluation (questions-words.txt format).

The other half of the BASELINE.json parity gate (the reference ships nothing
comparable, SURVEY §3.5). Protocol matches the original compute-accuracy tool:
3CosAdd over unit-normalized vectors, question words excluded from candidates,
questions with any OOV word skipped.

File format: `: section-name` headers, then `a b c d` lines meaning
a:b :: c:d  (predict d from b - a + c).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from ..data.vocab import Vocab


@dataclass
class AnalogyResult:
    accuracy: float
    correct: int
    total: int
    skipped_oov: int
    by_section: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    # Mean rank of the gold answer among candidates (1 = top). Accuracy
    # saturates once every gold ranks first; the rank stays continuous, so
    # parity harnesses keep sensitivity after both sides hit 100%. Tied
    # similarities take the average of their tied ranks
    # (count(>) + (count(==)+1)/2), so quantized embeddings (bf16 tables)
    # don't rank optimistically.
    mean_gold_rank: float = 0.0
    # Questions whose gold answer repeats a question word (d in {a,b,c}):
    # the exclusion mask makes them unanswerable by construction, so they
    # are skipped rather than scored at rank ~V. Generated grids never
    # produce these; malformed file-based question sets can.
    skipped_degenerate: int = 0


def load_questions(path: str) -> List[Tuple[str, List[Tuple[str, str, str, str]]]]:
    sections: List[Tuple[str, List]] = []
    current: List = []
    name = "(default)"
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if parts[0] == ":":
                if current:
                    sections.append((name, current))
                name = " ".join(parts[1:]) or "(unnamed)"
                current = []
            elif len(parts) == 4:
                current.append(tuple(p.lower() for p in parts))
    if current:
        sections.append((name, current))
    return sections


def evaluate_analogies(
    W: np.ndarray,
    vocab: Vocab,
    path: str,
    batch_size: int = 512,
    restrict_vocab: int = 30000,
    method: str = "3cosadd",
) -> AnalogyResult:
    """3CosAdd (default) or 3CosMul over a questions-words.txt file; see
    evaluate_analogy_sections for the protocol."""
    return evaluate_analogy_sections(
        W, vocab, load_questions(path), batch_size, restrict_vocab, method
    )


def evaluate_analogy_sections(
    W: np.ndarray,
    vocab: Vocab,
    sections: List[Tuple[str, List[Tuple[str, str, str, str]]]],
    batch_size: int = 512,
    restrict_vocab: int = 30000,
    method: str = "3cosadd",
) -> AnalogyResult:
    """3CosAdd (compute-accuracy) or 3CosMul (Levy & Goldberg 2014) with
    the compute-accuracy conventions.

    3CosMul scores each candidate d' as
    cos01(d',b) * cos01(d',c) / (cos01(d',a) + 1e-3) with cosines shifted
    to [0,1] — the multiplicative objective amplifies small differences in
    the larger terms and is the other standard protocol (gensim
    most_similar_cosmul); published numbers differ between the two, so
    the method is explicit in the result and CLI output.

    Takes in-memory (section, questions) lists so harnesses with generated
    questions (benchmarks/parity.py planted-relation corpus) share the exact
    scoring path the file-based CLI eval uses.

    restrict_vocab: candidate answers come from the most frequent N words
    (the original tool's `threshold`, default 30000), which also decides OOV
    skips — matching how published text8 numbers are produced.
    """
    if method not in ("3cosadd", "3cosmul"):
        raise ValueError(f"method must be 3cosadd or 3cosmul, got {method!r}")
    V = min(len(vocab), restrict_vocab) if restrict_vocab else len(vocab)
    # shared query kernel (serve/query): the restricted table is
    # row-normalized once and resident on device; score planes come back
    # as writable [chunk, V] f32 arrays for the mask/rank math below.
    from ..serve.query import get_engine

    eng = get_engine(W, vocab, restrict=V)

    correct = total = skipped = degenerate = 0
    rank_sum = 0.0
    by_section: Dict[str, Tuple[int, int]] = {}
    for name, questions in sections:
        ids = []
        for a, b, c, d in questions:
            if not all(w in vocab and vocab[w] < V for w in (a, b, c, d)):
                skipped += 1
            elif d in (a, b, c):
                # gold is excluded from candidates below — unanswerable
                degenerate += 1
            else:
                ids.append((vocab[a], vocab[b], vocab[c], vocab[d]))
        sec_correct = 0
        for i in range(0, len(ids), batch_size):
            chunk = np.asarray(ids[i : i + batch_size])
            if len(chunk) == 0:
                continue
            a, b, c, d = chunk.T
            if method == "3cosmul":
                # all three candidate-cosine planes, shifted to [0, 1]
                ca = (eng.cosine_planes(a) + 1.0) / 2.0
                cb = (eng.cosine_planes(b) + 1.0) / 2.0
                cc = (eng.cosine_planes(c) + 1.0) / 2.0
                sims = cb * cc / (ca + 1e-3)  # [chunk, V]
            else:
                sims = eng.analogy_planes(a, b, c)  # [chunk, V]
            rows = np.arange(len(chunk))
            sims[rows, a] = -np.inf  # exclude question words
            sims[rows, b] = -np.inf
            sims[rows, c] = -np.inf
            pred = sims.argmax(axis=1)
            sec_correct += int((pred == d).sum())
            gold = sims[rows, d][:, None]
            # average-of-tied-ranks: count(==) includes gold itself, so the
            # tie-free case reduces to the familiar count(>) + 1
            rank_sum += float(
                ((sims > gold).sum(axis=1) + ((sims == gold).sum(axis=1) + 1) / 2.0).sum()
            )
        by_section[name] = (sec_correct, len(ids))
        correct += sec_correct
        total += len(ids)
    return AnalogyResult(
        accuracy=correct / total if total else 0.0,
        correct=correct,
        total=total,
        skipped_oov=skipped,
        by_section=by_section,
        mean_gold_rank=rank_sum / total if total else 0.0,
        skipped_degenerate=degenerate,
    )
