"""Structured training logs.

The reference's observability is a single in-place printf of alpha + percent
every 100 sentences (Word2Vec.cpp:382-385). Here every log record is a dict
(step, epoch, alpha, loss, progress, words_per_sec) routed through a callback;
`progress_logger` renders the reference-style single-line console view with
the north-star words/sec added, and `jsonl_logger` writes machine-readable
JSONL for dashboards.
"""

from __future__ import annotations

import json
import sys
from typing import Callable, Dict, IO, Optional


def progress_logger(stream: IO = sys.stderr) -> Callable[[Dict], None]:
    """Reference-style one-line progress (Word2Vec.cpp:384) + words/sec."""

    def log(m: Dict) -> None:
        if "event" in m:
            # one-off event records (e.g. the resident-path resolution) get
            # their own line instead of crashing the \r progress format
            detail = " ".join(f"{k}={v}" for k, v in m.items() if k != "event")
            stream.write(f"\n[{m['event']}] {detail}\n")
        else:
            stream.write(
                f"\ralpha: {m['alpha']:.6f}  progress: {100 * m.get('progress', 0):6.2f}%  "
                f"loss: {m['loss']:.4f}  {m['words_per_sec']:,.0f} words/sec "
            )
        stream.flush()

    return log


def jsonl_logger(path: str) -> Callable[[Dict], None]:
    f = open(path, "a", buffering=1)

    def log(m: Dict) -> None:
        f.write(json.dumps(m) + "\n")

    return log


def tensorboard_logger(logdir: str) -> Callable[[Dict], None]:
    """Scalar summaries (loss, alpha, words/sec, progress) per step for
    TensorBoard — the SURVEY §5 "optional TensorBoard scalars" hook. Uses
    tensorboardX, which writes standard event files without a TF dependency.
    """
    from tensorboardX import SummaryWriter

    writer = SummaryWriter(logdir)

    def log(m: Dict) -> None:
        step = int(m.get("step", 0))
        for key in ("loss", "alpha", "words_per_sec", "progress"):
            if key in m:
                writer.add_scalar(f"train/{key}", float(m[key]), step)
        writer.flush()

    return log


def tee(*loggers: Optional[Callable[[Dict], None]]) -> Callable[[Dict], None]:
    active = [l for l in loggers if l is not None]

    def log(m: Dict) -> None:
        for l in active:
            l(m)

    return log
