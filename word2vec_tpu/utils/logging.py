"""Structured training logs.

The reference's observability is a single in-place printf of alpha + percent
every 100 sentences (Word2Vec.cpp:382-385). Here every log record is a dict
(step, epoch, alpha, loss, progress, words_per_sec, plus whatever health /
phase telemetry the run enables) routed through a callback; `progress_logger`
renders the reference-style single-line console view with the north-star
words/sec added, and `jsonl_logger` writes machine-readable JSONL for
dashboards.

Sinks are composed through `obs.export.MetricsHub` (one fan-out callable,
one close point); `tee` remains for direct library use. Every sink that
holds a resource exposes `.close()` so the hub — or an atexit fallback —
can flush it: a jsonl log that loses its tail on interpreter teardown is
worse than no log.
"""

from __future__ import annotations

import atexit
import json
import sys
from typing import Callable, Dict, IO, Optional


def progress_logger(stream: IO = sys.stderr) -> Callable[[Dict], None]:
    """Reference-style one-line progress (Word2Vec.cpp:384) + words/sec.

    Tolerates partial records: telemetry event records and health-only
    records need not carry loss/words_per_sec, and a missing key renders as
    its neutral value instead of raising KeyError mid-training."""

    def log(m: Dict) -> None:
        if "event" in m:
            # one-off event records (e.g. the resident-path resolution) get
            # their own line instead of crashing the \r progress format
            detail = " ".join(f"{k}={v}" for k, v in m.items() if k != "event")
            stream.write(f"\n[{m['event']}] {detail}\n")
        else:
            stream.write(
                f"\ralpha: {m.get('alpha', float('nan')):.6f}  "
                f"progress: {100 * m.get('progress', 0):6.2f}%  "
                f"loss: {m.get('loss', float('nan')):.4f}  "
                f"{m.get('words_per_sec', 0.0):,.0f} words/sec "
            )
        stream.flush()

    return log


def jsonl_logger(path: str) -> Callable[[Dict], None]:
    """Append machine-readable JSONL records to `path`.

    The returned callable carries a `.close()` (idempotent) that flushes and
    releases the file handle; it is also registered with atexit as a
    fallback, so a driver that never reaches its close point still flushes
    the log on interpreter exit instead of leaking the handle."""
    f = open(path, "a", buffering=1)
    state = {"open": True}

    def log(m: Dict) -> None:
        if state["open"]:
            f.write(json.dumps(m, default=str) + "\n")

    def close() -> None:
        if state["open"]:
            state["open"] = False
            try:
                f.flush()
            finally:
                f.close()

    log.close = close
    atexit.register(close)
    return log


def tensorboard_logger(logdir: str) -> Callable[[Dict], None]:
    """Scalar summaries (loss, alpha, words/sec, progress, health counters)
    per step for TensorBoard — the SURVEY §5 "optional TensorBoard scalars"
    hook. Uses tensorboardX, which writes standard event files without a TF
    dependency; when tensorboardX is not installed the sink degrades to a
    one-line warning and a no-op (a missing optional viewer must not kill a
    training run that only incidentally asked for it).
    """
    try:
        from tensorboardX import SummaryWriter
    except ImportError:
        import warnings

        warnings.warn(
            "tensorboardX is not installed; TensorBoard logging to "
            f"{logdir!r} is disabled (pip install tensorboardX to enable)",
            stacklevel=2,
        )

        def noop(m: Dict) -> None:
            pass

        noop.close = lambda: None
        return noop

    writer = SummaryWriter(logdir)

    def log(m: Dict) -> None:
        if "event" in m:
            return
        step = int(m.get("step", 0))
        for key, val in m.items():
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            if key in ("step", "epoch"):
                continue
            writer.add_scalar(f"train/{key}", float(val), step)
        writer.flush()

    log.close = writer.close
    return log


def tee(*loggers: Optional[Callable[[Dict], None]]) -> Callable[[Dict], None]:
    """Minimal fan-out for direct library use; drivers use obs.MetricsHub
    (same contract, plus sink close handling)."""
    active = [l for l in loggers if l is not None]

    def log(m: Dict) -> None:
        for l in active:
            l(m)

    return log
