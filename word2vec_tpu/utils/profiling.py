"""Tracing / profiling subsystem.

The reference has none (SURVEY §5: the only perf artifact is the `-Ofast
-march=native` build comment, main.cpp:2). The TPU-native replacements:

  * `trace(logdir)` — context manager around `jax.profiler.trace`; captures a
    device trace (XLA ops, fusion boundaries, HBM traffic) viewable in
    TensorBoard / xprof. Wrap any training region with it; the CLI exposes it
    as `--profile DIR`.
  * `annotate(name)` — host-side named region that shows up on the trace
    timeline (wraps `jax.profiler.TraceAnnotation`), for marking batcher /
    transfer / step phases.
  * `StepTimer` — a `jax.block_until_ready` wall-clock harness for steady-
    state step timing with percentile stats, used by benchmarks/ablate.py
    and bench.py-style meters. Timing without blocking measures dispatch,
    not compute — this forces the sync.

Words/sec metering itself lives in the Trainer's log records
(utils/logging.py); this module is for *why is the step slow*, not *how fast
is it going*.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, List, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler device+host trace into `logdir`.

    View with: tensorboard --logdir <logdir>  (or xprof). Safe on any
    backend; on TPU the trace includes per-op device timing.
    """
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Named region on the profiler timeline (host side)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Steady-state step timing: call `lap(result)` once per step.

    `lap` blocks on the step's output before reading the clock, so each
    recorded lap is true wall time of (host overhead + device compute),
    not dispatch latency. Skips the first `warmup` laps (compile).
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.laps: List[float] = []
        self._seen = 0
        self._t: Optional[float] = None

    def lap(self, result) -> None:
        jax.block_until_ready(result)
        now = time.perf_counter()
        if self._t is not None:
            self._seen += 1
            if self._seen > self.warmup:
                self.laps.append(now - self._t)
        self._t = now

    def stats(self) -> dict:
        if not self.laps:
            return {"laps": 0}
        laps = sorted(self.laps)
        n = len(laps)
        # nearest-rank percentile: ceil(q*n) - 1
        p90 = max(0, -(-9 * n // 10) - 1)
        return {
            "laps": n,
            "mean_ms": 1e3 * sum(laps) / n,
            "p50_ms": 1e3 * laps[n // 2],
            "p90_ms": 1e3 * laps[p90],
            "min_ms": 1e3 * laps[0],
            "max_ms": 1e3 * laps[-1],
        }
