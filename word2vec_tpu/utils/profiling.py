"""Tracing / profiling subsystem.

The reference has none (SURVEY §5: the only perf artifact is the `-Ofast
-march=native` build comment, main.cpp:2). The TPU-native replacements:

  * `trace(logdir)` — context manager around `jax.profiler.trace`; captures a
    device trace (XLA ops, fusion boundaries, HBM traffic) viewable in
    TensorBoard / xprof. Wrap any training region with it; the CLI exposes it
    as `--profile DIR`.
  * `annotate(name)` — host-side named region that shows up on the trace
    timeline (wraps `jax.profiler.TraceAnnotation`), for marking batcher /
    transfer / step phases.
  * `StepTimer` — a `jax.block_until_ready` wall-clock harness for steady-
    state step timing with percentile stats, used by benchmarks/ablate.py
    and bench.py-style meters. Timing without blocking measures dispatch,
    not compute — this forces the sync.
  * `step_flops` / `step_hbm_bytes` — analytic per-optimizer-step work
    accounting (algorithmic FLOPs and HBM traffic) for every kernel route
    (pair / band-XLA / band-Pallas / positional hs). These are the shared
    counters behind the autotuned execution planner's cost model
    (tune/cost_model.py) and bench.py's predicted-cost record: one
    definition, so the number the planner ranks candidates by is the same
    number the bench artifact reports.

Words/sec metering itself lives in the Trainer's log records
(utils/logging.py); this module is for *why is the step slow*, not *how fast
is it going*.
"""

from __future__ import annotations

import contextlib
import math
import time
from typing import Dict, Iterator, List, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler device+host trace into `logdir`.

    View with: tensorboard --logdir <logdir>  (or xprof). Safe on any
    backend; on TPU the trace includes per-op device timing.
    """
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Named region on the profiler timeline (host side)."""
    return jax.profiler.TraceAnnotation(name)


def lap_stats(laps: List[float]) -> dict:
    """Percentile stats over a list of wall-clock laps (seconds -> ms).

    Shared by StepTimer and obs/phases.PhaseRecorder so the p50/p90 a bench
    reports and the p50/p90 a phase breakdown reports are the same math
    (nearest-rank percentile: ceil(q*n) - 1)."""
    if not laps:
        return {"laps": 0}
    laps = sorted(laps)
    n = len(laps)
    p90 = max(0, -(-9 * n // 10) - 1)
    return {
        "laps": n,
        "mean_ms": 1e3 * sum(laps) / n,
        "p50_ms": 1e3 * laps[n // 2],
        "p90_ms": 1e3 * laps[p90],
        "min_ms": 1e3 * laps[0],
        "max_ms": 1e3 * laps[-1],
    }


class StepTimer:
    """Steady-state step timing: call `lap(result)` once per step.

    `lap` blocks on the step's output before reading the clock, so each
    recorded lap is true wall time of (host overhead + device compute),
    not dispatch latency. Skips the first `warmup` laps (compile).
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.laps: List[float] = []
        self._seen = 0
        self._t: Optional[float] = None

    def lap(self, result) -> None:
        jax.block_until_ready(result)
        now = time.perf_counter()
        if self._t is not None:
            self._seen += 1
            if self._seen > self.warmup:
                self.laps.append(now - self._t)
        self._t = now

    def stats(self) -> dict:
        return lap_stats(self.laps)


# --------------------------------------------------------------------------
# Analytic per-step work accounting (the planner's and bench.py's counters)
# --------------------------------------------------------------------------

def _dtype_bytes(name: str) -> int:
    return 2 if name == "bfloat16" else 4


def step_geometry(config, vocab_size: int) -> Dict:
    """Resolved step-shape geometry for one dispatched optimizer step.

    Pure shape math (no jax): B rows x L positions, the band chunking
    (ops/banded.resolve_chunk) and the negative-pool shape, as the kernels
    will actually realize them. The planner, the cost model, and bench.py
    all read step shapes from here so they can never disagree.
    """
    from ..ops.banded import resolve_chunk

    B, L, W = config.batch_rows, config.max_sentence_len, config.window
    S = resolve_chunk(L, W, config.band_chunk)
    if S == 0:
        C, slab, plane = 1, L, B * L * L
    else:
        C = -(-L // S)
        slab = S + 2 * W
        plane = B * C * S * slab
    NB = 1 if config.negative_scope == "batch" else B
    return {
        "B": B,
        "L": L,
        "W": W,
        "d": config.word_dim,
        "S": S,
        "C": C,
        "slab": slab,
        "plane": plane,
        "KP": config.shared_negatives,
        "NB": NB,
        "K": config.negative,
        "avg_path": max(1, math.ceil(math.log2(max(2, vocab_size)))),
        "layout": getattr(config, "table_layout", "split"),
        "kernel": config.resolved_kernel,
        "route": (
            "pair"
            if config.resolved_kernel == "pair"
            else ("band-hs" if config.use_hs else "band-ns")
        ),
        "backend": config.band_backend,
        "table_bytes": _dtype_bytes(config.dtype),
        "compute_bytes": _dtype_bytes(config.compute_dtype),
    }


def step_flops(config, vocab_size: int) -> float:
    """Algorithmic FLOPs one optimizer step executes (not model-useful
    FLOPs — masked band slots count, exactly as the hardware pays them).

    Band ns: three band contractions over the [B, C, S, S+2W] logit plane
    (qk logits, sv center-grad, vs context-grad) at 2*plane*d each, plus the
    shared-negative side's three [B*L, KP] contractions. Pair: the unrolled
    P = B*L*2W enumeration against K+1 targets, 3 * 2d per target
    (bench.model_flops_per_target's accounting). Positional hs: like pair
    with the padded Huffman path length in place of K+1.
    """
    g = step_geometry(config, vocab_size)
    B, L, d, W = g["B"], g["L"], g["d"], g["W"]
    if g["route"] == "pair":
        targets = (g["K"] + 1) if config.use_ns else g["avg_path"]
        return 6.0 * B * L * 2 * W * targets * d
    if g["route"] == "band-hs":
        # positional kernel: every (center, path-slot) pair scores/updates a
        # d-row; the padded path length bounds it
        return 6.0 * B * L * g["avg_path"] * d + 12.0 * B * L * g["avg_path"]
    # band-ns: positive band plane + shared-negative block + elementwise
    return (
        6.0 * g["plane"] * d
        + 6.0 * B * L * g["KP"] * d
        + 12.0 * g["plane"]
        + 8.0 * B * L * g["KP"]
    )


def step_hbm_bytes(config, vocab_size: int) -> Dict[str, float]:
    """Analytic HBM traffic of one optimizer step, split by origin:

      table_io       — embedding-row gathers + read-modify-write scatters
      intermediates  — materialized row tensors / logit planes re-read by
                       later ops (XLA band chain; ~0 for the fused Pallas
                       kernel, which keeps them in VMEM — the traffic
                       contrast prose-documented in ops/pallas_band.py)
      layout_copies  — the {0,2,1}<->{2,1,0} copies XLA inserts around the
                       overlap-add chain (measured 2.14 ms = 27% of the r2
                       step; absent on the pallas, pallas_oa and
                       slab-scatter paths — pallas_oa replaces the chain
                       with a VMEM overlap-add kernel, ops/pallas_overlap)
      scatter_rows   — a COUNT, not bytes: rows fed to the step's table
                       scatter-adds. The r2 trace measured XLA's sorted
                       scatter at ~21 ns/row REGARDLESS of row width
                       (PERF.md "Why not a Pallas scatter kernel"), so
                       scatter cost is row machinery the byte roofline
                       cannot see — the cost model prices this count
                       separately (tune/cost_model.SCATTER_SEC_PER_ROW),
                       and it is the term the table LAYOUT moves: the
                       unified [V, 2, d] slab scatters the shared sorted
                       id set once at doubled width instead of twice.
      dma_rows       — a COUNT: per-row DMAs the pallas_fused kernels
                       issue INSIDE the step (in-kernel gathers + the
                       aliased scatter's read-modify-writes). Zero for
                       every other backend (their gathers/scatters are
                       priced as table_io bytes + scatter_rows). Priced
                       by tune/cost_model.DMA_SEC_PER_ROW — the fused
                       step's whole bet is that back-to-back in-kernel
                       DMAs underprice XLA's scatter row machinery, which
                       is exactly the sensitivity the counterfactual-flip
                       test pins (tests/test_tune.py).
      programs       — a COUNT: separately scheduled device programs the
                       step's op chain splits into (gathers / band
                       matmuls / overlap-add / scatters). The dispatch
                       tail the fused step exists to delete: ~1 program
                       per kernel for pallas_fused vs the XLA chain's
                       ~9 (tune/cost_model.PROGRAM_GAP_MS prices the
                       inter-program gaps).
      total          — sum of the BYTE terms (scatter_rows/dma_rows/
                       programs excluded)

    Absolute bytes are a model, not a measurement — the value is in the
    ORDERING (pallas < xla band << pair at bench shapes) and the terms'
    scaling, which the planner's pruning relies on and
    tests/test_tune.py pins.
    """
    g = step_geometry(config, vocab_size)
    B, L, d = g["B"], g["L"], g["d"]
    tb, f32 = g["table_bytes"], 4
    if g["route"] == "pair":
        P = B * L * 2 * g["W"]
        targets = (g["K"] + 1) if config.use_ns else g["avg_path"]
        gathers = (P + P * targets) * d * tb
        scatters = 3.0 * (P + P * targets) * d * tb  # RMW + index machinery
        inter = 2.0 * P * targets * f32  # logits/grads planes
        return {
            "table_io": gathers + scatters,
            "intermediates": inter,
            "layout_copies": 0.0,
            # per-pair enumeration scatters every (pair, target) row
            "scatter_rows": float(P + P * targets),
            "dma_rows": 0.0,
            "programs": 4.0,
            "total": gathers + scatters + inter,
        }
    if g["route"] == "band-hs":
        rows = B * L * g["avg_path"]
        table_io = 4.0 * rows * d * tb
        inter = 4.0 * B * L * d * f32
        # positional kernel: the padded [B, L+2W, C] path-row buffer is the
        # syn1 scatter (PERF.md "~21 ms of row machinery" at dim200 scale);
        # the two-tier split replaces the dense-prefix levels with a slice
        # add, leaving only the short tails (~avg_path - log2(top)) to
        # scatter. Plus the B*L center/context rows on emb_in.
        path = g["avg_path"]
        if getattr(config, "hs_dense_top", 0):
            path = max(
                1.0, path - math.log2(max(2, config.hs_dense_top))
            )
        return {
            "table_io": table_io,
            "intermediates": inter,
            "layout_copies": 0.0,
            "scatter_rows": float(B * (L + 2 * g["W"]) * path + B * L),
            "dma_rows": 0.0,
            "programs": 6.0,
            "total": table_io + inter,
        }
    # --- band ns ---
    ein_rows = B * L * d
    slab_rows = B * g["C"] * g["slab"] * d
    neg_rows = g["NB"] * g["KP"] * d
    # gathers once + scatter read-modify-write (~2x) for each touched row set
    table_io = 3.0 * (ein_rows + slab_rows + neg_rows) * tb
    # Scatter-row machinery (the per-LAYOUT term): token-order paths issue
    # two B*L-row sorted scatters (one per table) + the negative rows; the
    # unified layout covers both tables with ONE B*L-row scatter at doubled
    # width; slab-space paths (slab_scatter, the fused pallas kernel) trade
    # one token-order scatter for a (S+2W)/S-larger slab-id scatter.
    slab_side = g["backend"] == "pallas" or (config.slab_scatter and g["S"] > 0)
    dma_rows = 0.0
    programs = 9.0  # the XLA chain's gather/matmul/overlap-add/scatter ops
    if slab_side:
        scatter_rows = B * L + B * g["C"] * g["slab"] + g["NB"] * g["KP"]
    elif g["layout"] == "unified":
        scatter_rows = B * L + g["NB"] * g["KP"]
    else:
        scatter_rows = 2 * B * L + g["NB"] * g["KP"]
    if g["backend"] == "pallas_fused":
        # Fully-fused step (ops/pallas_step.py): gathers and the doubled-
        # width sorted scatter happen INSIDE the kernels as per-row DMAs
        # (dma_rows), and the only XLA scatter left is the negative-row
        # tail. The intermediates term collapses to the token-order
        # [B, L, 2, d] gradient stack crossing HBM once out of the grad
        # kernel and once into the scatter kernel — the band planes, the
        # gathered row stack and the overlap-add chain never leave VMEM.
        scatter_rows = g["NB"] * g["KP"]
        dma_rows = float(
            B * L                          # center rows, both planes/DMA
            + B * g["C"] * g["slab"]       # context slab rows
            + g["NB"] * g["KP"]            # negative rows
            + 2 * B * L                    # scatter read-modify-writes
        )
        programs = 3.0  # grad kernel + scatter kernel + negative scatter
        inter = 4.0 * ein_rows * f32  # the [B, L, 2, d] grad stack, out+in
        copies = 0.0
    elif g["backend"] == "pallas":
        # each row tensor crosses HBM exactly once in and once out
        # (kernel outputs d_h/d_ctx/d_neg in f32)
        inter = (ein_rows + slab_rows + neg_rows) * tb + (
            B * g["C"] * g["S"] * d + slab_rows + neg_rows
        ) * f32
        copies = 0.0
        # one compute kernel + XLA gathers and the three scatters;
        # pallas_oa stays at the XLA chain's count — its kernel replaces
        # the overlap-add chain 1:1 (the win there was bytes, not programs)
        programs = 6.0
    elif g["backend"] == "pallas_oa" and g["S"] > 0:
        # the XLA chain's traffic, with the overlap-add done in VMEM by
        # ops/pallas_overlap.py: the layout-copy term disappears and the
        # kernel itself streams the slab-space grad plane in and the
        # token-order plane out once (~2x slab_rows, sequential — no
        # LAYOUT_COPY_INEFFICIENCY multiplier applies)
        inter = (
            4.0 * (ein_rows + slab_rows) * g["compute_bytes"]
            + 4.0 * g["plane"] * f32
            + 2.0 * slab_rows * f32
        )
        copies = 0.0
    else:
        # XLA chain: row tensors re-read by the four band contractions, and
        # the [B, C, S, S+2W] logit/grad planes round-trip between them
        inter = 4.0 * (ein_rows + slab_rows) * g["compute_bytes"] + 4.0 * g[
            "plane"
        ] * f32
        copies = (
            0.0
            if (config.slab_scatter or g["S"] == 0)
            else 3.0 * slab_rows * f32
        )
    return {
        "table_io": table_io,
        "intermediates": inter,
        "layout_copies": copies,
        "scatter_rows": float(scatter_rows),
        "dma_rows": dma_rows,
        "programs": programs,
        "total": table_io + inter + copies,
    }
