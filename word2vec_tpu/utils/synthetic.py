"""Synthetic corpora for benchmarks, dry runs, and cross-implementation parity.

No-network environments have no text8; two generators stand in:

  * `zipf_vocab`/`zipf_corpus_ids` — a Zipf(1.0) token stream over a
    text8-sized vocabulary. Reproduces the performance-relevant corpus
    properties (vocab size, frequency skew, subsampling hit rate,
    negative-table shape) so throughput numbers transfer. No semantic
    structure — not for accuracy evaluation.
  * `topic_corpus`/`topic_similarity_pairs` — sentences with PLANTED topic
    structure: words of the same topic co-occur, so a correct word2vec
    recovers same-topic similarity. This is the accuracy-parity stand-in for
    WS-353 (BASELINE.md gate) when the real datasets are unreachable: train
    the C++ reference and this framework on the same generated stream and
    compare their eval scores (benchmarks/parity.py).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..data.vocab import Vocab


def zipf_vocab(vocab_size: int = 71000, total_words: int = 17_000_000) -> Vocab:
    """A vocab whose counts follow Zipf's law, like text8's (~71k words kept
    at min_count=5 out of ~17M tokens)."""
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    weights = 1.0 / ranks
    counts = np.maximum(
        (weights / weights.sum() * total_words).astype(np.int64), 5
    )
    words = [f"w{i}" for i in range(vocab_size)]
    return Vocab(words, counts)


def zipf_corpus_ids(
    vocab: Vocab, num_tokens: int, seed: int = 0, sentence_len: int = 1000
) -> list:
    """Token-id sentences drawn from the vocab's empirical distribution,
    chunked like the reference's text8 reader (main.cpp:66)."""
    rng = np.random.default_rng(seed)
    p = vocab.counts / vocab.counts.sum()
    flat = rng.choice(len(vocab), size=num_tokens, p=p).astype(np.int32)
    return [
        flat[i : i + sentence_len] for i in range(0, num_tokens, sentence_len)
    ]


def topic_corpus(
    n_topics: int = 8,
    words_per_topic: int = 40,
    shared_words: int = 20,
    n_tokens: int = 200_000,
    span_len: int = 20,
    p_shared: float = 0.25,
    seed: int = 0,
) -> Tuple[List[str], Dict[str, int]]:
    """A flat token stream with planted topic structure.

    The stream is a sequence of `span_len`-token spans; each span draws one
    topic and emits that topic's content words (Zipf-weighted within the
    topic) mixed with topic-agnostic shared words. Same-topic words therefore
    co-occur within any window <= span_len while cross-topic words co-occur
    only through shared words — exactly the contrast word2vec's objective
    should recover.

    Returns (tokens, topic_of): the flat token list (write it whitespace-
    separated for the reference's text8 reader, main.cpp:63-92) and the
    content-word -> topic map for building eval pairs.
    """
    rng = np.random.default_rng(seed)
    topic_words = [
        [f"t{t}w{i}" for i in range(words_per_topic)] for t in range(n_topics)
    ]
    shared = [f"s{i}" for i in range(shared_words)]
    zipf = 1.0 / np.arange(1, words_per_topic + 1)
    zipf /= zipf.sum()
    zipf_s = 1.0 / np.arange(1, shared_words + 1)
    zipf_s /= zipf_s.sum()

    tokens: List[str] = []
    n_spans = n_tokens // span_len
    topics = rng.integers(0, n_topics, size=n_spans)
    for t in topics:
        is_shared = rng.random(span_len) < p_shared
        content_ids = rng.choice(words_per_topic, size=span_len, p=zipf)
        shared_ids = rng.choice(shared_words, size=span_len, p=zipf_s)
        pool = topic_words[t]
        for k in range(span_len):
            tokens.append(
                shared[shared_ids[k]] if is_shared[k] else pool[content_ids[k]]
            )
    topic_of = {w: t for t, pool in enumerate(topic_words) for w in pool}
    return tokens, topic_of


def analogy_corpus(
    n_pairs: int = 16,
    words_per_topic: int = 20,
    marker_words: int = 20,
    n_tokens: int = 300_000,
    span_len: int = 20,
    p_pairword: float = 0.3,
    p_marker: float = 0.25,
    seed: int = 0,
) -> Tuple[List[str], List[Tuple[str, str, str, str]]]:
    """A token stream with planted RELATION structure for analogy parity.

    Word pairs (base_i, marked_i), one per topic i: both draw their contexts
    from topic i's pool, but marked_i's spans additionally mix in words from
    one SHARED marker pool. Distributionally, marked_i - base_i then points
    along the same marker direction for every i — the mechanism 3CosAdd
    (b - a + c -> d) exploits in real corpora (king-queen etc.), so a
    correct word2vec recovers the planted analogies and two implementations
    can be compared on the SAME questions (the Google-analogy half of the
    BASELINE parity gate, eval/analogy.py protocol).

    Returns (tokens, questions) with questions = all ordered pairs
    (base_i, marked_i, base_j, marked_j), i != j.
    """
    rng = np.random.default_rng(seed)
    topics = [
        [f"r{i}c{k}" for k in range(words_per_topic)] for i in range(n_pairs)
    ]
    markers = [f"mk{k}" for k in range(marker_words)]
    zipf_t = 1.0 / np.arange(1, words_per_topic + 1)
    zipf_t /= zipf_t.sum()
    zipf_m = 1.0 / np.arange(1, marker_words + 1)
    zipf_m /= zipf_m.sum()

    tokens: List[str] = []
    n_spans = n_tokens // span_len
    for s in range(n_spans):
        i = int(rng.integers(n_pairs))
        marked = bool(rng.integers(2))
        pairword = f"b{i}m" if marked else f"b{i}"
        r = rng.random(span_len)
        ctx_t = rng.choice(words_per_topic, size=span_len, p=zipf_t)
        ctx_m = rng.choice(marker_words, size=span_len, p=zipf_m)
        for k in range(span_len):
            if r[k] < p_pairword:
                tokens.append(pairword)
            elif marked and r[k] < p_pairword + p_marker:
                tokens.append(markers[ctx_m[k]])
            else:
                tokens.append(topics[i][ctx_t[k]])
    questions = [
        (f"b{i}", f"b{i}m", f"b{j}", f"b{j}m")
        for i in range(n_pairs)
        for j in range(n_pairs)
        if i != j
    ]
    return tokens, questions


def topic_similarity_pairs(
    topic_of: Dict[str, int],
    n_pairs: int = 400,
    seed: int = 0,
    same_score: float = 8.0,
    diff_score: float = 2.0,
) -> List[Tuple[str, str, float]]:
    """WS-353-shaped (word1, word2, gold) pairs from the planted topics:
    half same-topic (high gold), half cross-topic (low gold). Spearman of
    model cosines against these golds measures structure recovery; comparing
    two implementations' Spearman on the SAME pairs is the parity gate."""
    rng = np.random.default_rng(seed)
    by_topic: Dict[int, List[str]] = {}
    for w, t in topic_of.items():
        by_topic.setdefault(t, []).append(w)
    topics = sorted(by_topic)
    pairs: List[Tuple[str, str, float]] = []
    for i in range(n_pairs):
        if i % 2 == 0:
            t = topics[rng.integers(len(topics))]
            a, b = rng.choice(by_topic[t], size=2, replace=False)
            pairs.append((str(a), str(b), same_score))
        else:
            t1, t2 = rng.choice(topics, size=2, replace=False)
            a = rng.choice(by_topic[t1])
            b = rng.choice(by_topic[t2])
            pairs.append((str(a), str(b), diff_score))
    return pairs
