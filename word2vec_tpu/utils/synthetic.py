"""Synthetic corpora for benchmarks, dry runs, and cross-implementation parity.

No-network environments have no text8; two generators stand in:

  * `zipf_vocab`/`zipf_corpus_ids` — a Zipf(1.0) token stream over a
    text8-sized vocabulary. Reproduces the performance-relevant corpus
    properties (vocab size, frequency skew, subsampling hit rate,
    negative-table shape) so throughput numbers transfer. No semantic
    structure — not for accuracy evaluation.
  * `topic_corpus`/`topic_similarity_pairs` — sentences with PLANTED topic
    structure: words of the same topic co-occur, so a correct word2vec
    recovers same-topic similarity. This is the accuracy-parity stand-in for
    WS-353 (BASELINE.md gate) when the real datasets are unreachable: train
    the C++ reference and this framework on the same generated stream and
    compare their eval scores (benchmarks/parity.py).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

import numpy as np

from ..data.vocab import Vocab


def zipf_vocab(vocab_size: int = 71000, total_words: int = 17_000_000) -> Vocab:
    """A vocab whose counts follow Zipf's law, like text8's (~71k words kept
    at min_count=5 out of ~17M tokens)."""
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    weights = 1.0 / ranks
    counts = np.maximum(
        (weights / weights.sum() * total_words).astype(np.int64), 5
    )
    words = [f"w{i}" for i in range(vocab_size)]
    return Vocab(words, counts)


def zipf_corpus_ids(
    vocab: Vocab, num_tokens: int, seed: int = 0, sentence_len: int = 1000
) -> list:
    """Token-id sentences drawn from the vocab's empirical distribution,
    chunked like the reference's text8 reader (main.cpp:66)."""
    rng = np.random.default_rng(seed)
    p = vocab.counts / vocab.counts.sum()
    flat = rng.choice(len(vocab), size=num_tokens, p=p).astype(np.int32)
    return [
        flat[i : i + sentence_len] for i in range(0, num_tokens, sentence_len)
    ]


def topic_corpus(
    n_topics: int = 8,
    words_per_topic: int = 40,
    shared_words: int = 20,
    n_tokens: int = 200_000,
    span_len: int = 20,
    p_shared: float = 0.25,
    zipf_exponent: float = 1.0,
    seed: int = 0,
) -> Tuple[List[str], Dict[str, int]]:
    """A flat token stream with planted topic structure.

    The stream is a sequence of `span_len`-token spans; each span draws one
    topic and emits that topic's content words (Zipf-weighted within the
    topic) mixed with topic-agnostic shared words. Same-topic words therefore
    co-occur within any window <= span_len while cross-topic words co-occur
    only through shared words — exactly the contrast word2vec's objective
    should recover.

    Returns (tokens, topic_of): the flat token list (write it whitespace-
    separated for the reference's text8 reader, main.cpp:63-92) and the
    content-word -> topic map for building eval pairs.
    """
    rng = np.random.default_rng(seed)
    topic_words = [
        [f"t{t}w{i}" for i in range(words_per_topic)] for t in range(n_topics)
    ]
    shared = [f"s{i}" for i in range(shared_words)]
    zipf = 1.0 / np.arange(1, words_per_topic + 1) ** zipf_exponent
    zipf /= zipf.sum()
    zipf_s = 1.0 / np.arange(1, shared_words + 1) ** zipf_exponent
    zipf_s /= zipf_s.sum()

    tokens: List[str] = []
    n_spans = n_tokens // span_len
    topics = rng.integers(0, n_topics, size=n_spans)
    for t in topics:
        is_shared = rng.random(span_len) < p_shared
        content_ids = rng.choice(words_per_topic, size=span_len, p=zipf)
        shared_ids = rng.choice(shared_words, size=span_len, p=zipf_s)
        pool = topic_words[t]
        for k in range(span_len):
            tokens.append(
                shared[shared_ids[k]] if is_shared[k] else pool[content_ids[k]]
            )
    topic_of = {w: t for t, pool in enumerate(topic_words) for w in pool}
    return tokens, topic_of


def analogy_corpus(
    n_rows: int = 8,
    n_cols: int = 4,
    words_per_pool: int = 20,
    n_tokens: int = 300_000,
    span_len: int = 20,
    p_cell: float = 0.2,
    seed: int = 0,
) -> Tuple[List[str], List[Tuple[str, str, str, str]]]:
    """A token stream with planted COMPOSITIONAL structure for analogy parity.

    A grid of cell words c{i}_{j}: each span picks a grid cell (i, j) and
    emits the cell word mixed with words from row pool i and column pool j.
    Distributionally a cell word is then row_i + col_j, so

        c{i}_{k} - c{i}_{j} + c{l}_{j}  ->  row_l + col_k  =  c{l}_{k}

    — exactly the additive mechanism 3CosAdd exploits in real corpora
    (king - man + woman -> queen), with the row pools playing "semantic"
    content and the column pools the shared relation (tense/gender/...).
    Row-pool words lack the column component and column-pool words lack the
    row component, so the planted answer beats both candidate families only
    when BOTH components were learned: a real instrument, unlike a
    same-topic-nearest-neighbor test. Two implementations trained on the
    same stream are compared on the SAME questions (the Google-analogy half
    of the BASELINE parity gate, eval/analogy.py protocol; an earlier
    marker-pool design was unrecoverable by construction — the markers
    co-occurred with the whole topic pool, so content words absorbed the
    relation direction and crowded out every answer).

    Returns (tokens, questions) with questions = all
    (c{i}_{j}, c{i}_{k}, c{l}_{j}, c{l}_{k}), i != l, j != k.
    """
    rng = np.random.default_rng(seed)
    rows = [
        [f"row{i}w{k}" for k in range(words_per_pool)] for i in range(n_rows)
    ]
    cols = [
        [f"col{j}w{k}" for k in range(words_per_pool)] for j in range(n_cols)
    ]
    zipf = 1.0 / np.arange(1, words_per_pool + 1)
    zipf /= zipf.sum()

    tokens: List[str] = []
    n_spans = n_tokens // span_len
    for _ in range(n_spans):
        i = int(rng.integers(n_rows))
        j = int(rng.integers(n_cols))
        r = rng.random(span_len)
        ctx_r = rng.choice(words_per_pool, size=span_len, p=zipf)
        ctx_c = rng.choice(words_per_pool, size=span_len, p=zipf)
        p_pool = p_cell + (1.0 - p_cell) / 2.0
        for k in range(span_len):
            if r[k] < p_cell:
                tokens.append(f"c{i}_{j}")
            elif r[k] < p_pool:
                tokens.append(rows[i][ctx_r[k]])
            else:
                tokens.append(cols[j][ctx_c[k]])
    questions = [
        (f"c{i}_{j}", f"c{i}_{k}", f"c{l}_{j}", f"c{l}_{k}")
        for i in range(n_rows)
        for l in range(n_rows)  # noqa: E741
        for j in range(n_cols)
        for k in range(n_cols)
        if i != l and j != k
    ]
    return tokens, questions


def graded_pair_corpus(
    n_pairs: int = 32,
    pool_words: int = 12,
    n_tokens: int = 240_000,
    span_len: int = 20,
    alpha_lo: float = 0.06,
    alpha_hi: float = 0.94,
    p_center: float = 0.3,
    seed: int = 0,
) -> Tuple[List[str], List[Tuple[str, str, float]]]:
    """A token stream with GRADED planted similarity + its gold pairs.

    The two-level topic golds (topic_similarity_pairs: same=8.0/diff=2.0)
    saturate Spearman at the 0.866 tie ceiling — every parity artifact
    since r2 showed the identical value, so the metric had stopped
    discriminating (VERDICT r4 weak item 5). This generator restores a
    fully graded axis: pair k's words (a{k}, b{k}) draw their context from
    a pair-SHARED pool with probability alpha_k and from per-side PRIVATE
    pools otherwise, with the alphas on a unique grid in
    [alpha_lo, alpha_hi]. True distributional similarity between a{k} and
    b{k} is strictly monotone in alpha_k (their context distributions
    overlap exactly on the shared pool's mass), so gold = alpha_k gives
    n_pairs UNIQUE ranks and model-cosine Spearman against them moves
    continuously with training quality instead of clipping at a tie
    ceiling.

    Spans alternate center and context tokens so every center occurrence
    sits inside a window of its own context draws (any window >= 1 sees
    the planted distribution). Returns (tokens, pairs) with
    pairs = [(a_k, b_k, alpha_k)] sorted by k.
    """
    rng = np.random.default_rng(seed)
    alphas = np.linspace(alpha_lo, alpha_hi, n_pairs)
    zipf = 1.0 / np.arange(1, pool_words + 1)
    zipf /= zipf.sum()

    tokens: List[str] = []
    n_spans = n_tokens // span_len
    ks = rng.integers(0, n_pairs, size=n_spans)
    sides = rng.integers(0, 2, size=n_spans)
    for k, side in zip(ks, sides):
        center = f"g{k}{'ab'[side]}"
        shared_draw = rng.random(span_len) < alphas[k]
        ids = rng.choice(pool_words, size=span_len, p=zipf)
        coin = rng.random(span_len) < p_center
        for t in range(span_len):
            if coin[t]:
                tokens.append(center)
            elif shared_draw[t]:
                tokens.append(f"gs{k}w{ids[t]}")
            else:
                tokens.append(f"gp{k}{'ab'[side]}w{ids[t]}")
    pairs = [
        (f"g{k}a", f"g{k}b", float(alphas[k])) for k in range(n_pairs)
    ]
    return tokens, pairs


def mixed_eval_corpus(
    n_tokens: int = 4_000_000,
    graded_frac: float = 0.25,
    n_pairs: int = 48,
    span_len: int = 20,
    seed: int = 0,
    **topic_kw,
) -> Tuple[List[str], Dict[str, int], List[Tuple[str, str, float]]]:
    """Topic corpus with graded-overlap spans interleaved: ONE training
    stream that carries BOTH quality instruments.

    The pure graded corpus at quality_full scale is unrepresentative —
    n_pairs=48 gives a ~1.8k-word vocab, so 4M tokens hammer every row
    (trust-region engagement dominates; the r5 phase-3 run measured
    clip_engaged 41k with spearman_graded 0.61). Mixing graded spans at
    `graded_frac` into a production-shaped topic corpus dilutes the pair
    words to realistic frequencies while keeping both gold sets
    evaluable from the same trained vectors: the two-level topic
    golds/purity AND the unique-rank graded golds.

    Returns (tokens, topic_of, graded_pairs); build topic golds with
    topic_similarity_pairs(topic_of).
    """
    rng = np.random.default_rng(seed + 2)
    t_tokens = int(n_tokens * (1.0 - graded_frac))
    tokens_t, topic_of = topic_corpus(
        n_tokens=t_tokens, span_len=span_len, seed=seed, **topic_kw
    )
    tokens_g, gpairs = graded_pair_corpus(
        n_pairs=n_pairs, n_tokens=n_tokens - t_tokens,
        span_len=span_len, seed=seed + 1,
    )
    spans = [
        tokens_t[i:i + span_len] for i in range(0, len(tokens_t), span_len)
    ] + [
        tokens_g[i:i + span_len] for i in range(0, len(tokens_g), span_len)
    ]
    rng.shuffle(spans)
    return [t for s in spans for t in s], topic_of, gpairs


#: naming conventions of the planted-structure generators above, recognized
#: by planted_probe_golds: graded pair centers, analogy grid cells, topic
#: content words
_GRADED_A = re.compile(r"^g(\d+)a$")
_GRID_CELL = re.compile(r"^c(\d+)_(\d+)$")
_TOPIC_WORD = re.compile(r"^t(\d+)w(\d+)$")


def planted_probe_golds(
    words: List[str],
    max_pairs: int = 64,
    max_questions: int = 96,
    seed: int = 0,
) -> Tuple[List[Tuple[str, str, float]], List[Tuple[str, str, str, str]]]:
    """Recover (pairs, analogy questions) gold sets from a vocabulary built
    over the planted-structure generators in this module — the in-training
    quality probe's held-out instrument (obs/quality.py).

    The generators encode their structure in the word names, so the golds
    are recoverable from the vocabulary alone — no side channel between
    corpus synthesis and the probe:

      * graded_pair_corpus centers g{k}a/g{k}b: the planted similarity
        alpha_k is linspace-monotone in k, so gold = k preserves the exact
        rank order Spearman is scored against;
      * analogy_corpus cells c{i}_{j}: every (c i_j, c i_k, c l_j, c l_k)
        with i != l, j != k is a planted 3CosAdd question (strided down to
        max_questions for even grid coverage);
      * topic_corpus content words t{t}w{i}: two-level similarity pairs
        (same topic 1.0, cross topic 0.0), deterministic draw.

    A vocabulary with none of these (a real corpus, a Zipf stream) returns
    ([], []): the probe then runs stats-only (row norms, drift, effective
    rank) unless the user supplies --probe-pairs/--probe-analogies files.
    """
    wordset = set(words)
    pairs: List[Tuple[str, str, float]] = []
    graded = sorted(
        int(m.group(1)) for w in words if (m := _GRADED_A.match(w))
    )
    for k in graded:
        if f"g{k}b" in wordset:
            pairs.append((f"g{k}a", f"g{k}b", float(k)))
    if len(pairs) > max_pairs:
        idx = np.linspace(0, len(pairs) - 1, max_pairs).astype(int)
        pairs = [pairs[i] for i in idx]

    cells = sorted(
        (int(m.group(1)), int(m.group(2)))
        for w in words if (m := _GRID_CELL.match(w))
    )
    cellset = set(cells)
    rows = sorted({i for i, _ in cells})
    cols = sorted({j for _, j in cells})
    questions = [
        (f"c{i}_{j}", f"c{i}_{k}", f"c{l}_{j}", f"c{l}_{k}")
        for i in rows for l in rows for j in cols for k in cols
        if i != l and j != k
        and {(i, j), (i, k), (l, j), (l, k)} <= cellset
    ]
    if len(questions) > max_questions:
        idx = np.linspace(0, len(questions) - 1, max_questions).astype(int)
        questions = [questions[i] for i in idx]

    if not pairs:
        topic_of = {
            w: int(m.group(1)) for w in words if (m := _TOPIC_WORD.match(w))
        }
        sizes: Dict[int, int] = {}
        for t in topic_of.values():
            sizes[t] = sizes.get(t, 0) + 1
        # min_count can strand a topic on one surviving word; same-topic
        # pair draws need two
        topic_of = {w: t for w, t in topic_of.items() if sizes[t] >= 2}
        if len(set(topic_of.values())) >= 2:
            pairs = topic_similarity_pairs(
                topic_of, n_pairs=min(max_pairs, 64), seed=seed,
                same_score=1.0, diff_score=0.0,
            )
    return pairs, questions


def topic_similarity_pairs(
    topic_of: Dict[str, int],
    n_pairs: int = 400,
    seed: int = 0,
    same_score: float = 8.0,
    diff_score: float = 2.0,
) -> List[Tuple[str, str, float]]:
    """WS-353-shaped (word1, word2, gold) pairs from the planted topics:
    half same-topic (high gold), half cross-topic (low gold). Spearman of
    model cosines against these golds measures structure recovery; comparing
    two implementations' Spearman on the SAME pairs is the parity gate."""
    rng = np.random.default_rng(seed)
    by_topic: Dict[int, List[str]] = {}
    for w, t in topic_of.items():
        by_topic.setdefault(t, []).append(w)
    topics = sorted(by_topic)
    pairs: List[Tuple[str, str, float]] = []
    for i in range(n_pairs):
        if i % 2 == 0:
            t = topics[rng.integers(len(topics))]
            a, b = rng.choice(by_topic[t], size=2, replace=False)
            pairs.append((str(a), str(b), same_score))
        else:
            t1, t2 = rng.choice(topics, size=2, replace=False)
            a = rng.choice(by_topic[t1])
            b = rng.choice(by_topic[t2])
            pairs.append((str(a), str(b), diff_score))
    return pairs
