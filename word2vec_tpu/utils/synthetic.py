"""Synthetic Zipfian corpora for benchmarks and dry runs.

No-network environments have no text8; a Zipf(1.0) token stream over a
text8-sized vocabulary reproduces the performance-relevant corpus properties
(vocab size, frequency skew, subsampling hit rate, negative-table shape) so
throughput numbers transfer. Not meant for accuracy evaluation.
"""

from __future__ import annotations

import numpy as np

from ..data.vocab import Vocab


def zipf_vocab(vocab_size: int = 71000, total_words: int = 17_000_000) -> Vocab:
    """A vocab whose counts follow Zipf's law, like text8's (~71k words kept
    at min_count=5 out of ~17M tokens)."""
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    weights = 1.0 / ranks
    counts = np.maximum(
        (weights / weights.sum() * total_words).astype(np.int64), 5
    )
    words = [f"w{i}" for i in range(vocab_size)]
    return Vocab(words, counts)


def zipf_corpus_ids(
    vocab: Vocab, num_tokens: int, seed: int = 0, sentence_len: int = 1000
) -> list:
    """Token-id sentences drawn from the vocab's empirical distribution,
    chunked like the reference's text8 reader (main.cpp:66)."""
    rng = np.random.default_rng(seed)
    p = vocab.counts / vocab.counts.sum()
    flat = rng.choice(len(vocab), size=num_tokens, p=p).astype(np.int32)
    return [
        flat[i : i + sentence_len] for i in range(0, num_tokens, sentence_len)
    ]
