"""Synthetic corpora for benchmarks, dry runs, and cross-implementation parity.

No-network environments have no text8; two generators stand in:

  * `zipf_vocab`/`zipf_corpus_ids` — a Zipf(1.0) token stream over a
    text8-sized vocabulary. Reproduces the performance-relevant corpus
    properties (vocab size, frequency skew, subsampling hit rate,
    negative-table shape) so throughput numbers transfer. No semantic
    structure — not for accuracy evaluation.
  * `topic_corpus`/`topic_similarity_pairs` — sentences with PLANTED topic
    structure: words of the same topic co-occur, so a correct word2vec
    recovers same-topic similarity. This is the accuracy-parity stand-in for
    WS-353 (BASELINE.md gate) when the real datasets are unreachable: train
    the C++ reference and this framework on the same generated stream and
    compare their eval scores (benchmarks/parity.py).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..data.vocab import Vocab


def zipf_vocab(vocab_size: int = 71000, total_words: int = 17_000_000) -> Vocab:
    """A vocab whose counts follow Zipf's law, like text8's (~71k words kept
    at min_count=5 out of ~17M tokens)."""
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    weights = 1.0 / ranks
    counts = np.maximum(
        (weights / weights.sum() * total_words).astype(np.int64), 5
    )
    words = [f"w{i}" for i in range(vocab_size)]
    return Vocab(words, counts)


def zipf_corpus_ids(
    vocab: Vocab, num_tokens: int, seed: int = 0, sentence_len: int = 1000
) -> list:
    """Token-id sentences drawn from the vocab's empirical distribution,
    chunked like the reference's text8 reader (main.cpp:66)."""
    rng = np.random.default_rng(seed)
    p = vocab.counts / vocab.counts.sum()
    flat = rng.choice(len(vocab), size=num_tokens, p=p).astype(np.int32)
    return [
        flat[i : i + sentence_len] for i in range(0, num_tokens, sentence_len)
    ]


def topic_corpus(
    n_topics: int = 8,
    words_per_topic: int = 40,
    shared_words: int = 20,
    n_tokens: int = 200_000,
    span_len: int = 20,
    p_shared: float = 0.25,
    seed: int = 0,
) -> Tuple[List[str], Dict[str, int]]:
    """A flat token stream with planted topic structure.

    The stream is a sequence of `span_len`-token spans; each span draws one
    topic and emits that topic's content words (Zipf-weighted within the
    topic) mixed with topic-agnostic shared words. Same-topic words therefore
    co-occur within any window <= span_len while cross-topic words co-occur
    only through shared words — exactly the contrast word2vec's objective
    should recover.

    Returns (tokens, topic_of): the flat token list (write it whitespace-
    separated for the reference's text8 reader, main.cpp:63-92) and the
    content-word -> topic map for building eval pairs.
    """
    rng = np.random.default_rng(seed)
    topic_words = [
        [f"t{t}w{i}" for i in range(words_per_topic)] for t in range(n_topics)
    ]
    shared = [f"s{i}" for i in range(shared_words)]
    zipf = 1.0 / np.arange(1, words_per_topic + 1)
    zipf /= zipf.sum()
    zipf_s = 1.0 / np.arange(1, shared_words + 1)
    zipf_s /= zipf_s.sum()

    tokens: List[str] = []
    n_spans = n_tokens // span_len
    topics = rng.integers(0, n_topics, size=n_spans)
    for t in topics:
        is_shared = rng.random(span_len) < p_shared
        content_ids = rng.choice(words_per_topic, size=span_len, p=zipf)
        shared_ids = rng.choice(shared_words, size=span_len, p=zipf_s)
        pool = topic_words[t]
        for k in range(span_len):
            tokens.append(
                shared[shared_ids[k]] if is_shared[k] else pool[content_ids[k]]
            )
    topic_of = {w: t for t, pool in enumerate(topic_words) for w in pool}
    return tokens, topic_of


def topic_similarity_pairs(
    topic_of: Dict[str, int],
    n_pairs: int = 400,
    seed: int = 0,
    same_score: float = 8.0,
    diff_score: float = 2.0,
) -> List[Tuple[str, str, float]]:
    """WS-353-shaped (word1, word2, gold) pairs from the planted topics:
    half same-topic (high gold), half cross-topic (low gold). Spearman of
    model cosines against these golds measures structure recovery; comparing
    two implementations' Spearman on the SAME pairs is the parity gate."""
    rng = np.random.default_rng(seed)
    by_topic: Dict[int, List[str]] = {}
    for w, t in topic_of.items():
        by_topic.setdefault(t, []).append(w)
    topics = sorted(by_topic)
    pairs: List[Tuple[str, str, float]] = []
    for i in range(n_pairs):
        if i % 2 == 0:
            t = topics[rng.integers(len(topics))]
            a, b = rng.choice(by_topic[t], size=2, replace=False)
            pairs.append((str(a), str(b), same_score))
        else:
            t1, t2 = rng.choice(topics, size=2, replace=False)
            a = rng.choice(by_topic[t1])
            b = rng.choice(by_topic[t2])
            pairs.append((str(a), str(b), diff_score))
    return pairs
