"""Breach-triggered profiler capture: on-chip evidence, armed by the SLO plane.

An SLO breach (obs/slo.py) tells you a run regressed; the artifact that
says WHY — an xprof/Perfetto device trace — used to be hand-queued into
`tpu_queue*.sh` hours later, against a run that no longer exists. This
module closes that loop: a third SignalBus consumer (after FleetHealth and
ElasticPolicy) arms `jax.profiler` for a BOUNDED window the moment a rule
enters breach, and dumps a schema-checked capture manifest next to
flight.json so the evidence is self-documenting.

Discipline (all pinned by tests/test_devmem.py):

  bounded      — a capture runs for exactly `steps` step/chunk boundaries
                 (`--profile-on-breach N`), then stops; `finish()` stops a
                 window the run ended inside. Never an unbounded trace.
  one per      — the breach episode's single `slo_breach` event (obs/slo.py
  episode        emits one per episode by construction) requests one
                 capture; a cooldown additionally gates re-arming, so a
                 flapping rule cannot turn the profiler into a firehose.
  boundary-    — triggers only REQUEST a capture (`request()` is a flag
  armed          write); arming happens at the next step boundary on the
                 training thread (`on_boundary` from Trainer._check_stop),
                 so signal handlers (SIGUSR2) and bus callbacks never call
                 into jax themselves. Idle boundaries are one None-check.
  structural   — a backend whose profiler cannot start writes the capture
  degrade        manifest with `status: "error"` and the exception, rc
                 untouched: the manifest is the contract, the trace files
                 are the payload (validate_capture_doc gates both shapes).

Programmatic windows ride the same machinery: `schedule(a, b)` arms at
step >= a and stops at step >= b (`--profile-steps A:B` in cli.py and
bench.py), and SIGUSR2 (resilience/shutdown.install_usr2_profile) requests
an on-demand window plus a memory-ledger dump without stopping the run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

SCHEMA = 1

#: default bounded window, in step/chunk boundaries
CAPTURE_STEPS_DEFAULT = 8
#: default seconds between captures (breach episodes inside the cooldown
#: are counted but not captured)
COOLDOWN_S_DEFAULT = 120.0
#: hard cap on captures per process — a run-away trigger cannot fill a disk
MAX_CAPTURES_DEFAULT = 8


def validate_capture_doc(doc: Dict) -> Dict[str, int]:
    """Schema gate for capture_<n>.json (CI + tests); returns summary
    counts, raises ValueError naming the first offending field — the
    fleet.json/trace.json contract: an unreadable artifact is not
    evidence."""
    if not isinstance(doc, dict):
        raise ValueError("not a capture manifest: not an object")
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"bad schema {doc.get('schema')!r} (want {SCHEMA})")
    if doc.get("event") != "profiler_capture":
        raise ValueError(f"bad event {doc.get('event')!r}")
    if not isinstance(doc.get("reason"), str) or not doc["reason"]:
        raise ValueError("missing reason")
    if doc.get("status") not in ("ok", "error"):
        raise ValueError(f"bad status {doc.get('status')!r}")
    if doc["status"] == "ok":
        for key in ("armed_step", "stopped_step"):
            if not isinstance(doc.get(key), int):
                raise ValueError(f"missing integer {key}")
        if doc["stopped_step"] < doc["armed_step"]:
            raise ValueError(
                f"stopped_step {doc['stopped_step']} precedes armed_step "
                f"{doc['armed_step']}"
            )
        if not isinstance(doc.get("trace_dir"), str):
            raise ValueError("missing trace_dir")
        if not isinstance(doc.get("files"), list):
            raise ValueError("missing files list")
    else:
        if not isinstance(doc.get("error"), str):
            raise ValueError("status=error without error text")
    if not isinstance(doc.get("steps_budget"), int):
        raise ValueError("missing steps_budget")
    return {
        "files": len(doc.get("files") or ()),
        "steps": (
            doc.get("stopped_step", 0) - doc.get("armed_step", 0)
            if doc["status"] == "ok" else 0
        ),
    }


class ProfilerCapture:
    """Bounded jax.profiler windows with a schema-checked manifest each."""

    def __init__(
        self,
        out_dir: str,
        steps: int = CAPTURE_STEPS_DEFAULT,
        cooldown_s: float = COOLDOWN_S_DEFAULT,
        max_captures: int = MAX_CAPTURES_DEFAULT,
        log_fn: Optional[Callable[[Dict], None]] = None,
        flight=None,
    ):
        self.out_dir = out_dir
        self.steps = max(1, int(steps))
        self.cooldown_s = float(cooldown_s)
        self.max_captures = max(1, int(max_captures))
        self.log_fn = log_fn
        self.flight = flight
        self._lock = threading.Lock()
        #: pending request reason (signal handlers / bus callbacks write it;
        #: the training thread consumes it at the next boundary)
        self._requested: Optional[str] = None
        #: scheduled [a, b) step window (--profile-steps)
        self._window: Optional[tuple] = None
        self.active = False
        self._reason = ""
        self._armed_step = 0
        self._stop_after: Optional[int] = None
        self._trace_dir = ""
        self.captures = 0
        self.suppressed = 0
        self._last_capture_t: Optional[float] = None
        self.manifests: List[str] = []

    # ------------------------------------------------------------ triggers
    def request(self, reason: str) -> bool:
        """Ask for a capture at the next step boundary. Safe from any
        thread or signal context — a flag write, nothing else. Returns
        False (and counts `suppressed`) inside the cooldown, when a
        capture is already active/pending, or past the capture cap."""
        with self._lock:
            if self.active or self._requested is not None:
                self.suppressed += 1
                return False
            if self.captures >= self.max_captures:
                self.suppressed += 1
                return False
            if (
                self._last_capture_t is not None
                and time.monotonic() - self._last_capture_t < self.cooldown_s
            ):
                self.suppressed += 1
                return False
            self._requested = str(reason)
            return True

    def schedule(self, start_step: int, stop_step: int) -> None:
        """Programmatic window: arm at step >= start, stop at step >= stop
        (`--profile-steps A:B`). Cooldown does not apply — the operator
        asked for exactly this window."""
        if stop_step <= start_step:
            raise ValueError(
                f"--profile-steps window is empty: [{start_step}, "
                f"{stop_step})"
            )
        with self._lock:
            self._window = (int(start_step), int(stop_step))

    def attach(self, bus) -> Callable[[], None]:
        """Subscribe the breach trigger to a SignalBus's `slo` topic: one
        capture request per breach episode (obs/slo.py emits one
        slo_breach per episode). Returns the unsubscribe callable."""
        def on_slo(ev: Dict) -> None:
            if ev.get("event") == "slo_breach":
                self.request(
                    f"slo_breach:{ev.get('rule', ev.get('signal', '?'))}"
                )

        return bus.subscribe("slo", on_slo)

    # ------------------------------------------------------------ boundary
    def on_boundary(self, step: int) -> None:
        """The trainer beat (Trainer._check_stop). Idle boundaries (no
        request, no window, not active) are two None-checks — no jax, no
        clock, no device work."""
        if self.active:
            if self._stop_after is not None and step >= self._stop_after:
                self._stop(step)
            return
        if self._window is not None:
            a, b = self._window
            if step >= b:
                self._window = None
            elif step >= a:
                self._window = None
                self._arm("scheduled", step, stop_after=b)
                return
        if self._requested is not None:
            with self._lock:
                reason, self._requested = self._requested, None
            self._arm(reason, step, stop_after=int(step) + self.steps)

    def finish(self, step: Optional[int] = None) -> None:
        """Run end: stop a window the run ended inside (the bounded
        contract holds on every exit path)."""
        if self.active:
            self._stop(int(step) if step is not None else self._armed_step)

    # ------------------------------------------------------------ internals
    def _arm(self, reason: str, step: int, stop_after: int) -> None:
        self.captures += 1
        n = self.captures
        self._reason = reason
        self._armed_step = int(step)
        self._stop_after = int(stop_after)
        self._trace_dir = os.path.join(self.out_dir, f"profile_{n}")
        self._last_capture_t = time.monotonic()
        err: Optional[str] = None
        try:
            os.makedirs(self._trace_dir, exist_ok=True)
            import jax

            jax.profiler.start_trace(self._trace_dir)
            self.active = True
        except Exception as e:  # noqa: BLE001 — structural degrade
            err = f"{type(e).__name__}: {e}"
        if err is not None:
            # the manifest is still the contract — status carries the why
            self._write_manifest(n, step, err=err)
            self.active = False
        self._note({"event": "profiler_armed", "reason": reason,
                    "step": int(step), "capture": n,
                    "status": "error" if err else "ok"})

    def _stop(self, step: int) -> None:
        err: Optional[str] = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — structural degrade
            err = f"{type(e).__name__}: {e}"
        self.active = False
        self._stop_after = None
        path = self._write_manifest(
            self.captures, step, err=err, stopped=True
        )
        self._note({
            "event": "profiler_capture",
            "reason": self._reason,
            "capture": self.captures,
            "armed_step": self._armed_step,
            "stopped_step": int(step),
            "manifest": path,
            "status": "error" if err else "ok",
        })

    def _write_manifest(self, n: int, step: int,
                        err: Optional[str] = None,
                        stopped: bool = False) -> Optional[str]:
        files: List[str] = []
        if stopped and err is None:
            for root, _dirs, names in os.walk(self._trace_dir):
                for name in names:
                    files.append(os.path.relpath(
                        os.path.join(root, name), self._trace_dir
                    ))
        doc: Dict = {
            "schema": SCHEMA,
            "event": "profiler_capture",
            "created_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "capture": n,
            "reason": self._reason,
            "steps_budget": self.steps,
            "status": "error" if err else "ok",
        }
        if err:
            doc["error"] = err
        else:
            doc.update({
                "armed_step": self._armed_step,
                "stopped_step": int(step),
                "trace_dir": self._trace_dir,
                "files": sorted(files),
            })
        path = os.path.join(self.out_dir, f"capture_{n}.json")
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            return None
        self.manifests.append(path)
        return path

    def _note(self, rec: Dict) -> None:
        if self.flight is not None:
            note = getattr(self.flight, "log_record", None)
            if note is not None:
                note(rec)
        if self.log_fn is not None:
            try:
                self.log_fn(dict(rec))
            except Exception:  # noqa: BLE001 — telemetry about telemetry
                pass

    def summary(self) -> Dict:
        """Manifest end-field: how many windows ran, how many triggers the
        cooldown swallowed, where the manifests are."""
        return {
            "captures": self.captures,
            "suppressed": self.suppressed,
            "steps_budget": self.steps,
            "cooldown_s": self.cooldown_s,
            "manifests": list(self.manifests),
        }
