"""Compiled-program cost harvest: what XLA says each executable costs.

The cost model (tune/cost_model.py) predicts step time from ANALYTIC
FLOP/byte counters (utils/profiling.py) plus three hand-calibrated anchors.
Those counters are our arithmetic about the program; the compiler has its
own, attached to every executable it emits: `compiled.cost_analysis()`
(flops, bytes accessed) and `compiled.memory_analysis()` (argument/output/
temp/code sizes). This module banks that device truth next to the analytic
numbers so the model's error — and the anchors' drift — stays observable
from every run's own artifacts (manifest, bench record), which is what
feeds `tune/cost_model.cost_calibrate`.

Mechanics: the trainers CAPTURE each jitted program's call signature the
first time it is dispatched (`CostHarvest.capture` — a tree-map of the
live arguments to ShapeDtypeStructs, so nothing holds donated buffers and
the hot loop pays a set lookup on later dispatches), and `finalize()` walks
the captured programs AFTER the run: `fn.lower(*avals).compile()` reuses
jax's lowering/compilation caches where the traced call already populated
them, and any residual compile cost lands outside the measured loop either
way. Every row degrades structurally — a backend whose cost analysis is
unavailable banks `{"ok": false, "error": ...}` for that program, never a
crash (the devmem present-from-zero contract).

jax 0.4.x returns cost_analysis as a list of one dict on some backends and
a bare dict on others; `_normalize_cost` absorbs both.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple


def _normalize_cost(cost) -> Dict[str, float]:
    """cost_analysis() -> {"flops", "bytes_accessed", ...} (missing keys
    simply absent; utilization breakdown keys dropped)."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out: Dict[str, float] = {}
    for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed"),
                     ("transcendentals", "transcendentals")):
        v = cost.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[dst] = float(v)
    return out


def analyze_compiled(compiled) -> Dict:
    """One jax.stages.Compiled -> a harvest row (cost + memory analysis)."""
    row: Dict = {"ok": True}
    try:
        row.update(_normalize_cost(compiled.cost_analysis()))
    except Exception as e:  # noqa: BLE001 — structural degrade per row
        row["cost_error"] = f"{type(e).__name__}: {e}"
    try:
        mem = compiled.memory_analysis()
        for attr, dst in (
            ("temp_size_in_bytes", "temp_bytes"),
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
            ("generated_code_size_in_bytes", "generated_code_bytes"),
        ):
            v = getattr(mem, attr, None)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row[dst] = int(v)
    except Exception as e:  # noqa: BLE001 — structural degrade per row
        row["memory_error"] = f"{type(e).__name__}: {e}"
    return row


def _avals(args: Tuple, kwargs: Optional[Dict]):
    """Live call arguments -> ShapeDtypeStructs (scalars pass through).
    Holding avals instead of arrays means captured signatures survive
    buffer donation and pin no device memory."""
    import jax
    import numpy as np

    def one(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        if isinstance(x, (int, float, bool)):
            return x
        return jax.ShapeDtypeStruct((), np.asarray(x).dtype)

    return (
        jax.tree_util.tree_map(one, tuple(args)),
        jax.tree_util.tree_map(one, dict(kwargs or {})),
    )


class CostHarvest:
    """Registry of jitted programs captured at dispatch, analyzed at end."""

    def __init__(self, host: int = 0):
        self.host = int(host)
        self._lock = threading.Lock()
        #: name -> (fn, arg avals, kw avals) pending analysis
        self._pending: Dict[str, Tuple] = {}
        #: name -> finished row
        self.programs: Dict[str, Dict] = {}
        self._seen: set = set()

    def want(self, name: str) -> bool:
        """Hot-loop gate: has this program been captured yet? One set
        lookup — the only cost the dispatch path pays after the first."""
        return name not in self._seen

    def capture(self, name: str, fn: Callable, args: Tuple,
                kwargs: Optional[Dict] = None) -> None:
        """Record one program's call signature (idempotent per name).
        Cheap by design: a tree-map to avals, no lowering, no compile —
        the dispatch that triggered it proceeds undisturbed."""
        with self._lock:
            if name in self._seen:
                return
            self._seen.add(name)
        try:
            a, kw = _avals(args, kwargs)
        except Exception as e:  # noqa: BLE001 — capture must never kill a step
            with self._lock:
                self.programs[name] = {
                    "program": name, "ok": False,
                    "error": f"capture: {type(e).__name__}: {e}",
                }
            return
        with self._lock:
            self._pending[name] = (fn, a, kw)

    def finalize(self) -> Dict:
        """Lower+compile every captured signature and bank its analysis.
        Runs AFTER training (cli.py / bench.py), so even a backend that
        re-compiles on the AOT path costs nothing inside the measured
        loop. Returns report(). Idempotent: finished programs skip."""
        with self._lock:
            pending = dict(self._pending)
            self._pending.clear()
        for name, (fn, args, kwargs) in pending.items():
            row: Dict = {"program": name}
            try:
                lowered = fn.lower(*args, **kwargs)
                compiled = lowered.compile()
                row.update(analyze_compiled(compiled))
            except Exception as e:  # noqa: BLE001 — structural degrade
                row["ok"] = False
                row["error"] = f"{type(e).__name__}: {e}"
            with self._lock:
                self.programs[name] = row
        return self.report()

    # ------------------------------------------------------------- output
    def report(self) -> Dict:
        """The manifest / bench-record payload: per-program rows plus
        cross-program totals (the gauge record's numeric fields)."""
        with self._lock:
            rows = [dict(r) for _, r in sorted(self.programs.items())]
        totals: Dict[str, float] = {}
        for key in ("flops", "bytes_accessed", "temp_bytes",
                    "generated_code_bytes"):
            vals = [r[key] for r in rows if isinstance(r.get(key), (int, float))]
            if vals:
                totals[key] = float(sum(vals))
        return {
            "host": self.host,
            "programs": rows,
            "programs_ok": sum(1 for r in rows if r.get("ok")),
            "programs_failed": sum(1 for r in rows if not r.get("ok", False)),
            "totals": totals,
        }

    def gauge_record(self) -> Optional[Dict]:
        """One flat "cost_harvest" event record -> `w2v_cost_harvest_*`
        gauges (obs/export.GAUGE_EVENTS). None before any program banked."""
        rep = self.report()
        if not rep["programs"]:
            return None
        rec: Dict = {
            "event": "cost_harvest",
            "cost_harvest_programs": len(rep["programs"]),
            "cost_harvest_programs_ok": rep["programs_ok"],
        }
        for key, dst in (
            ("flops", "cost_harvest_flops"),
            ("bytes_accessed", "cost_harvest_bytes"),
            ("temp_bytes", "cost_harvest_temp_bytes"),
            ("generated_code_bytes", "cost_harvest_code_bytes"),
        ):
            if key in rep["totals"]:
                rec[dst] = rep["totals"][key]
        return rec
