"""Online quality telemetry: in-training embedding-quality probes + the
degeneracy sentinel.

The paper's only success measures are downstream embedding quality (analogy
accuracy, word similarity), yet until this module the observability stack
was blind to it: the round-5 band-kernel collapse (analogy 0.0 vs pair's
0.74 on the same stream, benchmarks/BAND_DEGENERACY_r5.md) was a one-shot
pre-training warning, invisible mid-run. This module closes the loop:

  ProbeSet        — the held-out probe material: graded similarity pairs,
                    planted analogy questions, and a tracked-word set for
                    neighbor-overlap drift. Synthesized from the vocabulary
                    for planted-structure corpora (utils/synthetic.
                    planted_probe_golds recovers the golds from the
                    generators' word naming) or loaded from user files
                    (--probe-pairs / --probe-analogies). With neither, the
                    probe runs stats-only.
  QualityProbe    — at a configurable cadence of step/sync boundaries
                    (trainers call it from the shared _check_stop hook), a
                    read-only view of the live tables (zero-copy plane via
                    models/params.logical_table; ONE jax.device_get per
                    probe, zero added syncs on non-probe steps — pinned by
                    tests/test_quality.py) is scored through the serve
                    QueryEngine's jit'd batched top-k kernel: planted
                    Spearman + analogy accuracy, Jaccard@k neighbor drift
                    vs the previous probe, and cheap health statistics
                    (row-norm p50/p99, in/out-plane norm ratio, spectral
                    effective rank on a sampled submatrix). Every probe
                    emits one gauge record (w2v_quality_* via the
                    MetricsHub) + one counter event (w2v_quality_probes_
                    total), a probe span + 'C' counters on the TraceRing,
                    and a row in the flight recorder's quality ring — the
                    last N rows ride in every flight.json dump.
  QualitySentinel — turns the static degeneracy fence dynamic: a sustained
                    drop of the planted score below the floor (or below a
                    fraction of its peak, or an effective-rank collapse
                    toward a rank-deficient table) escalates warn ->
                    checkpoint-and-continue -> QualityAlert, mirroring the
                    DivergenceError contract (--quality-budget; budget 0 =
                    warn only). The CLI maps an escaped QualityAlert to
                    EXIT_QUALITY (rc=3) with a flight.json dump whose
                    quality ring carries the probe rows that led there.

`score_table` is the shared scoring core: the trainers' probe, the serve
CLI's startup probe (w2v_quality_* gauges on /metrics when serving a table
exported mid-training), and the CI quality gate all call the same function
against the same engine kernels.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

#: process exit code of a QualityAlert abort (cli.py): distinct from 1
#: (usage/crash), 2 (DivergenceError), 75/76 (requeue codes)
EXIT_QUALITY = 3

#: metric keys the sentinel watches, in preference order (first present
#: wins): the planted analogy score collapses hardest in the measured
#: degeneracy domain, so it leads
SENTINEL_WATCH = ("quality_analogy_accuracy", "quality_spearman")


# ------------------------------------------------------------------ probe set
@dataclass
class ProbeSet:
    """Held-out probe material; any subset may be empty (stats-only)."""

    pairs: List[Tuple[str, str, float]] = field(default_factory=list)
    analogies: List[Tuple[str, str, str, str]] = field(default_factory=list)
    tracked: List[str] = field(default_factory=list)
    source: str = "stats-only"

    @classmethod
    def synthesize(
        cls, vocab, max_pairs: int = 64, max_analogies: int = 96,
        track: int = 24,
    ) -> "ProbeSet":
        """Probe golds recovered from a planted-structure vocabulary
        (utils/synthetic.planted_probe_golds); stats-only when the
        vocabulary carries no recognizable planted naming."""
        from ..utils.synthetic import planted_probe_golds

        pairs, questions = planted_probe_golds(
            list(vocab.words), max_pairs=max_pairs,
            max_questions=max_analogies,
        )
        src = "planted" if (pairs or questions) else "stats-only"
        return cls(
            pairs=pairs, analogies=questions,
            tracked=list(vocab.words[:track]), source=src,
        )

    @classmethod
    def from_files(
        cls, vocab, pairs_path: Optional[str] = None,
        analogies_path: Optional[str] = None, track: int = 24,
    ) -> "ProbeSet":
        """User-supplied probe files: pairs in the WS-353 shape
        (eval/similarity.load_word_pairs), analogies in questions-words
        format (eval/analogy.load_questions)."""
        pairs: List[Tuple[str, str, float]] = []
        questions: List[Tuple[str, str, str, str]] = []
        if pairs_path:
            from ..eval.similarity import load_word_pairs

            pairs = load_word_pairs(pairs_path)
        if analogies_path:
            from ..eval.analogy import load_questions

            for _name, qs in load_questions(analogies_path):
                questions.extend(qs)
        # track probe words first (they are what the golds move), padded
        # with the most frequent vocabulary words
        tracked: List[str] = []
        for w1, w2, _ in pairs:
            for w in (w1, w2):
                if w in vocab and w not in tracked:
                    tracked.append(w)
                if len(tracked) >= track:
                    break
            if len(tracked) >= track:
                break
        for w in vocab.words:
            if len(tracked) >= track:
                break
            if w not in tracked:
                tracked.append(w)
        return cls(
            pairs=pairs, analogies=questions, tracked=tracked,
            source="files",
        )


# ------------------------------------------------------------------- scoring
def _effective_rank(M: np.ndarray) -> float:
    """Spectral effective rank exp(H(p)), p = s^2 / sum s^2 — continuous in
    [0, min(M.shape)]; a table collapsing toward rank deficiency drives it
    down long before any single score does. 0.0 for a zero matrix."""
    s = np.linalg.svd(np.asarray(M, np.float64), compute_uv=False)
    tot = float((s * s).sum())
    if tot <= 0.0:
        return 0.0
    p = (s * s) / tot
    p = p[p > 0]
    return float(np.exp(-(p * np.log(p)).sum()))


def score_table(
    W: np.ndarray,
    vocab,
    probe_set: ProbeSet,
    k: int = 10,
    prev_neighbors: Optional[Dict[int, np.ndarray]] = None,
    W_out: Optional[np.ndarray] = None,
    sample_rows: int = 1024,
    rank_rows: int = 256,
    seed: int = 0,
) -> Tuple[Dict[str, float], Dict[int, np.ndarray]]:
    """Score one table snapshot; returns (record, neighbor_id_sets).

    Everything flows through one serve/query engine (normalize-once,
    jit'd batched top-k): pair cosines for Spearman, score planes for the
    analogy protocol (eval/analogy.evaluate_analogy_sections — the exact
    file-based eval path, so in-training scores are comparable to offline
    ones), and the top-k kernel for the drift sets. Deterministic under a
    fixed seed: the sampled row sets are a pure function of (V, seed).
    """
    from ..serve.query import get_engine

    W = np.asarray(W)
    if W.shape[0] > len(vocab):
        # unadmitted online-growth reserve rows (config.vocab_reserve) are
        # not words: scoring/health stats must not see their random init
        W = W[: len(vocab)]
        if W_out is not None:
            W_out = np.asarray(W_out)[: len(vocab)]
    rec: Dict[str, float] = {}
    eng = get_engine(W, vocab, restrict=len(vocab))

    if probe_set.pairs:
        from ..eval.similarity import spearman

        ij, gold = [], []
        for w1, w2, g in probe_set.pairs:
            if w1 in vocab and w2 in vocab:
                ij.append((vocab[w1], vocab[w2]))
                gold.append(g)
        if len(gold) >= 3:
            arr = np.asarray(ij, np.int32)
            cos = eng.pair_cosines(arr[:, 0], arr[:, 1])
            rec["quality_spearman"] = round(
                spearman(cos, np.asarray(gold, np.float64)), 4
            )
            rec["quality_pairs_used"] = float(len(gold))

    if probe_set.analogies:
        from ..eval.analogy import evaluate_analogy_sections

        r = evaluate_analogy_sections(
            W, vocab, [("probe", list(probe_set.analogies))],
            restrict_vocab=len(vocab),
        )
        if r.total:
            rec["quality_analogy_accuracy"] = round(r.accuracy, 4)
            rec["quality_analogy_mean_rank"] = round(r.mean_gold_rank, 3)
        rec["quality_analogy_total"] = float(r.total)
        # computed-but-dropped no more: a probe set full of OOV/degenerate
        # rows must not read as a clean 0-question pass
        rec["quality_analogy_skipped_oov"] = float(r.skipped_oov)
        rec["quality_analogy_skipped_degenerate"] = float(
            r.skipped_degenerate
        )

    # neighbor-overlap drift vs the previous probe (Jaccard@k per tracked
    # word; absent on the first probe)
    tracked_ids = [
        vocab[w] for w in probe_set.tracked
        if w in vocab and vocab[w] < eng.V
    ]
    neighbors: Dict[int, np.ndarray] = {}
    if tracked_ids:
        sets = eng.neighbor_id_sets(np.asarray(tracked_ids, np.int32), k=k)
        neighbors = {i: s for i, s in zip(tracked_ids, sets)}
        if prev_neighbors:
            jac = []
            for i, cur in neighbors.items():
                prev = prev_neighbors.get(i)
                if prev is None:
                    continue
                a, b = set(map(int, cur)), set(map(int, prev))
                denom = len(a | b)
                jac.append(len(a & b) / denom if denom else 1.0)
            if jac:
                rec["quality_drift_jaccard_mean"] = round(
                    float(np.mean(jac)), 4
                )
                rec["quality_drift_jaccard_min"] = round(
                    float(np.min(jac)), 4
                )

    # cheap embedding-health statistics on deterministically sampled rows
    V = W.shape[0]
    rng = np.random.default_rng(seed)
    rows = (
        np.arange(V) if V <= sample_rows
        else np.sort(rng.choice(V, size=sample_rows, replace=False))
    )
    Wf = np.asarray(W, np.float32)
    norms = np.linalg.norm(Wf[rows], axis=1)
    rec["quality_row_norm_p50"] = round(float(np.percentile(norms, 50)), 6)
    rec["quality_row_norm_p99"] = round(float(np.percentile(norms, 99)), 6)
    if W_out is not None:
        out_norms = np.linalg.norm(
            np.asarray(W_out, np.float32)[rows], axis=1
        )
        # the ns output table inits to zeros, so the first probes' ratio is
        # legitimately +Inf — the Prometheus exposition spells it
        med_out = float(np.percentile(out_norms, 50))
        med_in = float(np.percentile(norms, 50))
        rec["quality_norm_ratio_in_out"] = round(
            med_in / med_out, 4
        ) if med_out > 0 else float("inf")
    r_rows = (
        np.arange(V) if V <= rank_rows
        else np.sort(rng.choice(V, size=rank_rows, replace=False))
    )
    rec["quality_effective_rank"] = round(_effective_rank(Wf[r_rows]), 3)
    return rec, neighbors


# ------------------------------------------------------------------ sentinel
class QualityAlert(RuntimeError):
    """Sustained in-training quality degradation past the budget.

    Structured payload mirroring DivergenceError: `.step`, `.metric`,
    `.value`, `.peak`, `.floor`, `.streak`, `.budget`, `.in_domain`, and
    `.record()` for manifests/JSONL."""

    def __init__(
        self,
        step: int,
        metric: str,
        value: Optional[float],
        peak: Optional[float],
        floor: float,
        streak: int,
        budget: int,
        in_domain: bool = False,
        reasons: Optional[List[str]] = None,
    ):
        self.step = step
        self.metric = metric
        self.value = value
        self.peak = peak
        self.floor = floor
        self.streak = streak
        self.budget = budget
        self.in_domain = in_domain
        self.reasons = list(reasons or [])
        domain = (
            " inside the measured band+ns degeneracy domain "
            "(benchmarks/BAND_DEGENERACY_r5.md)" if in_domain else ""
        )
        super().__init__(
            f"embedding quality degraded for {streak} consecutive probes "
            f"(budget {budget}){domain}: {metric}={value} vs peak {peak} "
            f"(floor {floor}) at step {step}; "
            + "; ".join(self.reasons)
        )

    def record(self) -> Dict:
        return {
            "event": "quality_alert",
            "step": self.step,
            "metric": self.metric,
            "value": self.value,
            "peak": self.peak,
            "floor": self.floor,
            "streak": self.streak,
            "budget": self.budget,
            "in_domain": self.in_domain,
            "reasons": self.reasons,
        }


class QualitySentinel:
    """Escalating watch over the probe's score stream.

    Degraded = the watched planted score sits below `floor` (after `grace`
    scored probes — early training legitimately scores low, so the floor
    must not fire before the model had a chance to learn), OR below
    (1 - drop) of its own peak (only once a real peak >= floor exists —
    the learn-then-collapse signature of the band degeneracy,
    BAND_DEGENERACY_r5.md's 0.9997 -> 0.085 trajectory), OR the effective
    rank collapsed below `rank_collapse` of its peak (the drift-toward-
    rank-deficiency signature). Escalation, mirroring the DivergenceError
    contract:

        budget == 0      every degraded probe -> "warn" (log only)
        streak == budget -> "checkpoint" (checkpoint-and-continue, once
                            per degradation window)
        streak >= 2*budget -> raises QualityAlert (cli.py: rc=3)
    """

    def __init__(
        self,
        budget: int = 0,
        floor: float = 0.1,
        drop: float = 0.5,
        rank_collapse: float = 0.25,
        grace: int = 0,
        in_domain: bool = False,
    ):
        self.budget = int(budget)
        self.floor = float(floor)
        self.drop = float(drop)
        self.rank_collapse = float(rank_collapse)
        self.grace = int(grace)
        self._scored = 0
        self.in_domain = bool(in_domain)
        self.peak: Optional[float] = None
        self.rank_peak: Optional[float] = None
        self.streak = 0
        self._checkpointed = False
        self.last_reasons: List[str] = []

    def observe(self, rec: Dict, step: int) -> Optional[str]:
        """One probe record -> None | "warn" | "checkpoint"; raises
        QualityAlert past 2x the budget."""
        metric = next((m for m in SENTINEL_WATCH if m in rec), None)
        score = rec.get(metric) if metric else None
        reasons: List[str] = []
        if score is not None:
            self._scored += 1
            if self.peak is None or score > self.peak:
                self.peak = float(score)
            if score < self.floor and self._scored > self.grace:
                reasons.append(
                    f"{metric} {score:.4f} < floor {self.floor:.4f}"
                )
            elif (
                self.peak is not None
                and self.peak >= self.floor
                and score < (1.0 - self.drop) * self.peak
            ):
                reasons.append(
                    f"{metric} {score:.4f} fell below "
                    f"{1.0 - self.drop:.2f}x its peak {self.peak:.4f}"
                )
        er = rec.get("quality_effective_rank")
        if er is not None:
            if self.rank_peak is None or er > self.rank_peak:
                self.rank_peak = float(er)
            elif er < self.rank_collapse * self.rank_peak:
                reasons.append(
                    f"effective rank {er:.1f} collapsed below "
                    f"{self.rank_collapse:.2f}x its peak "
                    f"{self.rank_peak:.1f}"
                )
        if not reasons:
            self.streak = 0
            self._checkpointed = False
            self.last_reasons = []
            return None
        self.streak += 1
        self.last_reasons = reasons
        if self.budget and self.streak >= 2 * self.budget:
            raise QualityAlert(
                step=step, metric=metric or "quality_effective_rank",
                value=None if score is None else float(score),
                peak=self.peak, floor=self.floor, streak=self.streak,
                budget=self.budget, in_domain=self.in_domain,
                reasons=reasons,
            )
        if self.budget and self.streak >= self.budget and not self._checkpointed:
            self._checkpointed = True
            return "checkpoint"
        return "warn"


# --------------------------------------------------------------------- probe
class QualityProbe:
    """The in-training probe the trainers beat at step/sync boundaries.

    `due(step)` is one integer compare — the non-probe-step cost; `probe()`
    does ONE jax.device_get of the needed table planes (logical_table
    views, so a unified [V, 2, d] slab is sliced, never copied whole
    host-side) and scores everything host/engine-side. Wire via
    `trainer.quality_probe = QualityProbe(...)` or config.
    quality_probe_every (the Trainer then builds a synthesized default).
    """

    def __init__(
        self,
        vocab,
        probe_set: Optional[ProbeSet] = None,
        every: int = 100,
        k: int = 10,
        sample_rows: int = 1024,
        rank_rows: int = 256,
        log_fn: Optional[Callable[[Dict], None]] = None,
        flight=None,
        sentinel: Optional[QualitySentinel] = None,
        seed: int = 0,
        history: int = 32,
    ):
        self.vocab = vocab
        self.set = probe_set or ProbeSet.synthesize(vocab)
        self.every = int(every)
        self.k = int(k)
        self.sample_rows = int(sample_rows)
        self.rank_rows = int(rank_rows)
        self.log_fn = log_fn
        self.flight = flight
        self.sentinel = sentinel
        self.seed = int(seed)
        self.history: collections.deque = collections.deque(
            maxlen=max(1, history)
        )
        self.probes = 0
        self.last_step = 0
        self._prev_neighbors: Optional[Dict[int, np.ndarray]] = None
        #: checkpoint-and-continue hook (the CLI wires the run's checkpoint
        #: callback); called once per sentinel degradation window
        self.checkpoint_fn: Optional[Callable[[], None]] = None

    # ------------------------------------------------------------- cadence
    def due(self, step: int) -> bool:
        """Distance-based, not modulo: chunked dispatch advances the step
        counter by whole chunks and must not skip over a boundary."""
        return (
            self.every > 0 and step > 0
            and step - self.last_step >= self.every
        )

    # -------------------------------------------------------------- probing
    def probe(self, params: Dict, step: int) -> Dict:
        """Score the live tables at `step`. Raises QualityAlert when the
        sentinel's escalation crosses 2x its budget."""
        import jax

        from ..models.params import logical_table

        t0 = time.perf_counter()
        self.last_step = int(step)
        views = {"emb_in": logical_table(params, "emb_in")}
        try:
            views["emb_out_ns"] = logical_table(params, "emb_out_ns")
        except KeyError:
            pass  # hs runs: no ns output plane, the ratio stat is skipped
        host = jax.device_get(views)  # the ONE device sync per probe
        rec: Dict = {"step": int(step)}
        scores, neighbors = score_table(
            np.asarray(host["emb_in"], np.float32),
            self.vocab,
            self.set,
            k=self.k,
            prev_neighbors=self._prev_neighbors,
            W_out=host.get("emb_out_ns"),
            sample_rows=self.sample_rows,
            rank_rows=self.rank_rows,
            seed=self.seed,
        )
        rec.update(scores)
        self._prev_neighbors = neighbors
        dur = time.perf_counter() - t0
        rec["quality_probe_ms"] = round(1e3 * dur, 3)
        self.probes += 1
        self.history.append(dict(rec))

        if self.flight is not None:
            # probe span + counter events on the trace timeline, plus the
            # quality ring every flight.json dump embeds
            self.flight.ring.complete(
                "quality_probe", t0, dur, args={"step": int(step)}
            )
            self.flight.ring.counter(
                "quality",
                {k: v for k, v in rec.items()
                 if k != "step" and isinstance(v, (int, float))},
            )
            self.flight.note_quality(rec)
        self._log(rec)
        # present-from-zero counter (obs/export.EVENT_COUNTERS)
        self._log({"event": "quality_probe", "step": int(step)})

        if self.sentinel is not None:
            try:
                action = self.sentinel.observe(rec, step)
            except QualityAlert as e:
                self._log(e.record())
                if self.flight is not None:
                    self.flight.note_quality(e.record())
                raise
            if action == "checkpoint":
                if self.checkpoint_fn is not None:
                    self.checkpoint_fn()
                self._log({
                    "event": "quality_checkpoint",
                    "step": int(step),
                    "streak": self.sentinel.streak,
                    "reasons": self.sentinel.last_reasons,
                })
            elif action == "warn":
                self._log({
                    "event": "quality_warn",
                    "step": int(step),
                    "streak": self.sentinel.streak,
                    "budget": self.sentinel.budget,
                    "reasons": self.sentinel.last_reasons,
                })
        return rec

    def _log(self, rec: Dict) -> None:
        if self.flight is not None and "event" in rec:
            self.flight.log_record(rec)
        if self.log_fn is not None:
            self.log_fn(rec)
