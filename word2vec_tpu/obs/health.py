"""On-device health counters + the host-side divergence tripwire.

The reference's only health signal is the loss printed every 100 sentences
(Word2Vec.cpp:382-385); ours until now was a single warn-once on a
non-finite loss observed at the log cadence (so `log_every=0` runs burned
TPU time on NaN params until the epoch ended). This module closes that gap
in two layers:

  device side — `instrument_step` wraps any kernel step built by
    ops/train_step.make_train_step and EXTENDS ITS METRICS DICT inside the
    existing jit/scan program, so the counters cost zero extra dispatches:

      nonfinite_loss    always (a scalar compare on the loss the kernel
                        already computes — free)
      grad_sq, update_sq_<table>, nonfinite_params, alpha_sum
                        only with config.health_metrics: these diff the
                        updated tables against their pre-step values, which
                        costs one extra read of each [V, d] table per step
                        AND defeats the donation aliasing of the table
                        buffers (XLA must keep the old value live), so the
                        full counters are opt-in — throughput runs keep the
                        free tripwire only.

    All counters are float32 scalars and strictly ADDITIVE, because the
    micro-step wrapper tree-sums metrics across sub-blocks and the chunk
    runners lax.scan-stack them: sums over any aggregation window stay
    meaningful (alpha_sum sums micro_steps alphas per dispatch — divide by
    micro_steps host-side, see `health_record`).

  host side — `HealthMonitor` consumes the counters through the trainers'
    existing one-step-lagged metrics drain (train.Trainer), counts
    CONSECUTIVE non-finite observations, and raises a structured
    `DivergenceError` (offending step, last counters, last-good checkpoint
    hint) once the streak exceeds config.divergence_budget. No new host
    syncs: the monitor only ever sees metrics the drain already fetched.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

#: metrics-dict key prefix of the per-table update-magnitude counters
UPDATE_SQ_PREFIX = "update_sq_"


class DivergenceError(RuntimeError):
    """Training diverged: the non-finite streak exceeded the budget.

    Structured payload for harnesses: `.step` (the observation that tripped
    the budget), `.streak`, `.first_step` (first non-finite observation of
    the streak's run), `.counters` (the last drained health counters), and
    `.checkpoint_hint` (where to resume from, when the run checkpointed).
    """

    def __init__(
        self,
        step: int,
        streak: int,
        first_step: Optional[int] = None,
        counters: Optional[Dict[str, float]] = None,
        checkpoint_hint: Optional[str] = None,
    ):
        self.step = step
        self.streak = streak
        self.first_step = first_step
        self.counters = dict(counters or {})
        self.checkpoint_hint = checkpoint_hint
        shown = {
            k: v for k, v in self.counters.items()
            if k in ("loss_sum", "nonfinite_loss", "nonfinite_params", "grad_sq")
        }
        super().__init__(
            f"training diverged: non-finite loss for {streak} consecutive "
            f"observations (first at step {first_step}), failing at step "
            f"{step}; counters: {shown}; last good checkpoint: "
            f"{checkpoint_hint or 'none taken this run'}"
        )

    def record(self) -> Dict:
        """Structured event payload — what the resilience supervisor logs
        and manifests carry for a divergence, without re-parsing the
        message string."""
        return {
            "event": "divergence",
            "step": self.step,
            "streak": self.streak,
            "first_step": self.first_step,
            "checkpoint_hint": self.checkpoint_hint,
        }


def instrument_step(
    base: Callable, config, tp_axis: Optional[str] = None
) -> Callable:
    """Wrap a kernel step so its metrics carry the health counters.

    Runs INSIDE the caller's jit (ops/train_step.make_train_step applies it
    under the micro wrapper and every chunk scan), so nothing here adds a
    dispatch or a host sync. With tensor parallelism the per-table stats are
    psum'd over `tp_axis` first: each dim shard's partial squared norm /
    non-finite count becomes the global value, replicated over the model
    axis — which is exactly the invariant the sharded trainers' metrics
    aggregation (psum over model, divided by tp) assumes of every metric.
    """
    import jax
    import jax.numpy as jnp

    full = bool(getattr(config, "health_metrics", False))

    def _subtables(name, new, old):
        """(public_name, new, old) triples; the fused [V, 2, d] ns stack
        (ops/band_step.fuse_tables) reports as its two public tables so the
        telemetry keys don't depend on the chunk runner's fusion state."""
        from ..ops.band_step import FUSED_KEY, FUSED_SUBTABLES

        if name == FUSED_KEY:
            for i, sub in enumerate(FUSED_SUBTABLES):
                yield sub, new[:, i], old[:, i]
        else:
            yield name, new, old

    def step(params, tokens, key, alpha):
        new_params, metrics = base(params, tokens, key, alpha)
        metrics = dict(metrics)
        # free tripwire: the loss the kernel already computed, compared once
        metrics["nonfinite_loss"] = (
            ~jnp.isfinite(metrics["loss_sum"])
        ).astype(jnp.float32)
        if not full:
            return new_params, metrics
        metrics["alpha_sum"] = jnp.asarray(alpha, jnp.float32)
        grad_sq = jnp.float32(0.0)
        bad = jnp.float32(0.0)
        for name in sorted(new_params):
            for sub, new_t, old_t in _subtables(name, new_params[name], params[name]):
                delta = new_t.astype(jnp.float32) - old_t.astype(jnp.float32)
                sq = jnp.sum(delta * delta)
                nf = jnp.sum(~jnp.isfinite(new_t.astype(jnp.float32)))
                nf = nf.astype(jnp.float32)
                if tp_axis is not None:
                    sq = jax.lax.psum(sq, tp_axis)
                    nf = jax.lax.psum(nf, tp_axis)
                metrics[UPDATE_SQ_PREFIX + sub] = sq
                grad_sq = grad_sq + sq
                bad = bad + nf
        metrics["grad_sq"] = grad_sq
        metrics["nonfinite_params"] = bad
        return new_params, metrics

    return step


def health_record(m: Dict, micro_steps: int = 1) -> Dict[str, float]:
    """Host-side log-record fields from a fetched metrics dict.

    Works on per-step scalars and chunk-stacked [S] arrays alike (sums over
    the window; norms are sqrt-of-summed-squares, i.e. the window's
    cumulative update magnitude). Empty when the step carries no health
    counters (instrumentation off in an externally-built step)."""
    rec: Dict[str, float] = {}
    if "nonfinite_loss" in m:
        rec["nonfinite_loss_steps"] = float(np.sum(m["nonfinite_loss"]))
    if "nonfinite_params" in m:
        rec["nonfinite_params"] = float(np.sum(m["nonfinite_params"]))
    if "grad_sq" in m:
        rec["grad_norm"] = float(np.sqrt(np.sum(m["grad_sq"])))
    if "alpha_sum" in m:
        rec["alpha_device"] = float(
            np.mean(np.asarray(m["alpha_sum"])) / max(1, micro_steps)
        )
    for k in m:
        if k.startswith(UPDATE_SQ_PREFIX):
            rec["update_norm_" + k[len(UPDATE_SQ_PREFIX):]] = float(
                np.sqrt(np.sum(m[k]))
            )
    return rec


class HealthMonitor:
    """Consecutive-non-finite tracking over the trainers' lagged drain.

    `observe` (per-step loop) and `observe_chunk` (chunked drivers) are
    called once per FETCHED metrics payload — the observation cadence is the
    drain cadence, independent of log_every, exactly like the hs
    tail-overflow warning. budget == 0 disables the tripwire (counting
    still runs, for TrainReport.health)."""

    def __init__(self, budget: int = 0, micro_steps: int = 1):
        self.budget = int(budget)
        self.micro_steps = max(1, int(micro_steps))
        self.streak = 0
        self.max_streak = 0
        self.observations = 0
        self.nonfinite_steps = 0
        self.first_nonfinite_step: Optional[int] = None
        self.grad_sq_total = 0.0
        self.last: Dict[str, float] = {}
        #: set by the trainer whenever a checkpoint lands (the error's hint)
        self.checkpoint_hint: Optional[str] = None

    # ------------------------------------------------------------ observing
    def observe(self, m: Dict, at_step: int) -> None:
        """One drained per-step metrics dict (scalars)."""
        self.last = {k: float(np.sum(v)) for k, v in m.items()}
        self.grad_sq_total += float(np.sum(m.get("grad_sq", 0.0)))
        self._advance(float(np.sum(m.get("nonfinite_loss", 0.0))) > 0, at_step)

    def observe_chunk(
        self, m: Dict, end_step: int, real_steps: Optional[int] = None
    ) -> None:
        """One drained chunk's metrics ([S]-stacked). Trailing pad steps of
        a partial chunk are observed too (an all-padding batch keeps the
        previous loss character, so they extend — never reset — a genuine
        streak); step attribution maps scan slot i of the `real_steps`
        leading real slots onto end_step - real_steps + 1 + i."""
        self.last = {k: float(np.sum(v)) for k, v in m.items()}
        self.grad_sq_total += float(np.sum(m.get("grad_sq", 0.0)))
        arr = np.atleast_1d(np.asarray(m.get("nonfinite_loss", 0.0)))
        n = len(arr)
        real = n if real_steps is None else min(real_steps, n)
        start = end_step - real
        for i, v in enumerate(arr):
            self._advance(float(v) > 0, min(start + i + 1, end_step))

    def _advance(self, bad: bool, at_step: int) -> None:
        self.observations += 1
        if not bad:
            self.streak = 0
            return
        if self.streak == 0:
            self.first_nonfinite_step = at_step
        self.streak += 1
        self.nonfinite_steps += 1
        self.max_streak = max(self.max_streak, self.streak)
        if self.budget and self.streak >= self.budget:
            raise DivergenceError(
                at_step,
                self.streak,
                first_step=self.first_nonfinite_step,
                counters=self.last,
                checkpoint_hint=self.checkpoint_hint,
            )

    # ------------------------------------------------------------- summary
    def summary(self) -> Dict:
        """TrainReport.health payload."""
        out = {
            "observations": self.observations,
            "nonfinite_loss_steps": self.nonfinite_steps,
            "max_streak": self.max_streak,
            "divergence_budget": self.budget,
        }
        if self.grad_sq_total > 0.0:
            out["grad_norm_cumulative"] = float(np.sqrt(self.grad_sq_total))
        return out
