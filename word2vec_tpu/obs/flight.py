"""Always-on flight recorder: every failure artifact carries its own timeline.

The watchdog's `stall.json` names the wedged phase and the DivergenceError
names the failing step — but neither shows what the run was DOING in the
steps before it died, and by the time a failure is being debugged the run is
gone. The flight recorder closes that gap the way avionics do: a bounded
in-memory ring records the last N steps of span events (via the
PhaseRecorder's tracer hook), health counters (via the trainers' lagged
metrics drain), and log records, ALWAYS — no flag, no I/O, no device
interaction (recording is a deque append under a lock; the <1% overhead
contract is pinned in tests/test_trace.py and banked by
benchmarks/trace_overhead.py). On any failure path the ring is dumped as
`flight.json` into `--metrics-dir`:

    divergence   — cli.py's DivergenceError handler (reason "diverged")
    stall        — resilience/watchdog.StepWatchdog._fire, BEFORE the
                   os._exit(EXIT_STALLED) (reason "stalled")
    preemption   — cli.py's SIGTERM/preempted exit (reason "preempted")
    peer loss    — cli.py's SyncTimeout handler (reason "peer_lost")
    on demand    — SIGUSR1 (resilience/shutdown.install_usr1_dump) dumps
                   `flight_usr1.json` + all-thread stacks without stopping

The dump embeds a full Chrome-trace document (obs/trace.py), so a failure
artifact opens directly in Perfetto and feeds
`python -m word2vec_tpu.obs.tracediff` like any exported trace.

The module-level `activate()`/`active()` pair mirrors faults.activate():
`Trainer.train()` installs its recorder for the duration of the run so
signal handlers and the watchdog's monitor thread can find the live ring
without threading it through every call chain.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional


def _process_index() -> int:
    """This process's fleet rank for the trace's process track — the same
    pid the heartbeat rows carry. Never imports-or-dies: a dump must work
    even when jax is mid-teardown."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — best-effort identity
        return 0


class FlightRecorder:
    """Bounded ring of the last N steps of spans + counters + log records."""

    #: steps of history kept (counters / log records ring depth; the event
    #: ring holds EVENTS_PER_STEP times as many entries)
    STEPS = 256
    EVENTS_PER_STEP = 16

    def __init__(self, steps: int = STEPS,
                 events_per_step: int = EVENTS_PER_STEP):
        from .trace import TraceRing

        self.steps = max(1, int(steps))
        self.ring = TraceRing(capacity=self.steps * max(1, events_per_step))
        self._lock = threading.Lock()
        self.counters: collections.deque = collections.deque(maxlen=self.steps)
        self.records: collections.deque = collections.deque(maxlen=self.steps)
        #: last-N quality-probe rows (obs/quality.QualityProbe) — embedded
        #: in every dump so a failure artifact shows the quality trajectory
        #: that led there, not just the perf timeline
        self.quality: collections.deque = collections.deque(maxlen=32)
        #: last-N derived-signal rows and SLO events (obs/signals.py /
        #: obs/slo.py): a failure artifact carries the windowed signal
        #: trajectory — and any warn/breach escalation — that led there
        self.signals: collections.deque = collections.deque(maxlen=64)
        #: last-N memory-ledger rows (obs/devmem.MemoryLedger): every
        #: flight.json shows the device-memory trajectory that led to the
        #: failure — an OOM artifact names its own watermark history
        self.memory: collections.deque = collections.deque(maxlen=64)
        #: the last step boundary observed (None before any)
        self.last_step: Optional[int] = None

    # ------------------------------------------------------------ recording
    def note_step(self, step: int, t0: float, dur_s: float,
                  kind: str = "step", **args) -> None:
        """One step/chunk/epoch parent span ('X' with the step index in
        args) — the trainers call this at every boundary."""
        if kind in ("step", "chunk"):
            self.last_step = int(step)
        self.ring.complete(kind, t0, dur_s, args={"step": int(step), **args})

    def note_counters(self, step: int, counters: Dict[str, float]) -> None:
        """One drained health-counter observation (the lagged metrics drain
        — obs/health.py): a counter trace event plus a ring row."""
        row: Dict[str, float] = {"step": int(step)}
        for k, v in counters.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            row[k] = float(v)
        with self._lock:
            self.counters.append(row)
        self.ring.counter(
            "health", {k: v for k, v in row.items() if k != "step"}
        )

    def note_heartbeat(self, rows, step: int) -> None:
        """One multi-process heartbeat's (pid, stop, step, p50) rows —
        recorded so a peer-loss dump shows the fleet's last known state,
        and so the merged trace can attribute tracks to hosts."""
        try:
            clean = [[float(x) for x in r] for r in rows]
        except (TypeError, ValueError):
            return
        self.ring.instant(
            "heartbeat", args={"at_step": int(step), "rows": clean}
        )

    def note_quality(self, row: Dict) -> None:
        """One quality-probe row (or sentinel alert record): the bounded
        quality ring every flight.json dump carries."""
        with self._lock:
            self.quality.append(dict(row))

    def note_signal(self, row: Dict) -> None:
        """One derived-signal window row or SLO event (obs/signals.py):
        the bounded signal ring every flight.json dump carries."""
        with self._lock:
            self.signals.append(dict(row))

    def note_mem(self, row: Dict) -> None:
        """One memory-ledger sample (obs/devmem.MemoryLedger): the bounded
        memory ring every flight.json dump carries."""
        with self._lock:
            self.memory.append(dict(row))

    def log_record(self, rec: Dict) -> None:
        """One log record (sink-compatible: the trainers' _log feeds this
        alongside the run's MetricsHub)."""
        with self._lock:
            self.records.append(dict(rec))

    # ------------------------------------------------------------- dumping
    def snapshot(self, reason: str, extra: Optional[Dict] = None) -> Dict:
        """The flight.json payload: an embedded Chrome-trace doc plus the
        counter and log-record tails."""
        from .trace import chrome_trace_doc

        with self._lock:
            counters = list(self.counters)
            records = list(self.records)
            quality = list(self.quality)
            signals = list(self.signals)
            memory = list(self.memory)
        snap: Dict = {
            "event": "flight",
            "reason": reason,
            "created_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "last_step": self.last_step,
            "dropped_events": self.ring.dropped,
            "trace": chrome_trace_doc(
                self.ring.events(), process_index=_process_index()
            ),
            "counters": counters,
            "log_records": records,
            "quality": quality,
            "signals": signals,
            "memory": memory,
        }
        if extra:
            snap.update(extra)
        return snap

    def dump(self, metrics_dir: str, reason: str,
             extra: Optional[Dict] = None,
             filename: str = "flight.json") -> Optional[str]:
        """Write the snapshot into `metrics_dir` (atomic tmp+rename).
        Best-effort by contract: returns the path, or None on any failure —
        a dump must never mask the failure it documents."""
        try:
            os.makedirs(metrics_dir, exist_ok=True)
            path = os.path.join(metrics_dir, filename)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(reason, extra), f, indent=2,
                          default=str)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — see docstring
            return None


# ---------------------------------------------------- process-wide recorder
# The watchdog's monitor thread and the SIGUSR1 handler need the LIVE
# recorder without a reference being threaded to them; Trainer.train()
# scopes its recorder here (same pattern as faults.activate()).
_ACTIVE: Optional[FlightRecorder] = None


def activate(recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install the process-wide flight recorder; returns the previous one
    (restore it in a finally when scoping)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = recorder
    return prev


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def dump_active(metrics_dir: str, reason: str,
                extra: Optional[Dict] = None,
                filename: str = "flight.json") -> Optional[str]:
    """Dump the process-wide recorder, if any (the watchdog's fallback when
    it was constructed without an explicit recorder)."""
    fr = _ACTIVE
    if fr is None:
        return None
    return fr.dump(metrics_dir, reason, extra=extra, filename=filename)
