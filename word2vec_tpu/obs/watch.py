"""Terminal fleet dashboard: `python -m word2vec_tpu.obs.watch --dir DIR`.

The second shipped read-only consumer of the signal plane (the first is the
fleet-health verdict in TrainReport): tails `fleet.json` (obs/fleet.py) in a
metrics directory and renders the fleet's derived signals as a compact
refreshing table — throughput trend, straggler attribution, SLO state from
the run's manifest — with zero interaction with the run itself (it reads
artifacts the signal plane already writes; killing the watcher changes
nothing).

`--once` renders a single snapshot and exits (testable / pipe-friendly);
the default loop refreshes every `--interval` seconds with an ANSI
clear-home, no curses dependency.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List, Optional

#: windows shown in the trend table
SHOW_WINDOWS = 12


def _load(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _sparkline(vals: List[float]) -> str:
    blocks = "▁▂▃▄▅▆▇█"
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        blocks[min(len(blocks) - 1, int((v - lo) / span * (len(blocks) - 1)))]
        for v in vals
    )


def render(doc: Dict, slo: Optional[Dict] = None) -> str:
    """fleet.json (+ optional manifest slo summary) -> the dashboard text.
    Pure string assembly, so tests can pin it without a terminal."""
    lines: List[str] = []
    windows = doc.get("windows", [])
    last = doc.get("last") or {}
    hosts = doc.get("hosts", [])
    lines.append(
        f"fleet: {len(hosts)} host(s) {hosts} · "
        f"{doc.get('windows_total', 0)} window(s)"
        + (f" · {doc.get('window_steps')} steps/window"
           if doc.get("window_steps") else "")
        + f" · generated {doc.get('generated_utc', '?')}"
    )
    tp = [w["throughput_wps"] for w in windows if "throughput_wps" in w]
    if tp:
        lines.append(
            f"  throughput_wps   {tp[-1]:>12,.1f}  {_sparkline(tp[-SHOW_WINDOWS:])}"
        )
    for key, label in (
        ("step_time_p50_ms_median", "step_p50_ms"),
        ("input_bound_ratio_mean", "input_bound"),
        ("quality_planted_min", "quality_min"),
        ("serve_qps", "serve_qps"),
        ("serve_p99_ms_max", "serve_p99_ms"),
        ("cache_hit_mean", "cache_hit"),
        # device-memory rows (obs/devmem.py via the signal plane): the
        # fleet's worst-host headroom and peak HBM watermark
        ("mem_headroom_frac_min", "mem_headroom"),
        ("mem_peak_bytes_max", "mem_peak_bytes"),
    ):
        series = [w[key] for w in windows if key in w]
        if series:
            lines.append(
                f"  {label:<16} {series[-1]:>12,.3f}  "
                f"{_sparkline(series[-SHOW_WINDOWS:])}"
            )
    if last.get("mem_worst_host") is not None:
        lines.append(
            f"  mem worst host   host {last['mem_worst_host']} "
            f"({last.get('mem_headroom_frac_min', '?')} headroom frac)"
        )
    s = doc.get("straggler")
    if s:
        lines.append(
            f"  straggler        host {s['host']} "
            f"(worst in {s['windows_worst']} window(s), "
            f"{s['max_vs_median']}x fleet median)"
        )
    elif last:
        lines.append("  straggler        none named")
    if slo:
        lines.append(
            f"  slo              {slo.get('state', '?')} "
            f"({slo.get('breaches_total', 0)} breach(es), "
            f"{slo.get('warns_total', 0)} warn(s))"
        )
        for r in slo.get("rules", ()):
            lines.append(
                f"    {r.get('state', '?'):<7} {r.get('rule', '?')}"
                + (f"  last={r['last_value']}" if "last_value" in r else "")
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m word2vec_tpu.obs.watch",
        description="tail fleet.json as a terminal dashboard",
    )
    ap.add_argument("--dir", required=True,
                    help="metrics directory holding fleet.json "
                         "(and optionally manifest.json for SLO state)")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot and exit")
    args = ap.parse_args(argv)
    fleet_path = os.path.join(args.dir, "fleet.json")
    man_path = os.path.join(args.dir, "manifest.json")
    while True:
        doc = _load(fleet_path)
        man = _load(man_path) or {}
        slo = man.get("slo")
        if doc is None:
            out = f"waiting for {fleet_path} ..."
        else:
            out = render(doc, slo)
        if args.once:
            print(out)
            return 0 if doc is not None else 1
        print("\x1b[2J\x1b[H" + out, flush=True)
        time.sleep(max(0.2, args.interval))


if __name__ == "__main__":
    raise SystemExit(main())
