"""Metric exporters: one fan-out hub, many sinks.

`MetricsHub` replaces the ad-hoc `utils.logging.tee(...)` wiring: drivers
hold ONE callable, sinks are registered once, and everything closeable is
flushed/closed in one place at run end (the jsonl handle leak this PR's
satellite fixes was exactly a missing single close point). Any callable is
a sink — `progress_logger`, `jsonl_logger`, `tensorboard_logger`, and the
Prometheus textfile exporter below.

`prometheus_textfile(path)` maintains a node-exporter-style textfile: every
record updates a gauge set, and the whole exposition is atomically
rewritten (tmp + rename, so a scraping collector never reads a torn file).
Numeric top-level record keys become `w2v_<key>` gauges; the nested
per-phase stats dict (obs/phases.PhaseRecorder.snapshot) flattens to
`w2v_phase_<stat>{phase="..."}`. Non-numeric values are skipped — gauges
are for continuous signals — but RESILIENCE EVENT records increment
monotonic counters (EVENT_COUNTERS below: recoveries / stalls / peer losses
/ resume fallbacks), always present in the exposition from zero so a
dashboard can alert on `increase()` without waiting for the first incident.
Every rewrite stamps `w2v_exposition_timestamp_seconds` so a scraper can
tell a live file from a dead run's last exposition.
"""

from __future__ import annotations

import os
import re
import time
from typing import Callable, Dict, List, Optional

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

#: event-record kinds counted as monotonic counters. The events arrive on
#: the same hub the JSONL sees: the supervisor logs auto_recover, the
#: trainers log resume_fallback, cli.py feeds stalled / peer_lost on the
#: corresponding abort paths (the stall path via the watchdog's flush_fn,
#: since os._exit skips every atexit hook), and the quality probe
#: (obs/quality.py) logs quality_probe on every probe and quality_alert
#: when the sentinel escalates past its budget. All present in the
#: exposition from zero so a dashboard can alert on `increase()` without
#: waiting for the first incident.
EVENT_COUNTERS = {
    "auto_recover": "w2v_recoveries_total",
    "stalled": "w2v_stalls_total",
    "peer_lost": "w2v_peer_lost_total",
    "resume_fallback": "w2v_resume_fallbacks_total",
    "quality_probe": "w2v_quality_probes_total",
    "quality_alert": "w2v_quality_alerts_total",
    # elastic shrink/grow (resilience/elastic.py): a remesh event fires on
    # both legs — the recovering generation counts it before its in-place
    # exec, and in-process ShardedTrainer.remesh() calls count here too; a
    # rejoined host's admission counts peer_rejoin on every fleet member.
    # (The w2v_mesh_size GAUGE rides the ordinary record path: the CLI logs
    # a numeric mesh_size record at every generation start.)
    "remesh": "w2v_remesh_total",
    "peer_rejoin": "w2v_peer_rejoin_total",
    # rank-0 survival (resilience/elastic.py): a rendezvous re-election —
    # the incumbent host died and the lowest surviving rank bound its
    # standby address to host the round. Counted by every survivor that
    # participated (elected host and joiners alike).
    "rendezvous_election": "w2v_rendezvous_elections_total",
    # purpose-driven remeshes (resilience/policy.py): a shrink/grow whose
    # trigger was the elastic policy, not a failure. Fires alongside the
    # plain remesh counter on the recovering generation's hub.
    "policy_remesh": "w2v_policy_remesh_total",
    # SLO breaches (obs/slo.py): a rule that stayed breached for its `for=`
    # budget of consecutive windows. A breach is a log + event, never an
    # exit — but a dashboard must be able to alert on increase() from zero.
    "slo_breach": "w2v_slo_breaches_total",
    # continuous training (stream/driver.py): online-growth admissions,
    # and hot table swaps into a live serve engine — accepted swaps and
    # quality-gate refusals counted separately, so a dashboard can alert
    # on refusals climbing while swaps stall (a degrading trainer).
    "vocab_growth": "w2v_vocab_growth_total",
    "table_swap": "w2v_table_swaps_total",
    "table_swap_refused": "w2v_table_swap_refused_total",
    # device-truth observability (obs/profiler.py): completed bounded
    # profiler windows — a dashboard alerting on breaches can confirm the
    # evidence capture actually ran (increase() on both counters together).
    "profiler_capture": "w2v_profiler_captures_total",
}

#: event kinds whose NUMERIC fields also land as gauges. Mesh topology
#: (w2v_mesh_size / w2v_mesh_processes / w2v_elastic_generation) is a
#: continuous signal that only changes at remesh boundaries, so it rides
#: the event channel (one record per generation, rendered as a labelled
#: line by the console sink) but must still be scrapeable as a gauge.
#: "signals" rows (obs/signals.py, one per closed window: w2v_signal_*)
#: and "fleet" rows (obs/fleet.py rank-0 aggregation: w2v_fleet_*) are the
#: signal plane's continuous outputs and ride the same channel. "stream"
#: rows (stream/driver.py, one per segment boundary) carry the
#: continuous-training gauges: w2v_vocab_size / w2v_stream_tokens_total /
#: w2v_stream_segment / w2v_vocab_generation — emitted once at run start
#: too, so the gauges are present from zero.
#: "mem" rows (obs/devmem.MemoryLedger, one per ledger sample) carry the
#: device-memory watermarks: w2v_mem_bytes_in_use / w2v_mem_peak_bytes /
#: w2v_mem_bytes_limit / w2v_mem_headroom_frac / w2v_mem_available —
#: present from zero (a statless CPU backend emits zeroed rows rather
#: than nothing). "cost_harvest" rows (obs/harvest.CostHarvest) carry the
#: compiled-program totals: w2v_cost_harvest_flops / _bytes / _programs.
GAUGE_EVENTS = ("mesh", "signals", "fleet", "stream", "mem", "cost_harvest")

#: seconds one sink call may take before the hub detaches it as wedged —
#: generous (a prom textfile rewrite is microseconds; a hung NFS mount or
#: a blocking network sink is what this catches)
SLOW_SINK_S = 5.0


class MetricsHub:
    """Fan out one log record to every registered sink; close them once.

    Sink failures are ISOLATED: a sink that raises, or whose single call
    exceeds `slow_sink_s` wall seconds, is warned about and DETACHED — the
    hub sits inside the training step loop and the serve batch path, and a
    full disk or a wedged remote sink must degrade telemetry, never kill
    the work it observes (regression-pinned with a poisoned sink in
    tests/test_signals.py). A detached sink is still closed by close(), so
    a half-written file gets its flush."""

    def __init__(self, *sinks: Optional[Callable[[Dict], None]],
                 slow_sink_s: float = SLOW_SINK_S):
        self._sinks: List[Callable[[Dict], None]] = []
        self._detached: List[Callable[[Dict], None]] = []
        self.slow_sink_s = float(slow_sink_s)
        for s in sinks:
            self.add(s)

    @property
    def sinks(self) -> List[Callable[[Dict], None]]:
        return list(self._sinks)

    def add(self, sink: Optional[Callable[[Dict], None]]):
        """Register a sink (None is ignored, so callers can pass optional
        sinks unconditionally). Returns the sink for chaining."""
        if sink is not None:
            self._sinks.append(sink)
        return sink

    def _detach(self, sink, why: str) -> None:
        import warnings

        try:
            self._sinks.remove(sink)
        except ValueError:
            return
        self._detached.append(sink)
        warnings.warn(
            f"metrics sink {sink!r} detached: {why}. Telemetry from this "
            "sink stops here; the run continues.",
            stacklevel=3,
        )

    def __call__(self, record: Dict) -> None:
        for s in list(self._sinks):
            t0 = time.perf_counter()
            try:
                s(record)
            except Exception as e:  # noqa: BLE001 — see class docstring
                self._detach(s, f"raised {e!r}")
                continue
            if (
                self.slow_sink_s
                and time.perf_counter() - t0 > self.slow_sink_s
            ):
                self._detach(
                    s,
                    f"one call took > {self.slow_sink_s:g}s "
                    "(wedged or blocking sink)",
                )

    def close(self) -> None:
        """Flush/close every sink that supports it — detached sinks
        included (their files deserve a flush). Best-effort: a sink
        failing to close must not mask a training result that is already
        computed (the failure is warned, not raised)."""
        for s in self._sinks + self._detached:
            close = getattr(s, "close", None)
            if close is None:
                continue
            try:
                close()
            except Exception as e:  # noqa: BLE001 — see docstring
                import warnings

                warnings.warn(
                    f"metrics sink {s!r} failed to close: {e}", stacklevel=2
                )


def _metric_name(key: str) -> str:
    name = "w2v_" + _NAME_OK.sub("_", str(key))
    return name


def _label_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class PrometheusTextfile:
    """Gauge-set sink writing the Prometheus text exposition format."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(parent, exist_ok=True)
        # (name, labels-tuple) -> float; insertion order = exposition order
        self._gauges: Dict = {}
        # resilience counters, present from zero (see EVENT_COUNTERS)
        self._counters: Dict[str, float] = {
            name: 0.0 for name in EVENT_COUNTERS.values()
        }
        # real cumulative histograms (name -> {"le", "counts", "sum",
        # "count"}): the latest cumulative state per metric — the feeder
        # (obs/signals.Histogram, serve/metrics.ServeStats) accumulates;
        # this sink only renders _bucket/_sum/_count. A p99-as-gauge cannot
        # be aggregated across replicas; bucket counts can be summed.
        self._hists: Dict[str, Dict] = {}

    @staticmethod
    def _is_hist(key: str, val) -> bool:
        return (
            key.endswith("_hist")
            and isinstance(val, dict)
            and isinstance(val.get("le"), list)
            and isinstance(val.get("counts"), list)
            and len(val["counts"]) == len(val["le"]) + 1
            and "sum" in val
            and "count" in val
        )

    def _set_hist(self, key: str, val: Dict) -> None:
        self._hists[_metric_name(key[: -len("_hist")])] = val

    def __call__(self, record: Dict) -> None:
        if "event" in record:
            # one-off notices are not gauges — but resilience events count,
            # and GAUGE_EVENTS carry continuous signals worth scraping
            dirty = False
            name = EVENT_COUNTERS.get(record["event"])
            if name is not None:
                self._counters[name] += 1.0
                dirty = True
            if record["event"] in GAUGE_EVENTS:
                for key, val in record.items():
                    if self._is_hist(key, val):
                        self._set_hist(key, val)
                        dirty = True
                        continue
                    if key == "event" or isinstance(val, bool) or not (
                        isinstance(val, (int, float))
                    ):
                        continue
                    self._set(_metric_name(key), (), val)
                    dirty = True
            if dirty:
                self._write()
            return
        for key, val in record.items():
            if key == "phases" and isinstance(val, dict):
                for phase, stats in val.items():
                    if not isinstance(stats, dict):
                        continue
                    for stat, sv in stats.items():
                        if isinstance(sv, bool) or not isinstance(sv, (int, float)):
                            continue
                        self._set(
                            f"w2v_phase_{_NAME_OK.sub('_', stat)}",
                            (("phase", str(phase)),),
                            sv,
                        )
                continue
            if self._is_hist(key, val):
                self._set_hist(key, val)
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            self._set(_metric_name(key), (), val)
        self._write()

    def _set(self, name: str, labels, value) -> None:
        self._gauges[(name, labels)] = float(value)

    @staticmethod
    def _fmt(value: float) -> str:
        # the exposition format spells non-finite values NaN/+Inf/-Inf
        if value != value:
            return "NaN"
        if value == float("inf"):
            return "+Inf"
        if value == float("-inf"):
            return "-Inf"
        return repr(value)

    def render(self) -> str:
        """The full text exposition as a string — the serve HTTP
        `/metrics` endpoint returns this directly; `_write` persists the
        same bytes to the textfile."""
        return "\n".join(self._render_lines()) + "\n"

    def _render_lines(self) -> List[str]:
        by_name: Dict[str, List] = {}
        for (name, labels), value in self._gauges.items():
            by_name.setdefault(name, []).append((labels, value))
        lines = []
        for name, series in by_name.items():
            lines.append(f"# HELP {name} word2vec_tpu training metric")
            lines.append(f"# TYPE {name} gauge")
            for labels, value in series:
                if labels:
                    lbl = ",".join(
                        f'{k}="{_label_escape(v)}"' for k, v in labels
                    )
                    lines.append(f"{name}{{{lbl}}} {self._fmt(value)}")
                else:
                    lines.append(f"{name} {self._fmt(value)}")
        for name, hist in self._hists.items():
            lines.append(f"# HELP {name} word2vec_tpu latency histogram")
            lines.append(f"# TYPE {name} histogram")
            for bound, count in zip(hist["le"], hist["counts"]):
                lines.append(
                    f'{name}_bucket{{le="{float(bound):g}"}} '
                    f"{self._fmt(float(count))}"
                )
            lines.append(
                f'{name}_bucket{{le="+Inf"}} '
                f"{self._fmt(float(hist['counts'][-1]))}"
            )
            lines.append(f"{name}_sum {self._fmt(float(hist['sum']))}")
            lines.append(f"{name}_count {self._fmt(float(hist['count']))}")
        for name, value in self._counters.items():
            lines.append(f"# HELP {name} word2vec_tpu event counter")
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {self._fmt(value)}")
        # when this exposition was last rewritten (a scraper's liveness check)
        ts_name = "w2v_exposition_timestamp_seconds"
        lines.append(f"# HELP {ts_name} unix time of the last exposition write")
        lines.append(f"# TYPE {ts_name} gauge")
        lines.append(f"{ts_name} {self._fmt(time.time())}")
        return lines

    def _write(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render())
        os.replace(tmp, self.path)

    def close(self) -> None:
        if self._gauges or self._hists or any(self._counters.values()):
            self._write()


def prometheus_textfile(path: str) -> PrometheusTextfile:
    """Factory matching the utils.logging sink-constructor idiom."""
    return PrometheusTextfile(path)
