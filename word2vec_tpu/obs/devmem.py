"""HBM memory ledger: device-truth memory accounting on the existing planes.

Every telemetry layer so far observes the HOST's view of the run: span
clocks, counters the step program itself computes, windowed signals over
both. What the DEVICE is doing with its memory — live bytes, the peak
watermark, how much headroom a vocab-growth rebuild or a serve table swap
actually has — was a black box, probed exactly once by the resident-corpus
budget gate (ops/resident.py, which now routes through `device_memory_stats`
below). This module turns that one-off probe into a ledger:

  device_memory_stats — the ONE funnel for `device.memory_stats()`:
                        normalized {bytes_in_use, peak_bytes_in_use,
                        bytes_limit, bytes_reserved} or None on backends
                        that report nothing (CPU returns None/{} — the
                        graceful-degrade contract: gauges present from
                        zero, never a crash). The resident budget gate and
                        the ledger share it so the two can never disagree
                        on what the device said.

  MemoryLedger        — per-phase watermark accounting, beaten from
                        `Trainer._check_stop` at step/chunk boundaries.
                        Non-sample boundaries are ONE integer compare —
                        zero extra device dispatches (memory_stats is a
                        host-side client call, and even that only runs on
                        the sample cadence; pinned by tests/test_devmem.py
                        alongside the watchdog/signals beat contract).
                        Every sample attributes the live/peak deltas to the
                        phase that produced them (init, table placement,
                        train step, vocab-growth rebuild, serve table swap)
                        and emits ONE "mem" event record whose numeric
                        fields become `w2v_mem_*` gauges
                        (obs/export.GAUGE_EVENTS), a row on the flight
                        recorder's bounded memory ring (every flight.json
                        carries the recent memory trajectory), and — via
                        the SignalEngine's hub-sink harvest — a
                        `mem_headroom_frac` derived signal, which makes
                        memory SLO-able with the existing grammar
                        (`--slo 'mem_headroom_frac<0.1:for=2'` breaches
                        like any other rule, and obs/fleet.py merges the
                        per-host rows with worst-host attribution).

  growth headroom     — `forecast()` projects rows-remaining until table
                        growth exhausts the budget: free HBM divided by the
                        realized bytes/row of the configured table layout.
                        Landed in the manifest so a `--vocab-reserve` run
                        can see whether its reserve even fits BEFORE the
                        admission boundary recompiles into an OOM.

Like the flight recorder, the module keeps an `activate()`/`active()`
process-wide slot so call sites that cannot thread a reference (the serve
engine's `swap_table`, the SIGUSR2 dump) find the live ledger.

`W2V_FAKE_MEMORY_STATS` (a `key=value,...` spec) substitutes for the device
report — the CI/chaos hook that lets a CPU run exercise the full
mem-SLO-breach -> profiler-capture path where no real HBM exists. It is a
test hook by contract, never set in production.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Dict, List, Optional

#: default step/chunk boundaries between train-phase samples. One sample is
#: one host-side client call per local device — cheap, but not free, so the
#: beat dilutes it; every non-sample boundary is an integer compare.
SAMPLE_EVERY_DEFAULT = 50

#: bounded per-ledger row history (flight keeps its own ring; this one
#: backs summary() and the SIGUSR2 dump)
ROWS_KEPT = 256

#: phase names the ledger attributes watermarks to (free-form strings are
#: accepted; these are the wired ones)
PHASE_INIT = "init"
PHASE_TABLE_PLACE = "table_place"
PHASE_TRAIN = "train_step"
PHASE_VOCAB_GROWTH = "vocab_growth"
PHASE_SERVE_SWAP = "serve_swap"

#: the CI/test substitution hook (see module docstring)
FAKE_STATS_ENV = "W2V_FAKE_MEMORY_STATS"

_STAT_KEYS = (
    "bytes_in_use", "peak_bytes_in_use", "bytes_limit", "bytes_reserved",
)


def _fake_stats() -> Optional[Dict[str, int]]:
    spec = os.environ.get(FAKE_STATS_ENV)
    if not spec:
        return None
    out: Dict[str, int] = {}
    for clause in spec.split(","):
        key, _, val = clause.partition("=")
        key = key.strip()
        if key in _STAT_KEYS:
            try:
                out[key] = int(float(val))
            except ValueError:
                continue
    return out or None


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """Normalized memory stats of one device, or None when the backend
    reports nothing (CPU returns None or {}). Never raises: an
    unaddressable device (a remote mesh peer) degrades to None, same as a
    statless backend — callers gate on the result, not on exceptions."""
    fake = _fake_stats()
    if fake is not None:
        return dict(fake)
    if device is None:
        try:
            import jax

            device = jax.local_devices()[0]
        except Exception:  # noqa: BLE001 — stats are advisory
            return None
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — see docstring
        return None
    if not stats:
        return None
    out = {k: int(stats[k]) for k in _STAT_KEYS if k in stats}
    return out or None


def headroom_fraction(stats: Dict[str, int]) -> Optional[float]:
    """free / limit of one normalized stats dict; None without a limit."""
    limit = stats.get("bytes_limit")
    if not limit:
        return None
    free = max(0, int(limit) - int(stats.get("bytes_in_use", 0)))
    return free / float(limit)


def table_row_bytes(config) -> int:
    """Realized bytes one vocabulary row costs in the embedding tables:
    both planes (input + output — split pair or unified slab, same total)
    at the configured storage dtype. The growth-forecast denominator.
    (bfloat16 is not a numpy dtype name; sized explicitly.)"""
    import numpy as np

    dtype = str(getattr(config, "dtype", "float32"))
    itemsize = 2 if dtype == "bfloat16" else np.dtype(dtype).itemsize
    return 2 * int(config.word_dim) * int(itemsize)


class MemoryLedger:
    """Per-phase device-memory watermarks on the run's existing planes."""

    def __init__(
        self,
        sample_every: int = SAMPLE_EVERY_DEFAULT,
        devices=None,
        log_fn: Optional[Callable[[Dict], None]] = None,
        flight=None,
        host: int = 0,
        row_bytes: int = 0,
        vocab_reserve: int = 0,
    ):
        self.sample_every = max(1, int(sample_every))
        #: explicit device list (tests pass stubs; None = lazy local devices
        #: — resolved per sample so a remesh'd process follows its mesh)
        self.devices = devices
        self.log_fn = log_fn
        self.flight = flight
        self.host = int(host)
        #: growth-forecast inputs (0 disables the forecast fields)
        self.row_bytes = int(row_bytes)
        self.vocab_reserve = int(vocab_reserve)
        #: False until a sample actually returned stats; the CPU degrade is
        #: available=False with zeroed gauges, never an error
        self.available = False
        self._lock = threading.Lock()
        self.rows: collections.deque = collections.deque(maxlen=ROWS_KEPT)
        #: phase -> {"samples", "bytes_in_use_max", "peak_bytes_max"}
        self.phases: Dict[str, Dict[str, float]] = {}
        self.samples = 0
        self._next_sample_step: Optional[int] = None
        self._last_stats: Optional[Dict[str, int]] = None

    # ------------------------------------------------------------- sampling
    def _device_list(self) -> List:
        if self.devices is not None:
            return list(self.devices)
        try:
            import jax

            return list(jax.local_devices())
        except Exception:  # noqa: BLE001 — stats are advisory
            return []

    def _read(self) -> Optional[Dict[str, int]]:
        """Worst-local-device stats: max bytes_in_use/peak, min limit —
        the per-process attribution the fleet merge needs (each rank
        reports ITS local devices; obs/fleet.py names the worst host)."""
        fake = _fake_stats()
        if fake is not None:
            return dict(fake)
        per_dev = [
            s for s in (
                device_memory_stats(d) for d in self._device_list()
            ) if s
        ]
        if not per_dev:
            return None
        out: Dict[str, int] = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_reserved"):
            vals = [s[key] for s in per_dev if key in s]
            if vals:
                out[key] = max(vals)
        limits = [s["bytes_limit"] for s in per_dev if "bytes_limit" in s]
        if limits:
            out["bytes_limit"] = min(limits)
        return out or None

    def sample(self, phase: str, step: Optional[int] = None) -> Dict:
        """One ledger sample attributed to `phase`. Always returns a row
        (and emits the gauges) — on a statless backend the byte fields are
        zero and `mem_available` is 0, so dashboards see the series exist
        from the first scrape (present-from-zero), and nothing crashes."""
        stats = self._read()
        row: Dict = {
            "event": "mem",
            "phase": str(phase),
            "host": self.host,
            "mem_available": int(stats is not None),
            "mem_bytes_in_use": 0,
            "mem_peak_bytes": 0,
            "mem_bytes_limit": 0,
        }
        if step is not None:
            row["step"] = int(step)
        if stats is not None:
            self.available = True
            self._last_stats = dict(stats)
            row["mem_bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            row["mem_peak_bytes"] = int(
                stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
            )
            if stats.get("bytes_limit"):
                row["mem_bytes_limit"] = int(stats["bytes_limit"])
                hf = headroom_fraction(stats)
                if hf is not None:
                    row["mem_headroom_frac"] = round(hf, 6)
            rows_left = self._rows_remaining(stats)
            if rows_left is not None:
                row["mem_growth_rows_remaining"] = rows_left
        with self._lock:
            self.samples += 1
            self.rows.append(dict(row))
            ph = self.phases.setdefault(
                str(phase),
                {"samples": 0, "bytes_in_use_max": 0, "peak_bytes_max": 0},
            )
            ph["samples"] += 1
            ph["bytes_in_use_max"] = max(
                ph["bytes_in_use_max"], row["mem_bytes_in_use"]
            )
            ph["peak_bytes_max"] = max(
                ph["peak_bytes_max"], row["mem_peak_bytes"]
            )
        if self.flight is not None:
            note = getattr(self.flight, "note_mem", None)
            if note is not None:
                note(row)
        if self.log_fn is not None:
            self.log_fn(dict(row))
        return row

    def on_boundary(self, step: int) -> None:
        """The trainer beat (Trainer._check_stop): one integer compare on
        non-sample boundaries — no client call, no dispatch, nothing."""
        if self._next_sample_step is None:
            # first boundary: sample immediately so short runs still land
            # one train-phase row (the signals first-window discipline)
            self._next_sample_step = int(step) + self.sample_every
            self.sample(PHASE_TRAIN, step=step)
            return
        if step < self._next_sample_step:
            return
        self._next_sample_step = int(step) + self.sample_every
        self.sample(PHASE_TRAIN, step=step)

    # ------------------------------------------------------------ forecast
    def _rows_remaining(self, stats: Dict[str, int]) -> Optional[int]:
        """Rows of table growth the CURRENT free memory could still hold at
        the realized bytes/row (0 disables). The vocab-growth headroom
        forecast: reserve rows are pre-allocated at init, so this measures
        how far a FUTURE re-init (a bigger --vocab-reserve, a table
        rebuild) could stretch before the budget is gone."""
        if self.row_bytes <= 0:
            return None
        limit = stats.get("bytes_limit")
        if not limit:
            return None
        free = max(0, int(limit) - int(stats.get("bytes_in_use", 0)))
        return int(free // self.row_bytes)

    def forecast(self) -> Optional[Dict]:
        """The manifest's growth-headroom block (None before any live
        sample or without row-bytes wiring)."""
        if self.row_bytes <= 0:
            return None
        stats = self._last_stats
        rows_left = self._rows_remaining(stats) if stats else None
        out: Dict = {
            "row_bytes": self.row_bytes,
            "vocab_reserve": self.vocab_reserve,
            "reserve_bytes": self.row_bytes * self.vocab_reserve,
            "rows_remaining": rows_left,
        }
        if rows_left is not None and self.vocab_reserve > 0:
            out["reserve_fits"] = bool(rows_left >= 0)
        return out

    # ------------------------------------------------------------- summary
    def summary(self) -> Dict:
        """TrainReport.device_memory / manifest payload: availability, the
        overall and per-phase watermarks, and the growth forecast."""
        with self._lock:
            rows = list(self.rows)
            phases = {k: dict(v) for k, v in self.phases.items()}
        out: Dict = {
            "available": self.available,
            "samples": self.samples,
            "sample_every": self.sample_every,
            "phases": phases,
        }
        if rows:
            out["peak_bytes"] = max(r["mem_peak_bytes"] for r in rows)
            out["last_bytes_in_use"] = rows[-1]["mem_bytes_in_use"]
            hfs = [
                r["mem_headroom_frac"] for r in rows
                if "mem_headroom_frac" in r
            ]
            if hfs:
                out["headroom_frac_min"] = round(min(hfs), 6)
                out["headroom_frac_last"] = hfs[-1]
        fc = self.forecast()
        if fc is not None:
            out["growth_forecast"] = fc
        return out

    def dump(self, path: str, reason: str = "on_demand") -> Optional[str]:
        """Write the ledger (summary + recent rows) as one JSON file — the
        SIGUSR2 on-demand artifact. Best-effort like a flight dump."""
        import json

        try:
            parent = os.path.dirname(os.path.abspath(path)) or "."
            os.makedirs(parent, exist_ok=True)
            doc = {
                "event": "mem_ledger",
                "reason": reason,
                "created_utc": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                "summary": self.summary(),
                "rows": list(self.rows),
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — a dump must never kill the run
            return None


# ----------------------------------------------------- process-wide ledger
# swap_table (serve/query.py) and the SIGUSR2 handler need the live ledger
# without a reference threaded through their call chains — the same pattern
# as obs/flight.activate().
_ACTIVE: Optional[MemoryLedger] = None


def activate(ledger: Optional[MemoryLedger]) -> Optional[MemoryLedger]:
    """Install the process-wide ledger; returns the previous one."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = ledger
    return prev


def active() -> Optional[MemoryLedger]:
    return _ACTIVE


def sample_active(phase: str, step: Optional[int] = None) -> Optional[Dict]:
    """Sample the process-wide ledger, if any (the swap/growth call sites'
    no-op-when-unwired form)."""
    led = _ACTIVE
    if led is None:
        return None
    return led.sample(phase, step=step)
