"""Trace-diff attribution: WHICH span explains a step-time delta.

The question every A/B throughput comparison ends at is "plan B is 1.4
ms/step slower — where?". Until now answering it meant capturing two xprof
traces and eyeballing timelines. This module answers it from the exported
Chrome-trace artifacts (obs/trace.py) directly:

    python -m word2vec_tpu.obs.tracediff A.json B.json [--json] [--top N]

`summarize` reduces a trace to per-span stats normalized PER OPTIMIZER STEP
(the step/chunk parent events carry the step count, so per-step and chunked
traces compare on the same axis); `diff` subtracts two summaries and ranks
spans by the magnitude of their signed per-step delta — the top row IS the
attribution. The same `summarize` feeds bench.py's banked `trace_summary`
(per-span p50 + top step-time contributors) and the planner's
measured-vs-predicted cost rows (tune/cost_model.attribution_rows), so the
number a human reads in a diff is the number the records bank.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional, Union

from .trace import STEP_PARENTS, load_trace


def _events_of(trace: Union[Dict, Iterable[Dict]]) -> List[Dict]:
    if isinstance(trace, dict):
        return list(trace.get("traceEvents", []))
    return list(trace)


def summarize(trace: Union[Dict, Iterable[Dict]], top: int = 3) -> Dict:
    """Per-span stats over one trace (doc or raw ring events).

    Returns {steps, step_ms, spans: {name: {count, total_ms, p50_ms,
    ms_per_step}}, top_contributors: [{span, ms_per_step, share_of_step}]}.
    `steps` sums the step/chunk parents' widths (a chunk parent carries
    args.steps), so ms_per_step is per OPTIMIZER step on both dispatch
    paths; without parents (a bare span trace) the per-step fields are None
    and contributors rank by total time.
    """
    events = _events_of(trace)
    parents = [
        e for e in events
        if e.get("ph") == "X" and e.get("name") in STEP_PARENTS
    ]
    n_steps = sum(
        int((e.get("args") or {}).get("steps", 1)) for e in parents
    )
    parent_ms = sum(float(e.get("dur", 0.0)) for e in parents) / 1e3
    durs_by_span: Dict[str, List[float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name")
        if name in STEP_PARENTS or name == "epoch":
            continue  # parents would double-count their children
        durs_by_span.setdefault(name, []).append(
            float(e.get("dur", 0.0)) / 1e3
        )
    spans: Dict[str, Dict] = {}
    for name in sorted(durs_by_span):
        durs = sorted(durs_by_span[name])
        total = sum(durs)
        spans[name] = {
            "count": len(durs),
            "total_ms": round(total, 4),
            "p50_ms": round(durs[len(durs) // 2], 4),
            "ms_per_step": round(total / n_steps, 4) if n_steps else None,
        }
    step_ms = round(parent_ms / n_steps, 4) if n_steps else None
    ranked = sorted(spans, key=lambda n: -spans[n]["total_ms"])[:top]
    contributors = [
        {
            "span": n,
            "ms_per_step": spans[n]["ms_per_step"],
            "share_of_step": (
                round(spans[n]["ms_per_step"] / step_ms, 4)
                if step_ms else None
            ),
        }
        for n in ranked
    ]
    return {
        "steps": n_steps,
        "step_ms": step_ms,
        "spans": spans,
        "top_contributors": contributors,
    }


def diff(trace_a: Union[Dict, Iterable[Dict]],
         trace_b: Union[Dict, Iterable[Dict]]) -> Dict:
    """Attribute the B-minus-A step-time delta to named spans.

    Every span present in either trace gets a signed per-step delta row;
    rows are ranked by |delta|, and each carries its share of the total
    step delta (shares can exceed 1 when spans moved in opposite
    directions — the signs say which)."""
    sa, sb = summarize(trace_a), summarize(trace_b)
    step_a, step_b = sa.get("step_ms"), sb.get("step_ms")
    step_delta = (
        round(step_b - step_a, 4)
        if step_a is not None and step_b is not None else None
    )
    rows: List[Dict] = []
    for name in sorted(set(sa["spans"]) | set(sb["spans"])):
        a_ms = (sa["spans"].get(name) or {}).get("ms_per_step") or 0.0
        b_ms = (sb["spans"].get(name) or {}).get("ms_per_step") or 0.0
        delta = round(b_ms - a_ms, 4)
        row = {
            "span": name,
            "a_ms_per_step": round(a_ms, 4),
            "b_ms_per_step": round(b_ms, 4),
            "delta_ms_per_step": delta,
        }
        if step_delta:
            row["share_of_step_delta"] = round(delta / step_delta, 4)
        rows.append(row)
    rows.sort(key=lambda r: -abs(r["delta_ms_per_step"]))
    return {
        "steps_a": sa["steps"],
        "steps_b": sb["steps"],
        "step_ms_a": step_a,
        "step_ms_b": step_b,
        "step_delta_ms": step_delta,
        "spans": rows,
        "top_attribution": rows[0]["span"] if rows else None,
    }


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:9.4f}"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m word2vec_tpu.obs.tracediff",
        description="attribute a step-time delta between two exported "
                    "traces (--trace DIR artifacts or flight.json's "
                    "embedded trace) to named spans",
    )
    ap.add_argument("trace_a", help="baseline trace JSON (A)")
    ap.add_argument("trace_b", help="candidate trace JSON (B)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable diff instead of a table")
    ap.add_argument("--top", type=int, default=10,
                    help="span rows shown in the table (all rows in --json)")
    args = ap.parse_args(argv)
    docs = []
    for path in (args.trace_a, args.trace_b):
        try:
            doc = load_trace(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            # flight.json embeds its trace one level down — accept it too
            try:
                with open(path) as f:
                    raw = json.load(f)
                doc = raw["trace"]
            except Exception:  # noqa: BLE001 — report the original error
                print(f"error: {path}: {e}", file=sys.stderr)
                return 1
        docs.append(doc)
    d = diff(docs[0], docs[1])
    if args.json:
        print(json.dumps(d, indent=2))
        return 0
    print(
        f"step time: A {_fmt_ms(d['step_ms_a'])} ms  ->  "
        f"B {_fmt_ms(d['step_ms_b'])} ms  "
        f"(delta {_fmt_ms(d['step_delta_ms'])} ms/step; "
        f"{d['steps_a']} vs {d['steps_b']} steps)"
    )
    print(f"{'span':>14}  {'A ms/step':>9}  {'B ms/step':>9}  "
          f"{'delta':>9}  share")
    for row in d["spans"][:args.top]:
        share = row.get("share_of_step_delta")
        print(
            f"{row['span']:>14}  {_fmt_ms(row['a_ms_per_step'])}  "
            f"{_fmt_ms(row['b_ms_per_step'])}  "
            f"{_fmt_ms(row['delta_ms_per_step'])}  "
            f"{'' if share is None else f'{100 * share:+.1f}%'}"
        )
    if d["top_attribution"]:
        print(f"attribution: {d['top_attribution']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
