"""Derived-signal plane: windowed time series over streams that already exist.

Every telemetry layer so far is per-process and RAW: PR 3's counters, PR 6's
spans, PR 8's `w2v_serve_*` gauges, PR 9's quality rows. A control loop (serve
autoscale, elastic shrink/grow policy) cannot subscribe to raw streams — it
needs *derived, decision-grade signals*: "throughput over the last window,
versus its own baseline", "is one host 4x slower than the fleet median". This
module is that derivation layer:

  SignalEngine  — a small windowed time-series engine. Training mode: the
                  trainers beat `on_boundary(step, words_done)` at every
                  step/chunk boundary (one clock read + integer compare off
                  the window edge — ZERO device fetches, the same contract as
                  the watchdog beat); every `window` steps the engine closes a
                  window and derives named signals from host-side state it
                  already has:

                    throughput_wps     words trained / window wall
                    step_time_p50_ms   p50/p90 of boundary-to-boundary time
                    input_bound_ratio  input-stall fraction from the
                                       PhaseRecorder's span totals delta
                    straggler_skew     worst-host p50 / fleet median, from
                                       the PeerAgreement heartbeat rows
                                       (multi-process only)
                    quality_planted    the QualityProbe's planted score
                                       (fed from its gauge records)

                  Serve mode (`window_s`): the server feeds ServeStats
                  snapshots and the engine derives serve_qps / serve_p99_ms /
                  cache_hit per wall-clock window.

  Signal        — one named series: a bounded ring of (window, value) with
                  EWMA / p50 / p90 / per-window slope stats.

  SignalBus     — subscribe(name, cb): the control-ready pub/sub surface.
                  Shipped read-only: the fleet-health verdict in TrainReport
                  and `python -m word2vec_tpu.obs.watch` consume it; serve
                  autoscale (ROADMAP 1d) and elastic policy (5b) are the
                  intended writers-of-actions later. Callbacks are isolated —
                  a raising subscriber is warned and dropped, never allowed
                  to kill a training step.

Windows are identified by `step // window` — the PR 6 trace-merge lesson:
hosts share no clock, but they do share the step counter, so window ids are
comparable across the fleet and obs/fleet.py can merge per-host rows
deterministically. (Serve replicas share no step counter either; serve mode
keys windows on epoch seconds // window_s instead — NTP-grade alignment,
good enough for dashboard-and-policy aggregation.)

Each closed window emits ONE compact row: an "event":"signals" record on the
run's MetricsHub (numeric fields become `w2v_signal_*` gauges via
obs/export.GAUGE_EVENTS), a line in `signals_p<host>.jsonl` under
--metrics-dir (the fleet aggregator's input), a row on the flight recorder's
bounded signal ring (every flight.json carries the recent signal history),
and a publish on the bus. SLO rules (obs/slo.py) are evaluated against the
same row — breach maps to a structured event, NEVER an exit: this PR
observes, it does not actuate.

The standing overhead contract is banked like trace/watchdog/quality before
it: benchmarks/signal_overhead.py (<1% wall) and tests/test_signals.py pin
both the wall and the zero-added-device-fetch invariant.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional

#: default optimizer steps per derived-signal window (training mode)
WINDOW_STEPS_DEFAULT = 50
#: default seconds per window (serve mode)
WINDOW_SECS_DEFAULT = 10.0
#: per-signal ring depth: stats come from the most recent windows
RING_WINDOWS = 256
#: default EWMA smoothing factor (weight of the newest window)
EWMA_ALPHA = 0.3

#: cumulative step-time histogram bucket bounds, seconds (le-style; +Inf is
#: implicit). Spans CPU-smoke chunk walls down to on-chip step times.
STEP_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0,
)


def percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile (same convention as serve/metrics.py and
    profiling.lap_stats: no interpolation)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(q * len(s) + 0.5) - 1))
    return s[idx]


def ewma(values: List[float], alpha: float = EWMA_ALPHA) -> float:
    """Exponentially-weighted moving average, oldest-first input."""
    if not values:
        return 0.0
    acc = float(values[0])
    for v in values[1:]:
        acc = alpha * float(v) + (1.0 - alpha) * acc
    return acc


def slope(points: List) -> float:
    """Least-squares slope of (x, y) points — the signal's per-window trend
    (value units per window). 0.0 with fewer than two distinct x."""
    if len(points) < 2:
        return 0.0
    xs = [float(x) for x, _ in points]
    ys = [float(y) for _, y in points]
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    den = sum((x - mx) ** 2 for x in xs)
    if den <= 0.0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / den


class Histogram:
    """Cumulative histogram in Prometheus semantics: per-bucket counts are
    monotonic totals (le-bounded), plus _sum and _count — the aggregatable
    form a p99 GAUGE can never be (you cannot merge per-replica p99s, but
    you can sum per-replica bucket counts). Rendered by
    obs/export.PrometheusTextfile from any record key ending in `_hist`."""

    def __init__(self, buckets=STEP_TIME_BUCKETS):
        self.le = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.le) + 1)  # last slot = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.sum += v
        self.count += 1
        for i, bound in enumerate(self.le):
            if v <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def to_record(self) -> Dict:
        """The exposition payload (cumulative le counts, the wire shape the
        Prometheus sink renders as _bucket/_sum/_count)."""
        cum, acc = [], 0
        for c in self.counts:
            acc += c
            cum.append(acc)
        return {
            "le": list(self.le),
            "counts": cum,
            "sum": round(self.sum, 6),
            "count": self.count,
        }


class Signal:
    """One named windowed series with ring-bounded stats."""

    def __init__(self, name: str, ring: int = RING_WINDOWS):
        self.name = name
        self._ring: collections.deque = collections.deque(maxlen=ring)

    def observe(self, window: int, value: float) -> None:
        self._ring.append((int(window), float(value)))

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def last(self) -> Optional[float]:
        return self._ring[-1][1] if self._ring else None

    def stats(self) -> Dict:
        pts = list(self._ring)
        vals = [v for _, v in pts]
        if not vals:
            return {"n": 0}
        return {
            "n": len(vals),
            "last": round(vals[-1], 6),
            "ewma": round(ewma(vals), 6),
            "p50": round(percentile(vals, 0.50), 6),
            "p90": round(percentile(vals, 0.90), 6),
            "slope_per_window": round(slope(pts), 6),
        }


class SignalBus:
    """Named-topic pub/sub for derived signals. `subscribe` returns an
    unsubscribe callable; a raising callback is warned and DETACHED (the
    bus must never kill the step loop that publishes into it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Callable]] = {}

    def subscribe(self, name: str, cb: Callable[[Dict], None]) -> Callable[[], None]:
        with self._lock:
            self._subs.setdefault(name, []).append(cb)

        def unsubscribe() -> None:
            with self._lock:
                try:
                    self._subs.get(name, []).remove(cb)
                except ValueError:
                    pass

        return unsubscribe

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(k for k, v in self._subs.items() if v)

    def publish(self, name: str, payload: Dict) -> None:
        with self._lock:
            cbs = list(self._subs.get(name, ()))
        for cb in cbs:
            try:
                cb(payload)
            except Exception as e:  # noqa: BLE001 — see class docstring
                warnings.warn(
                    f"signal bus subscriber {cb!r} on {name!r} raised "
                    f"{e!r}; detaching it",
                    stacklevel=2,
                )
                with self._lock:
                    try:
                        self._subs.get(name, []).remove(cb)
                    except ValueError:
                        pass


class FleetHealth:
    """Read-only bus consumer: the fleet-health verdict TrainReport carries.
    Tracks the worst SLO state seen and the last fleet/signals row — a
    one-glance "did the run stay inside its SLOs, and who lagged"."""

    _RANK = {"ok": 0, "warn": 1, "breach": 2}

    def __init__(self, bus: SignalBus):
        self._lock = threading.Lock()
        self.state = "ok"
        self.worst_state = "ok"
        self.breaches = 0
        self.warns = 0
        self.last_fleet: Optional[Dict] = None
        self.last_window: Optional[int] = None
        self._unsubs = [
            bus.subscribe("slo", self._on_slo),
            bus.subscribe("fleet", self._on_fleet),
            bus.subscribe("signals", self._on_signals),
        ]

    def _on_slo(self, ev: Dict) -> None:
        state = {"slo_breach": "breach", "slo_warn": "warn"}.get(
            ev.get("event"), "ok"
        )
        with self._lock:
            self.state = state
            if self._RANK[state] > self._RANK[self.worst_state]:
                self.worst_state = state
            if state == "breach":
                self.breaches += 1
            elif state == "warn":
                self.warns += 1

    def _on_fleet(self, row: Dict) -> None:
        with self._lock:
            self.last_fleet = dict(row)

    def _on_signals(self, row: Dict) -> None:
        with self._lock:
            self.last_window = row.get("window")

    def verdict(self) -> Dict:
        with self._lock:
            out = {
                "verdict": self.worst_state,
                "current": self.state,
                "slo_breaches": self.breaches,
                "slo_warns": self.warns,
                "windows": self.last_window,
            }
            if self.last_fleet:
                out["fleet_hosts"] = self.last_fleet.get("fleet_hosts")
                out["fleet_throughput_wps"] = self.last_fleet.get(
                    "fleet_throughput_wps"
                )
                if self.last_fleet.get("fleet_straggler_host") is not None:
                    out["straggler_host"] = self.last_fleet.get(
                        "fleet_straggler_host"
                    )
            return out

    def close(self) -> None:
        for u in self._unsubs:
            u()


class SignalEngine:
    """The per-process signal plane: windowed derivation + row publishing.

    Training mode (the default): construct with `window` steps and beat
    `on_boundary(step, words_done)` from the step loop (Trainer._check_stop
    does this). Serve mode: construct with `window_s` seconds and feed
    `observe_serve(stats_record)` from the stats loop.

    The engine is also a MetricsHub SINK (`engine(record)`): registered on
    the run's hub it harvests the quality probe's gauge records (and, in
    serve mode, the stats snapshots) without any new plumbing. Its own
    published rows carry "event":"signals" and are ignored on re-entry.
    """

    def __init__(
        self,
        window: int = WINDOW_STEPS_DEFAULT,
        window_s: Optional[float] = None,
        phases=None,
        flight=None,
        log_fn: Optional[Callable[[Dict], None]] = None,
        metrics_dir: Optional[str] = None,
        host: int = 0,
        slo=None,
        bus: Optional[SignalBus] = None,
        aggregator=None,
    ):
        self.window = max(1, int(window))
        self.window_s = float(window_s) if window_s else None
        self.phases = phases
        self.flight = flight
        self.log_fn = log_fn
        self.metrics_dir = metrics_dir
        self.host = int(host)
        #: SLO evaluator (obs/slo.SloEvaluator) run against every closed
        #: window's signal values; its events route back through _emit_event
        self.slo = slo
        self.bus = bus or SignalBus()
        self.health = FleetHealth(self.bus)
        #: rank-0 fleet aggregator (obs/fleet.FleetAggregator) run after
        #: every window close — None on non-primary hosts
        self.aggregator = aggregator
        self._lock = threading.Lock()
        self._signals: Dict[str, Signal] = {}
        self._windows_closed = 0
        self._rows_file = None
        self._rows_path = None
        if metrics_dir:
            os.makedirs(metrics_dir, exist_ok=True)
            self._rows_path = os.path.join(
                metrics_dir, f"signals_p{self.host}.jsonl"
            )
            # line-buffered append: rows must be visible to a concurrently
            # running aggregator/watcher, like the jsonl metrics sink
            self._rows_file = open(self._rows_path, "a", buffering=1)
        # --------------------------- training-window accumulation state
        self._win_id: Optional[int] = None
        self._win_t0 = 0.0
        self._win_words0 = 0
        self._win_step0 = 0
        self._win_durs: List[float] = []
        self._last_t: Optional[float] = None
        self._last_step: Optional[int] = None
        self._phase_base: Dict[str, float] = {}
        self.step_hist = Histogram()
        # latest values harvested from other streams, picked up at close
        self._latest: Dict[str, float] = {}
        self._heartbeat: Optional[Dict] = None
        # --------------------------------------- serve-window state
        self._serve_win: Optional[int] = None
        self._serve_last: Optional[Dict] = None

    # ------------------------------------------------------ training feed
    def on_boundary(self, step: int, words_done: int) -> None:
        """One step/chunk boundary. Hot path: a clock read, a duration
        append, and an integer compare — device-free by construction (the
        zero-added-fetch pin in tests/test_signals.py)."""
        now = time.perf_counter()
        wid = int(step) // self.window
        if self._win_id is None:
            self._open_window(wid, step, words_done, now)
            self._last_t, self._last_step = now, int(step)
            return
        if self._last_t is not None and step > (self._last_step or 0):
            # per-OPTIMIZER-step duration: a chunk boundary spans many steps
            dur = (now - self._last_t) / max(1, int(step) - self._last_step)
            self._win_durs.append(dur)
            self.step_hist.observe(dur)
        self._last_t, self._last_step = now, int(step)
        if wid != self._win_id:
            self._close_window(step, words_done, now)
            self._open_window(wid, step, words_done, now)

    def note_heartbeat(self, rows, step: int) -> None:
        """One PeerAgreement heartbeat's (pid, stop, step, p50[, elastic])
        rows: derive the fleet-skew view this host will publish at its next
        window close. Host-side floats only — the allgather already paid
        the collective."""
        try:
            clean = [[float(x) for x in r] for r in rows]
        except (TypeError, ValueError):
            return
        p50s = sorted(r[3] for r in clean)
        if not p50s:
            return
        med = percentile(p50s, 0.50)
        worst = max(clean, key=lambda r: r[3])
        skew = (worst[3] / med) if med > 0 else 1.0
        with self._lock:
            self._heartbeat = {
                "straggler_skew": round(skew, 4),
                "straggler_host": int(worst[0]),
                "fleet_median_p50_ms": round(med, 3),
                "at_step": int(step),
            }

    # ----------------------------------------------------- hub-sink feed
    def __call__(self, record: Dict) -> None:
        """MetricsHub sink: harvest quality/serve streams from the records
        that already flow. Own rows (event=signals/fleet/slo_*) are ignored
        — the engine publishes through the same hub it listens on."""
        ev = record.get("event")
        if isinstance(ev, str) and (
            ev in ("signals", "fleet") or ev.startswith("slo_")
        ):
            return
        if ev == "mem":
            # memory-ledger rows (obs/devmem.py): the headroom fraction
            # becomes a derived signal, which is what makes memory
            # SLO-able ('mem_headroom_frac<0.1' breaches like any rule)
            # and fleet-mergeable with worst-host attribution. Statless
            # backends (mem_available=0) feed nothing — a zero here would
            # read as a full device and breach every headroom SLO.
            if record.get("mem_available"):
                with self._lock:
                    hf = record.get("mem_headroom_frac")
                    if isinstance(hf, (int, float)) and not isinstance(
                        hf, bool
                    ):
                        self._latest["mem_headroom_frac"] = float(hf)
                    pk = record.get("mem_peak_bytes")
                    if isinstance(pk, (int, float)) and not isinstance(
                        pk, bool
                    ):
                        self._latest["mem_peak_bytes"] = float(pk)
            return
        planted = None
        for key in ("quality_analogy_accuracy", "quality_spearman"):
            v = record.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                planted = float(v)
                break
        if planted is not None:
            with self._lock:
                self._latest["quality_planted"] = planted
        if self.window_s and "serve_qps" in record:
            self.observe_serve(record)

    # -------------------------------------------------------- serve feed
    def observe_serve(self, rec: Dict, now: Optional[float] = None) -> None:
        """One ServeStats snapshot. Windows key on epoch seconds //
        window_s so replica rows merge by window id (see module notes)."""
        if not self.window_s:
            return
        t = time.time() if now is None else float(now)
        wid = int(t // self.window_s)
        keep = {}
        for src, name in (
            ("serve_qps", "serve_qps"),
            ("serve_p99_ms", "serve_p99_ms"),
            ("serve_cache_hit_rate", "cache_hit"),
        ):
            v = rec.get(src)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                keep[name] = float(v)
        hist = rec.get("serve_latency_seconds_hist")
        if self._serve_win is None:
            self._serve_win = wid
        elif wid != self._serve_win and self._serve_last is not None:
            row = {
                "event": "signals",
                "window": self._serve_win,
                "host": self.host,
                "mode": "serve",
            }
            for name, v in self._serve_last.items():
                if name == "serve_latency_seconds_hist":
                    row[name] = v
                else:
                    self._observe_signal(name, self._serve_win, v)
                    row[f"signal_{name}"] = round(v, 6)
            self._publish_row(row)
            self._serve_win = wid
        last = dict(keep)
        if hist:
            last["serve_latency_seconds_hist"] = hist
        self._serve_last = last or self._serve_last

    # --------------------------------------------------------- windowing
    def _open_window(self, wid: int, step: int, words: int, now: float) -> None:
        self._win_id = wid
        self._win_t0 = now
        self._win_words0 = int(words)
        self._win_step0 = int(step)
        self._win_durs = []
        if self.phases is not None:
            snap = self.phases.snapshot()
            self._phase_base = {
                name: s.get("total_ms", 0.0) for name, s in snap.items()
            }

    def _close_window(self, step: int, words: int, now: float) -> None:
        wid = self._win_id
        if wid is None:
            return
        wall = max(1e-9, now - self._win_t0)
        steps = int(step) - self._win_step0
        words_done = int(words) - self._win_words0
        row: Dict = {
            "event": "signals",
            "window": wid,
            "step": int(step),
            "host": self.host,
            "window_wall_s": round(wall, 4),
            "window_steps": steps,
            "window_words": words_done,
        }
        values: Dict[str, float] = {
            "throughput_wps": words_done / wall,
        }
        if self._win_durs:
            values["step_time_p50_ms"] = 1e3 * percentile(self._win_durs, 0.5)
            values["step_time_p90_ms"] = 1e3 * percentile(self._win_durs, 0.9)
        if self.phases is not None:
            values.update(self._input_bound_ratio())
            # host-attributable loop time: window wall NOT inside any
            # loop-stalling span. On a lockstep fleet (synchronous
            # collectives — the CPU/gloo backend, or any tight sync
            # cadence) every host's step TIME equalizes to the slowest
            # host's, so p50 cannot attribute a straggler; the time a host
            # spends outside its spans (a wedged fault hook, GC, slow host
            # code between dispatches) is the share only IT can explain —
            # obs/fleet.py prefers it for worst-host attribution.
            values["host_overhead_ms"] = self._host_overhead_ms(wall)
        with self._lock:
            hb = dict(self._heartbeat) if self._heartbeat else None
            latest = dict(self._latest)
        if hb is not None:
            values["straggler_skew"] = hb["straggler_skew"]
            row["straggler_host"] = hb["straggler_host"]
        for name, v in latest.items():
            values[name] = v
        for name, v in values.items():
            self._observe_signal(name, wid, v)
            row[f"signal_{name}"] = round(float(v), 6)
        row["step_time_seconds_hist"] = self.step_hist.to_record()
        self._windows_closed += 1
        self._publish_row(row)

    def _input_bound_ratio(self) -> Dict[str, float]:
        """Input-stall fraction over THIS window, from the PhaseRecorder's
        loop-stalling span totals delta (same phases the verdict uses)."""
        from .phases import COMPUTE_PHASES, INPUT_PHASES

        snap = self.phases.snapshot()
        totals = {n: s.get("total_ms", 0.0) for n, s in snap.items()}

        def delta(names) -> float:
            return sum(
                max(0.0, totals.get(n, 0.0) - self._phase_base.get(n, 0.0))
                for n in names
            )

        inp = delta(INPUT_PHASES)
        comp = delta(COMPUTE_PHASES)
        if inp + comp <= 0.0:
            return {}
        return {"input_bound_ratio": inp / (inp + comp)}

    def _host_overhead_ms(self, wall_s: float) -> float:
        """Window wall minus the LOOP-STALLING span totals' delta (input +
        compute phases + checkpoint + quality_probe + the fleet waits
        replica_sync/agree — h2d is overlapped producer time and would
        double-subtract). Clamped at zero: span clocks and the window
        clock are read at slightly different moments."""
        from .phases import COMPUTE_PHASES, INPUT_PHASES

        snap = self.phases.snapshot()
        spans = 0.0
        for name in INPUT_PHASES + COMPUTE_PHASES + (
            "checkpoint", "quality_probe", "replica_sync", "agree",
        ):
            total = (snap.get(name) or {}).get("total_ms", 0.0)
            spans += max(0.0, total - self._phase_base.get(name, 0.0))
        return max(0.0, 1e3 * wall_s - spans)

    def _observe_signal(self, name: str, wid: int, value: float) -> None:
        with self._lock:
            sig = self._signals.get(name)
            if sig is None:
                sig = self._signals[name] = Signal(name)
            sig.observe(wid, value)

    # -------------------------------------------------------- publishing
    def _publish_row(self, row: Dict) -> None:
        if self._rows_file is not None:
            try:
                self._rows_file.write(json.dumps(row, default=str) + "\n")
            except (OSError, ValueError):
                pass
        if self.flight is not None:
            self.flight.note_signal(row)
        if self.log_fn is not None:
            self.log_fn(dict(row))
        self.bus.publish("signals", row)
        for key, v in row.items():
            if key.startswith("signal_"):
                self.bus.publish(key[len("signal_"):], {
                    "window": row.get("window"), "host": self.host,
                    "value": v,
                })
        if self.slo is not None:
            values = {
                k[len("signal_"):]: v for k, v in row.items()
                if k.startswith("signal_")
            }
            for ev in self.slo.evaluate(values, row.get("window")):
                self._emit_event(ev)
        if self.aggregator is not None:
            try:
                fleet_row = self.aggregator.aggregate()
            except Exception as e:  # noqa: BLE001 — aggregation is advisory
                warnings.warn(
                    f"fleet aggregation failed: {e!r}", stacklevel=2
                )
                fleet_row = None
            if fleet_row:
                if self.log_fn is not None:
                    self.log_fn(dict(fleet_row))
                self.bus.publish("fleet", fleet_row)

    def _emit_event(self, ev: Dict) -> None:
        """One structured SLO event: onto the run's sinks (the Prometheus
        sink counts slo_breach into w2v_slo_breaches_total), the flight
        recorder's signal ring AND record ring (every flight.json names
        the breach), and the bus."""
        if self.flight is not None:
            self.flight.note_signal(ev)
            self.flight.log_record(ev)
            ring = getattr(self.flight, "ring", None)
            if ring is not None:
                ring.instant(ev.get("event", "slo"), args={
                    k: v for k, v in ev.items() if k != "event"
                })
        if self.log_fn is not None:
            self.log_fn(dict(ev))
        self.bus.publish("slo", ev)

    # --------------------------------------------------------- reporting
    def signal_stats(self) -> Dict[str, Dict]:
        with self._lock:
            return {name: s.stats() for name, s in self._signals.items()}

    def report(self) -> Optional[Dict]:
        """TrainReport.signals payload: per-signal stats, windows closed,
        the SLO summary, and the bus-fed fleet-health verdict. None when
        no window ever closed (a run shorter than one window)."""
        stats = self.signal_stats()
        if not stats and self._windows_closed == 0:
            return None
        out: Dict = {
            "window_steps": self.window,
            "windows": self._windows_closed,
            "signals": stats,
            "fleet_health": self.health.verdict(),
        }
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        return out

    def finish(self, step: Optional[int] = None,
               words_done: Optional[int] = None) -> None:
        """Close the in-flight partial window (end of the run: the tail
        still deserves a row) and flush the row file."""
        if (
            self._win_id is not None
            and step is not None
            and words_done is not None
            and int(step) > self._win_step0
        ):
            self._close_window(int(step), int(words_done), time.perf_counter())
            self._win_id = None
        if self.window_s and self._serve_last is not None:
            # serve tail: emit the last accumulated serve window
            self._serve_win = (self._serve_win or 0)
            self.observe_serve({}, now=(self._serve_win + 1) * self.window_s)
        if self._rows_file is not None:
            try:
                self._rows_file.flush()
            except (OSError, ValueError):
                pass
        if self.aggregator is not None:
            # final forced pass: mid-run aggregation is interval-throttled
            # (FleetAggregator.MIN_INTERVAL_S), so the tail windows may not
            # have been merged yet — the run-end fleet.json must be complete
            try:
                fleet_row = self.aggregator.aggregate(force=True)
            except Exception:  # noqa: BLE001 — aggregation is advisory
                fleet_row = None
            if fleet_row:
                if self.log_fn is not None:
                    self.log_fn(dict(fleet_row))
                self.bus.publish("fleet", fleet_row)

    def close(self) -> None:
        self.health.close()
        if self._rows_file is not None:
            try:
                self._rows_file.close()
            except (OSError, ValueError):
                pass
            self._rows_file = None
