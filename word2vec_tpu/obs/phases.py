"""Host-side phase-timing breakdown: where does a training step's wall time go?

`utils/profiling.annotate` puts named regions on the xprof timeline, but
reading them requires capturing and opening a trace. `PhaseRecorder` is the
always-on counterpart: a thread-safe span recorder the trainers wrap around
the same regions —

    batcher_wait — the training loop blocked pulling the next batch/chunk
                   from the prefetch queue (the host input pipeline could
                   not keep ahead of the device)
    h2d          — host->device placement of a batch/chunk; runs in the
                   prefetch PRODUCER thread, so a large h2d total alongside
                   a small batcher_wait means the copy overlap is working
    dispatch     — host time spent issuing the (async) device program
    device_wait  — the loop blocked fetching already-dispatched metrics
                   (the lagged drain): device-side backpressure
    checkpoint   — checkpoint callback wall time

— and aggregates into per-phase p50/p90 (shared percentile math with
profiling.StepTimer) plus an input-bound-vs-compute-bound verdict. The
verdict compares only the phases that STALL the training loop:
batcher_wait (input side) against dispatch + device_wait (device side);
h2d and checkpoint are reported but excluded, since overlapped producer
time stalls nothing.

Recording one span is two perf_counter reads and a lock — cheap enough to
leave on for every run, including bench.py's measured epochs.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterable, Iterator, Optional

from ..utils.profiling import annotate, lap_stats

#: phases that stall the training loop on the input side / device side
INPUT_PHASES = ("batcher_wait",)
COMPUTE_PHASES = ("dispatch", "device_wait")


class PhaseRecorder:
    """Thread-safe named-span recorder with bounded per-phase sample rings."""

    #: per-phase sample cap: percentiles come from the most recent samples
    #: (ring overwrite), totals/counts from every span ever recorded
    MAX_SAMPLES = 4096

    def __init__(self, tracer=None):
        #: optional span sink (obs/trace.TraceRing, duck-typed: anything
        #: with .complete(name, t0, dur_s)): every closed span also becomes
        #: one timeline event — the flight recorder's feed. reset() leaves
        #: it alone; set to None to detach.
        self.tracer = tracer
        self._lock = threading.Lock()
        self._laps: Dict[str, list] = {}
        self._counts: Dict[str, int] = {}
        self._totals: Dict[str, float] = {}
        # currently-OPEN spans per thread: {thread id: [(name, t0), ...]}.
        # The stall watchdog (resilience/watchdog.py) reads this to name the
        # wedged phase of a hung step — a span that never closes is exactly
        # the evidence completed-lap stats can't show.
        self._active: Dict[int, list] = {}

    def reset(self) -> None:
        with self._lock:
            self._laps.clear()
            self._counts.clear()
            self._totals.clear()
            self._active.clear()

    # ------------------------------------------------------------ recording
    def note(self, name: str, seconds: float) -> None:
        """Record one externally-timed span."""
        with self._lock:
            n = self._counts.get(name, 0)
            self._counts[name] = n + 1
            self._totals[name] = self._totals.get(name, 0.0) + seconds
            laps = self._laps.setdefault(name, [])
            if len(laps) < self.MAX_SAMPLES:
                laps.append(seconds)
            else:
                laps[n % self.MAX_SAMPLES] = seconds

    def _enter(self, name: str, t0: float) -> None:
        tid = threading.get_ident()
        with self._lock:
            self._active.setdefault(tid, []).append((name, t0))

    def _exit(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            stack = self._active.get(tid)
            if stack:
                stack.pop()
            if not stack:
                self._active.pop(tid, None)

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        """Time a region (and annotate it on the profiler timeline)."""
        with annotate(name):
            t0 = time.perf_counter()
            self._enter(name, t0)
            try:
                yield
            finally:
                self._exit()
                dur = time.perf_counter() - t0
                self.note(name, dur)
                if self.tracer is not None:
                    self.tracer.complete(name, t0, dur)

    def timed_iter(self, iterable: Iterable, name: str) -> Iterator:
        """Yield from `iterable`, recording each next() as one `name` span
        (the consumer-side blocked-on-producer time of a prefetch queue)."""
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            self._enter(name, t0)
            try:
                item = next(it)
            except StopIteration:
                return
            finally:
                self._exit()
            dur = time.perf_counter() - t0
            self.note(name, dur)
            if self.tracer is not None:
                self.tracer.complete(name, t0, dur)
            yield item

    # ------------------------------------------------------- liveness view
    def open_spans(self) -> Dict[str, float]:
        """{phase: seconds open} of every currently-OPEN span, keeping the
        oldest occurrence per name across threads. Empty between spans."""
        now = time.perf_counter()
        with self._lock:
            out: Dict[str, float] = {}
            for stack in self._active.values():
                for name, t0 in stack:
                    age = now - t0
                    if age > out.get(name, -1.0):
                        out[name] = age
            return out

    def wedged_phase(self) -> Optional[str]:
        """The phase most plausibly responsible for a stalled step: the
        longest-open LOOP-STALLING span (batcher_wait / dispatch /
        device_wait / checkpoint — overlapped producer h2d stalls nothing),
        falling back to the longest-open span of any name, or None when no
        span is open (the hang is in the loop body itself or on device)."""
        opens = self.open_spans()
        if not opens:
            return None
        stalling = {
            n: a for n, a in opens.items()
            if n in INPUT_PHASES + COMPUTE_PHASES + ("checkpoint",)
        }
        pick = stalling or opens
        return max(pick, key=lambda n: pick[n])

    # ----------------------------------------------------------- reporting
    def snapshot(self) -> Dict[str, Dict]:
        """{phase: {count, total_ms, p50_ms, p90_ms, ...}} — {} before any
        span lands, so log records can include it conditionally."""
        with self._lock:
            out = {}
            for name, laps in self._laps.items():
                s = lap_stats(laps)
                s["count"] = self._counts[name]
                s["total_ms"] = 1e3 * self._totals[name]
                out[name] = s
            return out

    def verdict(self) -> Dict:
        """Input-bound vs compute-bound, from loop-stalling totals only."""
        with self._lock:
            inp = sum(self._totals.get(p, 0.0) for p in INPUT_PHASES)
            comp = sum(self._totals.get(p, 0.0) for p in COMPUTE_PHASES)
        if inp + comp <= 0.0:
            return {"verdict": "indeterminate", "input_fraction": None}
        frac = inp / (inp + comp)
        return {
            "verdict": "input-bound" if frac > 0.5 else "compute-bound",
            "input_fraction": round(frac, 4),
        }

    def report(self) -> Optional[Dict]:
        """TrainReport.phases payload: per-phase stats + the verdict.
        None when nothing was recorded (a trainer that never ran)."""
        snap = self.snapshot()
        if not snap:
            return None
        return {"phases": snap, **self.verdict()}
