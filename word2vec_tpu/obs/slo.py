"""Declarative SLO rules over derived signals (obs/signals.py).

Rule grammar (one clause; `--slo` takes a comma-separated list or a path to
a `.json` file):

    <signal><op><threshold>[:key=val]...

    throughput_wps<0.8*baseline:for=5     sustained-throughput SLO: breach
                                          when throughput sits below 80% of
                                          its own established baseline for
                                          5 consecutive windows
    serve_p99_ms>250:for=3                latency SLO against a literal bound
    quality_planted<0.5                   quality floor (default for=3)

  op          `<` (breach when value drops below) or `>` (breach when value
              exceeds)
  threshold   a literal float, or `F*baseline` — `baseline` is established
              per rule as the median of the first `baseline=N` observed
              windows (default 3); until established the rule is pending
              and never fires
  :for=N      consecutive breaching windows before `warn` escalates to
              `breach` (default 3); the FIRST breaching window is `warn`
  :baseline=N windows used to establish the baseline (default 3)

Escalation is a per-rule state machine evaluated once per closed window:

    ok -> warn   (first breaching window)
    warn -> breach (N consecutive breaching windows)
    * -> ok      (any conforming window resets the streak — structured
                 `slo_recovered` event when leaving warn/breach)

Every transition emits a structured SloEvent record (`event`:
slo_warn | slo_breach | slo_recovered) that lands on the run's sinks, the
flight ring, and the signal bus; `slo_breach` increments the
present-from-zero `w2v_slo_breaches_total` counter (obs/export.py). A breach
maps to a log + event, NEVER an exit — this layer observes; the control
loops that will subscribe to it (serve autoscale, elastic policy) actuate.

Parse errors follow the PR 5 fault-spec contract: they name the clause and
its character offset in the spec (`SloError: rule 2 ('qps>>5') at offset
21: ...`) so a typo'd rule fails in milliseconds, not after the corpus scan.
"""

from __future__ import annotations

import json
import re
from typing import Callable, Dict, List, Optional

#: default consecutive breaching windows before warn escalates to breach
FOR_DEFAULT = 3
#: default windows used to establish a `baseline`-relative threshold
BASELINE_DEFAULT = 3

_SIGNAL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_NUM_RE = re.compile(r"^[-+]?(\d+\.?\d*|\.\d+)([eE][-+]?\d+)?$")


class SloError(ValueError):
    """A malformed SLO rule spec (clause + offset in the message)."""


class SloRule:
    """One parsed rule: signal, comparison, threshold (literal or
    baseline-relative), escalation budget."""

    def __init__(self, signal: str, op: str, factor: float,
                 relative: bool, for_n: int = FOR_DEFAULT,
                 baseline_n: int = BASELINE_DEFAULT, text: str = ""):
        self.signal = signal
        self.op = op
        self.factor = float(factor)
        #: True = threshold is factor * established baseline
        self.relative = bool(relative)
        self.for_n = max(1, int(for_n))
        self.baseline_n = max(1, int(baseline_n))
        self.text = text or str(self)

    def __str__(self) -> str:
        thr = f"{self.factor:g}*baseline" if self.relative else f"{self.factor:g}"
        return f"{self.signal}{self.op}{thr}:for={self.for_n}"

    def to_json(self) -> Dict:
        return {
            "rule": self.text,
            "signal": self.signal,
            "op": self.op,
            "factor": self.factor,
            "relative": self.relative,
            "for": self.for_n,
            "baseline_windows": self.baseline_n,
        }

    # ------------------------------------------------------------ parsing
    @classmethod
    def parse(cls, clause: str) -> "SloRule":
        """One clause (no clause/offset context — parse_slo wraps that)."""
        m = re.match(r"^([^<>]+)([<>])(.+)$", clause)
        if not m:
            raise ValueError(
                "expected <signal><op><threshold> with op '<' or '>'"
            )
        signal, op, rest = m.group(1).strip(), m.group(2), m.group(3)
        if not _SIGNAL_RE.match(signal):
            raise ValueError(f"bad signal name {signal!r}")
        if "<" in rest or ">" in rest:
            raise ValueError(f"more than one comparison operator in {clause!r}")
        parts = rest.split(":")
        thr = parts[0].strip()
        relative = False
        if "*" in thr:
            fac, _, base = thr.partition("*")
            if base.strip() != "baseline":
                raise ValueError(
                    f"threshold {thr!r}: only '<factor>*baseline' is "
                    "supported on the right of '*'"
                )
            thr = fac.strip()
            relative = True
        elif thr == "baseline":
            thr, relative = "1.0", True
        if not _NUM_RE.match(thr):
            raise ValueError(f"threshold {parts[0].strip()!r} is not a number")
        kwargs = {"for_n": FOR_DEFAULT, "baseline_n": BASELINE_DEFAULT}
        for kv in parts[1:]:
            key, sep, val = kv.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"option {kv!r} is not key=value")
            if key == "for":
                dest = "for_n"
            elif key == "baseline":
                dest = "baseline_n"
            else:
                raise ValueError(
                    f"unknown option {key!r} (expected for= or baseline=)"
                )
            try:
                n = int(val)
            except ValueError:
                raise ValueError(f"option {key}={val!r} is not an integer")
            if n < 1:
                raise ValueError(f"option {key}={n} must be >= 1")
            kwargs[dest] = n
        return cls(signal, op, float(thr), relative, text=clause.strip(),
                   **kwargs)


def parse_slo(spec: str) -> List[SloRule]:
    """`--slo` spec -> rules. A spec that is a path to a `.json` file loads
    rules from it (a JSON list of rule strings, or of objects with a
    "rule" field). Errors name clause + offset, the fault-spec contract."""
    spec = (spec or "").strip()
    if not spec:
        return []
    if spec.endswith(".json"):
        try:
            with open(spec) as f:
                doc = json.load(f)
        except OSError as e:
            raise SloError(f"cannot read SLO file {spec!r}: {e}")
        except json.JSONDecodeError as e:
            raise SloError(f"SLO file {spec!r} is not valid JSON: {e}")
        if not isinstance(doc, list):
            raise SloError(
                f"SLO file {spec!r}: expected a JSON list of rules, got "
                f"{type(doc).__name__}"
            )
        clauses = []
        for i, item in enumerate(doc):
            if isinstance(item, str):
                clauses.append(item)
            elif isinstance(item, dict) and isinstance(item.get("rule"), str):
                clauses.append(item["rule"])
            else:
                raise SloError(
                    f"SLO file {spec!r}: rule {i + 1} must be a string or "
                    'an object with a "rule" field'
                )
        spec_text = ",".join(clauses)
    else:
        spec_text = spec
    rules: List[SloRule] = []
    offset = 0
    for i, tok in enumerate(spec_text.split(",")):
        clause = tok.strip()
        if clause:
            try:
                rules.append(SloRule.parse(clause))
            except ValueError as e:
                raise SloError(
                    f"rule {i + 1} ({clause!r}) at offset {offset}: {e}"
                )
        offset += len(tok) + 1
    return rules


class _RuleState:
    def __init__(self, rule: SloRule):
        self.rule = rule
        self.state = "ok"
        self.streak = 0
        self.baseline: Optional[float] = None
        self.baseline_samples: List[float] = []
        self.breaches = 0
        self.warns = 0
        self.last_value: Optional[float] = None


class SloEvaluator:
    """Evaluate parsed rules against each closed window's signal values.

    `evaluate` returns the structured event records for this window (empty
    most of the time); the caller routes them to sinks/flight/bus. The
    evaluator never raises out of evaluate() and never exits — observe,
    don't actuate."""

    def __init__(self, rules: List[SloRule],
                 clock: Optional[Callable[[], float]] = None):
        self.rules = list(rules)
        self._states = [_RuleState(r) for r in self.rules]

    def __bool__(self) -> bool:
        return bool(self.rules)

    def threshold(self, st: _RuleState) -> Optional[float]:
        r = st.rule
        if not r.relative:
            return r.factor
        if st.baseline is None:
            return None
        return r.factor * st.baseline

    def evaluate(self, values: Dict[str, float],
                 window: Optional[int]) -> List[Dict]:
        events: List[Dict] = []
        for st in self._states:
            r = st.rule
            v = values.get(r.signal)
            if v is None or isinstance(v, bool):
                continue
            v = float(v)
            st.last_value = v
            if r.relative and st.baseline is None:
                st.baseline_samples.append(v)
                if len(st.baseline_samples) >= r.baseline_n:
                    s = sorted(st.baseline_samples)
                    st.baseline = s[len(s) // 2]  # median
                continue  # baseline windows never count against the rule
            thr = self.threshold(st)
            if thr is None:
                continue
            breached = v < thr if r.op == "<" else v > thr
            base = {
                "rule": r.text,
                "signal": r.signal,
                "value": round(v, 6),
                "threshold": round(thr, 6),
                "window": window,
            }
            if st.baseline is not None:
                base["baseline"] = round(st.baseline, 6)
            if breached:
                st.streak += 1
                if st.streak >= r.for_n and st.state != "breach":
                    st.state = "breach"
                    st.breaches += 1
                    events.append({
                        "event": "slo_breach", "streak": st.streak, **base,
                    })
                elif st.streak < r.for_n and st.state == "ok":
                    st.state = "warn"
                    st.warns += 1
                    events.append({
                        "event": "slo_warn", "streak": st.streak, **base,
                    })
            else:
                if st.state != "ok":
                    events.append({
                        "event": "slo_recovered",
                        "from": st.state,
                        "streak": st.streak,
                        **base,
                    })
                st.state = "ok"
                st.streak = 0
        return events

    def summary(self) -> Dict:
        """Manifest / TrainReport payload: per-rule state + totals."""
        worst = "ok"
        rank = {"ok": 0, "warn": 1, "breach": 2}
        rows = []
        for st in self._states:
            if rank[st.state] > rank[worst]:
                worst = st.state
            row = {
                "rule": st.rule.text,
                "state": st.state,
                "streak": st.streak,
                "breaches": st.breaches,
                "warns": st.warns,
            }
            if st.baseline is not None:
                row["baseline"] = round(st.baseline, 6)
            if st.last_value is not None:
                row["last_value"] = round(st.last_value, 6)
            rows.append(row)
        return {
            "state": worst,
            "breaches_total": sum(st.breaches for st in self._states),
            "warns_total": sum(st.warns for st in self._states),
            "rules": rows,
        }
