"""Cross-host signal aggregation: per-host rows -> one fleet view.

Each process's SignalEngine (obs/signals.py) writes one compact row per
closed window into its own `signals_p<host>.jsonl` under `--metrics-dir` —
the same shared-directory, per-process-file discipline as the PR 6 trace
export (`trace_p<i>.json`), and for the same reason: hosts share no clock,
but they DO share the window id (steps advance in lockstep across a fleet;
serve replicas key on epoch seconds), so rows merge deterministically BY
WINDOW ID no matter how skewed the wall clocks are.

Two consumers run the merge:

  rank 0, in-training  — the trainer's SignalEngine carries a
                         FleetAggregator and re-aggregates after every
                         window close: `fleet.json` in --metrics-dir plus
                         one "event":"fleet" record whose numeric fields
                         become `w2v_fleet_*` gauges (obs/export).
  standalone           — `python -m word2vec_tpu.obs.fleet --dir DIR`
                         aggregates a directory of serve-replica (or
                         training) signal files on an interval, for fleets
                         with no rank 0 (N serve processes behind a front).

The merged view derives the decision-grade aggregates the per-host rows
cannot express alone: fleet throughput (sum), the WORST STRAGGLER with host
attribution (max per-host step-time p50 vs the fleet median, plus the
heartbeat-derived skew when present), input-bound fraction (mean), planted
quality (min — the fleet is only as good as its worst replica's table), and
serve qps (sum) / p99 (max) / cache hit (mean).

`validate_fleet_doc` is the schema gate CI runs against every fleet.json —
same contract as obs/trace.validate_trace_doc: an unreadable artifact is
not evidence.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Dict, List, Optional

SCHEMA = 1

#: windows kept in the fleet.json window list (the full per-host history
#: stays in the signals_p*.jsonl files)
KEEP_WINDOWS = 64

#: straggler attribution floor: a host is only named when its step-time p50
#: exceeds the fleet median by this factor (median-of-one fleets never name)
STRAGGLER_FACTOR = 1.5
#: absolute floor for the host-overhead discriminator (ms per window):
#: below it the spread is clock crumbs, not a straggler
STRAGGLER_MIN_OVERHEAD_MS = 100.0


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    mid = len(s) // 2
    # true median (even n averages the middle pair): with the upper-middle
    # convention a 2-host fleet's "median" IS its worst host, so a straggler
    # could never be named at the smallest fleet size that has one
    return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])


def read_signal_rows(path: str, offset: int = 0):
    """Parse one signals_p*.jsonl from `offset`; returns (rows, new_offset).
    Tolerates a torn last line (the writer appends concurrently)."""
    rows: List[Dict] = []
    try:
        with open(path) as f:
            f.seek(offset)
            while True:
                pos = f.tell()
                line = f.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    return rows, pos  # torn tail: re-read next pass
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(row, dict) and row.get("event") == "signals":
                    rows.append(row)
            return rows, f.tell()
    except OSError:
        return rows, offset


def merge_rows(rows: List[Dict]) -> List[Dict]:
    """Per-host signal rows -> per-window fleet rows, sorted by window id.

    Deterministic by construction: grouping keys on the window id (never a
    timestamp), hosts sort numerically inside a window, and every aggregate
    is order-independent (sum/min/max/mean/median) — pinned by the skewed
    3-host test in tests/test_signals.py."""
    by_window: Dict[int, Dict[int, Dict]] = {}
    for row in rows:
        w = row.get("window")
        h = row.get("host", 0)
        if not isinstance(w, int):
            continue
        # latest row wins per (window, host): a re-published window (resume,
        # aggregator re-read) must not double-count
        by_window.setdefault(w, {})[int(h)] = row
    out: List[Dict] = []
    for w in sorted(by_window):
        hosts = by_window[w]
        merged: Dict = {
            "window": w,
            "hosts": sorted(hosts),
        }

        def vals(key: str) -> List:
            return [
                (h, hosts[h][f"signal_{key}"]) for h in sorted(hosts)
                if isinstance(hosts[h].get(f"signal_{key}"), (int, float))
                and not isinstance(hosts[h].get(f"signal_{key}"), bool)
            ]

        tp = vals("throughput_wps")
        if tp:
            merged["throughput_wps"] = round(sum(v for _, v in tp), 3)
            slowest = min(tp, key=lambda kv: kv[1])
            merged["throughput_min_host"] = slowest[0]
        p50 = vals("step_time_p50_ms")
        if p50:
            med = _median([v for _, v in p50])
            worst_host, worst_v = max(p50, key=lambda kv: kv[1])
            merged["step_time_p50_ms_median"] = round(med, 3)
            merged["step_time_p50_ms_max"] = round(worst_v, 3)
            if med > 0 and worst_v / med >= STRAGGLER_FACTOR and len(p50) > 1:
                merged["straggler"] = {
                    "host": worst_host,
                    "step_time_p50_ms": round(worst_v, 3),
                    "vs_median": round(worst_v / med, 3),
                }
        ov = vals("host_overhead_ms")
        if ov and len(ov) > 1:
            # the lockstep-fleet discriminator (obs/signals.py notes): on a
            # synchronous-collective backend every host's step time
            # equalizes to the slowest host's, so p50 cannot name the
            # straggler — but the time a host spends OUTSIDE its spans is
            # attributable to it alone. Preferred over the p50 pick when
            # it clears both an absolute floor (clock-skew crumbs stay
            # anonymous) and the factor bar.
            med = _median([v for _, v in ov])
            worst_host, worst_v = max(ov, key=lambda kv: kv[1])
            merged["host_overhead_ms_max"] = round(worst_v, 3)
            if worst_v > max(STRAGGLER_MIN_OVERHEAD_MS,
                             STRAGGLER_FACTOR * med):
                merged["straggler"] = {
                    "host": worst_host,
                    "host_overhead_ms": round(worst_v, 3),
                    "vs_median": round(worst_v / max(med, 1.0), 3),
                }
        skew = vals("straggler_skew")
        if skew:
            merged["straggler_skew_max"] = round(max(v for _, v in skew), 3)
        ibr = vals("input_bound_ratio")
        if ibr:
            merged["input_bound_ratio_mean"] = round(
                sum(v for _, v in ibr) / len(ibr), 4
            )
        q = vals("quality_planted")
        if q:
            merged["quality_planted_min"] = round(min(v for _, v in q), 4)
        qps = vals("serve_qps")
        if qps:
            merged["serve_qps"] = round(sum(v for _, v in qps), 3)
        p99 = vals("serve_p99_ms")
        if p99:
            merged["serve_p99_ms_max"] = round(max(v for _, v in p99), 3)
        ch = vals("cache_hit")
        if ch:
            merged["cache_hit_mean"] = round(
                sum(v for _, v in ch) / len(ch), 4
            )
        mh = vals("mem_headroom_frac")
        if mh:
            # device-memory view (obs/devmem.py): the fleet has the
            # headroom of its WORST host — that host is where the next
            # vocab growth or table swap OOMs, so it gets the attribution
            # (the host_overhead straggler discipline, applied to memory)
            worst_host, worst_v = min(mh, key=lambda kv: kv[1])
            merged["mem_headroom_frac_min"] = round(worst_v, 6)
            merged["mem_worst_host"] = worst_host
        mp = vals("mem_peak_bytes")
        if mp:
            merged["mem_peak_bytes_max"] = max(v for _, v in mp)
        out.append(merged)
    return out


def fleet_doc(windows: List[Dict], window_steps: Optional[int] = None) -> Dict:
    """Assemble the fleet.json document from merged windows."""
    hosts = sorted({h for w in windows for h in w.get("hosts", ())})
    # overall straggler attribution: the host most often named worst, with
    # its peak skew — "who do I go look at" in one field
    counts: Dict[int, int] = {}
    peak: Dict[int, float] = {}
    for w in windows:
        s = w.get("straggler")
        if s:
            counts[s["host"]] = counts.get(s["host"], 0) + 1
            peak[s["host"]] = max(peak.get(s["host"], 0.0), s["vs_median"])
    straggler = None
    if counts:
        worst = max(counts, key=lambda h: (counts[h], peak[h]))
        straggler = {
            "host": worst,
            "windows_worst": counts[worst],
            "max_vs_median": round(peak[worst], 3),
        }
    doc: Dict = {
        "schema": SCHEMA,
        "event": "fleet_doc",
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "hosts": hosts,
        "windows_total": len(windows),
        "windows": windows[-KEEP_WINDOWS:],
        "last": windows[-1] if windows else None,
        "straggler": straggler,
    }
    if window_steps:
        doc["window_steps"] = int(window_steps)
    return doc


def validate_fleet_doc(doc: Dict) -> Dict[str, int]:
    """Schema gate for fleet.json (CI + tests); returns summary counts.
    Raises ValueError naming the first offending field."""
    if not isinstance(doc, dict):
        raise ValueError("not a fleet document: not an object")
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"bad schema {doc.get('schema')!r} (want {SCHEMA})")
    if not isinstance(doc.get("hosts"), list):
        raise ValueError("missing hosts list")
    windows = doc.get("windows")
    if not isinstance(windows, list):
        raise ValueError("missing windows list")
    last_w = None
    for i, w in enumerate(windows):
        if not isinstance(w, dict) or not isinstance(w.get("window"), int):
            raise ValueError(f"window {i}: missing integer window id")
        if not isinstance(w.get("hosts"), list) or not w["hosts"]:
            raise ValueError(f"window {i}: missing hosts")
        if last_w is not None and w["window"] <= last_w:
            raise ValueError(
                f"window {i}: ids not strictly increasing "
                f"({w['window']} after {last_w})"
            )
        last_w = w["window"]
        s = w.get("straggler")
        if s is not None and not isinstance(s.get("host"), int):
            raise ValueError(f"window {i}: straggler without integer host")
    return {
        "hosts": len(doc["hosts"]),
        "windows": len(windows),
        "stragglers": sum(1 for w in windows if w.get("straggler")),
    }


class FleetAggregator:
    """Incremental merge of every signals_p*.jsonl in a directory.

    `aggregate()` tail-reads new rows (per-file byte offsets, so repeated
    aggregation is O(new rows), not O(history^2)), re-merges, atomically
    rewrites `fleet.json`, and returns one flat "event":"fleet" gauge
    record for the hub (None when nothing merged yet)."""

    #: minimum seconds between aggregation passes: the caller may invoke
    #: aggregate() at every window close, but re-merging + rewriting
    #: fleet.json that often would dominate the signal plane's cost on
    #: fast-step shapes (the <1% contract); `force=True` (run end) always
    #: runs so the final artifact is complete
    MIN_INTERVAL_S = 1.0

    def __init__(self, metrics_dir: str, out_name: str = "fleet.json",
                 window_steps: Optional[int] = None):
        self.metrics_dir = metrics_dir
        self.out_path = os.path.join(metrics_dir, out_name)
        self.window_steps = window_steps
        self._offsets: Dict[str, int] = {}
        self._rows: List[Dict] = []
        self._last_run = 0.0

    def aggregate(self, force: bool = False) -> Optional[Dict]:
        now = time.monotonic()
        if not force and now - self._last_run < self.MIN_INTERVAL_S:
            return None
        self._last_run = now
        for path in sorted(
            glob.glob(os.path.join(self.metrics_dir, "signals_p*.jsonl"))
        ):
            rows, off = read_signal_rows(path, self._offsets.get(path, 0))
            self._offsets[path] = off
            self._rows.extend(rows)
        if not self._rows:
            return None
        windows = merge_rows(self._rows)
        doc = fleet_doc(windows, window_steps=self.window_steps)
        tmp = self.out_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, self.out_path)
        except OSError:
            pass  # the gauge record below still carries the fleet view
        return self.gauge_record(doc)

    @staticmethod
    def gauge_record(doc: Dict) -> Optional[Dict]:
        """fleet.json -> one flat record whose numeric fields become
        `w2v_fleet_*` gauges (obs/export.GAUGE_EVENTS)."""
        last = doc.get("last")
        if not last:
            return None
        rec: Dict = {
            "event": "fleet",
            "fleet_hosts": len(doc.get("hosts", ())),
            "fleet_window": last["window"],
            "fleet_windows_total": doc.get("windows_total", 0),
        }
        for src, dst in (
            ("throughput_wps", "fleet_throughput_wps"),
            ("step_time_p50_ms_median", "fleet_step_time_p50_ms"),
            ("step_time_p50_ms_max", "fleet_step_time_p50_ms_max"),
            ("straggler_skew_max", "fleet_straggler_skew"),
            ("input_bound_ratio_mean", "fleet_input_bound_ratio"),
            ("quality_planted_min", "fleet_quality_planted_min"),
            ("serve_qps", "fleet_serve_qps"),
            ("serve_p99_ms_max", "fleet_serve_p99_ms"),
            ("cache_hit_mean", "fleet_cache_hit"),
            ("mem_headroom_frac_min", "fleet_mem_headroom_frac"),
            ("mem_peak_bytes_max", "fleet_mem_peak_bytes"),
            ("mem_worst_host", "fleet_mem_worst_host"),
        ):
            if src in last:
                rec[dst] = last[src]
        s = (doc.get("straggler") or last.get("straggler"))
        if s:
            rec["fleet_straggler_host"] = s["host"]
        return rec


def main(argv=None) -> int:
    """Standalone aggregator: `python -m word2vec_tpu.obs.fleet --dir DIR`
    — the serve-replica form, where no training rank 0 exists to host the
    merge. `--once` aggregates and exits (CI); the default loops."""
    ap = argparse.ArgumentParser(
        prog="python -m word2vec_tpu.obs.fleet",
        description="merge per-host signal rows into fleet.json",
    )
    ap.add_argument("--dir", required=True,
                    help="directory holding signals_p*.jsonl (each host's "
                         "--metrics-dir, shared or collected)")
    ap.add_argument("--out", default="fleet.json",
                    help="output filename inside --dir")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="seconds between aggregation passes")
    ap.add_argument("--once", action="store_true",
                    help="aggregate one pass and exit (CI / cron form)")
    ap.add_argument("--json", action="store_true",
                    help="print the fleet gauge record per pass")
    args = ap.parse_args(argv)
    agg = FleetAggregator(args.dir, out_name=args.out)
    while True:
        rec = agg.aggregate()
        if args.json and rec:
            print(json.dumps(rec))
        if args.once:
            if rec is None:
                print(
                    f"no signal rows under {args.dir} "
                    "(expected signals_p*.jsonl)",
                )
                return 1
            return 0
        time.sleep(max(0.2, args.interval))


if __name__ == "__main__":
    raise SystemExit(main())
