"""Step-scoped structured tracing: a bounded event ring + Chrome-trace export.

PR 3's `PhaseRecorder` answers *where does step time go on average* (per-phase
p50/p90); what it cannot answer is *what was this run doing in the seconds
before it died*, or *why is plan B 1.4 ms/step slower than plan A* — both need
the TIMELINE, not the aggregate. This module is that timeline:

  TraceRing         — a thread-safe bounded ring of Chrome-trace events.
                      Recording one event is a dict build + a deque append
                      under a lock (no allocation cliffs, no I/O, no device
                      interaction), cheap enough to leave on for every run —
                      the flight recorder (obs/flight.py) does exactly that,
                      and the <1% overhead contract is pinned in
                      tests/test_trace.py + benchmarks/trace_overhead.py.
  chrome_trace_doc  — ring events -> a Chrome-trace/Perfetto JSON document
                      (one process track per host, threads renumbered to
                      stable small tids). Open in ui.perfetto.dev or
                      chrome://tracing.
  merge_traces      — merge per-process docs into one multi-track doc,
                      aligned BY STEP INDEX: hosts share no clock, but they
                      do share the global step counter (the same invariant
                      the collective cadence rides), so the earliest step
                      boundary every host recorded becomes the common t0.
                      Host identity comes from each doc's process_index
                      metadata — the same pid the heartbeat rows carry.
  validate_trace_doc — the schema check CI and tests run against every
                      exported artifact (an unopenable trace is not evidence).

Event vocabulary (all host-side wall clock, ts/dur in microseconds):
  'X' complete spans — the PhaseRecorder phases (batcher_wait / h2d /
      dispatch / device_wait / checkpoint) plus the step/chunk/epoch parents
      the trainers emit at boundaries (args carry the step index);
  'C' counter events — the health counters from the trainers' lagged
      metrics drain (loss, grad_norm, nonfinite counts);
  'i' instant events — one-off marks (multi-process heartbeat rows).

`python -m word2vec_tpu.obs.tracediff A.json B.json` attributes a step-time
delta between two exported traces to named spans (obs/tracediff.py).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional

#: default event capacity of a ring (~16 events/step x 512 steps)
DEFAULT_CAPACITY = 8192

#: X-event names that are step-scoped PARENTS, not phase spans: their args
#: carry the optimizer-step index ("step", and "steps" for the chunk width),
#: which is what the cross-host merge and tracediff's per-step math key on
STEP_PARENTS = ("step", "chunk")


class TraceRing:
    """Thread-safe bounded ring of Chrome-trace events.

    Timestamps are `time.perf_counter()` microseconds relative to the ring's
    construction (`t0`), so they compose directly with the PhaseRecorder's
    span clocks; `wall0` anchors the axis to wall time for humans. When the
    ring is full the oldest event is overwritten (`dropped` counts how many)
    — the flight recorder wants the LAST N steps, not the first.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max(1, int(capacity))
        )
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.dropped = 0

    def _push(self, ev: Dict) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    # ------------------------------------------------------------ recording
    def complete(
        self,
        name: str,
        t0: float,
        dur_s: float,
        args: Optional[Dict] = None,
    ) -> None:
        """One finished span ('X'): `t0` is a perf_counter read, `dur_s`
        seconds. This is the PhaseRecorder's emission hook (obs/phases.py)."""
        ev: Dict = {
            "name": name,
            "ph": "X",
            "ts": round(1e6 * (t0 - self.t0), 1),
            "dur": round(1e6 * dur_s, 1),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        self._push(ev)

    def counter(self, name: str, values: Dict[str, float]) -> None:
        """One counter sample ('C'): numeric series (the health drain)."""
        self._push({
            "name": name,
            "ph": "C",
            "ts": round(1e6 * (time.perf_counter() - self.t0), 1),
            "tid": threading.get_ident(),
            "args": {k: float(v) for k, v in values.items()},
        })

    def instant(self, name: str, args: Optional[Dict] = None) -> None:
        """One instantaneous mark ('i')."""
        ev: Dict = {
            "name": name,
            "ph": "i",
            "ts": round(1e6 * (time.perf_counter() - self.t0), 1),
            "tid": threading.get_ident(),
            "s": "p",  # process-scoped mark
        }
        if args:
            ev["args"] = args
        self._push(ev)

    # ------------------------------------------------------------ reporting
    def events(self) -> List[Dict]:
        """Snapshot of the ring's events, oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# --------------------------------------------------------------- documents
def chrome_trace_doc(
    events: Iterable[Dict],
    process_index: int = 0,
    process_name: Optional[str] = None,
    metadata: Optional[Dict] = None,
) -> Dict:
    """Assemble ring events into one Chrome-trace/Perfetto JSON document.

    One process track (`pid` = the jax process index — the same id the
    heartbeat rows carry, which is what lets merge_traces name hosts);
    thread ids are renumbered to stable small ints in order of first
    appearance, with 'M' metadata events naming the tracks.
    """
    pid = int(process_index)
    tid_map: Dict = {}
    out: List[Dict] = []
    for ev in events:
        ev = dict(ev)
        raw_tid = ev.pop("tid", 0)
        tid = tid_map.setdefault(raw_tid, len(tid_map))
        ev["pid"] = pid
        ev["tid"] = tid
        out.append(ev)
    meta_events: List[Dict] = [{
        "name": "process_name",
        "ph": "M",
        "pid": pid,
        "tid": 0,
        "args": {"name": process_name or f"host {pid}"},
    }]
    for raw, tid in tid_map.items():
        meta_events.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": "main" if tid == 0 else f"thread-{tid}"},
        })
    return {
        "traceEvents": meta_events + out,
        "displayTimeUnit": "ms",
        "metadata": {
            "process_index": pid,
            "exported_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            **(metadata or {}),
        },
    }


def write_trace(path: str, doc: Dict) -> str:
    """Atomic write (tmp + rename, like the manifest writer)."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"), default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_trace(path: str) -> Dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome-trace document")
    return doc


def validate_trace_doc(doc: Dict) -> Dict[str, int]:
    """Schema check over a Chrome-trace document; returns per-phase event
    counts. Raises ValueError naming the first offending event — the same
    validation CI's trace job runs on every exported artifact."""
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        raise ValueError("not a Chrome-trace document: no traceEvents list")
    counts: Dict[str, int] = {}
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        for field in ("name", "ph"):
            if not isinstance(ev.get(field), str) or not ev[field]:
                raise ValueError(f"event {i}: missing {field!r}")
        ph = ev["ph"]
        if not isinstance(ev.get("pid"), int) or not isinstance(
            ev.get("tid"), int
        ):
            raise ValueError(f"event {i} ({ev['name']!r}): pid/tid not ints")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                raise ValueError(
                    f"event {i} ({ev['name']!r}): bad ts {ts!r}"
                )
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"event {i} ({ev['name']!r}): bad dur {dur!r}"
                )
        counts[ph] = counts.get(ph, 0) + 1
    return counts


# ------------------------------------------------------------------- merge
def _doc_pid(doc: Dict) -> int:
    md = doc.get("metadata") or {}
    pid = md.get("process_index")
    if isinstance(pid, int):
        return pid
    for ev in doc.get("traceEvents", []):
        if isinstance(ev.get("pid"), int):
            return ev["pid"]
    return 0


def _step_starts(doc: Dict) -> Dict[int, float]:
    """{step index: start ts} from a doc's step/chunk parent events (first
    occurrence wins; step counters only advance, so first == earliest)."""
    starts: Dict[int, float] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "X" and ev.get("name") in STEP_PARENTS:
            s = (ev.get("args") or {}).get("step")
            if isinstance(s, (int, float)):
                starts.setdefault(int(s), float(ev["ts"]))
    return starts


def merge_traces(docs: List[Dict]) -> Dict:
    """Merge per-process trace docs into one multi-track document.

    Hosts share no wall clock, but the global step counter advances in
    lockstep across the fleet (the collective cadence depends on it), so
    timelines are aligned by STEP INDEX: the earliest step boundary present
    in EVERY doc becomes the common anchor and each doc's timestamps shift
    so its anchor lands at the reference doc's. Docs with no common step
    (or none at all) fall back to aligning their earliest event. Process
    identity (the track pid) comes from each doc's process_index metadata —
    the same pid the heartbeat rows carry. Deterministic: docs are sorted
    by pid first, so input order never changes the output.
    """
    docs = [d for d in docs if d and d.get("traceEvents")]
    if not docs:
        return {
            "traceEvents": [],
            "displayTimeUnit": "ms",
            "metadata": {"merged": True, "processes": []},
        }
    docs = sorted(docs, key=_doc_pid)
    step_maps = [_step_starts(d) for d in docs]
    common = set(step_maps[0])
    for m in step_maps[1:]:
        common &= set(m)
    anchor = min(common) if common else None

    def doc_min_ts(d: Dict) -> float:
        return min(
            (
                float(e["ts"])
                for e in d["traceEvents"]
                if e.get("ph") != "M" and "ts" in e
            ),
            default=0.0,
        )

    ref_min = doc_min_ts(docs[0])
    events: List[Dict] = []
    pids: List[int] = []
    seen: set = set()
    for d, m in zip(docs, step_maps):
        if anchor is not None:
            off = step_maps[0][anchor] - m[anchor]
        else:
            off = ref_min - doc_min_ts(d)
        pid = _doc_pid(d)
        while pid in seen:  # collision: keep tracks distinct, deterministic
            pid += 1
        seen.add(pid)
        pids.append(pid)
        for ev in d["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M" and "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + off, 1)
            events.append(ev)
    # normalize: alignment offsets can push pre-anchor events negative
    tmin = min(
        (e["ts"] for e in events if e.get("ph") != "M" and "ts" in e),
        default=0.0,
    )
    if tmin < 0:
        for e in events:
            if e.get("ph") != "M" and "ts" in e:
                e["ts"] = round(e["ts"] - tmin, 1)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged": True,
            "processes": pids,
            "anchor_step": anchor,
        },
    }
