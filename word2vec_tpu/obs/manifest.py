"""Run manifests: what exactly did this run execute?

Every telemetry-enabled run writes one `manifest.json` next to its metrics
so a JSONL record / bench artifact / prom scrape can always be traced back
to the REALIZED configuration — not the flags the user typed, but what the
planner resolved them to (band backend, plan source, probe count), on which
device, under which jax/jaxlib, at which git sha. The r4 forwarding-audit
lesson generalized: a number whose provenance can't be reconstructed from
its own directory is not evidence.

`manifest_dict` is pure assembly (usable by bench.py for its one-line JSON
record, with `include_config=False` to keep the line short); `write_manifest`
adds the atomic tmp+replace file write the checkpoint writer uses.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional

SCHEMA = 1


def git_sha() -> Optional[str]:
    """HEAD sha of the repo this package runs from; None outside a checkout
    (installed wheels, missing git binary) — the manifest must never make a
    run fail."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = (out.stdout or "").strip()
    return sha if out.returncode == 0 and sha else None


def runtime_versions() -> Dict[str, Optional[str]]:
    import jax

    versions: Dict[str, Optional[str]] = {
        "python": sys.version.split()[0],
        "jax": getattr(jax, "__version__", None),
    }
    try:
        import jaxlib

        versions["jaxlib"] = getattr(jaxlib, "__version__", None)
    except ImportError:
        versions["jaxlib"] = None
    try:
        import numpy

        versions["numpy"] = numpy.__version__
    except ImportError:  # pragma: no cover - numpy is a hard dep
        versions["numpy"] = None
    try:
        from importlib import metadata

        versions["libtpu"] = metadata.version("libtpu")
    except Exception:
        versions["libtpu"] = None
    return versions


def device_info() -> Dict:
    """Where the run actually executed (the --emit-device contract's data)."""
    import jax

    dev = jax.devices()[0]
    return {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
    }


def manifest_dict(
    config,
    vocab_size: Optional[int] = None,
    plan_resolution=None,
    include_config: bool = True,
    extra: Optional[Dict] = None,
) -> Dict:
    """Assemble a run manifest from the REALIZED config (pass the trainer's
    config, which carries any applied plan — not the pre-plan one)."""
    man: Dict = {
        "schema": SCHEMA,
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "argv": list(sys.argv),
        "vocab_size": vocab_size,
        # the realized step shapes, whether they came from flags or a plan
        "plan": config.current_plan().to_json(),
        "plan_source": "flags",
        "band_backend": config.band_backend,
        "kernel": config.resolved_kernel,
        "device": device_info(),
        "versions": runtime_versions(),
        "git_sha": git_sha(),
    }
    if plan_resolution is not None:
        man["plan_source"] = plan_resolution.source
        man["plan_key"] = plan_resolution.key
        man["plan_predicted"] = plan_resolution.predicted
        man["plan_probes"] = len(plan_resolution.probes)
    if include_config:
        man["config"] = dataclasses.asdict(config)
    if extra:
        man.update(extra)
    return man


def write_manifest(path: str, config, **kwargs) -> Dict:
    """manifest_dict + atomic write; returns the written dict."""
    man = manifest_dict(config, **kwargs)
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=2, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return man


def append_manifest_event(path: str, key: str, record: Dict) -> Optional[Dict]:
    """Append `record` to the manifest's `key` LIST field (creating it),
    atomically. The elastic wiring uses this for `mesh_events`: every
    shrink/grow decision, rendezvous re-election, and generation start
    lands as one ordered row in the same file that pins the run's
    configuration, surviving the in-place exec that separates generations
    (the new generation carries the prior list forward before rewriting
    its manifest). Since PR 13 every remesh/generation_start row also
    carries the DECIDING rendezvous address (`rendezvous` — moves after a
    rank-0 election) and the `trigger` (failure | policy | rejoin |
    launch), so one manifest read reconstructs who decided each topology
    and why. Same never-fail-the-run contract as update_manifest."""
    try:
        with open(path) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    events = man.get(key)
    if not isinstance(events, list):
        events = []
    events.append(dict(record))
    return update_manifest(path, {key: events})


def update_manifest(path: str, fields: Dict) -> Optional[Dict]:
    """Merge `fields` into an existing manifest (atomic rewrite).

    The resilience wiring uses this to record how a run ENDED — `shutdown:
    clean|preempted|diverged`, recovery events — in the same file that
    already pins how it started, so one read answers both. Returns the
    updated dict, or None when the manifest is missing/unreadable: the
    update must never fail a run whose training result already exists."""
    try:
        with open(path) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    man.update(fields)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(man, f, indent=2, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        return None
    return man
