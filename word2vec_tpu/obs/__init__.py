"""Telemetry subsystem (PR 3 + PR 6 + PR 9): health counters, phase timing,
manifests, exporters, tracing, flight recorder, quality probes.

    obs.health    — on-device health counters inside the existing jit/scan
                    (instrument_step), the lagged-drain HealthMonitor, and
                    the structured DivergenceError tripwire
    obs.phases    — host-side phase-timing breakdown (PhaseRecorder) with an
                    input-bound-vs-compute-bound verdict
    obs.manifest  — run manifests: realized plan/backend, device, versions,
                    git sha
    obs.export    — MetricsHub sink fan-out + the Prometheus textfile sink
                    (gauges, event counters, exposition timestamp)
    obs.trace     — step-scoped span tracing: bounded event ring,
                    Chrome-trace/Perfetto export, deterministic cross-host
                    merge by step index
    obs.flight    — always-on flight recorder: the last N steps of spans +
                    counters + log records + quality-probe rows, dumped as
                    flight.json on every failure path (divergence / stall /
                    preemption / peer loss / quality alert) and on demand
                    via SIGUSR1
    obs.quality   — in-training embedding-quality probes (QualityProbe:
                    planted Spearman/analogy, neighbor drift, effective
                    rank through the serve query kernel) and the degeneracy
                    sentinel (QualitySentinel -> QualityAlert, rc=3)
    obs.tracediff — `python -m word2vec_tpu.obs.tracediff A.json B.json`:
                    attribute a step-time delta between two traces to named
                    spans; also the trace_summary bench.py banks
    obs.signals   — derived-signal plane (SignalEngine): windowed time
                    series (EWMA/p50/p90/slope) over streams that already
                    exist — throughput, step time, input-bound ratio,
                    straggler skew, quality, serve qps/p99 — plus the
                    control-ready SignalBus and the fleet-health verdict
    obs.slo       — declarative SLO rules (`--slo
                    'throughput_wps<0.8*baseline:for=5'`) evaluated per
                    window: ok -> warn -> breach escalation, structured
                    SloEvents, w2v_slo_breaches_total — observe, never exit
    obs.fleet     — cross-host aggregation: per-host signal rows merged BY
                    WINDOW ID into fleet.json + w2v_fleet_* gauges with
                    worst-straggler host attribution; also the standalone
                    `python -m word2vec_tpu.obs.fleet` replica aggregator
    obs.watch     — `python -m word2vec_tpu.obs.watch --dir DIR`: terminal
                    dashboard tailing fleet.json
    obs.devmem    — HBM memory ledger (MemoryLedger): device.memory_stats()
                    per-phase watermarks beaten from the step loop, the
                    mem_headroom_frac derived signal (SLO-able), w2v_mem_*
                    gauges present from zero even on statless backends, and
                    the growth-headroom forecast in the manifest
    obs.harvest   — compiled-program cost harvest (CostHarvest): XLA's own
                    cost_analysis()/memory_analysis() per jitted executable,
                    captured as avals at first dispatch, analyzed after the
                    run, banked next to the analytic prediction it audits
    obs.profiler  — bounded jax.profiler windows (ProfilerCapture): armed by
                    SLO breaches (--profile-on-breach), --profile-steps A:B,
                    or SIGUSR2; one capture per breach episode with a
                    schema-checked manifest next to flight.json

Drivers (train.Trainer, parallel.ShardedTrainer, cli.py, bench.py) all
route through here; utils/logging.py keeps the individual log sinks.
"""

from .devmem import MemoryLedger, device_memory_stats
from .export import MetricsHub, prometheus_textfile
from .fleet import FleetAggregator, merge_rows, validate_fleet_doc
from .flight import FlightRecorder
from .harvest import CostHarvest
from .profiler import ProfilerCapture, validate_capture_doc
from .health import DivergenceError, HealthMonitor, health_record
from .manifest import manifest_dict, write_manifest
from .phases import PhaseRecorder
from .quality import (
    ProbeSet, QualityAlert, QualityProbe, QualitySentinel, score_table,
)
from .signals import FleetHealth, SignalBus, SignalEngine
from .slo import SloError, SloEvaluator, SloRule, parse_slo
from .trace import TraceRing, chrome_trace_doc, merge_traces, write_trace

__all__ = [
    "MemoryLedger",
    "device_memory_stats",
    "CostHarvest",
    "ProfilerCapture",
    "validate_capture_doc",
    "MetricsHub",
    "prometheus_textfile",
    "FleetAggregator",
    "merge_rows",
    "validate_fleet_doc",
    "FleetHealth",
    "SignalBus",
    "SignalEngine",
    "SloError",
    "SloEvaluator",
    "SloRule",
    "parse_slo",
    "FlightRecorder",
    "DivergenceError",
    "HealthMonitor",
    "health_record",
    "manifest_dict",
    "write_manifest",
    "PhaseRecorder",
    "ProbeSet",
    "QualityAlert",
    "QualityProbe",
    "QualitySentinel",
    "score_table",
    "TraceRing",
    "chrome_trace_doc",
    "merge_traces",
    "write_trace",
]
