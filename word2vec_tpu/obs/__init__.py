"""Telemetry subsystem (PR 3): health counters, phase timing, manifests,
exporters.

    obs.health   — on-device health counters inside the existing jit/scan
                   (instrument_step), the lagged-drain HealthMonitor, and
                   the structured DivergenceError tripwire
    obs.phases   — host-side phase-timing breakdown (PhaseRecorder) with an
                   input-bound-vs-compute-bound verdict
    obs.manifest — run manifests: realized plan/backend, device, versions,
                   git sha
    obs.export   — MetricsHub sink fan-out + the Prometheus textfile sink

Drivers (train.Trainer, parallel.ShardedTrainer, cli.py, bench.py) all
route through here; utils/logging.py keeps the individual log sinks.
"""

from .export import MetricsHub, prometheus_textfile
from .health import DivergenceError, HealthMonitor, health_record
from .manifest import manifest_dict, write_manifest
from .phases import PhaseRecorder

__all__ = [
    "MetricsHub",
    "prometheus_textfile",
    "DivergenceError",
    "HealthMonitor",
    "health_record",
    "manifest_dict",
    "write_manifest",
    "PhaseRecorder",
]
