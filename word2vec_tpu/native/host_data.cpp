// Native host-side data layer for word2vec_tpu.
//
// TPU-native equivalent of the reference's C++ data layer (main.cpp:63-92
// text8 reader, Word2Vec.cpp:132-169 vocab count, Word2Vec.cpp:212-230
// string->index encoding), redesigned for a streaming, array-oriented host:
// the host's only jobs are (a) counting words, (b) turning the corpus into
// one flat int32 id stream, (c) filling fixed-shape [B, L] batch buffers.
// Everything else lives on the device.
//
// Exposed as a plain C ABI consumed via ctypes (word2vec_tpu/native/__init__.py);
// the Python implementations remain as always-available fallbacks.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC host_data.cpp -o libw2vhost.so

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

inline uint64_t hash_bytes(const char* s, size_t n) {
    // FNV-1a
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < n; ++i) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

// Open-addressing (linear probe) map from byte-string -> int64 value.
// Keys point into an arena or into the mmap'd corpus; the map never owns them.
struct StrMap {
    struct Ent {
        const char* p = nullptr;
        uint32_t len = 0;
        int64_t val = 0;
    };
    std::vector<Ent> slots;
    size_t mask = 0;
    size_t used = 0;

    explicit StrMap(size_t expected) {
        size_t cap = 64;
        while (cap < expected * 2) cap <<= 1;
        slots.resize(cap);
        mask = cap - 1;
    }

    void grow() {
        std::vector<Ent> old = std::move(slots);
        slots.clear();
        slots.resize(old.size() * 2);
        mask = slots.size() - 1;
        used = 0;
        for (const Ent& e : old)
            if (e.p) *insert_slot(e.p, e.len) = e;
    }

    Ent* insert_slot(const char* p, uint32_t len) {
        size_t i = hash_bytes(p, len) & mask;
        while (slots[i].p) {
            if (slots[i].len == len && memcmp(slots[i].p, p, len) == 0)
                return &slots[i];
            i = (i + 1) & mask;
        }
        ++used;
        slots[i].p = p;
        slots[i].len = len;
        return &slots[i];
    }

    // Returns slot for key, inserting with val=0 if absent. May grow.
    Ent* upsert(const char* p, uint32_t len) {
        if (used * 3 > slots.size() * 2) grow();
        return insert_slot(p, len);
    }

    const Ent* lookup(const char* p, uint32_t len) const {
        size_t i = hash_bytes(p, len) & mask;
        while (slots[i].p) {
            if (slots[i].len == len && memcmp(slots[i].p, p, len) == 0)
                return &slots[i];
            i = (i + 1) & mask;
        }
        return nullptr;
    }
};

struct MappedFile {
    const char* data = nullptr;
    size_t size = 0;
    int fd = -1;
    bool mapped = false;
    std::vector<char> fallback;

    bool open(const char* path) {
        fd = ::open(path, O_RDONLY);
        if (fd < 0) return false;
        struct stat st;
        if (fstat(fd, &st) != 0) {
            ::close(fd);
            return false;
        }
        size = (size_t)st.st_size;
        if (size == 0) {
            data = "";
            return true;
        }
        void* m = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
        if (m != MAP_FAILED) {
            data = (const char*)m;
            mapped = true;
            madvise(m, size, MADV_SEQUENTIAL);
            return true;
        }
        fallback.resize(size);
        ssize_t got = pread(fd, fallback.data(), size, 0);
        if ((size_t)got != size) {
            ::close(fd);
            return false;
        }
        data = fallback.data();
        return true;
    }

    ~MappedFile() {
        if (mapped) munmap((void*)data, size);
        if (fd >= 0) ::close(fd);
    }
};

inline bool is_space(char c) {
    return c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

struct Counter {
    // words stored contiguously in an arena; entries reference it
    std::vector<char> arena;
    struct Word {
        size_t ofs;
        uint32_t len;
        int64_t count;
    };
    std::vector<Word> words;
    long long total = 0;
};

struct VocabHandle {
    std::vector<char> arena;
    StrMap map;
    explicit VocabHandle(size_t n) : map(n) {}
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- counting
// Tokenize `path` by whitespace and count distinct words.
// Returns an opaque Counter*, or nullptr on I/O error.
void* w2v_count_file(const char* path) {
    MappedFile f;
    if (!f.open(path)) return nullptr;

    // First pass: count with keys pointing into the mmap.
    StrMap map(1 << 16);
    const char* p = f.data;
    const char* end = f.data + f.size;
    long long total = 0;
    while (p < end) {
        while (p < end && is_space(*p)) ++p;
        const char* w = p;
        while (p < end && !is_space(*p)) ++p;
        if (p > w) {
            map.upsert(w, (uint32_t)(p - w))->val += 1;
            ++total;
        }
    }

    // Copy surviving keys into an arena that outlives the mmap.
    Counter* c = new Counter();
    c->total = total;
    size_t bytes = 0;
    for (const auto& e : map.slots)
        if (e.p) bytes += e.len;
    c->arena.resize(bytes);
    size_t ofs = 0;
    for (const auto& e : map.slots) {
        if (!e.p) continue;
        memcpy(c->arena.data() + ofs, e.p, e.len);
        c->words.push_back({ofs, e.len, e.val});
        ofs += e.len;
    }
    return c;
}

long long w2v_counter_size(void* h) { return (long long)((Counter*)h)->words.size(); }
long long w2v_counter_total(void* h) { return ((Counter*)h)->total; }

// Copy entry i's word bytes into buf (cap bytes incl. NUL); returns count,
// or -1 if i out of range / buf too small.
long long w2v_counter_entry(void* h, long long i, char* buf, long long cap) {
    Counter* c = (Counter*)h;
    if (i < 0 || (size_t)i >= c->words.size()) return -1;
    const Counter::Word& w = c->words[(size_t)i];
    if ((long long)w.len + 1 > cap) return -1;
    memcpy(buf, c->arena.data() + w.ofs, w.len);
    buf[w.len] = '\0';
    return w.count;
}

void w2v_counter_free(void* h) { delete (Counter*)h; }

// ----------------------------------------------------------------- vocab
// Build a word->id lookup from `n` NUL-terminated words (id = position).
void* w2v_vocab_create(const char** words, long long n) {
    VocabHandle* v = new VocabHandle((size_t)n);
    size_t bytes = 0;
    for (long long i = 0; i < n; ++i) bytes += strlen(words[i]);
    v->arena.resize(bytes);
    size_t ofs = 0;
    for (long long i = 0; i < n; ++i) {
        size_t len = strlen(words[i]);
        memcpy(v->arena.data() + ofs, words[i], len);
        auto* e = v->map.upsert(v->arena.data() + ofs, (uint32_t)len);
        e->val = i;
        ofs += len;
    }
    return v;
}

void w2v_vocab_free(void* h) { delete (VocabHandle*)h; }

// ----------------------------------------------------------------- encode
// Stream-tokenize `path`, mapping tokens to int32 ids (OOV dropped, matching
// Word2Vec.cpp:223). mode 0: plain stream (text8); mode 1: emit -1 at each
// newline run (line_docs sentence boundary, Word2Vec.cpp:19-30).
// Writes at most `cap` ids to `out`; returns number written, or -1 on error.
long long w2v_encode_file(const char* path, void* vocab, int mode,
                          int32_t* out, long long cap) {
    VocabHandle* v = (VocabHandle*)vocab;
    MappedFile f;
    if (!f.open(path)) return -1;
    const char* p = f.data;
    const char* end = f.data + f.size;
    long long n = 0;
    bool pending_break = false;
    while (p < end) {
        while (p < end && is_space(*p)) {
            if (mode == 1 && *p == '\n') pending_break = true;
            ++p;
        }
        const char* w = p;
        while (p < end && !is_space(*p)) ++p;
        if (p > w) {
            if (pending_break && n > 0 && n < cap) out[n++] = -1;
            pending_break = false;
            const auto* e = v->map.lookup(w, (uint32_t)(p - w));
            if (e) {
                if (n >= cap) return n;  // caller sized the buffer; stop clean
                out[n++] = (int32_t)e->val;
            }
        }
    }
    return n;
}

// ------------------------------------------------------------- batch fill
// Fill a [B, L] int32 batch (pad -1) from the packed corpus
// (flat ids + row table) following `order[pos : pos+B]`. Rows past the end
// of `order` stay fully padded. Returns the number of real tokens written.
long long w2v_fill_batch(const int32_t* flat, const int64_t* starts,
                         const int32_t* lens, const int64_t* order,
                         long long num_rows, long long pos, long long B,
                         long long L, int32_t* out) {
    long long words = 0;
    for (long long r = 0; r < B; ++r) {
        int32_t* dst = out + r * L;
        long long oi = pos + r;
        if (oi >= num_rows) {
            for (long long j = 0; j < L; ++j) dst[j] = -1;
            continue;
        }
        int64_t row = order[oi];
        int64_t s = starts[row];
        int32_t n = lens[row];
        if (n > L) n = (int32_t)L;
        memcpy(dst, flat + s, (size_t)n * sizeof(int32_t));
        for (long long j = n; j < L; ++j) dst[j] = -1;
        words += n;
    }
    return words;
}

}  // extern "C"
