"""ctypes bindings for the native host data layer (host_data.cpp).

The shared library is compiled on first use with g++ (cached next to the
source); every entry point has a pure-Python fallback, so the framework works
identically without a toolchain — just slower on the host-side corpus pass.

Public API:
    available() -> bool
    count_file(path) -> (counts dict, total_words)   [vocab counting]
    encode_file(path, vocab, mode) -> np.ndarray[int32]
    fill_batch(flat, starts, lens, order, pos, out) -> words  [batch assembly]
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "host_data.cpp")
_LIB_PATH = os.path.join(_HERE, "libw2vhost.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

MODE_STREAM = 0  # text8-style whitespace stream
MODE_LINES = 1   # newline = sentence boundary (-1 separators)


def _build() -> Optional[str]:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        _SRC, "-o", _LIB_PATH,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return _LIB_PATH
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError):
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        path = _LIB_PATH
        if not os.path.exists(path) or os.path.getmtime(path) < os.path.getmtime(_SRC):
            path = _build()
        if path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            # A checked-in .so built on another machine can be unloadable
            # here (e.g. a newer glibc symbol version) while the toolchain
            # compiles the source just fine — rebuild once from source
            # before declaring the native layer unavailable.
            path = _build()
            if path is None:
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                _build_failed = True
                return None
        lib.w2v_count_file.restype = ctypes.c_void_p
        lib.w2v_count_file.argtypes = [ctypes.c_char_p]
        lib.w2v_counter_size.restype = ctypes.c_longlong
        lib.w2v_counter_size.argtypes = [ctypes.c_void_p]
        lib.w2v_counter_total.restype = ctypes.c_longlong
        lib.w2v_counter_total.argtypes = [ctypes.c_void_p]
        lib.w2v_counter_entry.restype = ctypes.c_longlong
        lib.w2v_counter_entry.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_char_p, ctypes.c_longlong,
        ]
        lib.w2v_counter_free.restype = None
        lib.w2v_counter_free.argtypes = [ctypes.c_void_p]
        lib.w2v_vocab_create.restype = ctypes.c_void_p
        lib.w2v_vocab_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_longlong,
        ]
        lib.w2v_vocab_free.restype = None
        lib.w2v_vocab_free.argtypes = [ctypes.c_void_p]
        lib.w2v_encode_file.restype = ctypes.c_longlong
        lib.w2v_encode_file.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_longlong,
        ]
        lib.w2v_fill_batch.restype = ctypes.c_longlong
        lib.w2v_fill_batch.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------ counting
def count_file(path: str) -> Tuple[Dict[str, int], int]:
    """Word counts + total tokens. Native if possible, else pure Python."""
    lib = _load()
    if lib is None:
        return _count_file_py(path)
    h = lib.w2v_count_file(path.encode())
    if not h:
        raise OSError(f"cannot read {path}")
    try:
        n = lib.w2v_counter_size(h)
        total = lib.w2v_counter_total(h)
        buf = ctypes.create_string_buffer(1 << 16)
        counts: Dict[str, int] = {}
        for i in range(n):
            c = lib.w2v_counter_entry(h, i, buf, len(buf))
            if c < 0:
                raise RuntimeError("counter entry overflow")
            w = buf.value.decode("utf-8", errors="replace")
            # distinct invalid-byte tokens can decode to the same U+FFFD
            # string: merge counts rather than overwrite (matches the Python
            # fallback, which decodes before counting). Note such tokens still
            # fail to match raw corpus bytes in encode_file and are dropped as
            # OOV there — a documented native/Python divergence for non-UTF8
            # corpora (text8/enwik9 are ASCII).
            counts[w] = counts.get(w, 0) + c
        return counts, int(total)
    finally:
        lib.w2v_counter_free(h)


def _count_file_py(path: str) -> Tuple[Dict[str, int], int]:
    from collections import Counter

    counter: Counter = Counter()
    total = 0
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            toks = line.split()
            counter.update(toks)
            total += len(toks)
    return dict(counter), total


# ------------------------------------------------------------------- encode
def encode_file(
    path: str, vocab, mode: int = MODE_STREAM, max_tokens: Optional[int] = None
) -> np.ndarray:
    """Corpus -> flat int32 id stream (OOV dropped, Word2Vec.cpp:223; mode
    LINES inserts -1 at sentence boundaries). `vocab` is a data.vocab.Vocab.

    max_tokens: total corpus token count if known (from count_file) — sizes
    the output buffer tightly (ids + separators <= 2*tokens). Without it the
    bound falls back to the file byte count.
    """
    lib = _load()
    if lib is None:
        return _encode_file_py(path, vocab, mode)
    if max_tokens is not None:
        cap = 2 * max_tokens + 2 if mode == MODE_LINES else max_tokens + 2
    else:
        # ids + separators <= whitespace tokens + sentences <= bytes + 2
        cap = os.path.getsize(path) + 2
    out = np.empty(cap, dtype=np.int32)
    words = [w.encode() for w in vocab.words]
    arr = (ctypes.c_char_p * len(words))(*words)
    vh = lib.w2v_vocab_create(arr, len(words))
    if not vh:
        raise MemoryError("vocab handle")
    try:
        n = lib.w2v_encode_file(
            path.encode(), vh, mode,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap,
        )
        if n < 0:
            raise OSError(f"cannot read {path}")
        return out[:n].copy()
    finally:
        lib.w2v_vocab_free(vh)


def _encode_file_py(path: str, vocab, mode: int) -> np.ndarray:
    w2i = vocab.word2id
    ids: list = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            toks = [w2i[t] for t in line.split() if t in w2i]
            if mode == MODE_LINES:
                if toks and ids:
                    ids.append(-1)
                ids.extend(toks)
            else:
                ids.extend(toks)
    return np.asarray(ids, dtype=np.int32)


# --------------------------------------------------------------- batch fill
def fill_batch(
    flat: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    order: np.ndarray,
    pos: int,
    out: np.ndarray,
) -> int:
    """Fill out[B, L] (pad -1) from packed-corpus rows order[pos:pos+B];
    returns real-token count. Native if possible."""
    lib = _load()
    if lib is None:
        return _fill_batch_py(flat, starts, lens, order, pos, out)
    B, L = out.shape
    return int(
        lib.w2v_fill_batch(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(order), pos, B, L,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    )


def _fill_batch_py(flat, starts, lens, order, pos, out) -> int:
    B, L = out.shape
    out[:] = -1
    words = 0
    for r in range(B):
        oi = pos + r
        if oi >= len(order):
            continue
        row = int(order[oi])
        s, n = int(starts[row]), min(int(lens[row]), L)
        out[r, :n] = flat[s : s + n]
        words += n
    return words
