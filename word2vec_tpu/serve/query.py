"""The shared batched query kernel: one jit'd top-k matmul for everything.

Before this module, the cosine/top-k math lived three times in eval/ —
`neighbors.nearest_neighbors`, `neighbors.analogy_query`, and the analogy
evaluator — each renormalizing the FULL table on every call (an O(V*d) host
pass per query) and ranking with `np.argpartition`, whose tie order is
unstable. The `QueryEngine` replaces all of them:

  * the table is row-normalized ONCE (`unit_norm`) and placed on device,
    resident for the engine's lifetime, in f32 or bf16 (int8 files
    dequantize on load — io/embeddings.load_embeddings_int8);
  * every query kind reduces to one shape: a weighted combination of up to
    3 table rows (neighbors: +row_i; analogy a:b::c:? : -a +b +c),
    renormalized, scored against the whole table as a `[B, V]` matmul with
    f32 accumulation, query tokens masked to -inf, `jax.lax.top_k`;
  * batch and k are padded to power-of-two buckets so a serving mix of
    sizes reuses a handful of compiled programs instead of recompiling per
    request shape;
  * ties are returned in ascending-index order (host-side stable reorder of
    the top-k slice), so tied scores have ONE documented order instead of
    argpartition's arbitrary one.

`get_engine(W, vocab)` is the module-level cache the eval/ shims use: same
array object + same restriction -> same engine, so two successive
`nearest_neighbors` calls normalize the table once (pinned by a regression
test). The cache holds a weakref to W, never W itself — it cannot extend an
exported table's lifetime. Mutating W in place is NOT observed; pass a
fresh array (every exporter does) or build a QueryEngine directly.
"""

from __future__ import annotations

import collections
import threading
import weakref
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.vocab import Vocab

#: serving dtypes for the resident table (int8 is a FILE format — it
#: dequantizes into one of these on load, the cross-dtype path)
TABLE_DTYPES = ("float32", "bfloat16")


def unit_norm(W: np.ndarray) -> np.ndarray:
    """Row-normalize once, host-side, in f32 — THE normalization every
    query path shares (the eval modules' former per-call `W / ||W||`)."""
    W = np.asarray(W, dtype=np.float32)
    return W / np.maximum(np.linalg.norm(W, axis=1, keepdims=True), 1e-12)


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


# ------------------------------------------------------------ jit kernels
# Module-level jit'd functions taking the table as an argument: engines
# with the same (V, d, dtype) share compiled programs.
@jax.jit
def _combine_queries(table, ids, w):
    """[B, 3] row ids + weights -> [B, d] unit queries, f32.

    Neighbors: ids=(i,i,i), w=(1,0,0). Analogy a:b::c:? : ids=(a,b,c),
    w=(-1,1,1) — exactly `Wn[b] - Wn[a] + Wn[c]`, renormalized (3CosAdd).
    Padding rows (ids=-1 clamped to 0, w=0) come out as zero queries.
    """

    rows = table[jnp.clip(ids, 0, table.shape[0] - 1)].astype(jnp.float32)
    q = (w[:, :, None] * rows).sum(axis=1)
    return q / jnp.maximum(
        jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12
    )


@partial(jax.jit, static_argnums=(3,))
def _topk_kernel(table, q, mask, k):
    """[B, d] unit queries -> top-k (scores, ids) over the [V, d] table.

    The ONE fused kernel behind every neighbor/analogy query: a [B, V]
    cosine matmul with f32 accumulation (bf16 tables don't accumulate in
    bf16), -inf masking of the query tokens (mask is [B, M] row ids, -1 =
    no mask), then `jax.lax.top_k`.
    """

    scores = jax.lax.dot_general(
        q.astype(table.dtype), table,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, V]
    rows = jnp.arange(scores.shape[0])[:, None]
    valid = mask >= 0
    idx = jnp.where(valid, mask, 0)
    # masked slots drop to -inf; invalid slots min() against +inf (no-op)
    fill = jnp.where(valid, -jnp.inf, jnp.inf).astype(scores.dtype)
    scores = scores.at[rows, idx].min(fill)
    return jax.lax.top_k(scores, k)


@jax.jit
def _query_planes(table, ids, w):
    """Full [B, V] cosine planes of combined queries (the analogy
    evaluator's 3CosAdd path needs every candidate's score for gold-rank
    math, not just the top k)."""

    q = _combine_queries(table, ids, w)
    return jax.lax.dot_general(
        q.astype(table.dtype), table,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@jax.jit
def _row_planes(table, ids):
    """[B, V] cosine planes of raw table rows (3CosMul's ca/cb/cc)."""

    q = table[ids]
    return jax.lax.dot_general(
        q, table, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@jax.jit
def _pair_cosines(table, i, j):
    """Per-pair cosine of rows i and j (rows are unit, so a plain dot)."""

    a = table[i].astype(jnp.float32)
    b = table[j].astype(jnp.float32)
    return (a * b).sum(axis=-1)


class QueryEngine:
    """A row-normalized table resident on device + the batched kernels.

    `restrict` keeps only the most frequent `restrict` rows (the analogy
    evaluator's `restrict_vocab` protocol); words mapping past it are OOV
    to this engine.
    """

    #: batch rows are padded to the next power of two up to this cap; a
    #: bigger batch is split by the caller (the server's max_batch <= this)
    MAX_BATCH_BUCKET = 1024

    def __init__(
        self,
        W: np.ndarray,
        vocab: Vocab,
        table_dtype: str = "float32",
        restrict: Optional[int] = None,
    ):
        if table_dtype not in TABLE_DTYPES:
            raise ValueError(
                f"table_dtype must be one of {TABLE_DTYPES}, got "
                f"{table_dtype!r} (int8 is a file format: load it with "
                "io/embeddings.load_embeddings_int8, it dequantizes here)"
            )
        self.vocab = vocab
        V = W.shape[0] if restrict is None else min(W.shape[0], int(restrict))
        Wn = unit_norm(np.asarray(W)[:V])
        dt = jnp.bfloat16 if table_dtype == "bfloat16" else jnp.float32
        self.table = jax.device_put(jnp.asarray(Wn, dtype=dt))
        self.table_dtype = table_dtype
        self.V, self.d = int(V), int(Wn.shape[1])
        #: monotonically increasing swap generation (0 = the construction
        #: table); /stats and the streaming driver's swap events expose it
        self.generation = 0
        self._swap_lock = threading.Lock()

    # ------------------------------------------------------------ hot swap
    def swap_table(self, W: np.ndarray, vocab: Optional[Vocab] = None,
                   allow_shrink: bool = False) -> int:
        """Atomically replace the resident table with fresh embeddings —
        the continuous-training hot swap (stream/driver.py): normalize and
        place the NEW table first (the expensive part happens while the old
        one keeps serving), then flip the references. In-flight queries
        snapshot the (table, V, vocab) triple once at entry (batch_topk),
        so every request is answered entirely by one table generation and
        ZERO requests drop across a swap.

        The new vocabulary may only EXTEND the old one (grow-only): ids
        resolved against the old vocab stay valid against the new table.
        A shrinking swap would let a concurrently-admitted id index past
        the new V — refused unless `allow_shrink` (single-threaded
        callers). Returns the new generation."""
        Wn = unit_norm(np.asarray(W))
        if vocab is not None and len(vocab) < Wn.shape[0]:
            Wn = Wn[: len(vocab)]
        if Wn.shape[0] < self.V and not allow_shrink:
            raise ValueError(
                f"swap_table would SHRINK the table ({self.V} -> "
                f"{Wn.shape[0]} rows): ids resolved against the old "
                "vocabulary could index past the new one mid-flight; pass "
                "allow_shrink=True only from single-threaded callers"
            )
        if Wn.shape[1] != self.d:
            raise ValueError(
                f"swap_table dim mismatch: engine serves d={self.d}, new "
                f"table has d={Wn.shape[1]}"
            )
        dt = jnp.bfloat16 if self.table_dtype == "bfloat16" else jnp.float32
        new_table = jax.device_put(jnp.asarray(Wn, dtype=dt))
        # the swap's transient double-residency (old table serving + new
        # table placed) is the serve tier's memory spike — attribute it on
        # the process-wide HBM ledger when one is wired (obs/devmem.py;
        # no-op otherwise)
        from ..obs import devmem as _devmem

        _devmem.sample_active("serve_swap")
        with self._swap_lock:
            # the flip: queries already past their snapshot keep the old
            # device table alive (jax arrays are immutable); new requests
            # see the new triple
            self.table = new_table
            self.V = int(Wn.shape[0])
            if vocab is not None:
                self.vocab = vocab
            self.generation += 1
            return self.generation

    # ------------------------------------------------------------- lookup
    def ids_of(self, words: Sequence[str]) -> np.ndarray:
        """Word strings -> row ids; KeyError NAMES the missing word (the
        eval CLI prints these verbatim)."""
        out = np.empty(len(words), dtype=np.int32)
        for n, w in enumerate(words):
            if w not in self.vocab or self.vocab[w] >= self.V:
                raise KeyError(f"{w!r} not in vocabulary")
            out[n] = self.vocab[w]
        return out

    # ------------------------------------------------------ batched top-k
    def batch_topk(
        self, ids: np.ndarray, weights: np.ndarray, k: int
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """The serving entry point: [B, 3] ids + weights -> per-row
        (indices, scores), already k-clamped, -inf-filtered, and
        tie-stable (score desc, index asc). Pads B and k to power-of-two
        buckets so the compiled-program set stays small.

        The (table, V) pair is snapshotted ONCE here, so a concurrent
        swap_table never splits one request across two table generations
        — the zero-drop hot-swap contract (tests/test_stream.py)."""
        table, V = self.table, self.V
        B = int(ids.shape[0])
        if B == 0:
            return []
        if B > self.MAX_BATCH_BUCKET:
            return (
                self.batch_topk(ids[: self.MAX_BATCH_BUCKET],
                                weights[: self.MAX_BATCH_BUCKET], k)
                + self.batch_topk(ids[self.MAX_BATCH_BUCKET:],
                                  weights[self.MAX_BATCH_BUCKET:], k)
            )
        k = max(1, min(int(k), V))
        kb = min(V, _next_pow2(k))
        Bb = _next_pow2(B)
        ids_p = np.full((Bb, 3), -1, dtype=np.int32)
        w_p = np.zeros((Bb, 3), dtype=np.float32)
        ids_p[:B] = ids
        w_p[:B] = weights
        q = _combine_queries(table, ids_p, w_p)
        vals, top = _topk_kernel(table, q, ids_p, kb)
        vals = np.asarray(vals)[:B]
        top = np.asarray(top)[:B]
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        for r in range(B):
            v, t = vals[r], top[r]
            keep = np.isfinite(v)
            v, t = v[keep], t[keep]
            # deterministic tie order: score desc, then index asc (lexsort's
            # last key is primary). top_k output is already score-sorted, so
            # this only reorders WITHIN tied runs.
            order = np.lexsort((t, -v))[:k]
            out.append((t[order], v[order]))
        return out

    # -------------------------------------------------------- query kinds
    def neighbors_batch(
        self, words: Sequence[str], k: int = 10
    ) -> List[List[Tuple[str, float]]]:
        """Top-k cosine neighbors per word, the word itself masked."""
        wid = self.ids_of(words)
        ids = np.stack([wid, wid, wid], axis=1)
        w = np.tile(np.array([[1.0, 0.0, 0.0]], np.float32), (len(wid), 1))
        return [self._decode(t, v) for t, v in self.batch_topk(ids, w, k)]

    def neighbor_id_sets(
        self, ids: np.ndarray, k: int = 10
    ) -> List[np.ndarray]:
        """Top-k neighbor ROW IDS per raw row id (self masked) — the
        in-training quality probe's drift instrument (obs/quality.py):
        Jaccard@k between successive probes needs id sets, not decoded
        words, and must not re-run the word->id OOV checks per probe."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        trip = np.stack([ids, ids, ids], axis=1)
        w = np.tile(np.array([[1.0, 0.0, 0.0]], np.float32), (len(ids), 1))
        return [t for t, _ in self.batch_topk(trip, w, k)]

    def analogy_batch(
        self, triples: Sequence[Tuple[str, str, str]], k: int = 5
    ) -> List[List[Tuple[str, float]]]:
        """a:b :: c:? by 3CosAdd per triple; a, b, c masked."""
        flat = [w for t in triples for w in t]
        wid = self.ids_of(flat).reshape(-1, 3)
        w = np.tile(np.array([[-1.0, 1.0, 1.0]], np.float32), (len(wid), 1))
        return [self._decode(t, v) for t, v in self.batch_topk(wid, w, k)]

    def similarity_batch(
        self, pairs: Sequence[Tuple[str, str]]
    ) -> List[float]:
        """Cosine per (word, word) pair."""
        flat = [w for p in pairs for w in p]
        wid = self.ids_of(flat).reshape(-1, 2)
        return [float(x) for x in np.asarray(
            _pair_cosines(self.table, wid[:, 0], wid[:, 1])
        )]

    def _decode(
        self, idx: np.ndarray, scores: np.ndarray
    ) -> List[Tuple[str, float]]:
        words = self.vocab.words
        return [(words[int(i)], float(s)) for i, s in zip(idx, scores)]

    # ------------------------------------------------- eval-harness planes
    def pair_cosines(self, i: np.ndarray, j: np.ndarray) -> np.ndarray:
        """Cosines of row pairs by index (similarity.evaluate_pairs)."""
        return np.array(_pair_cosines(
            self.table, np.asarray(i, np.int32), np.asarray(j, np.int32)
        ))

    def analogy_planes(
        self, a: np.ndarray, b: np.ndarray, c: np.ndarray
    ) -> np.ndarray:
        """[B, V] 3CosAdd score planes, unmasked and WRITABLE (the analogy
        evaluator applies its own exclusion mask and rank math)."""
        ids = np.stack([a, b, c], axis=1).astype(np.int32)
        w = np.tile(np.array([[-1.0, 1.0, 1.0]], np.float32), (len(ids), 1))
        return np.array(_query_planes(self.table, ids, w))

    def cosine_planes(self, ids: np.ndarray) -> np.ndarray:
        """[B, V] cosine planes of table rows (3CosMul's three planes)."""
        return np.array(_row_planes(
            self.table, np.asarray(ids, np.int32)
        ))


# -------------------------------------------------------------- engine cache
# The normalize-once contract for the eval/ shims: repeat queries against
# the SAME exported array reuse one engine (and its one unit_norm pass +
# one device table). Keyed on id(W) with a weakref guard — a recycled id
# whose original array died is a miss, never a stale hit.
_CACHE_LOCK = threading.Lock()
_CACHE_CAP = 4
_ENGINE_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def get_engine(
    W: np.ndarray,
    vocab: Vocab,
    table_dtype: str = "float32",
    restrict: Optional[int] = None,
) -> QueryEngine:
    """The cached-engine entry point eval/ uses (see module docstring)."""
    W = np.asarray(W)
    key = (id(W), id(vocab), table_dtype, restrict)
    with _CACHE_LOCK:
        hit = _ENGINE_CACHE.get(key)
        if hit is not None:
            ref, eng = hit
            if ref() is W:
                _ENGINE_CACHE.move_to_end(key)
                return eng
            del _ENGINE_CACHE[key]
    eng = QueryEngine(W, vocab, table_dtype=table_dtype, restrict=restrict)
    with _CACHE_LOCK:
        try:
            _ENGINE_CACHE[key] = (weakref.ref(W), eng)
        except TypeError:
            # a non-weakref-able array subclass: serve it uncached
            return eng
        while len(_ENGINE_CACHE) > _CACHE_CAP:
            _ENGINE_CACHE.popitem(last=False)
    return eng


def clear_engine_cache() -> None:
    """Drop every cached engine (tests; also frees the device tables)."""
    with _CACHE_LOCK:
        _ENGINE_CACHE.clear()
