"""Serve-side observability primitives: latency/QPS stats + the LRU cache.

`ServeStats` is the serving counterpart of obs/phases.PhaseRecorder: a
thread-safe accumulator the server feeds per request and per coalesced
batch, snapshotted into MetricsHub records (one flat dict -> Prometheus
gauges `w2v_serve_*` via obs/export) and the `/stats` endpoint. Percentiles
come from a bounded sample ring (most recent LAT_SAMPLES requests), QPS
from a sliding window of completion times — "sustained" throughput, not
lifetime average, so a burst followed by idle doesn't flatter the number.

`LRUCache` is the hot-query result cache: (op, words, k) -> response dict.
A plain OrderedDict under a lock — hit/miss counters live here so the
hit-rate gauge can't drift from the cache that produced it.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

#: latency sample ring size (percentiles over the most recent N requests)
LAT_SAMPLES = 8192
#: sliding QPS window seconds
QPS_WINDOW_S = 30.0

#: serve-latency histogram bucket bounds, seconds (le-style; +Inf implicit).
#: Cumulative bucket counts are the AGGREGATABLE latency form: per-replica
#: p99 gauges cannot be merged, but bucket counts sum across a fleet —
#: exactly what obs/fleet.py's replica aggregation needs.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5,
)


def percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile over an unsorted sample list (0 <= q <= 1).
    The p99 the ISSUE banks needs finer resolution than profiling's
    lap_stats (p50/p90) exposes, hence a local helper sharing its
    convention (nearest rank, no interpolation)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(q * len(s) + 0.5) - 1))
    return s[idx]


class ServeStats:
    """Thread-safe serving counters + latency ring + sliding QPS window."""

    def __init__(self):
        self._lock = threading.Lock()
        self.t_start = time.monotonic()
        self.requests_total = 0
        self.errors_total = 0
        self.shed_429_total = 0
        self.batches_total = 0
        self.batch_items_total = 0
        self.batch_padded_total = 0
        self.inflight = 0
        self._lat: collections.deque = collections.deque(maxlen=LAT_SAMPLES)
        self._done_ts: collections.deque = collections.deque()
        #: per-op request counts ({"neighbors": n, ...})
        self.by_op: Dict[str, int] = {}
        # cumulative latency histogram (obs/signals.Histogram): monotonic
        # per-bucket totals + _sum/_count, rendered by the Prometheus sink
        # as w2v_serve_latency_seconds_{bucket,sum,count}
        from ..obs.signals import Histogram

        self._hist = Histogram(buckets=LATENCY_BUCKETS)

    # ------------------------------------------------------------ feeding
    def observe_request(self, op: str, dur_s: float, error: bool = False):
        now = time.monotonic()
        with self._lock:
            self.requests_total += 1
            self.by_op[op] = self.by_op.get(op, 0) + 1
            if error:
                self.errors_total += 1
            else:
                self._lat.append(dur_s)
                self._hist.observe(dur_s)
            self._done_ts.append(now)
            cutoff = now - QPS_WINDOW_S
            while self._done_ts and self._done_ts[0] < cutoff:
                self._done_ts.popleft()

    def observe_shed(self):
        with self._lock:
            self.shed_429_total += 1

    def observe_batch(self, items: int, padded: int):
        with self._lock:
            self.batches_total += 1
            self.batch_items_total += items
            self.batch_padded_total += max(items, padded)

    def adjust_inflight(self, delta: int):
        with self._lock:
            self.inflight += delta

    # --------------------------------------------------------- reporting
    def snapshot(self, cache: Optional["LRUCache"] = None) -> Dict:
        """One flat record: every numeric key becomes a `w2v_serve_*`
        Prometheus gauge through the hub (obs/export gauge naming)."""
        now = time.monotonic()
        with self._lock:
            lat = list(self._lat)
            cutoff = now - QPS_WINDOW_S
            window = [t for t in self._done_ts if t >= cutoff]
            span = min(QPS_WINDOW_S, max(1e-9, now - self.t_start))
            rec: Dict = {
                "serve_requests_total": self.requests_total,
                "serve_errors_total": self.errors_total,
                "serve_shed_429_total": self.shed_429_total,
                "serve_inflight": self.inflight,
                "serve_batches_total": self.batches_total,
                "serve_batch_fill_mean": (
                    self.batch_items_total / self.batches_total
                    if self.batches_total else 0.0
                ),
                "serve_batch_pad_efficiency": (
                    self.batch_items_total / self.batch_padded_total
                    if self.batch_padded_total else 0.0
                ),
                "serve_qps": len(window) / span,
                "serve_p50_ms": 1e3 * percentile(lat, 0.50),
                "serve_p90_ms": 1e3 * percentile(lat, 0.90),
                "serve_p99_ms": 1e3 * percentile(lat, 0.99),
                "serve_uptime_s": now - self.t_start,
                # the aggregatable latency form (see LATENCY_BUCKETS):
                # rendered as a real cumulative Prometheus histogram
                "serve_latency_seconds_hist": self._hist.to_record(),
            }
            for op, n in self.by_op.items():
                rec[f"serve_requests_{op}"] = n
        if cache is not None:
            rec.update(cache.stats())
        return rec


class LRUCache:
    """Bounded (op, words, k) -> response cache with hit/miss counters."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(0, int(capacity))
        self._lock = threading.Lock()
        self._d: "collections.OrderedDict" = collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Tuple) -> Optional[Dict]:
        if self.capacity == 0:
            return None
        with self._lock:
            hit = self._d.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._d.move_to_end(key)
            self.hits += 1
            return hit

    def put(self, key: Tuple, value: Dict) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def stats(self) -> Dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "serve_cache_size": len(self._d),
                "serve_cache_hits": self.hits,
                "serve_cache_misses": self.misses,
                "serve_cache_hit_rate": self.hits / total if total else 0.0,
            }
