"""Async embedding server: coalesced batches, LRU cache, shed, drain, chaos.

Stdlib-asyncio HTTP/1.1 + JSON (the container bakes no web framework; the
protocol surface is 5 routes and hand-parsing it keeps the dependency set
at zero):

    GET  /healthz                      liveness + table shape
    GET  /stats                        ServeStats snapshot as JSON
    GET  /metrics                      Prometheus text exposition
    GET  /v1/neighbors?word=w&k=10     curl-friendly single queries
         /v1/analogy?a=&b=&c=&k=5
         /v1/similarity?w1=&w2=
    POST /v1/query                     {"op": ...} or {"queries": [...]}

Request lifecycle — the tentpole mechanics:

  COALESCING  Query items land on one asyncio queue. The batcher takes the
  first item, keeps collecting for `coalesce_ms` (or until `max_batch`),
  then runs ONE padded device batch through the shared QueryEngine kernel
  in a worker thread (neighbors and analogies pack into the same [B, 3]
  ids+weights batch; similarities ride along as a pair-dot). The window
  trades p50 (queries wait for the window) against throughput (bigger
  matmuls, fewer dispatches) — PERF.md banks the tradeoff.

  CACHE  (op, words, k) hits return immediately and never enter the queue.

  SHEDDING  More than `max_pending` queued+running queries -> 429 with
  Retry-After, counted in `serve_shed_429_total`. A bounded queue keeps
  tail latency honest under overload instead of growing it unboundedly.

  DRAIN  SIGTERM (or `begin_drain()`) stops accepting connections, lets
  every accepted request finish, flushes sinks, exports the trace, dumps
  flight.json, exits 0. Past `drain_deadline_s` (or on a second signal) it
  exits EXIT_PREEMPTED=75 — the same requeue contract training uses
  (resilience/shutdown). SIGUSR1 dumps flight_usr1.json without stopping
  (resilience/shutdown.install_usr1_dump, shared with the trainers).

  CHAOS  `--faults` reuses resilience/faults.FaultPlan with the serve kinds
  {stall, hang, sigterm, oom}: stall/hang sleep in the batch executor (a
  slow device — the event loop, healthz, and shedding stay live), sigterm
  kills mid-request (the drain drill), and oom raises an XLA
  RESOURCE_EXHAUSTED-shaped error the server absorbs as 503s for that
  batch while staying up.

Observability: every request and batch is an 'X' span on the flight
recorder's TraceRing (`--trace DIR` exports a schema-valid Chrome-trace
doc; crash/drain paths dump flight.json), and ServeStats snapshots flow
through obs/export.MetricsHub to Prometheus gauges `w2v_serve_*`.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import signal
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.export import EVENT_COUNTERS, MetricsHub, PrometheusTextfile
from ..obs.flight import FlightRecorder
from ..obs.trace import chrome_trace_doc, write_trace
from ..resilience import faults as faults_mod
from ..resilience.shutdown import EXIT_PREEMPTED
from .metrics import LRUCache, ServeStats
from .query import QueryEngine, _next_pow2, _pair_cosines

#: fault kinds a serve FaultPlan may carry (resilience/faults.py); training
#: kinds that poison params or SIGKILL (nan, peer_dead) are rejected loudly
#: at startup instead of misfiring mid-request
SERVE_FAULT_KINDS = ("stall", "hang", "sigterm", "oom")

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 429: "Too Many Requests",
            500: "Internal Server Error", 503: "Service Unavailable",
            504: "Gateway Timeout"}


class _MemoryProm(PrometheusTextfile):
    """A PrometheusTextfile that never touches disk: the `/metrics`
    endpoint's backing store when no --metrics-dir/--prom-textfile is
    configured (render() is shared with the file-backed sink)."""

    def __init__(self):
        self.path = ""
        self._gauges = {}
        self._counters = {name: 0.0 for name in EVENT_COUNTERS.values()}
        self._hists = {}

    def _write(self) -> None:  # no file behind it
        pass


@dataclasses.dataclass
class ServeConfig:
    host: str = "127.0.0.1"
    port: int = 0                    # 0 = ephemeral (the bound port is
    coalesce_ms: float = 2.0         # printed in the ready line)
    max_batch: int = 256
    max_pending: int = 1024
    cache_size: int = 4096
    max_k: int = 100
    default_k: int = 10
    request_timeout_s: float = 30.0
    drain_deadline_s: float = 10.0
    stats_every_s: float = 5.0
    #: derived-signal window seconds (obs/signals.py serve mode): each
    #: closed wall-clock window emits one serve_qps/serve_p99_ms/cache_hit
    #: signal row into signals_p<pid>.jsonl under metrics_dir — the
    #: standalone fleet aggregator (python -m word2vec_tpu.obs.fleet)
    #: merges replica rows by epoch-derived window id. 0 disables.
    signal_window_s: float = 10.0
    metrics_dir: Optional[str] = None
    prom_textfile: Optional[str] = None
    trace_dir: Optional[str] = None
    faults: Optional[object] = None  # resilience.faults.FaultPlan
    install_signals: bool = False
    #: records fed into the metrics hub at construction (before the first
    #: request): the serve CLI's startup quality probe publishes its
    #: w2v_quality_* gauges + probe counter here, so a table exported
    #: mid-training serves its measured quality on /metrics from request 0
    startup_records: Optional[list] = None


class _Shed(Exception):
    """Control-flow for refused queries: (status, error message)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


@dataclasses.dataclass
class _WorkItem:
    op: str                        # neighbors | analogy | similarity
    ids: np.ndarray                # [3] (topk) or [2] (similarity)
    weights: Optional[np.ndarray]  # [3] for topk, None for similarity
    k: int
    future: "asyncio.Future"
    enq: float                     # perf_counter at enqueue
    cache_key: Tuple = ()          # populated by _admit


class _FaultState:
    """The FaultPlan.on_step shim: serve batches stand in for optimizer
    steps. params stays None — the allowed serve kinds never touch it."""

    def __init__(self, step: int):
        self.step = step
        self.params = None


class EmbeddingServer:
    """One engine, one coalescing batcher, one asyncio listener."""

    def __init__(self, engine: QueryEngine, config: Optional[ServeConfig] = None):
        self.engine = engine
        self.cfg = config or ServeConfig()
        if self.cfg.max_batch > engine.MAX_BATCH_BUCKET:
            raise ValueError(
                f"max_batch {self.cfg.max_batch} exceeds the engine's "
                f"batch bucket cap {engine.MAX_BATCH_BUCKET}"
            )
        plan = self.cfg.faults
        if plan is not None:
            bad = [f.kind for f in plan.faults
                   if f.kind not in SERVE_FAULT_KINDS]
            if bad:
                raise ValueError(
                    f"fault kind(s) {bad} not servable (serve supports: "
                    f"{', '.join(SERVE_FAULT_KINDS)})"
                )
        self.stats = ServeStats()
        self.cache = LRUCache(self.cfg.cache_size)
        self.flight = FlightRecorder()
        self.hub = MetricsHub()
        if self.cfg.prom_textfile:
            self.prom = self.hub.add(PrometheusTextfile(self.cfg.prom_textfile))
        elif self.cfg.metrics_dir:
            os.makedirs(self.cfg.metrics_dir, exist_ok=True)
            self.prom = self.hub.add(PrometheusTextfile(
                os.path.join(self.cfg.metrics_dir, "serve.prom")))
        else:
            self.prom = self.hub.add(_MemoryProm())
        if self.cfg.metrics_dir:
            from ..utils.logging import jsonl_logger

            self.hub.add(jsonl_logger(
                os.path.join(self.cfg.metrics_dir, "serve_metrics.jsonl")))
        # derived-signal plane, serve mode (obs/signals.py): windowed
        # serve_qps / serve_p99_ms / cache_hit rows for the replica fleet
        # aggregator, keyed on epoch seconds (replicas share no step
        # counter; NTP-grade alignment is enough for aggregation)
        self.signals = None
        if self.cfg.signal_window_s:
            from ..obs.signals import SignalEngine

            self.signals = SignalEngine(
                window_s=self.cfg.signal_window_s,
                metrics_dir=self.cfg.metrics_dir,
                host=os.getpid(),
                flight=self.flight,
                log_fn=self.hub,
            )
        for rec in self.cfg.startup_records or []:
            self.hub(dict(rec))
        self.port: Optional[int] = None
        self.exit_reason: Optional[str] = None
        self._draining = False
        self._busy = 0          # requests read but not yet fully responded
        self._queued = 0        # query items enqueued but unresolved
        self._batch_no = 0
        self._conns: set = set()
        self._usr1_uninstall = lambda: None

    # ----------------------------------------------------------- lifecycle
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._loop = loop
        self._done: "asyncio.Future" = loop.create_future()
        self._queue: "asyncio.Queue" = asyncio.Queue()
        # event faults (oom) are consulted through the module-level active
        # plan, same as training's checkpoint injection point
        self._prev_plan = (faults_mod.activate(self.cfg.faults)
                           if self.cfg.faults is not None else None)
        self._server = await asyncio.start_server(
            self._client, self.cfg.host, self.cfg.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._batcher_task = loop.create_task(self._batcher_main())
        self._stats_task = loop.create_task(self._stats_loop())
        if self.cfg.install_signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    loop.add_signal_handler(sig, self.begin_drain)
                except (NotImplementedError, RuntimeError, ValueError):
                    pass
            if self.cfg.metrics_dir:
                from ..resilience.shutdown import install_usr1_dump

                self._usr1_uninstall = install_usr1_dump(
                    self.cfg.metrics_dir, flight=self.flight)

    async def run(self) -> int:
        """Serve until drained/failed; returns the process exit code
        (0 = clean drain, EXIT_PREEMPTED=75 = forced, 1 = crash)."""
        if self.port is None:
            await self.start()
        code = await self._done
        await self._shutdown(code)
        return code

    def begin_drain(self) -> None:
        """First call: stop accepting, finish in-flight, then exit 0.
        Second call (the operator's second SIGTERM): stop waiting, exit
        EXIT_PREEMPTED now — mirroring ShutdownHandler's escalation."""
        if self._draining:
            self._finish(EXIT_PREEMPTED, "forced")
            return
        self._draining = True
        self._server.close()
        self._loop.create_task(self._drain_task())

    async def _drain_task(self) -> None:
        deadline = self._loop.time() + self.cfg.drain_deadline_s
        while self._loop.time() < deadline:
            if self._busy == 0 and self._queued == 0:
                self._finish(0, "drained")
                return
            await asyncio.sleep(0.01)
        self._finish(EXIT_PREEMPTED, "drain_deadline")

    def _finish(self, code: int, reason: str) -> None:
        if not self._done.done():
            self.exit_reason = reason
            self._done.set_result(code)

    async def _shutdown(self, code: int) -> None:
        await self._queue.put(None)  # batcher stop sentinel
        self._stats_task.cancel()
        for t in (self._batcher_task, self._stats_task):
            try:
                await asyncio.wait_for(t, 5.0)
            except (asyncio.CancelledError, asyncio.TimeoutError):
                pass
        self._usr1_uninstall()
        if self.cfg.faults is not None:
            faults_mod.activate(self._prev_plan)
        for w in list(self._conns):
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._publish_stats(final=True)
        self.hub.close()
        if self.cfg.trace_dir:
            doc = chrome_trace_doc(
                self.flight.ring.events(), process_name="serve",
                metadata={"serve": True, "exit_reason": self.exit_reason},
            )
            write_trace(os.path.join(self.cfg.trace_dir, "trace.json"), doc)
        if self.cfg.metrics_dir:
            # ALWAYS leave a flight: the chaos drill's contract is "drain
            # or 75, with a flight.json present" either way
            reason = {0: "drained"}.get(code, "preempted")
            self.flight.dump(
                self.cfg.metrics_dir, reason,
                extra={"exit_code": code, "exit_reason": self.exit_reason,
                       "stats": self.stats.snapshot(self.cache)},
            )

    # ----------------------------------------------------------- batching
    async def _batcher_main(self) -> None:
        try:
            await self._batcher()
        except Exception as e:  # noqa: BLE001 — batcher death = server down
            if self.cfg.metrics_dir:
                self.flight.dump(self.cfg.metrics_dir, "serve_crash",
                                 extra={"error": repr(e)})
            self._finish(1, f"batcher_crash: {e!r}")

    async def _batcher(self) -> None:
        loop = self._loop
        window = max(0.0, self.cfg.coalesce_ms) / 1e3
        while True:
            item = await self._queue.get()
            if item is None:
                return
            batch = [item]
            if window > 0 and self.cfg.max_batch > 1:
                deadline = loop.time() + window
                while len(batch) < self.cfg.max_batch:
                    left = deadline - loop.time()
                    if left <= 0:
                        break
                    try:
                        nxt = await asyncio.wait_for(self._queue.get(), left)
                    except asyncio.TimeoutError:
                        break
                    if nxt is None:
                        await self._queue.put(None)
                        break
                    batch.append(nxt)
            else:
                while len(batch) < self.cfg.max_batch:
                    try:
                        nxt = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if nxt is None:
                        await self._queue.put(None)
                        break
                    batch.append(nxt)
            self._batch_no += 1
            step = self._batch_no
            t0 = time.perf_counter()
            try:
                results = await loop.run_in_executor(
                    None, self._run_batch, step, batch)
            except Exception as e:  # noqa: BLE001 — fail THIS batch, serve on
                oom = "RESOURCE_EXHAUSTED" in str(e)
                msg = ("allocation failure (device out of memory): " if oom
                       else "batch execution failed: ") + str(e)
                self.flight.log_record(
                    {"event": "serve_batch_error", "step": step, "error": msg})
                results = {id(it): _Shed(503, msg) for it in batch}
            dur = time.perf_counter() - t0
            topk_n = sum(1 for it in batch if it.weights is not None)
            self.stats.observe_batch(len(batch), _next_pow2(max(1, topk_n)))
            self.flight.note_step(step, t0, dur, kind="step",
                                  fill=len(batch))
            for it in batch:
                res = results.get(id(it))
                if it.future.done():    # request timed out / cancelled
                    continue
                if isinstance(res, Exception):
                    it.future.set_exception(res)
                else:
                    it.future.set_result(res)

    def _run_batch(self, step: int, batch: List[_WorkItem]) -> Dict[int, Dict]:
        """Executor-thread body: fault hooks + the device batch. A raised
        exception fails the WHOLE batch (the caller converts to 503s)."""
        plan = self.cfg.faults
        if plan is not None:
            plan.on_step(_FaultState(step))   # stall / hang / sigterm
        faults_mod.raise_if_active("oom", where=f"serve_batch {step}")
        out: Dict[int, Dict] = {}
        topk = [it for it in batch if it.weights is not None]
        sims = [it for it in batch if it.weights is None]
        if topk:
            ids = np.stack([it.ids for it in topk])
            w = np.stack([it.weights for it in topk])
            kmax = max(it.k for it in topk)
            for it, (idx, sc) in zip(topk,
                                     self.engine.batch_topk(ids, w, kmax)):
                pairs = self.engine._decode(idx[: it.k], sc[: it.k])
                out[id(it)] = {"neighbors": [[wd, s] for wd, s in pairs]}
        if sims:
            ij = np.stack([it.ids for it in sims])
            cos = _pair_cosines(self.engine.table, ij[:, 0], ij[:, 1])
            for it, c in zip(sims, np.asarray(cos)):
                out[id(it)] = {"similarity": float(c)}
        return out

    # ------------------------------------------------------------ queries
    async def handle_query(self, q: Dict) -> Tuple[int, Dict]:
        """One query dict -> (status, payload).

        Raises nothing: every failure mode is a status + error payload
        (OOV 404, malformed 400, shed 429, draining/failed-batch 503,
        timeout 504)."""
        t0 = time.perf_counter()
        op = q.get("op")
        status, payload = 200, {}
        try:
            key, item = self._admit(q)
            if item is None:       # cache hit
                payload = dict(key)
            else:
                self._queued += 1
                self.stats.adjust_inflight(1)
                try:
                    payload = await asyncio.wait_for(
                        item.future, self.cfg.request_timeout_s)
                except asyncio.TimeoutError:
                    raise _Shed(504, "query timed out in the batch queue")
                finally:
                    self._queued -= 1
                    self.stats.adjust_inflight(-1)
                self.cache.put(item.cache_key, dict(payload))
                payload = dict(payload)
            payload["op"] = op
        except KeyError as e:
            status, payload = 404, {"op": op, "error": str(e).strip('"')}
        except _Shed as e:
            status, payload = e.status, {"op": op, "error": str(e)}
        except ValueError as e:
            status, payload = 400, {"op": op, "error": str(e)}
        dur = time.perf_counter() - t0
        self.stats.observe_request(str(op), dur, error=status != 200)
        self.flight.ring.complete(
            "request", t0, dur, args={"op": str(op), "status": status})
        return status, payload

    def _admit(self, q: Dict):
        """Parse + cache-check + shed-check; returns (cached_payload, None)
        on a hit or (None-keyed, _WorkItem) after enqueueing."""
        op = q.get("op")
        k = q.get("k", self.cfg.default_k)
        if not isinstance(k, int) or k < 1 or k > self.cfg.max_k:
            raise ValueError(
                f"k must be an int in [1, {self.cfg.max_k}], got {k!r}")
        if op == "neighbors":
            words = (q.get("word"),)
            if not isinstance(words[0], str):
                raise ValueError("neighbors needs a 'word' string")
            wid = self.engine.ids_of(words)
            ids = np.array([wid[0]] * 3, np.int32)
            weights = np.array([1.0, 0.0, 0.0], np.float32)
        elif op == "analogy":
            words = tuple(q.get(x) for x in ("a", "b", "c"))
            if not all(isinstance(w, str) for w in words):
                raise ValueError("analogy needs 'a', 'b', 'c' strings")
            ids = self.engine.ids_of(words).astype(np.int32)
            weights = np.array([-1.0, 1.0, 1.0], np.float32)
        elif op == "similarity":
            words = tuple(q.get(x) for x in ("w1", "w2"))
            if not all(isinstance(w, str) for w in words):
                raise ValueError("similarity needs 'w1', 'w2' strings")
            ids = self.engine.ids_of(words).astype(np.int32)
            weights, k = None, 1
        else:
            raise ValueError(
                f"op must be neighbors|analogy|similarity, got {op!r}")
        cache_key = (op, words, k)
        hit = self.cache.get(cache_key)
        if hit is not None:
            return hit, None
        if self._draining:
            raise _Shed(503, "draining: server is shutting down")
        if self._queued >= self.cfg.max_pending:
            self.stats.observe_shed()
            raise _Shed(429, f"overloaded: {self._queued} queries pending")
        item = _WorkItem(op=op, ids=ids, weights=weights, k=k,
                         future=self._loop.create_future(),
                         enq=time.perf_counter(), cache_key=cache_key)
        self._queue.put_nowait(item)
        return None, item

    # --------------------------------------------------------------- http
    async def _client(self, reader: "asyncio.StreamReader",
                      writer: "asyncio.StreamWriter") -> None:
        self._conns.add(writer)
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                self._busy += 1
                try:
                    method, path, headers, body = req
                    try:
                        status, payload, ctype = await self._route(
                            method, path, body)
                    except Exception as e:  # noqa: BLE001 — one bad request
                        status, ctype = 500, "application/json"
                        payload = {"error": f"internal error: {e!r}"}
                        self.flight.log_record(
                            {"event": "serve_500", "error": repr(e)})
                    keep = headers.get("connection", "").lower() != "close"
                    await self._write_response(
                        writer, status, payload, ctype, keep)
                finally:
                    self._busy -= 1
                if not keep:
                    break
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.LimitOverrunError):
            pass
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _read_request(reader):
        line = await reader.readline()
        if not line or not line.strip():
            return None
        parts = line.decode("latin-1").split()
        if len(parts) < 3:
            return None
        method, target = parts[0], parts[1]
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()
        body = b""
        try:
            n = int(headers.get("content-length", "0") or "0")
        except ValueError:
            n = 0
        if n > 0:
            body = await reader.readexactly(n)
        return method, target, headers, body

    @staticmethod
    async def _write_response(writer, status: int, payload, ctype: str,
                              keep: bool) -> None:
        if isinstance(payload, (dict, list)):
            body = (json.dumps(payload) + "\n").encode()
        elif isinstance(payload, str):
            body = payload.encode()
        else:
            body = payload
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Status')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {'keep-alive' if keep else 'close'}\r\n"
            + ("Retry-After: 1\r\n" if status == 429 else "")
            + "\r\n"
        )
        writer.write(head.encode("latin-1") + body)
        await writer.drain()

    async def _route(self, method: str, target: str,
                     body: bytes) -> Tuple[int, object, str]:
        parsed = urllib.parse.urlsplit(target)
        path = parsed.path
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True, "vocab": self.engine.V,
                         "dim": self.engine.d,
                         "table_dtype": self.engine.table_dtype,
                         "draining": self._draining}, "application/json"
        if method == "GET" and path == "/stats":
            return 200, self.stats.snapshot(self.cache), "application/json"
        if method == "GET" and path == "/metrics":
            self._publish_stats()
            return 200, self.prom.render(), "text/plain; version=0.0.4"
        if method == "GET" and path.startswith("/v1/"):
            qs = {k: v[-1] for k, v in
                  urllib.parse.parse_qs(parsed.query).items()}
            op = path[len("/v1/"):]
            q: Dict = {"op": op, **qs}
            if "k" in q:
                try:
                    q["k"] = int(q["k"])
                except ValueError:
                    return 400, {"error": f"k must be an int, got {q['k']!r}"
                                 }, "application/json"
            status, payload = await self.handle_query(q)
            return status, payload, "application/json"
        if method == "POST" and path == "/v1/query":
            try:
                doc = json.loads(body.decode("utf-8") or "{}")
            except (ValueError, UnicodeDecodeError) as e:
                return 400, {"error": f"bad JSON body: {e}"}, "application/json"
            if isinstance(doc, dict) and "queries" in doc:
                qs = doc["queries"]
                if not isinstance(qs, list) or not qs:
                    return 400, {"error": "'queries' must be a non-empty list"
                                 }, "application/json"
                results = await asyncio.gather(
                    *(self.handle_query(q) if isinstance(q, dict)
                      else _not_a_dict() for q in qs))
                return 200, {"results": [
                    {**payload, "status": status}
                    for status, payload in results
                ]}, "application/json"
            if isinstance(doc, dict):
                status, payload = await self.handle_query(doc)
                return status, payload, "application/json"
            return 400, {"error": "body must be a JSON object"
                         }, "application/json"
        if path in ("/healthz", "/stats", "/metrics", "/v1/query"):
            return 405, {"error": f"{method} not allowed on {path}"
                         }, "application/json"
        return 404, {"error": f"no route {method} {path}"}, "application/json"

    # ------------------------------------------------------------- metrics
    def _publish_stats(self, final: bool = False) -> None:
        rec = self.stats.snapshot(self.cache)
        if final:
            rec["kind"] = "serve_final"
        try:
            self.hub(rec)
        except Exception:  # noqa: BLE001 — a sink must not kill serving
            pass
        if self.signals is not None:
            try:
                self.signals.observe_serve(rec)
                if final:
                    self.signals.finish()
                    self.signals.close()
            except Exception:  # noqa: BLE001 — signals must not kill serving
                pass

    async def _stats_loop(self) -> None:
        every = max(0.05, self.cfg.stats_every_s)
        while True:
            await asyncio.sleep(every)
            self._publish_stats()


async def _not_a_dict() -> Tuple[int, Dict]:
    return 400, {"error": "each query must be a JSON object"}


async def serve_forever(engine: QueryEngine, config: ServeConfig,
                        ready_cb=None) -> int:
    """Build, start, announce (ready_cb(server) after bind), run to exit."""
    server = EmbeddingServer(engine, config)
    await server.start()
    if ready_cb is not None:
        ready_cb(server)
    return await server.run()
