"""Serve CLI — load an exported table, stand up the query server.

    python -m word2vec_tpu.serve --vectors vec.txt
    python -m word2vec_tpu.serve --vectors vec.bin --format binary
    python -m word2vec_tpu.serve --vectors vec.i8 --format int8 \\
        --table-dtype bfloat16 --port 8080 --metrics-dir mdir --trace tdir

When ready it prints ONE JSON line to stdout —
`{"event": "serving", "host": ..., "port": ..., "vocab": V, "dim": d}` —
then serves until SIGTERM/SIGINT (graceful drain, exit 0; second signal or
a blown drain deadline exits 75 for scheduler requeue, matching training's
resilience contract). Exit 1 = startup/crash failure.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import numpy as np

from ..data.vocab import Vocab
from ..io.embeddings import (
    load_embeddings_binary,
    load_embeddings_int8,
    load_embeddings_text,
)
from .query import QueryEngine
from .server import ServeConfig, serve_forever


def load_table(path: str, fmt: str = "text", layout: str = "reference"):
    """(words, f32 matrix) from any export format: text / binary / the
    int8 symmetric-quantized container (dequantized here — the cross-dtype
    path: int8 file -> f32/bf16 resident engine table)."""
    if fmt == "text":
        return load_embeddings_text(path)
    if fmt == "binary":
        return load_embeddings_binary(path, layout=layout)
    if fmt == "int8":
        return load_embeddings_int8(path)
    raise ValueError(f"format must be text|binary|int8, got {fmt!r}")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="word2vec_tpu.serve")
    ap.add_argument("--vectors", required=True, metavar="FILE",
                    help="exported embedding table (io/embeddings formats)")
    ap.add_argument("--format", choices=["text", "binary", "int8"],
                    default="text")
    ap.add_argument("--binary-layout", choices=["reference", "google"],
                    default="reference")
    ap.add_argument("--table-dtype", choices=["float32", "bfloat16"],
                    default="float32",
                    help="resident device table dtype (int8 files "
                    "dequantize into this)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="0 = ephemeral; the bound port is in the ready line")
    ap.add_argument("--coalesce-ms", type=float, default=2.0,
                    help="request-coalescing window: concurrent queries "
                    "arriving within it share one padded device batch "
                    "(0 = batch only what is already queued)")
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--max-pending", type=int, default=1024,
                    help="bounded queue: queries past this shed with 429")
    ap.add_argument("--cache-size", type=int, default=4096,
                    help="LRU result cache entries (0 disables)")
    ap.add_argument("--max-k", type=int, default=100)
    ap.add_argument("--request-timeout", type=float, default=30.0,
                    metavar="SECS")
    ap.add_argument("--drain-deadline", type=float, default=10.0,
                    metavar="SECS",
                    help="SIGTERM drain budget; past it exit 75 (requeue)")
    ap.add_argument("--stats-every", type=float, default=5.0, metavar="SECS")
    ap.add_argument("--metrics-dir", metavar="DIR",
                    help="serve.prom + serve_metrics.jsonl + flight.json")
    ap.add_argument("--prom-textfile", metavar="FILE")
    ap.add_argument("--trace", metavar="DIR", dest="trace_dir",
                    help="export the request/batch span timeline as a "
                    "Chrome-trace doc on shutdown (obs/trace.py)")
    ap.add_argument("--faults", metavar="SPEC", default="",
                    help="chaos plan (resilience/faults.py); serve kinds: "
                    "stall/hang/sigterm/oom, @k = batch number")
    ap.add_argument("--probe-pairs", metavar="FILE",
                    help="score the loaded table against word-pair golds "
                    "at startup (obs/quality.score_table) and publish the "
                    "w2v_quality_* gauges on /metrics — a table exported "
                    "mid-training serves its measured quality alongside "
                    "the serve gauges")
    ap.add_argument("--probe-analogies", metavar="FILE",
                    help="startup analogy-question probe "
                    "(questions-words.txt format; see --probe-pairs)")
    ap.add_argument("--quiet", action="store_true")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        words, W = load_table(args.vectors, args.format, args.binary_layout)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    vocab = Vocab(words, np.ones(len(words), dtype=np.int64))
    engine = QueryEngine(W, vocab, table_dtype=args.table_dtype)

    plan = None
    if args.faults:
        from ..resilience.faults import FaultPlan

        try:
            plan = FaultPlan.parse(args.faults)
        except ValueError as e:
            print(f"error: bad --faults spec: {e}", file=sys.stderr)
            return 1

    startup_records = None
    if args.probe_pairs or args.probe_analogies:
        # one-shot quality probe of the loaded table: the same scoring core
        # the in-training probe uses (obs/quality.score_table), published
        # through the server's hub so /metrics carries w2v_quality_* gauges
        # plus the present-from-zero probe counter
        from ..obs.quality import ProbeSet, score_table

        try:
            pset = ProbeSet.from_files(
                vocab, args.probe_pairs, args.probe_analogies
            )
        except (OSError, ValueError) as e:
            print(f"error: bad probe file: {e}", file=sys.stderr)
            return 1
        rec, _ = score_table(W, vocab, pset)
        startup_records = [rec, {"event": "quality_probe", "step": 0}]
        if not args.quiet:
            shown = {k: v for k, v in rec.items()
                     if k.startswith("quality_")}
            print(f"startup quality probe: {json.dumps(shown)}",
                  file=sys.stderr)

    cfg = ServeConfig(
        host=args.host, port=args.port, coalesce_ms=args.coalesce_ms,
        max_batch=args.max_batch, max_pending=args.max_pending,
        cache_size=args.cache_size, max_k=args.max_k,
        request_timeout_s=args.request_timeout,
        drain_deadline_s=args.drain_deadline,
        stats_every_s=args.stats_every, metrics_dir=args.metrics_dir,
        prom_textfile=args.prom_textfile, trace_dir=args.trace_dir,
        faults=plan, install_signals=True,
        startup_records=startup_records,
    )

    def ready(server) -> None:
        print(json.dumps({
            "event": "serving", "host": cfg.host, "port": server.port,
            "vocab": engine.V, "dim": engine.d,
            "table_dtype": engine.table_dtype,
        }), flush=True)
        if not args.quiet:
            print(f"serving {engine.V} x {engine.d} embeddings on "
                  f"http://{cfg.host}:{server.port} "
                  f"(coalesce {cfg.coalesce_ms} ms, cache "
                  f"{cfg.cache_size}, max-pending {cfg.max_pending})",
                  file=sys.stderr, flush=True)

    try:
        rc = asyncio.run(serve_forever(engine, cfg, ready_cb=ready))
    except ValueError as e:       # bad config (e.g. unservable fault kind)
        print(f"error: {e}", file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001 — crash path: leave evidence
        print(f"serve crashed: {e!r}", file=sys.stderr)
        return 1
    if rc == 0 and not args.quiet:
        print("drained clean (exit 0)", file=sys.stderr)
    elif rc != 0:
        print(f"serve exiting {rc} for requeue", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
