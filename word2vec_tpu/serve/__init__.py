"""Embedding serving subsystem: the "millions of users" leg (ROADMAP item 1).

Training exports tables; this package answers queries against them:

  query.py   — QueryEngine: a row-normalized table resident on device
               (f32/bf16, int8 files dequantize on load) and ONE jit'd
               batched top-k kernel behind every similarity / neighbor /
               analogy query. eval/ is rewired onto the same engine, so
               batch evaluation and online serving share one code path.
  server.py  — asyncio HTTP/JSON server: request coalescing into padded
               device batches, an LRU result cache, bounded-queue load
               shedding (429), graceful SIGTERM drain (exit 0, or
               EXIT_PREEMPTED=75 past the drain deadline), serve metrics
               through obs/export.MetricsHub, request/batch spans on the
               flight recorder's TraceRing, and FaultPlan chaos hooks.
  __main__   — `python -m word2vec_tpu.serve --vectors vec.txt ...`
"""

from .query import QueryEngine, get_engine, unit_norm  # noqa: F401
