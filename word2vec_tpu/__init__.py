"""word2vec_tpu — a TPU-native word2vec training framework.

Feature-parity re-design of lache/word2vec (C++/Eigen/OpenMP) for TPU:
host does strings/trees/tables, the device runs one fused jit step
(gather -> einsum -> sigmoid -> scatter-add), and multi-chip scaling uses
jax.sharding meshes instead of OpenMP Hogwild.

Quick start:
    from word2vec_tpu import Word2VecConfig, Vocab, PackedCorpus, Trainer
    from word2vec_tpu.data.corpus import text8_corpus

    cfg = Word2VecConfig(model="sg", train_method="ns", negative=5, word_dim=100)
    sents = list(text8_corpus("text8"))
    vocab = Vocab.build(sents, min_count=cfg.min_count)
    corpus = PackedCorpus.pack(vocab.encode_corpus(sents), cfg.max_sentence_len)
    state, report = Trainer(cfg, vocab, corpus).train()
"""

from .config import TunePlan, Word2VecConfig
from .data.batcher import BatchIterator, PackedCorpus
from .obs import DivergenceError, MetricsHub, PhaseRecorder
from .data.huffman import HuffmanCoding, build_huffman
from .data.negative import AliasTable, build_alias_table
from .data.vocab import Vocab
from .models.params import export_matrix, init_params
from .ops.tables import DeviceTables
from .ops.train_step import (
    jit_chunk_runner,
    jit_train_step,
    make_chunk_runner,
    make_train_step,
)
from .train import Trainer, TrainReport, TrainState

__version__ = "0.1.0"

__all__ = [
    "Word2VecConfig",
    "TunePlan",
    "Vocab",
    "PackedCorpus",
    "BatchIterator",
    "HuffmanCoding",
    "build_huffman",
    "AliasTable",
    "build_alias_table",
    "DeviceTables",
    "init_params",
    "export_matrix",
    "make_train_step",
    "jit_train_step",
    "make_chunk_runner",
    "jit_chunk_runner",
    "Trainer",
    "TrainState",
    "TrainReport",
    "DivergenceError",
    "MetricsHub",
    "PhaseRecorder",
    "__version__",
]
