"""Embedding-table parameters and their reference role mapping.

The reference holds three matrices (Word2Vec.h:53): `W` (uniform-init), `C`
(zeros, allocated iff ns) and `synapses1` (zeros, allocated iff hs)
(init at Word2Vec.cpp:198-210). Their *roles* swap between models
(SURVEY §2 "matrix-role swap"):

  skip-gram:  input/projection = W,  ns-output = C,          hs-output = synapses1
  cbow:       input/context   = C,  ns-output = W,          hs-output = synapses1

This module names matrices by role, not letter:
  emb_in      [V, d]   — gathered to form the projection h
  emb_out_ns  [V, d]   — ns target rows (present iff negative > 0)
  emb_out_hs  [V-1, d] — Huffman internal-node rows (present iff hs)

Init faithfully follows the reference: the W-role matrix is
uniform(-0.5, 0.5)/dim (Word2Vec.cpp:203-204), the others zero — with one
deliberate divergence: for cbow+hs the reference never allocates its input
matrix C at all (the SURVEY §2 latent bug: Word2Vec.cpp:208-209 vs :300), and
a zero-init input with a zero-init hs output can never leave the origin; here
cbow+hs gives emb_in the uniform init so training is live.

Export selection (`export_matrix`) mirrors main.cpp:196-202: hs+cbow saves C
(= emb_in here); everything else saves W (= emb_in for sg, emb_out_ns for
cbow+ns).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..config import Word2VecConfig

Params = Dict[str, jnp.ndarray]


def init_params(config: Word2VecConfig, vocab_size: int, key: jax.Array) -> Params:
    d = config.word_dim
    dtype = jnp.dtype(config.dtype)
    uniform = (
        jax.random.uniform(key, (vocab_size, d), jnp.float32, -0.5, 0.5) / d
    ).astype(dtype)
    zeros = jnp.zeros((vocab_size, d), dtype)

    params: Params = {}
    if config.model == "sg":
        params["emb_in"] = uniform          # W, Word2Vec.cpp:330
        if config.use_ns:
            params["emb_out_ns"] = zeros    # C, Word2Vec.cpp:348
    else:  # cbow
        if config.use_ns:
            params["emb_in"] = zeros        # C, Word2Vec.cpp:300 (zeros per :209)
            params["emb_out_ns"] = uniform  # W, Word2Vec.cpp:310
        else:
            # cbow+hs bug fix (see module docstring): live init for the input.
            params["emb_in"] = uniform
    if config.use_hs:
        params["emb_out_hs"] = jnp.zeros((vocab_size - 1, d), dtype)  # synapses1, :207
    return params


def export_matrix(
    params: Params, config: Word2VecConfig, side: str = "auto"
) -> jnp.ndarray:
    """The matrix to save.

    side="auto" mirrors the reference CLI exactly (main.cpp:196-202):
    hs+cbow saves C (the context/input matrix), everything else saves W.
    For cbow+ns that means the OUTPUT matrix — a choice the r5 graded
    instrument showed to be systematically bad in the reference itself
    (its saved cbow+ns matrix ANTICORRELATES with fine-grained
    similarity, CBOW_GRADED_CALIB_r5.jsonl; ours recovers it, but users
    may still want the other side). side="input"/"output" overrides:
    "input" = the gather-side table (centers for sg, contexts for cbow —
    emb_in; gensim's `wv`), "output" = the ns prediction-side table
    (emb_out_ns; gensim's `syn1neg`). "output" requires ns: the hs
    output table holds V-1 Huffman INTERNAL NODES, not word rows, so
    exporting it as word vectors would be meaningless."""
    if side == "input":
        return params["emb_in"]
    if side == "output":
        if not config.use_ns:
            raise ValueError(
                "export side='output' requires negative sampling: the hs "
                "output table rows are Huffman internal nodes, not words"
            )
        return params["emb_out_ns"]
    if side != "auto":
        raise ValueError(
            f"export side must be auto, input or output, got {side!r}"
        )
    if config.model == "cbow" and config.use_hs:
        return params["emb_in"]  # C, main.cpp:198-199
    if config.model == "cbow" and config.use_ns:
        return params["emb_out_ns"]  # W, main.cpp:201
    return params["emb_in"]  # W for sg, main.cpp:201
