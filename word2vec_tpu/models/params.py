"""Embedding-table parameters and their reference role mapping.

The reference holds three matrices (Word2Vec.h:53): `W` (uniform-init), `C`
(zeros, allocated iff ns) and `synapses1` (zeros, allocated iff hs)
(init at Word2Vec.cpp:198-210). Their *roles* swap between models
(SURVEY §2 "matrix-role swap"):

  skip-gram:  input/projection = W,  ns-output = C,          hs-output = synapses1
  cbow:       input/context   = C,  ns-output = W,          hs-output = synapses1

This module names matrices by role, not letter:
  emb_in      [V, d]   — gathered to form the projection h
  emb_out_ns  [V, d]   — ns target rows (present iff negative > 0)
  emb_out_hs  [V-1, d] — Huffman internal-node rows (present iff hs)

Init faithfully follows the reference: the W-role matrix is
uniform(-0.5, 0.5)/dim (Word2Vec.cpp:203-204), the others zero — with one
deliberate divergence: for cbow+hs the reference never allocates its input
matrix C at all (the SURVEY §2 latent bug: Word2Vec.cpp:208-209 vs :300), and
a zero-init input with a zero-init hs output can never leave the origin; here
cbow+hs gives emb_in the uniform init so training is live.

Table layouts (config.table_layout): the two ns tables can be STORED either
as two separate [V, d] arrays ("split", the historical layout) or as one
[V, 2, d] slab under FUSED_KEY ("unified") whose planes are FUSED_SUBTABLES
in order. The unified layout lets every band step gather and scatter both
tables' rows in ONE indexed op each — the sorted table scatters are
row-machinery-bound (~21 ns/row regardless of width, PERF.md), so one
[N, 2, d] scatter costs about half of two [N, d] scatters. The layout is
part of the parameter identity end to end (init, checkpoint, mesh specs,
export); `params_layout`/`convert_params_layout` translate losslessly
between the two, and `logical_table` reads a public table from either.

Export selection (`export_matrix`) mirrors main.cpp:196-202: hs+cbow saves C
(= emb_in here); everything else saves W (= emb_in for sg, emb_out_ns for
cbow+ns). Under the unified layout the returned matrix is a PLANE of the
slab — a [V, d] slice (a zero-copy view for host arrays), never a full
[V, 2, d] host materialization.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..config import Word2VecConfig

Params = Dict[str, jnp.ndarray]

FUSED_KEY = "emb_ns_fused"
#: stack-axis order of the public tables inside the fused [V, 2, d] array;
#: obs/health reports per-table update stats under these names whether the
#: slab comes from the unified layout or a chunk runner's fused_tables
#: restack, so telemetry keys are stable across layouts
FUSED_SUBTABLES = ("emb_in", "emb_out_ns")


def fuse_tables(params: Params) -> Params:
    """{emb_in [V,d], emb_out_ns [V,d]} -> {emb_ns_fused [V,2,d]} (other keys
    pass through). The stack axis is -2 so replicated mesh params
    ([R, V, d] -> [R, V, 2, d]) restack the same way. Used persistently by
    table_layout="unified" and transiently (at chunk boundaries,
    ops/train_step.make_chunk_runner) by config.fused_tables."""
    p = dict(params)
    p[FUSED_KEY] = jnp.stack(
        [p.pop("emb_in"), p.pop("emb_out_ns")], axis=-2
    )
    return p


def unfuse_tables(params: Params) -> Params:
    p = dict(params)
    f = p.pop(FUSED_KEY)
    p["emb_in"] = f[..., 0, :]
    p["emb_out_ns"] = f[..., 1, :]
    return p


def params_layout(params: Params) -> str:
    """The table layout these params realize: "unified" iff the fused slab
    key is present (config.table_layout's vocabulary)."""
    return "unified" if FUSED_KEY in params else "split"


def convert_params_layout(params: Params, target: str) -> Params:
    """Losslessly restack params into `target` layout ("split"|"unified").

    The conversion is exact in any dtype (a stack/unstack moves values, it
    never rounds), so a split-layout checkpoint resumes into a unified-layout
    run — and vice versa — with a bitwise-unchanged trajectory. Params that
    cannot represent the target (hs/pair runs have no {emb_in, emb_out_ns}
    pair to fuse) raise a ValueError naming both layouts instead of
    silently misreading rows.
    """
    if target not in ("split", "unified"):
        raise ValueError(f"unknown table layout {target!r}")
    src = params_layout(params)
    if src == target:
        return dict(params)
    if target == "unified":
        missing = [k for k in FUSED_SUBTABLES if k not in params]
        if missing:
            raise ValueError(
                f"cannot convert split-layout params to the unified table "
                f"layout: missing {missing} (present: {sorted(params)}). "
                f"The unified [V, 2, d] slab holds exactly {FUSED_SUBTABLES} "
                "— hs/pair parameter sets have no unified form"
            )
        return fuse_tables(params)
    return unfuse_tables(params)


def logical_table(params: Params, name: str) -> jnp.ndarray:
    """The public [V, d] table `name` from either layout.

    Unified params return a PLANE of the slab: for host (numpy) arrays
    that is a zero-copy view, and for device arrays a [V, d] slice — the
    full [V, 2, d] slab is never materialized host-side on the export
    paths (io/embeddings slice-and-stream contract, tests/test_unified.py).
    """
    if name in params:
        return params[name]
    if FUSED_KEY in params and name in FUSED_SUBTABLES:
        return params[FUSED_KEY][..., FUSED_SUBTABLES.index(name), :]
    raise KeyError(
        f"params ({params_layout(params)} layout, keys {sorted(params)}) "
        f"hold no table {name!r}"
    )


def init_params(config: Word2VecConfig, vocab_size: int, key: jax.Array) -> Params:
    d = config.word_dim
    dtype = jnp.dtype(config.dtype)
    # Online-growth headroom (config.vocab_reserve, stream/driver.py): the
    # word tables carry `reserve` extra rows from init, randomly
    # initialized by the SAME draw as live rows — admission later only
    # makes ids live, it never touches table bits, so pre-existing rows
    # stay bitwise identical across a growth boundary.
    cap = vocab_size + getattr(config, "vocab_reserve", 0)
    uniform = (
        jax.random.uniform(key, (cap, d), jnp.float32, -0.5, 0.5) / d
    ).astype(dtype)
    zeros = jnp.zeros((cap, d), dtype)

    params: Params = {}
    if config.model == "sg":
        params["emb_in"] = uniform          # W, Word2Vec.cpp:330
        if config.use_ns:
            params["emb_out_ns"] = zeros    # C, Word2Vec.cpp:348
    else:  # cbow
        if config.use_ns:
            params["emb_in"] = zeros        # C, Word2Vec.cpp:300 (zeros per :209)
            params["emb_out_ns"] = uniform  # W, Word2Vec.cpp:310
        else:
            # cbow+hs bug fix (see module docstring): live init for the input.
            params["emb_in"] = uniform
    if config.use_hs:
        params["emb_out_hs"] = jnp.zeros((vocab_size - 1, d), dtype)  # synapses1, :207
    if getattr(config, "table_layout", "split") == "unified":
        # same values, stacked at init: the unified trajectory is bitwise
        # the split trajectory (tests/test_unified.py)
        params = fuse_tables(params)
    return params


def export_matrix(
    params: Params, config: Word2VecConfig, side: str = "auto"
) -> jnp.ndarray:
    """The matrix to save.

    side="auto" mirrors the reference CLI exactly (main.cpp:196-202):
    hs+cbow saves C (the context/input matrix), everything else saves W.
    For cbow+ns that means the OUTPUT matrix — a choice the r5 graded
    instrument showed to be systematically bad in the reference itself
    (its saved cbow+ns matrix ANTICORRELATES with fine-grained
    similarity, CBOW_GRADED_CALIB_r5.jsonl; ours recovers it, but users
    may still want the other side). side="input"/"output" overrides:
    "input" = the gather-side table (centers for sg, contexts for cbow —
    emb_in; gensim's `wv`), "output" = the ns prediction-side table
    (emb_out_ns; gensim's `syn1neg`). "output" requires ns: the hs
    output table holds V-1 Huffman INTERNAL NODES, not word rows, so
    exporting it as word vectors would be meaningless.

    Both layouts are served: unified params yield the requested plane of
    the [V, 2, d] slab (logical_table), so exporters stream one [V, d]
    table without a host-side copy of the whole slab."""
    if side == "input":
        return logical_table(params, "emb_in")
    if side == "output":
        if not config.use_ns:
            raise ValueError(
                "export side='output' requires negative sampling: the hs "
                "output table rows are Huffman internal nodes, not words"
            )
        return logical_table(params, "emb_out_ns")
    if side != "auto":
        raise ValueError(
            f"export side must be auto, input or output, got {side!r}"
        )
    if config.model == "cbow" and config.use_hs:
        return logical_table(params, "emb_in")  # C, main.cpp:198-199
    if config.model == "cbow" and config.use_ns:
        return logical_table(params, "emb_out_ns")  # W, main.cpp:201
    return logical_table(params, "emb_in")  # W for sg, main.cpp:201
