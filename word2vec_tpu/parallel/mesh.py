"""Device-mesh construction for multi-chip training.

The reference's entire parallelism story is shared-memory OpenMP threads
(main.cpp:186, Word2Vec.cpp:375). The TPU-native replacement is a 2-D
jax.sharding.Mesh:

  axis "data"  — data parallelism: each shard holds an independent replica of
                 the embedding tables and trains on its own corpus shard;
                 replicas are periodically psum-averaged over ICI (the analog
                 of Hogwild's shared memory, and of the parameter-averaging
                 the reference never had; BASELINE.json north star).
  axis "model" — tensor parallelism: the embedding *dimension* is sharded;
                 each chip holds [V, d/TP] of every table and only [P, T]
                 logit partial-sums cross the interconnect (see
                 ops/train_step._score_and_update).

Both axes compose; (dp, tp) = (N, 1) is pure data parallel, (1, N) pure
tensor parallel. word2vec has no layer pipeline and no attention sequence
axis, so PP/SP/CP do not apply (SURVEY §5 "long-context": device cost is made
sequence-length-independent by fixed-shape batching instead).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def make_mesh(
    dp: int,
    tp: int,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A (dp, sp, tp) mesh over the first dp*sp*tp available devices.

    sp is the sequence/context-parallel axis: tokens are sharded along the
    row-position dimension and the band kernel halo-exchanges `window` edge
    tokens with ppermute neighbors (ops/band_step._halo_exchange) — the
    word2vec-scale analog of ring attention's neighbor exchange.

    On real hardware, `jax.devices()` order follows the torus topology, so
    adjacent mesh coordinates map to ICI neighbors; the `model` axis is the
    fastest-varying (innermost) so the per-step logit psum rides the
    tightest ICI ring, with the sp halo ppermute on the next ring out.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = dp * sp * tp
    if need > len(devices):
        raise ValueError(
            f"mesh ({dp}x{sp}x{tp}) needs {need} devices, have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(dp, sp, tp)
    return Mesh(grid, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
