"""Coordination-service shepherd: keep the jax coordinator endpoint alive
through rank 0's death.

`jax.distributed.initialize` hosts the coordination service INSIDE process
0 — so a SIGKILL of rank 0 takes the service endpoint with it, and every
survivor's error poller turns the broken PollForError RPC into LOG(QFATAL)
("Terminating process because the JAX distributed service detected fatal
errors", xla/pjrt/distributed/client.h) within seconds: the processes that
were about to run the elastic rank-0 recovery get SIGABRTed mid-election
(observed live in the rank-0-kill drill; the pybind
`missed_heartbeat_callback` escape hatch dies in a `std::bad_cast` casting
the absl::Status argument, so the callback cannot be defused from Python).

On an ELASTIC fleet the endpoint therefore moves OUT of the training
process: rank 0 spawns this module as a small subprocess that hosts ONLY
`get_distributed_runtime_service`, and every rank (rank 0 included)
connects as a plain client. Rank 0's death then breaks gloo data-plane
connections (the bounded collectives turn that into SyncTimeout — the
detection path) but the coordination endpoint stays reachable, the
survivors' pollers stay quiet, and the election + re-exec proceed at
leisure. The shepherd's service runs with a generous heartbeat tolerance
(the training layer's own deadlines detect death 10-50x faster), holds
the fleet's stdin pipe as a liveness leash — the parent's exec or death
closes it — and then lingers a bounded grace so in-flight recoveries
finish before the port is released.

    python -m word2vec_tpu.parallel.coordservice --port P --procs N \
        [--linger SECS] [--heartbeat-interval S] [--max-missing N]

Prints one `ready` line to stdout once the service is bound (the parent
blocks on it before connecting clients).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

#: seconds the shepherd keeps serving after its leash (stdin) closes —
#: must cover a full shrink recovery (detection + election + round +
#: exec) of the generation it coordinates
LINGER_DEFAULT = 240.0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m word2vec_tpu.parallel.coordservice"
    )
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--procs", type=int, required=True)
    ap.add_argument("--linger", type=float, default=LINGER_DEFAULT)
    ap.add_argument("--heartbeat-interval", type=int, default=10)
    ap.add_argument("--max-missing", type=int, default=30,
                    help="service-side missed-heartbeat tolerance; the "
                         "default 30 x 10s = ~300s keeps the service from "
                         "broadcasting a fatal task error while an elastic "
                         "recovery (which needs ~30s) is still running — "
                         "the training layer's --sync/--step deadlines own "
                         "prompt detection, not this channel")
    args = ap.parse_args(argv)

    from jaxlib import xla_extension as xe

    service = xe.get_distributed_runtime_service(
        f"[::]:{args.port}", args.procs,
        heartbeat_interval=args.heartbeat_interval,
        max_missing_heartbeats=args.max_missing,
    )
    print("ready", flush=True)
    # leash: block until the parent's pipe end closes (clean exit, SIGKILL,
    # or the CLOEXEC close at a generation exec) — read() returns b'' then
    try:
        while os.read(0, 4096):
            pass
    except OSError:
        pass
    time.sleep(args.linger)
    try:
        service.shutdown()
    except Exception:  # noqa: BLE001 — exiting anyway
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
