"""Multi-host (multi-process) distributed wiring.

The reference has no cross-node story at all — its parallelism ends at
shared-memory OpenMP threads (main.cpp:186, Word2Vec.cpp:375). SURVEY §5
names the TPU-native replacement as a first-class deliverable:
`jax.distributed` + a mesh over the GLOBAL device set, with the data axis
laid out so replica sync rides ICI within a slice and crosses DCN only
between slices.

Topology policy (the "How to Scale Your Model" recipe):
  - the `model` (tensor) axis and the `seq` (halo-exchange) axis carry
    per-step traffic — they must stay INSIDE a slice, on ICI;
  - the `data` axis carries traffic only every dp_sync_every steps (the
    pmean replica average, parallel/trainer.py), so it is the only axis
    allowed to span slices/DCN. `hybrid_axes` therefore factors dp into
    (dcn_dp = num_slices) x (ici_dp = dp / num_slices) and keeps sp, tp
    entirely in the ICI factor.

Single-process behavior is unchanged: `initialize_from_env` is a no-op
without coordinator configuration, and `make_global_mesh` falls back to
parallel.mesh.make_mesh over the local devices.

This environment has one host, so the multi-process branches cannot be
executed here; the factoring logic is unit-tested (tests/test_multihost.py)
and the single-process path is exercised by the whole parallel test suite.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional

import jax

from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, make_mesh

# Environment contract (set by the launcher on every host):
#   W2V_COORDINATOR  host:port of process 0           (e.g. "10.0.0.1:8476")
#   W2V_NUM_PROCS    total process count
#   W2V_PROC_ID      this process's rank in [0, num_procs)
#
# Elastic extension (resilience/elastic.py; CLI --elastic): each
# shrink/grow re-forms the runtime in a new GENERATION — same processes,
# new coordination service — so the contract gains:
#   W2V_ELASTIC_COORD  host:port of the elastic rendezvous (hosted by the
#                      CURRENT rank 0's process; defaults to the gen-0
#                      coordinator host at port+1000). No longer assumed
#                      stable: when rank 0 dies the survivors re-elect the
#                      rendezvous onto the lowest surviving rank's standby
#                      address and the next generation's COORD moves there.
#   W2V_ELASTIC_PEERS  comma list of per-rank STANDBY rendezvous addresses
#                      (entry r = where rank r would host the rendezvous if
#                      elected; entry 0 == W2V_ELASTIC_COORD). Defaults to
#                      the elastic host at port+rank. Rewritten per
#                      generation in new-rank order by the elastic exec.
#   W2V_ELASTIC_GEN    current generation (0 = the launch topology)
#   W2V_ELASTIC_PORT0  the gen-0 jax coordinator port; generation g's
#                      coordinator is that port + g, so re-formed fleets
#                      never collide with a half-dead predecessor service
#   W2V_ELASTIC_TRIGGER what decided the CURRENT generation (failure |
#                      policy | rejoin); recorded in the generation_start
#                      mesh event so the manifest names every remesh cause
ENV_COORDINATOR = "W2V_COORDINATOR"
ENV_NUM_PROCS = "W2V_NUM_PROCS"
ENV_PROC_ID = "W2V_PROC_ID"
ENV_ELASTIC_COORD = "W2V_ELASTIC_COORD"
ENV_ELASTIC_PEERS = "W2V_ELASTIC_PEERS"
ENV_ELASTIC_GEN = "W2V_ELASTIC_GEN"
ENV_ELASTIC_PORT0 = "W2V_ELASTIC_PORT0"
ENV_ELASTIC_TRIGGER = "W2V_ELASTIC_TRIGGER"


def generation_env(coordinator: str, num_processes: int, process_id: int,
                   gen: int) -> dict:
    """The W2V_* environment a re-formed generation launches under — the
    one place the elastic exec protocol spells the contract, so it can
    never drift from the names initialize_from_env reads."""
    return {
        ENV_COORDINATOR: coordinator,
        ENV_NUM_PROCS: str(int(num_processes)),
        ENV_PROC_ID: str(int(process_id)),
        ENV_ELASTIC_GEN: str(int(gen)),
    }

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistConfig:
    coordinator: str
    num_processes: int
    process_id: int

    @classmethod
    def from_env(cls, env=os.environ) -> Optional["DistConfig"]:
        """None unless configured for > 1 process; a missing W2V_PROC_ID with
        the rest configured is a hard error (defaulting it to 0 would give
        two hosts rank 0 and hang the coordinator with no useful message)."""
        coord = env.get(ENV_COORDINATOR)
        if not coord:
            return None
        n = int(env.get(ENV_NUM_PROCS, "1"))
        if n <= 1:
            return None
        pid = env.get(ENV_PROC_ID)
        if pid is None:
            raise ValueError(
                f"{ENV_COORDINATOR}/{ENV_NUM_PROCS} are set but "
                f"{ENV_PROC_ID} is not; every host must export its rank"
            )
        return cls(coord, n, int(pid))


def _enable_cpu_collectives() -> None:
    """Give the CPU backend a cross-process collectives implementation.

    jaxlib's CPU default is 'none', under which EVERY multi-process
    computation — shard_map psums, process_allgather, the whole distributed
    trainer — fails with "Multiprocess computations aren't implemented on
    the CPU backend". When the resolved platform includes cpu and the knob
    exists (jaxlib >= 0.4.34), switch it to gloo (TCP, brokered through the
    already-configured distributed client). Non-CPU platforms and older
    jaxlibs: no-op. Must run before the first backend use, which is why
    initialize_from_env calls it ahead of jax.distributed.initialize."""
    try:
        plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    except AttributeError:
        plats = os.environ.get("JAX_PLATFORMS", "")
    names = [p.strip() for p in str(plats).split(",") if p.strip()]
    if "cpu" not in names:
        return
    try:
        # the flag is update()-able but not attribute-readable on this
        # jax; read through the flag holder and fall back to "none"
        from jax._src import xla_bridge as _xb

        current = _xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except Exception:
        current = "none"
    if current not in (None, "none"):
        return  # operator already chose (e.g. mpi) — respect it
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # knob absent (old jaxlib) — single-process still works


#: the shepherd Popen, held for the life of this process: dropping it
#: would GC-close our end of the leash pipe and start the shepherd's
#: linger countdown mid-run (the exec/death close is the intended one)
_coordservice = None


def _spawn_coordservice(port: int, num_processes: int):
    """Start the coordination-service shepherd (parallel/coordservice.py)
    as a subprocess holding our pipe as a liveness leash. Returns the
    Popen once the service printed `ready`, or raises RuntimeError."""
    import subprocess

    global _coordservice
    proc = subprocess.Popen(
        [sys.executable, "-m", "word2vec_tpu.parallel.coordservice",
         "--port", str(port), "--procs", str(num_processes)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
    )
    line = proc.stdout.readline()
    if "ready" not in line:
        proc.kill()
        raise RuntimeError(
            f"coordination-service shepherd failed to start (got {line!r})"
        )
    _coordservice = proc
    return proc


def _initialize_elastic(cfg: DistConfig) -> bool:
    """jax.distributed.initialize for an ELASTIC fleet: the coordination
    service lives in a SHEPHERD SUBPROCESS that survives rank 0's death,
    and every rank (rank 0 included) connects as a plain client.

    Why: jax hosts the service inside process 0, and the client's error
    poller LOG(QFATAL)s the whole process the moment the service endpoint
    dies — so a SIGKILL of rank 0 used to SIGABRT every survivor within
    seconds, exactly while they were re-electing the rendezvous (observed
    live in the rank-0-kill drill; the pybind missed_heartbeat_callback
    escape hatch dies in std::bad_cast, so the callback cannot be defused
    from Python). With the endpoint out-of-process, rank-0 loss breaks
    only the gloo data plane — which the bounded collectives turn into
    SyncTimeout, the intended detection path — while the pollers stay
    quiet; the shepherd's generous service-side heartbeat tolerance
    (~300s vs the ~30s a recovery needs) keeps the fatal broadcast away,
    and its leash + linger bound its own lifetime.

    Replicates the CPU-relevant client core of
    jax._src.distributed.initialize against the private surface; returns
    False so the caller falls back to the public initialize (in-process
    service, die-fast pollers — the non-elastic semantics) if that
    surface moved."""
    try:
        from jax._src import distributed as jdist
        from jaxlib import xla_extension as xe

        state = jdist.global_state
        if state.client is not None:
            return True
        _, _, port = cfg.coordinator.rpartition(":")
        if cfg.process_id == 0 and state.service is None:
            _spawn_coordservice(int(port), cfg.num_processes)
        state.client = xe.get_distributed_runtime_client(
            cfg.coordinator, cfg.process_id,
            init_timeout=300, use_compression=True,
        )
        state.client.connect()
        state.process_id = cfg.process_id
        state.num_processes = cfg.num_processes
        try:
            state.initialize_preemption_sync_manager()
        except RuntimeError:
            pass  # already initialized (idempotent re-entry)
        return True
    except Exception as e:  # noqa: BLE001 — private surface moved
        import warnings

        warnings.warn(
            f"elastic coordination-service shepherd unavailable ({e!r}); "
            "falling back to the in-process service — rank-0 loss will "
            "degrade to abort-to-requeue on this jax",
            stacklevel=2,
        )
        return False


def initialize_from_env(env=os.environ, defuse_fatal: bool = False) -> bool:
    """Call jax.distributed.initialize from the W2V_* environment contract.

    Must run before the first backend use on every host. Returns True when
    distributed mode is active (now or from an earlier call), False for
    single-process. Idempotent. With `defuse_fatal` (elastic fleets), the
    coordination service is hosted by an out-of-process shepherd
    (`_initialize_elastic`) so a dead rank 0 cannot take the endpoint —
    and with it every survivor — down; non-elastic runs keep jax's
    in-process service and die-fast pollers, which double as their abort
    path.
    """
    global _initialized
    if _initialized:
        return True
    cfg = DistConfig.from_env(env)
    if cfg is None:
        return False
    _enable_cpu_collectives()
    if not (defuse_fatal and _initialize_elastic(cfg)):
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
    _initialized = True
    return True


def hybrid_axes(
    dp: int, sp: int, tp: int, num_slices: int
) -> tuple[tuple[int, int, int], tuple[int, int, int]]:
    """Factor the (data, seq, model) mesh into DCN x ICI shapes.

    Only the data axis may span slices (it syncs every dp_sync_every steps;
    seq/model traffic is per-step and must stay on ICI). Returns
    (dcn_shape, ici_shape), each (data, seq, model)-ordered, with
    dcn = (num_slices, 1, 1) and ici = (dp/num_slices, sp, tp).
    """
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if dp % num_slices != 0:
        raise ValueError(
            f"data-parallel width {dp} must be divisible by the slice count "
            f"{num_slices}: the data axis is the only one allowed to span "
            f"DCN, so each slice carries dp/num_slices replicas"
        )
    return (num_slices, 1, 1), (dp // num_slices, sp, tp)


def make_global_mesh(
    dp: int, tp: int, sp: int = 1, num_slices: Optional[int] = None
) -> jax.sharding.Mesh:
    """A (data, seq, model) mesh over the global device set.

    Single-process: identical to parallel.mesh.make_mesh. Multi-process on
    multi-slice hardware (devices report distinct slice_index, i.e. TPU
    slices joined by DCN): a hybrid DCN x ICI device grid via mesh_utils so
    mesh coordinates map to the physical topology per the policy above.
    Multi-process on a SINGLE slice — every CPU multi-process job, and TPU
    hosts sharing one pod slice — has no DCN boundary to respect (mesh_utils
    rejects dcn shapes there; found by executing benchmarks/multiproc.py):
    the grid is the process-ordered jax.devices() list reshaped data-major,
    which keeps each process's local devices contiguous along the data axis
    — the layout the per-process batch assembly assumes
    (ShardedTrainer._place, make_array_from_process_local_data).
    `num_slices` defaults to the detected slice count.
    """
    if jax.process_count() == 1:
        return make_mesh(dp, tp, sp)
    import numpy as np

    devs = jax.devices()
    if num_slices is None:
        num_slices = len({getattr(d, "slice_index", 0) for d in devs})
    if dp * sp * tp != len(devs):
        raise ValueError(
            f"mesh dp*sp*tp = {dp}*{sp}*{tp} must cover the global device "
            f"set ({len(devs)} devices across {jax.process_count()} processes)"
        )
    if num_slices > 1:
        from jax.experimental import mesh_utils

        dcn, ici = hybrid_axes(dp, sp, tp, num_slices)
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=ici, dcn_mesh_shape=dcn
        )
    else:
        grid = np.asarray(devs).reshape(dp, sp, tp)
    return jax.sharding.Mesh(grid, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))


def _global_agree(value: int, reduce_fn) -> int:
    if jax.process_count() == 1:
        return value
    import numpy as np
    from jax.experimental import multihost_utils

    from ..resilience.watchdog import bounded_call

    # Deadline-bounded: a peer that died mid-run turns this allgather into
    # an infinite hang for every survivor. With a sync deadline installed
    # (resilience/watchdog.set_sync_deadline, CLI --sync-deadline) the hang
    # becomes a SyncTimeout the driver converts into a coordinated
    # abort-to-requeue; without one, behavior is the old unbounded block.
    return int(reduce_fn(bounded_call(
        lambda: multihost_utils.process_allgather(np.int64(value)),
        what="global_agree allgather",
    )))


def global_agree_min(value: int) -> int:
    """The minimum of a per-process integer across all processes.

    Used to agree on a common number of global steps per epoch: processes
    feed their own corpus shards, and unequal shard sizes would otherwise
    make one host run a collective step the others never join (a hang, not
    an error). Single-process: identity.
    """
    import numpy as np

    return _global_agree(value, np.min)


def global_agree_sum(value: int) -> int:
    """Sum of a per-process integer across all processes (e.g. total corpus
    tokens for the batch-size auto-tuner). Single-process: identity."""
    import numpy as np

    return _global_agree(value, np.sum)


def global_agree_max(value: int) -> int:
    """Maximum of a per-process integer across all processes. Used as the
    any-of vote of the preemption protocol (resilience/shutdown.py): one
    host's SIGTERM flag becomes everyone's stop verdict at the same step
    boundary, so all processes leave the collective loop together instead
    of stranding the survivors in a step the evicted host never joins.
    Single-process: identity."""
    import numpy as np

    return _global_agree(value, np.max)


def global_heartbeat(values) -> "np.ndarray":
    """Allgather one small float row per process -> [P, len(values)].

    The liveness channel of resilience/watchdog.PeerAgreement: at the
    agreement cadence every process contributes (process id, stop flag,
    step, step-time p50 ms, elastic flag) in ONE collective — the stop
    vote, the straggler/desync attribution, and the elastic grow channel
    ride the same allgather the old global_agree_max used, so peer
    liveness costs no extra collective.
    Deadline-bounded like _global_agree: a dead peer raises SyncTimeout
    instead of hanging the fleet. Single-process: returns [[*values]]
    without touching the collective machinery.
    """
    import numpy as np

    row = np.asarray(values, dtype=np.float64)
    if jax.process_count() == 1:
        return row[None, :]
    from jax.experimental import multihost_utils

    from ..resilience.watchdog import bounded_call

    return np.asarray(bounded_call(
        lambda: multihost_utils.process_allgather(row),
        what="peer-liveness heartbeat allgather",
    ))
