"""Multi-chip training: shard_map step + periodic replica averaging.

Layout (see parallel/mesh.py for the axes; the mesh is (data, seq, model)):
  params   — every table carries a leading replica axis: [DP*SP, V, d],
             sharded PartitionSpec(("data", "seq"), None, "model"). Each
             (data, seq) shard trains its own replica slice [1, V, d/TP];
             each model shard holds a dim slice. HBM per chip: V * d / TP
             floats per table.
  tokens   — global [DP*B, L], PartitionSpec("data", "seq"): rows over data
             shards, row positions over seq shards.
  step     — ops/train_step with tp/dp/sp axes bound; inside one step the
             cross-chip traffic is the [P, T] logit psum on the model axis
             (tensor parallelism) and the window-token halo ppermute on the
             seq axis.
  sync     — every dp_sync_every steps, replicas are pmean-averaged over the
             data and seq axes (ICI all-reduce). This replaces the reference's
             shared-memory Hogwild (Word2Vec.cpp:375-394) and is the
             BASELINE.json north-star design ("periodically psum the embedding
             matrices over ICI").
  seq      — sequence/context parallelism for long rows: tokens' position
             axis is sharded, the band kernel halo-exchanges `window` edge
             tokens with ppermute neighbors, and each shard trains only the
             centers it owns (ops/band_step module docstring). Replica-wise
             it behaves like the data axis and shares its sync.

ShardedTrainer subclasses train.Trainer: the epoch loop, alpha schedule,
metering and checkpoint hooks are inherited; only param layout, batch
placement, and the sync hooks differ.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map

from ..config import Word2VecConfig
from ..data.batcher import BatchIterator, PackedCorpus
from ..data.vocab import Vocab
from ..models.params import Params, init_params
from ..ops.tables import DeviceTables
from ..ops.train_step import make_train_step
from ..train import Trainer, TrainState
from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, make_mesh

# params replicate over data AND seq shards (both train independent replicas
# between syncs); the leading replica axis is sharded over the two jointly.
# PARAM_SPEC is the split-layout [R, V, d] spec; param_spec(v) derives the
# rank-matched spec for ANY table rank — the unified layout's [R, V, 2, d]
# slab (config.table_layout, models/params.py) keeps its extra table axis
# unsharded between the replica and dim axes.
PARAM_SPEC = P((DATA_AXIS, SEQ_AXIS), None, MODEL_AXIS)
# tokens: rows over data shards, row positions over seq shards (band kernel
# halo-exchanges the window-crossing edges, ops/band_step._halo_exchange)
TOKEN_SPEC = P(DATA_AXIS, SEQ_AXIS)
REPLICA_AXES = (DATA_AXIS, SEQ_AXIS)


def param_spec(v) -> P:
    """PartitionSpec for one REPLICATED table array: leading replica axis
    over (data, seq), trailing embedding-dim axis over model, every middle
    axis (vocab; the unified layout's 2-wide table axis) unsharded. Rank-
    derived so split [R, V, d] and unified [R, V, 2, d] both resolve —
    works on concrete arrays and on tracers (only .ndim is read)."""
    return P((DATA_AXIS, SEQ_AXIS), *([None] * (v.ndim - 2)), MODEL_AXIS)


def param_specs(params: Params) -> dict:
    return {k: param_spec(v) for k, v in params.items()}


def replicate_params(params: Params, mesh: Mesh) -> Params:
    """[V, ...] -> [DP*SP, V, ...] identical replicas, sharded over the mesh.

    The replicated view is built host-side with np.broadcast_to (zero-copy);
    device_put then places only each shard's slice, so no single device ever
    materializes the full replicated array.
    """
    reps = mesh.shape[DATA_AXIS] * mesh.shape[SEQ_AXIS]
    out = {}
    for k, v in params.items():
        rep = np.broadcast_to(np.asarray(v), (reps, *v.shape))
        out[k] = jax.device_put(
            rep, NamedSharding(mesh, param_spec(rep))
        )
    return out


def unreplicate_params(params: Params) -> Params:
    """[DP*SP, V, d] -> [V, d]; call after a sync so replicas are equal."""
    return {k: v[0] for k, v in params.items()}


def assemble_local_replica(v: jax.Array) -> np.ndarray:
    """One full [V, ...] table from this process's addressable shards.

    After a sync every replica (leading axis) is identical, so any one will
    do — but in multi-host mode replica 0 may live on another host, and the
    model-axis dim slices of a replica must be re-concatenated. The hybrid
    mesh keeps the model axis inside a slice (parallel/multihost.py), so
    every process holds at least one complete replica's worth of dim shards.
    Works identically (and is tested) on a single-process virtual mesh.
    The dim axis is the LAST axis for both table layouts (split [R, V, d],
    unified [R, V, 2, d] — param_spec), so shards key on index[-1].
    """
    shards = v.addressable_shards
    rep = shards[0].index[0]  # leading-axis slice of some locally-held replica
    parts = {}
    for s in shards:
        if s.index[0] == rep:
            d0 = s.index[-1].start or 0
            parts[d0] = np.asarray(s.data)[0]
    return np.concatenate([parts[k] for k in sorted(parts)], axis=-1)


def _reject_pallas(config: Word2VecConfig) -> None:
    """shard_map cannot host the pallas band kernels yet: the Pallas
    interpreter's internal dynamic_slices are not vma-aware (crashes even
    on a 1x1x1 mesh on the CPU test backend), and no multi-chip hardware
    exists here to validate a real-TPU compile. Covers the fused band
    kernel (band_backend='pallas'), the overlap-add kernel ('pallas_oa',
    ops/pallas_overlap.py) and the fully-fused step ('pallas_fused',
    ops/pallas_step.py). Reject up front with the real reason — naming the
    incompatible lever (the mesh) and the supported alternative — instead
    of an internal JAX error mid-step."""
    if config.band_backend in ("pallas", "pallas_oa", "pallas_fused"):
        raise ValueError(
            f"band_backend={config.band_backend!r} is single-chip only "
            "(plain Trainer): shard_map cannot host pallas_call, so a "
            "sharded mesh is the incompatible lever here. Use "
            "band_backend='xla' for sharded training, or drop the mesh "
            "axes — see the scope note in ops/pallas_band.py"
        )


def make_sharded_step(config: Word2VecConfig, tables: DeviceTables, mesh: Mesh):
    """Jitted global-array step over the mesh (donates params)."""
    _reject_pallas(config)
    dp = mesh.shape[DATA_AXIS]
    sp = mesh.shape[SEQ_AXIS]
    tp = mesh.shape[MODEL_AXIS]
    inner = make_train_step(
        config,
        tables,
        tp_axis=MODEL_AXIS if tp > 1 else None,
        dp_axis=DATA_AXIS if dp > 1 else None,
        sp_axis=SEQ_AXIS if sp > 1 else None,
    )

    def local_step(params, tokens, key, alpha):
        # local views: params [1, V, d/TP], tokens [B, L]
        p = {k: v[0] for k, v in params.items()}
        new_p, metrics = inner(p, tokens, key, alpha)
        # loss/pairs are computed from full (psum'd) logits, so every model
        # shard already holds the same value; psum/tp collapses the model axis
        # (and proves replication to the vma checker), psum over data sums the
        # genuinely distinct per-shard contributions. This is the METRICS
        # CONTRACT every kernel- or telemetry-emitted counter must satisfy:
        # model-axis-replicated, additive over replicas (obs/health pre-psums
        # its per-dim-shard table stats over tp for exactly this reason).
        metrics = {
            k: jax.lax.psum(jax.lax.psum(v, MODEL_AXIS) / tp, REPLICA_AXES)
            for k, v in metrics.items()
        }
        return {k: v[None] for k, v in new_p.items()}, metrics

    def stepfn(params, tokens, key, alpha):
        specs = param_specs(params)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, TOKEN_SPEC, P(), P()),
            out_specs=(specs, P()),
        )(params, tokens, key, alpha)

    return jax.jit(stepfn, donate_argnums=0)


def make_sharded_chunk(config: Word2VecConfig, tables: DeviceTables, mesh: Mesh):
    """Chunked dispatch over the mesh: S global steps as one device program.

    chunk(params, tokens[S, DP*B, L], base_key, step0, alphas[S]) — the
    sharded analog of ops/train_step.make_chunk_runner: an inner lax.scan
    over the per-step shard_map body, same fold_in(base_key, step0 + i) RNG
    stream and per-step alphas as the per-step sharded driver, so the
    trajectory is identical and only dispatch granularity changes. Per-step
    metrics are psum'd inside the scan (replicated outputs, spec P()).

    Replica sync stays OUTSIDE the chunk at chunk boundaries;
    ShardedTrainer._resolve_chunk_len caps S at the sync dispatch interval
    so chunking never coarsens the reconciliation cadence.
    """
    _reject_pallas(config)
    dp = mesh.shape[DATA_AXIS]
    sp = mesh.shape[SEQ_AXIS]
    tp = mesh.shape[MODEL_AXIS]
    fused = config.fused_tables
    inner = make_train_step(
        config,
        tables,
        tp_axis=MODEL_AXIS if tp > 1 else None,
        dp_axis=DATA_AXIS if dp > 1 else None,
        sp_axis=SEQ_AXIS if sp > 1 else None,
        fused=fused,
    )

    def local_chunk(params, tokens, base_key, step0, alphas):
        p = {k: v[0] for k, v in params.items()}
        if fused:
            # per-shard restack: with tp the stacked [V, 2, d/TP] keeps the
            # dim sharding (stack axis 1 is local); amortizes over the chunk
            from ..models.params import fuse_tables, unfuse_tables

            p = fuse_tables(p)

        def body(pp, xs):
            toks, i, a = xs
            key = jax.random.fold_in(base_key, step0 + i)
            pp, m = inner(pp, toks, key, a)
            m = {
                k: jax.lax.psum(jax.lax.psum(v, MODEL_AXIS) / tp, REPLICA_AXES)
                for k, v in m.items()
            }
            return pp, m

        s = tokens.shape[0]
        idx = jnp.arange(s, dtype=jnp.int32)
        p, metrics = jax.lax.scan(body, p, (tokens, idx, alphas))
        if fused:
            p = unfuse_tables(p)
        return ({k: v[None] for k, v in p.items()}, metrics)

    def chunkfn(params, tokens, base_key, step0, alphas):
        specs = param_specs(params)
        return shard_map(
            local_chunk,
            mesh=mesh,
            in_specs=(specs, P(None, DATA_AXIS, SEQ_AXIS), P(), P(), P()),
            out_specs=(specs, P()),
        )(params, tokens, base_key, step0, alphas)

    return jax.jit(chunkfn, donate_argnums=0)


def make_sharded_resident_chunk(
    config: Word2VecConfig, tables: DeviceTables, mesh: Mesh
):
    """Resident-corpus chunked dispatch over the mesh (ops/resident.py).

    chunk(params, corpus, order, base_key, step0, epoch_t0, alphas[S]) — the
    sharded analog of ops/resident.make_resident_chunk_runner: the packed
    corpus and the epoch's row order are replicated over the mesh (spec P();
    text8 is ~68 MB/chip), and each (data, seq) shard assembles ITS OWN
    [B, L/sp] token block inside the scan — data shard j takes permuted row
    block t*dp + j, seq shard q takes column window [q*Lloc, (q+1)*Lloc).
    That reproduces exactly the global [dp*B, L] batch the streaming path
    builds on host and shards at placement time (TOKEN_SPEC), so the
    trajectory is identical (tests/test_resident.py) — with zero per-chunk
    token traffic. Single-process meshes only: multi-host runs feed
    per-process corpus SHARDS, which have no shared global row order.
    """
    _reject_pallas(config)
    from ..ops.resident import assemble_batch

    dp = mesh.shape[DATA_AXIS]
    sp = mesh.shape[SEQ_AXIS]
    tp = mesh.shape[MODEL_AXIS]
    fused = config.fused_tables
    inner = make_train_step(
        config,
        tables,
        tp_axis=MODEL_AXIS if tp > 1 else None,
        dp_axis=DATA_AXIS if dp > 1 else None,
        sp_axis=SEQ_AXIS if sp > 1 else None,
        fused=fused,
    )
    B = config.batch_rows
    Lloc = config.max_sentence_len // sp

    def local_chunk(params, corpus, order, base_key, step0, epoch_t0, alphas):
        p = {k: v[0] for k, v in params.items()}
        if fused:
            from ..models.params import fuse_tables, unfuse_tables

            p = fuse_tables(p)
        dpi = jax.lax.axis_index(DATA_AXIS)
        col0 = jax.lax.axis_index(SEQ_AXIS) * Lloc

        def body(pp, xs):
            i, a = xs
            toks = assemble_batch(
                corpus, order, (epoch_t0 + i) * dp + dpi, B, Lloc, col0
            )
            key = jax.random.fold_in(base_key, step0 + i)
            pp, m = inner(pp, toks, key, a)
            m = {
                k: jax.lax.psum(jax.lax.psum(v, MODEL_AXIS) / tp, REPLICA_AXES)
                for k, v in m.items()
            }
            return pp, m

        s = alphas.shape[0]
        idx = jnp.arange(s, dtype=jnp.int32)
        p, metrics = jax.lax.scan(body, p, (idx, alphas))
        if fused:
            p = unfuse_tables(p)
        return ({k: v[None] for k, v in p.items()}, metrics)

    def chunkfn(params, corpus, order, base_key, step0, epoch_t0, alphas):
        specs = param_specs(params)
        corpus_specs = {k: P() for k in corpus}
        return shard_map(
            local_chunk,
            mesh=mesh,
            in_specs=(specs, corpus_specs, P(), P(), P(), P(), P()),
            out_specs=(specs, P()),
        )(params, corpus, order, base_key, step0, epoch_t0, alphas)

    return jax.jit(chunkfn, donate_argnums=0)


def make_sync(mesh: Mesh):
    """Jitted pmean of all replicas over the data and seq axes (ICI
    all-reduce)."""

    def syncfn(params):
        specs = param_specs(params)

        def local(p):
            return {k: jax.lax.pmean(v, REPLICA_AXES) for k, v in p.items()}

        return shard_map(
            local, mesh=mesh, in_specs=(specs,), out_specs=specs
        )(params)

    return jax.jit(syncfn, donate_argnums=0)


def make_delta_sync(mesh: Mesh):
    """Delta-psum reconciliation (SURVEY §7(d); config.sync_mode="delta").

    sync(params, base) -> new_params, with
        new = base + pmean(bf16(params - base))
    over the replica axes. `base` is the (replica-identical) state of the
    last sync, so only the accumulated local UPDATES cross the wire — in
    bf16, which halves the ICI bytes of a full-table pmean. In exact
    arithmetic base + pmean(delta) == pmean(params); the bf16 rounding is
    relative to the delta's magnitude (per-sync drift ~eps_bf16 * |delta|),
    not the weights'. The caller keeps the next base as an explicit .copy()
    of the result (ShardedTrainer._run_sync) so the step's donated in-place
    updates never alias it.
    """

    def syncfn(params, base):
        specs = param_specs(params)

        def local(p, b):
            out = {}
            for k, v in p.items():
                wire = (v - b[k]).astype(jnp.bfloat16)  # bf16 on the wire
                mean_delta = jax.lax.pmean(wire, REPLICA_AXES)
                out[k] = b[k] + mean_delta.astype(v.dtype)
            return out

        return shard_map(
            local, mesh=mesh, in_specs=(specs, specs), out_specs=specs
        )(params, base)

    return jax.jit(syncfn, donate_argnums=(0, 1))


class ShardedTrainer(Trainer):
    """Data+sequence+tensor-parallel trainer; dp*sp*tp <= len(jax.devices())."""

    supports_chunking = True
    # resident corpus: each (data, seq) shard assembles its own token block
    # from a mesh-replicated corpus (make_sharded_resident_chunk);
    # multi-host runs stream (per-process corpus shards share no row order)
    supports_resident = True

    def __init__(
        self,
        config: Word2VecConfig,
        vocab: Vocab,
        corpus: PackedCorpus,
        dp: int = 1,
        tp: int = 1,
        sp: int = 1,
        mesh: Optional[Mesh] = None,
        log_fn=None,
    ):
        self._apply_mesh(
            mesh if mesh is not None else make_mesh(dp, tp, sp), config
        )
        self._last_sync_step: Optional[int] = None
        self._epoch_steps: Optional[int] = None
        super().__init__(config, vocab, corpus, log_fn=log_fn)

    # ------------------------------------------------------ mesh lifecycle
    def _apply_mesh(self, mesh: Mesh, config: Word2VecConfig) -> None:
        """Adopt `mesh` as this trainer's device mesh: derive the axis
        widths, validate the config against the RESOLVED shape, and rebuild
        the shardings. The one place mesh topology enters the trainer —
        __init__ routes through it, and remesh() re-enters it on a live
        instance (elastic shrink/grow, autoscaling)."""
        self.mesh = mesh
        self.dp = self.mesh.shape[DATA_AXIS]
        self.sp = self.mesh.shape[SEQ_AXIS]
        self.tp = self.mesh.shape[MODEL_AXIS]
        # validate against the *resolved* mesh, not the constructor args
        if config.word_dim % self.tp != 0:
            raise ValueError(
                f"word_dim {config.word_dim} not divisible by tp={self.tp}"
            )
        if config.max_sentence_len % self.sp != 0:
            raise ValueError(
                f"max_sentence_len {config.max_sentence_len} not divisible "
                f"by sp={self.sp}"
            )
        if self.sp > 1 and config.max_sentence_len // self.sp < config.window:
            # a shard slice shorter than the window would need halo tokens
            # from beyond its immediate neighbors (multi-hop exchange);
            # _halo_exchange is single-hop by design
            raise ValueError(
                f"per-shard slice {config.max_sentence_len // self.sp} is "
                f"shorter than window {config.window}; lower sp or raise "
                f"max_sentence_len"
            )
        if self.sp > 1 and config.scatter_mean:
            raise ValueError(
                "scatter_mean duplicate counts are shard-local and would "
                "diverge from single-chip semantics under sp > 1; use the "
                "default sum semantics with sequence parallelism"
            )
        self.token_sharding = NamedSharding(self.mesh, TOKEN_SPEC)
        self.procs = jax.process_count()
        if self.procs > 1 and self.dp % self.procs != 0:
            raise ValueError(
                f"multi-host: data-parallel width {self.dp} must be divisible "
                f"by the process count {self.procs} (each process feeds "
                f"dp/procs replicas; parallel/multihost.py)"
            )

    def remesh(
        self,
        mesh: Optional[Mesh] = None,
        dp: int = 0,
        tp: int = 0,
        sp: int = 0,
        state=None,
        checkpoint_dir: Optional[str] = None,
    ) -> "ShardedTrainer":
        """Re-form this trainer over a new device mesh, re-entrantly.

        Rebuilds everything mesh-derived — the step/sync programs, the
        PartitionSpecs both table layouts resolve through (param_spec), the
        token sharding, the chunk/resident runners (rebuilt lazily on the
        next train()), and the cross-process agreement caches — so a live
        trainer can change topology the way __init__ sets it up: the same
        `_apply_mesh` validation path, the same builders. This is the
        autoscaling primitive, and the core the elastic shrink/grow
        protocol (resilience/elastic.py) runs inside each generation.

        Parameters: pass `mesh`, or axis widths (`dp`/`tp`/`sp`, defaulting
        to the current values). With `state`, the live params are exported
        host-side on the OLD mesh (replica-synced) and re-sharded onto the
        new one — resuming is state-identical to handing the same host
        tables to a freshly constructed trainer of the new shape (pinned by
        tests/test_elastic.py for both table layouts). With
        `checkpoint_dir`, tables and counters are instead re-shard-loaded
        from the newest GOOD checkpoint through the existing integrity
        chain (io/checkpoint.load_checkpoint: sha256 verify, quarantine,
        .old fallback) — the elastic shrink semantics; it requires a
        `state` to import into (ValueError otherwise — a load with
        nowhere to land would be silently discarded).

        NOTE: the process-count and the jax global device set cannot change
        inside a live process (the coordination service has no member
        removal); cross-process elasticity re-enters through an in-place
        exec and lands here via __init__. In-process remesh is therefore a
        single-process (virtual or real multi-device) operation.
        """
        host_params = None
        ck_state = None
        if checkpoint_dir is not None:
            if state is None:
                raise ValueError(
                    "remesh(checkpoint_dir=...) re-shard-loads the "
                    "checkpoint tables into a live state and needs the "
                    "`state` to import them into — without it the loaded "
                    "params would be silently discarded. Pass state=, or "
                    "omit checkpoint_dir for a specs-only remesh."
                )
            from ..io.checkpoint import load_checkpoint

            ck_state, _cfg, _vocab = load_checkpoint(checkpoint_dir)
            host_params = ck_state.params
        elif state is not None:
            # synced, de-replicated host view taken on the OLD mesh
            host_params = self.export_params(state)
        self._apply_mesh(
            mesh if mesh is not None else make_mesh(
                dp or self.dp, tp or self.tp, sp or self.sp
            ),
            self.config,
        )
        self._build_step()
        self.chunk_fn = None
        self._resident_cache = None
        self._resident_ready = False
        self._epoch_steps = None  # agreed steps/epoch are topology-derived
        self._last_sync_step = None
        if state is not None and ck_state is not None:
            state.step = ck_state.step
            state.words_done = ck_state.words_done
            state.epoch = ck_state.epoch
        if state is not None and host_params is not None:
            self.import_params(host_params, state)
        self._log({
            "event": "remesh",
            "mesh_size": self.dp * self.sp * self.tp,
            "dp": self.dp, "sp": self.sp, "tp": self.tp,
            "source": "checkpoint" if checkpoint_dir else (
                "live" if state is not None else "specs-only"
            ),
        })
        if self.flight is not None:
            self.flight.ring.instant("remesh", args={
                "dp": self.dp, "sp": self.sp, "tp": self.tp,
            })
        return self

    # ---------------------------------------------------------------- hooks
    def _build_step(self) -> None:
        self.step_fn = make_sharded_step(self.config, self.tables, self.mesh)
        if self.config.sync_mode == "delta":
            self.sync_fn = make_delta_sync(self.mesh)
        else:
            self.sync_fn = make_sync(self.mesh)
        self.chunk_fn = None  # built lazily (train._train_chunked)
        self._sync_base: Optional[Params] = None

    def _init_params(self, key: jax.Array) -> Params:
        params = replicate_params(
            init_params(self.config, len(self.vocab), key), self.mesh
        )
        self._reset_sync_base(params)
        return params

    def _reset_sync_base(self, params: Params) -> None:
        """Delta sync tracks params-at-last-sync; (re)base whenever params
        are (re)placed wholesale (init, checkpoint import)."""
        if self.config.sync_mode == "delta":
            self._sync_base = {k: v.copy() for k, v in params.items()}

    def _run_sync(self, params: Params) -> Params:
        if self.config.sync_mode == "delta":
            if self._sync_base is None:
                # externally supplied state (train(state=...) without
                # init_state/import_params): base from the current params —
                # replicas are assumed reconciled at hand-off
                self._reset_sync_base(params)
            self._harvest_capture(
                "replica_sync", self.sync_fn, (params, self._sync_base)
            )
            params = self.sync_fn(params, self._sync_base)
            # distinct buffer: the step updates params in place (donation)
            self._sync_base = {k: v.copy() for k, v in params.items()}
        else:
            self._harvest_capture("replica_sync", self.sync_fn, (params,))
            params = self.sync_fn(params)
        self._bound_sync_wait(params)
        return params

    def _bound_sync_wait(self, params: Params) -> None:
        """Deadline-bound the replica-sync collective in MULTI-PROCESS mode.

        The pmean/psum is dispatched async; with a dead peer it never
        completes and the hang surfaces wherever the host next blocks on a
        device value — possibly a full dp_sync_every later, inside an
        unrelated fetch. When a sync deadline is installed (--sync-deadline)
        and peers exist, block on the sync result in a bounded worker so the
        hang is attributed HERE and raises SyncTimeout for the coordinated
        abort. Single-process or no deadline: no wait, no extra sync point
        (the step watchdog still bounds single-host device hangs)."""
        if self.procs <= 1:
            return
        from ..resilience.watchdog import bounded_call, sync_deadline

        deadline = sync_deadline()
        if not deadline:
            return
        bounded_call(
            lambda: jax.block_until_ready(params),
            what="replica-sync collective",
            deadline=deadline,
        )

    def _device_get(self, x):
        """Deadline-bound the metrics drain in MULTI-PROCESS mode: fetching
        a step's metrics blocks on the step's own collectives, so with a
        dead peer the hang surfaces here — between the bounded
        agree/heartbeat boundaries. Unbounded, only the step watchdog's
        os._exit(EXIT_STALLED) could end it; bounding it turns the wedge
        into the same SyncTimeout every other channel raises, which the
        elastic path (resilience/elastic.py) recovers from WITHOUT an exit.
        Single-process, or without a --sync-deadline: the plain fetch, zero
        added machinery (pinned by tests/test_elastic.py)."""
        if self.procs > 1:
            from ..resilience.watchdog import bounded_call, sync_deadline

            if sync_deadline():
                return bounded_call(
                    lambda: jax.device_get(x),
                    what="sharded metrics fetch",
                )
        return jax.device_get(x)

    def _batches(
        self, batcher: BatchIterator, epoch_index: int, skip: int = 0
    ) -> Iterator[Tuple[jnp.ndarray, int]]:
        """Group consecutive [B, L] batches into one sharded [DP*B, L]
        (the seq axis splits L at placement; no host-side reshaping).

        Single-process: this host supplies all dp row blocks. Multi-process:
        the corpus handed to this trainer is this process's shard, the
        batcher supplies dp/procs row blocks per global step, and
        make_array_from_process_local_data assembles the global array (data
        shard order follows process order, parallel/multihost.py). The word
        count is per-process; the alpha schedule stays consistent across
        hosts when corpus shards are of similar size. `skip` counts GLOBAL
        steps (the Trainer's resume unit); every process derives the same
        value from the replicated step counter, so collective cadence stays
        aligned across hosts.
        """
        local_dp = self.dp // self.procs
        limit = self._agreed_steps_per_epoch(batcher, local_dp)
        emitted = min(skip, limit)
        buf, words = [], 0
        for tokens, w in batcher.epoch(epoch_index, skip * local_dp):
            buf.append(tokens)
            words += w
            if len(buf) == local_dp:
                if emitted >= limit:
                    break  # larger shard: drop the excess this epoch
                yield self._place(np.concatenate(buf, axis=0)), words
                emitted += 1
                buf, words = [], 0
        if buf and emitted < limit:
            # pad the trailing global batch with empty rows
            pad = [np.full_like(buf[0], -1)] * (local_dp - len(buf))
            yield self._place(np.concatenate(buf + pad, axis=0)), words

    def _agreed_steps_per_epoch(self, batcher: BatchIterator, local_dp: int) -> int:
        """Global steps per epoch every process will run.

        Each process feeds its own corpus shard; the shard_map step is a
        collective, so all processes must issue the SAME number of steps —
        a host whose shard packs one extra batch would otherwise enter a
        collective alone and hang the job. Agreed once (cached), as the
        cross-process min of local capacity.
        """
        if self._epoch_steps is None:
            local = -(-batcher.steps_per_epoch() // local_dp)  # ceil
            if self.procs == 1:
                self._epoch_steps = local
            else:
                from .multihost import global_agree_min

                self._epoch_steps = global_agree_min(local)
        return self._epoch_steps

    def _resume_skip(self, state: TrainState, batcher: BatchIterator) -> int:
        """Resume position in GLOBAL steps (the sharded step counter's unit:
        one global step consumes local_dp local batches per process)."""
        local_dp = self.dp // self.procs
        spe = self._agreed_steps_per_epoch(batcher, local_dp)
        skip = state.step - state.epoch * spe
        # skip == spe: boundary checkpoint -> empty epoch, roll to the next
        if 0 <= skip <= spe:
            return skip
        # every process derives the same skip from the replicated counter,
        # so the fallback verdict is identical fleet-wide (no desync)
        return self._note_resume_fallback(state, skip, spe)

    # ------------------------------------------------------ chunked hooks
    def _resolve_chunk_len(self, batcher: BatchIterator) -> int:
        """Chunk length in GLOBAL steps, from the cross-process AGREED epoch
        length — deriving it from the local batch count (the base class's
        unit) would let processes with different shard sizes pick different
        chunk lengths and desynchronize the collective cadence. Sync runs at
        chunk boundaries, so the length is additionally capped to a divisor
        of the sync dispatch interval (reconciliation cadence unchanged)."""
        cfg = self.config
        if not self.supports_chunking or cfg.chunk_steps == 1:
            return 1
        local_dp = self.dp // self.procs
        steps = self._agreed_steps_per_epoch(batcher, local_dp)
        if cfg.chunk_steps == 0:
            s, _ = cfg.chunk_geometry(steps, cap=cfg.chunk_cap)
        else:
            s = min(cfg.chunk_steps, steps)
        if self.dp * self.sp > 1 and cfg.dp_sync_every:
            every = max(1, cfg.dp_sync_every // cfg.micro_steps)
            s = min(s, every)
            while every % s:  # syncs land exactly on per-step cadence
                s -= 1
        return max(1, s)

    def _build_chunk_fn(self):
        return make_sharded_chunk(self.config, self.tables, self.mesh)

    def _chunk_stream(self, batcher, epoch, skip, chunk_len):
        """[S, DP*B, L] chunks: local_dp row blocks per global step, S global
        steps per chunk; trailing partials padded with all-(-1) no-ops.
        Mirrors _batches' grouping and the agreed per-epoch step limit."""
        local_dp = self.dp // self.procs
        limit = self._agreed_steps_per_epoch(batcher, local_dp)
        emitted = min(skip, limit)
        steps: list = []
        words: list = []
        buf: list = []
        step_words = 0

        def flush_chunk():
            nonlocal steps, words
            dead = np.full_like(steps[0], -1)
            chunk = np.stack(steps + [dead] * (chunk_len - len(steps)))
            out = (chunk, words)
            steps, words = [], []
            return out

        for tokens, w in batcher.epoch(epoch, skip * local_dp):
            buf.append(tokens)
            step_words += w
            if len(buf) == local_dp:
                if emitted >= limit:
                    break
                steps.append(np.concatenate(buf, axis=0))
                words.append(step_words)
                emitted += 1
                buf, step_words = [], 0
                if len(steps) == chunk_len:
                    yield flush_chunk()
        if buf and emitted < limit:
            pad = [np.full_like(buf[0], -1)] * (local_dp - len(buf))
            steps.append(np.concatenate(buf + pad, axis=0))
            words.append(step_words)
        if steps:
            yield flush_chunk()

    def _place_tokens(self, np_chunk: np.ndarray) -> jnp.ndarray:
        with self.phases.span("h2d"):
            sharding = NamedSharding(self.mesh, P(None, DATA_AXIS, SEQ_AXIS))
            if self.procs == 1:
                return jax.device_put(np_chunk, sharding)
            return jax.make_array_from_process_local_data(sharding, np_chunk)

    # ------------------------------------------------- resident-corpus hooks
    def _build_resident(self):
        if self.procs > 1:
            if self.config.resident == "on":
                import warnings

                warnings.warn(
                    "config.resident='on' is single-process only (multi-host "
                    "feeds per-process corpus shards with no shared row "
                    "order); streaming from host.",
                    stacklevel=2,
                )
            return None
        return super()._build_resident()

    def _make_resident_runtime(self):
        from ..ops import resident as res

        rep = NamedSharding(self.mesh, P())
        corpus_dev = {
            k: jax.device_put(v, rep)
            for k, v in res.corpus_arrays(self.corpus).items()
        }
        return (
            make_sharded_resident_chunk(self.config, self.tables, self.mesh),
            corpus_dev,
        )

    def _resident_rows_per_step(self) -> int:
        # one global step consumes dp row blocks of batch_rows each; with
        # procs == 1 (guaranteed by _build_resident) this matches the agreed
        # steps/epoch: ceil(ceil(R/B)/dp) == ceil(R/(B*dp))
        return self.config.batch_rows * self.dp

    def _place_resident_order(self, order: np.ndarray) -> jnp.ndarray:
        return jax.device_put(
            order.astype(np.int32), NamedSharding(self.mesh, P())
        )

    def _place(self, local_rows: np.ndarray) -> jnp.ndarray:
        with self.phases.span("h2d"):
            if self.procs == 1:
                return jax.device_put(local_rows, self.token_sharding)
            return jax.make_array_from_process_local_data(
                self.token_sharding, local_rows
            )

    def _post_step(self, state: TrainState) -> None:
        cfg = self.config
        # dp_sync_every is calibrated in OPTIMIZER steps; with micro-stepping
        # one dispatch carries micro_steps of them, so the dispatch cadence
        # shrinks accordingly (else small-corpus auto geometry would stretch
        # the replica-averaging window by up to 64x). Distance-based rather
        # than modulo so chunked dispatch (step += chunk_len) can't step
        # over a boundary without syncing.
        every = max(1, cfg.dp_sync_every // cfg.micro_steps)
        since = state.step - (self._last_sync_step or 0)
        if self.dp * self.sp > 1 and cfg.dp_sync_every and since >= every:
            # own span: the sync wait is FLEET time (blocked on the slowest
            # replica), so it must land on the timeline and stay out of the
            # host-attributable overhead the signal plane derives
            # (obs/signals._host_overhead_ms)
            with self.phases.span("replica_sync"):
                state.params = self._run_sync(state.params)
            self._last_sync_step = state.step

    def _finalize(self, state: TrainState) -> None:
        if self.dp * self.sp > 1 and self._last_sync_step != state.step:
            with self.phases.span("replica_sync"):
                state.params = self._run_sync(state.params)
            self._last_sync_step = state.step

    def set_corpus(self, corpus) -> None:
        """Segment swap (stream/driver.py). The per-segment TrainState
        counters restart at 0, so the sync bookkeeping must restart with
        them: a stale `_last_sync_step` from the previous segment makes
        the distance check (`step - last >= every`) permanently negative
        and replica syncs silently STOP after the first segment — caught
        by the sharded mid-stream resume parity test. Steps/epoch is a
        per-corpus agreement (cross-process min of shard capacity), so it
        re-agrees per segment — the boundary is a sync boundary anyway."""
        super().set_corpus(corpus)
        self._last_sync_step = None
        self._epoch_steps = None

    def _probe_params(self, state: TrainState) -> Params:
        """Quality probes score the synced, de-replicated host export —
        the same table export/eval/checkpoints see — so a (dp, tp) mesh
        probe is bit-comparable to a single-chip probe of the same params
        (parity pinned by tests/test_quality.py). export_params runs the
        replica sync when one is pending, so the probed table reflects
        every shard's contribution at this boundary."""
        return self.export_params(state)

    def install_shutdown(self, handler, agree_every: int = 0) -> None:
        """Multihost-aware cooperative stop: a preemption notice usually
        hits ONE host, but every process must leave the collective step
        loop at the same global step or the survivors hang in a collective
        the stopped host never joins. Multi-process, the stop check is a
        resilience/watchdog.PeerAgreement: the same agreed-stop vote as
        PR 4's global_agree_max, but the allgather row now carries a
        liveness heartbeat (process id, step, step-time p50) — stragglers
        get logged with host attribution, and under --sync-deadline a dead
        peer raises SyncTimeout out of the collective instead of hanging
        the fleet. Cadence default: the replica-sync dispatch cadence, so
        a stop lands where replicas reconcile anyway. Single-process
        meshes get the plain flag read — no collective."""
        if agree_every <= 0:
            agree_every = max(
                1, self.config.dp_sync_every // self.config.micro_steps
            )
        if self.procs > 1:
            from ..resilience.watchdog import PeerAgreement

            self.stop_check = PeerAgreement(
                handler,
                agree_every=agree_every,
                step_time_fn=lambda: (
                    self.watchdog.step_stats().get("p50_ms", 0.0)
                    if self.watchdog is not None else 0.0
                ),
                log_fn=self.log_fn,
                # heartbeat pid rows on the flight timeline: a peer-loss
                # dump shows the fleet's last agreed state, and the merged
                # cross-host trace names its tracks (obs/trace.merge_traces)
                flight=self.flight,
                # elastic grow channel: the rendezvous host's pending-rejoin
                # poll rides the heartbeat row so the whole fleet admits a
                # restarted host at the SAME sync boundary (cli.py wires
                # trainer.elastic_poll before calling install_shutdown)
                elastic_fn=self.elastic_poll,
                # elastic policy channel (resilience/policy.py): the
                # rendezvous host's latched shrink verdict rides the same
                # row, so a purpose-driven eviction is delivered exactly
                # like a grow — one allgather, one boundary, whole fleet
                policy_fn=self.policy_poll,
                # fleet-skew feed: the same heartbeat rows derive the
                # straggler_skew signal (obs/signals.py — cli.py wires
                # trainer.signals before calling install_shutdown)
                signals=self.signals,
                # the heartbeat wait is fleet time: span it so it lands on
                # the timeline and outside host-attributable overhead
                phases=self.phases,
            ).check
        else:
            self.stop_check = handler.make_stop_check(process_count=1)

    # ------------------------------------------------------------- planning
    def plan_constraints(self):
        """Mesh-aware constraints for the autotuned planner: the pallas
        backend cannot live under shard_map (_reject_pallas), and candidate
        shapes must respect the mesh divisibility rules the constructor
        enforces (the planner never changes word_dim or max_sentence_len,
        so dp is the only live divider — exposed for block-token math)."""
        return {
            "dp": self.dp,
            "sp": self.sp,
            "tp": self.tp,
            "allow_pallas": False,
        }

    def plan_shapes(self):
        """Realized per-chunk shapes over the mesh: the global dispatch is
        dp row blocks wide, each shard sees an L/sp column slice and a d/tp
        dim slice, and the chunk length is the sync-cadence-capped global
        value (_resolve_chunk_len)."""
        shapes = super().plan_shapes()
        shapes.update(
            dp=self.dp,
            sp=self.sp,
            tp=self.tp,
            rows_per_dispatch=self.config.batch_rows * self.dp,
            cols_per_shard=self.config.max_sentence_len // self.sp,
            dim_per_shard=self.config.word_dim // self.tp,
        )
        return shapes

    # ----------------------------------------------------------------- api
    def export_params(self, state: TrainState) -> Params:
        """Synced, de-replicated [V, d] tables on host."""
        if self.dp * self.sp > 1 and self._last_sync_step != state.step:
            state.params = self._run_sync(state.params)
            self._last_sync_step = state.step
        if self.procs == 1:
            return {k: np.asarray(v[0]) for k, v in state.params.items()}
        # multi-host: replica 0 may be remote; assemble from local shards
        return {
            k: assemble_local_replica(v) for k, v in state.params.items()
        }

    def import_params(self, params: Params, state: TrainState) -> None:
        """Load unreplicated host tables (e.g. from a checkpoint) into the
        sharded layout. A checkpoint in the OTHER table layout (split
        [V, d] pair vs unified [V, 2, d] slab) is converted losslessly
        host-side first — or fails loudly naming both layouts
        (models/params.convert_params_layout)."""
        from ..models.params import convert_params_layout

        host = convert_params_layout(
            {k: np.asarray(v) for k, v in params.items()},
            self.config.table_layout,
        )
        state.params = replicate_params(
            {k: np.asarray(v) for k, v in host.items()}, self.mesh
        )
        self._reset_sync_base(state.params)
        self._last_sync_step = state.step
