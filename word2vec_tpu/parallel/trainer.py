"""Multi-chip training: shard_map step + periodic replica averaging.

Layout (see parallel/mesh.py for the axes):
  params   — every table carries a leading replica axis: [DP, V, d], sharded
             PartitionSpec("data", None, "model"). Each data shard trains its
             own replica slice [1, V, d/TP]; each model shard holds a dim
             slice. HBM per chip: V * d / TP floats per table.
  tokens   — global [DP*B, L], PartitionSpec("data", None): each data shard
             consumes its own corpus slice.
  step     — ops/train_step with tp_axis/dp_axis bound; inside one step the
             only cross-chip traffic is the [P, T] logit psum on the model
             axis (tensor parallelism).
  sync     — every dp_sync_every steps, replicas are pmean-averaged over the
             data axis (ICI all-reduce). This replaces the reference's shared-
             memory Hogwild (Word2Vec.cpp:375-394) and is the BASELINE.json
             north-star design ("periodically psum the embedding matrices
             over ICI").

ShardedTrainer subclasses train.Trainer: the epoch loop, alpha schedule,
metering and checkpoint hooks are inherited; only param layout, batch
placement, and the sync hooks differ.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Word2VecConfig
from ..data.batcher import BatchIterator, PackedCorpus
from ..data.vocab import Vocab
from ..models.params import Params, init_params
from ..ops.tables import DeviceTables
from ..ops.train_step import make_train_step
from ..train import Trainer, TrainState
from .mesh import DATA_AXIS, MODEL_AXIS, make_mesh

PARAM_SPEC = P(DATA_AXIS, None, MODEL_AXIS)
TOKEN_SPEC = P(DATA_AXIS, None)


def replicate_params(params: Params, mesh: Mesh) -> Params:
    """[V, d] -> [DP, V, d] identical replicas, sharded over the mesh.

    The replicated view is built host-side with np.broadcast_to (zero-copy);
    device_put then places only each shard's slice, so no single device ever
    materializes the full [DP, V, d] array.
    """
    dp = mesh.shape[DATA_AXIS]
    sharding = NamedSharding(mesh, PARAM_SPEC)
    return {
        k: jax.device_put(np.broadcast_to(np.asarray(v), (dp, *v.shape)), sharding)
        for k, v in params.items()
    }


def unreplicate_params(params: Params) -> Params:
    """[DP, V, d] -> [V, d]; call after a sync so replicas are equal."""
    return {k: v[0] for k, v in params.items()}


def make_sharded_step(config: Word2VecConfig, tables: DeviceTables, mesh: Mesh):
    """Jitted global-array step over the mesh (donates params)."""
    dp = mesh.shape[DATA_AXIS]
    tp = mesh.shape[MODEL_AXIS]
    inner = make_train_step(
        config,
        tables,
        tp_axis=MODEL_AXIS if tp > 1 else None,
        dp_axis=DATA_AXIS if dp > 1 else None,
    )

    def local_step(params, tokens, key, alpha):
        # local views: params [1, V, d/TP], tokens [B, L]
        p = {k: v[0] for k, v in params.items()}
        new_p, metrics = inner(p, tokens, key, alpha)
        # loss/pairs are computed from full (psum'd) logits, so every model
        # shard already holds the same value; psum/tp collapses the model axis
        # (and proves replication to the vma checker), psum over data sums the
        # genuinely distinct per-shard contributions.
        metrics = {
            k: jax.lax.psum(jax.lax.psum(v, MODEL_AXIS) / tp, DATA_AXIS)
            for k, v in metrics.items()
        }
        return {k: v[None] for k, v in new_p.items()}, metrics

    def stepfn(params, tokens, key, alpha):
        specs = {k: PARAM_SPEC for k in params}
        return jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, TOKEN_SPEC, P(), P()),
            out_specs=(specs, P()),
        )(params, tokens, key, alpha)

    return jax.jit(stepfn, donate_argnums=0)


def make_sync(mesh: Mesh):
    """Jitted pmean of all replicas over the data axis (ICI all-reduce)."""

    def syncfn(params):
        specs = {k: PARAM_SPEC for k in params}

        def local(p):
            return {k: jax.lax.pmean(v, DATA_AXIS) for k, v in p.items()}

        return jax.shard_map(
            local, mesh=mesh, in_specs=(specs,), out_specs=specs
        )(params)

    return jax.jit(syncfn, donate_argnums=0)


class ShardedTrainer(Trainer):
    """Data+tensor-parallel trainer. dp*tp must not exceed len(jax.devices())."""

    def __init__(
        self,
        config: Word2VecConfig,
        vocab: Vocab,
        corpus: PackedCorpus,
        dp: int = 1,
        tp: int = 1,
        mesh: Optional[Mesh] = None,
        log_fn=None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh(dp, tp)
        self.dp = self.mesh.shape[DATA_AXIS]
        self.tp = self.mesh.shape[MODEL_AXIS]
        # validate against the *resolved* mesh, not the constructor args
        if config.word_dim % self.tp != 0:
            raise ValueError(
                f"word_dim {config.word_dim} not divisible by tp={self.tp}"
            )
        self.token_sharding = NamedSharding(self.mesh, TOKEN_SPEC)
        self._last_sync_step: Optional[int] = None
        super().__init__(config, vocab, corpus, log_fn=log_fn)

    # ---------------------------------------------------------------- hooks
    def _build_step(self) -> None:
        self.step_fn = make_sharded_step(self.config, self.tables, self.mesh)
        self.sync_fn = make_sync(self.mesh)

    def _init_params(self, key: jax.Array) -> Params:
        return replicate_params(
            init_params(self.config, len(self.vocab), key), self.mesh
        )

    def _batches(self, batcher: BatchIterator) -> Iterator[Tuple[jnp.ndarray, int]]:
        """Group dp consecutive [B, L] batches into one sharded [DP*B, L]."""
        buf, words = [], 0
        for tokens, w in batcher.epoch():
            buf.append(tokens)
            words += w
            if len(buf) == self.dp:
                yield jax.device_put(
                    np.concatenate(buf, axis=0), self.token_sharding
                ), words
                buf, words = [], 0
        if buf:
            # pad the trailing global batch with empty rows
            pad = [np.full_like(buf[0], -1)] * (self.dp - len(buf))
            yield jax.device_put(
                np.concatenate(buf + pad, axis=0), self.token_sharding
            ), words

    def _post_step(self, state: TrainState) -> None:
        cfg = self.config
        if self.dp > 1 and cfg.dp_sync_every and state.step % cfg.dp_sync_every == 0:
            state.params = self.sync_fn(state.params)
            self._last_sync_step = state.step

    def _finalize(self, state: TrainState) -> None:
        if self.dp > 1 and self._last_sync_step != state.step:
            state.params = self.sync_fn(state.params)
            self._last_sync_step = state.step

    # ----------------------------------------------------------------- api
    def export_params(self, state: TrainState) -> Params:
        """Synced, de-replicated [V, d] tables on host."""
        if self.dp > 1 and self._last_sync_step != state.step:
            state.params = self.sync_fn(state.params)
            self._last_sync_step = state.step
        return {k: np.asarray(v[0]) for k, v in state.params.items()}

    def import_params(self, params: Params, state: TrainState) -> None:
        """Load unreplicated [V, d] tables (e.g. from a checkpoint) into the
        sharded layout."""
        state.params = replicate_params(
            {k: np.asarray(v) for k, v in params.items()}, self.mesh
        )
        self._last_sync_step = state.step
