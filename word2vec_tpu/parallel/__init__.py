"""Multi-chip parallelism: mesh construction, sharded step, replica sync.

See mesh.py for the axis design (data x model) and trainer.py for the
sharded training loop. CI exercises these on 8 virtual CPU devices
(tests/conftest.py); the driver's dryrun_multichip does the same via
__graft_entry__.py.
"""

from .mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from .trainer import (
    ShardedTrainer,
    make_sharded_step,
    make_sync,
    replicate_params,
    unreplicate_params,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "ShardedTrainer",
    "make_sharded_step",
    "make_sync",
    "replicate_params",
    "unreplicate_params",
]
