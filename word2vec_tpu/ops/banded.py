"""Window-blocked (chunked) band matmul primitives.

The band kernels score every (center i, context j) pair with |i - j| <= W.
Realizing that as dense [B, L, L] matmuls (band_step.py round 1) computes and
materializes L/(2W+1)-times more than the band needs — at the default L=192,
W=5 about 95% of the positive-side FLOPs and logit traffic is masked away
(VERDICT r1). These helpers restructure every band contraction so cost scales
with L * (S + 2W) instead of L^2:

  rows are split into C chunks of S positions; chunk c's contexts all lie in
  the S + 2W wide slab [c*S - W, c*S + S + W), so each chunk needs one
  [S, d] x [d, S+2W] matmul. Slab extraction and the transposed overlap-add
  are pure pad/reshape/slice/add compositions (no gather, no scatter), so XLA
  fuses them into the matmuls.

Chunk-coordinate invariant used throughout: padded position p = j + W, chunk
slab k = p - c*S, so a row at local offset s (global i = c*S + s) sees
distance |i - j| = |s + W - k| — a static [S, S+2W] matrix shared by all
chunks and batches.

Every helper takes the resolved chunk size S; S == 0 selects the dense path
(identical math, one [L, L] plane), which stays optimal for short rows where
L + 2W fits a single MXU tile anyway. Chunked-vs-dense exactness is pinned by
tests/test_banded.py.

"Scores" below means the band-plane representation: dense [B, L, L] when
S == 0, chunked [B, C, S, S+2W] otherwise. Elementwise ops (masking, sigmoid,
loss sums) apply to either representation unchanged, which is what keeps
band_step.py kernel logic representation-agnostic.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def resolve_chunk(L: int, W: int, requested: int = 0) -> int:
    """Chunk size S for row length L, window W. 0 = dense.

    Auto rule: stay dense while the whole row fits one 128-lane MXU tile
    (chunking below that only re-tiles work the MXU does anyway); otherwise
    size the slab S + 2W to 128 lanes. Explicit `requested` must keep the
    slab-overlap decomposition valid (S >= 2W, see overlap_add).
    """
    if requested:
        if requested < 2 * W:
            raise ValueError(
                f"band_chunk={requested} < 2*window={2 * W}: slab overlap-add "
                "requires S >= 2W"
            )
        return 0 if requested >= L else requested
    if L + 2 * W <= 128:
        return 0
    S = 128 - 2 * W
    if S < 2 * W:  # very wide windows: keep the slab two windows wide
        S = 2 * W
    return 0 if S >= L else S


def _geom(L: int, W: int, S: int):
    C = -(-L // S)  # ceil
    P = C * S + 2 * W  # padded position-axis length
    return C, P


def _pad_rows(x: jnp.ndarray, L_pad: int) -> jnp.ndarray:
    """Zero-pad axis 1 (rows) from L to L_pad."""
    pad = [(0, 0)] * x.ndim
    pad[1] = (0, L_pad - x.shape[1])
    return jnp.pad(x, pad)


def _pad_ctx(x: jnp.ndarray, W: int, P: int) -> jnp.ndarray:
    """Pad axis 1 (contexts) with W on the left, to total length P."""
    pad = [(0, 0)] * x.ndim
    pad[1] = (W, P - x.shape[1] - W)
    return jnp.pad(x, pad)


def _slabs(x_pad: jnp.ndarray, C: int, S: int, F: int) -> jnp.ndarray:
    """[B, P, ...] -> [B, C, S+F, ...]: overlapping context slabs by
    reshape+shift (chunk c = x_pad[:, c*S : c*S + S + F]), gather-free."""
    if x_pad.shape[1] < S + C * S:
        # the shifted view runs past P = C*S + F whenever F < S
        pad = [(0, 0)] * x_pad.ndim
        pad[1] = (0, S + C * S - x_pad.shape[1])
        x_pad = jnp.pad(x_pad, pad)
    body = x_pad[:, : C * S].reshape(x_pad.shape[0], C, S, *x_pad.shape[2:])
    tail = x_pad[:, S : S + C * S].reshape(
        x_pad.shape[0], C, S, *x_pad.shape[2:]
    )[:, :, :F]
    return jnp.concatenate([body, tail], axis=2)


def _overlap_add(y: jnp.ndarray, S: int, F: int) -> jnp.ndarray:
    """[B, C, S+F, ...] -> [B, C*S+F, ...]: transpose of _slabs — slab
    columns that alias the same padded position sum. Requires F <= S (so a
    slab overlaps only its immediate successor), guaranteed by resolve_chunk.
    """
    B, C = y.shape[0], y.shape[1]
    rest = y.shape[3:]
    body = y[:, :, :S].reshape(B, C * S, *rest)
    pad_tail = [(0, 0), (0, 0), (0, S - F)] + [(0, 0)] * len(rest)
    tail = jnp.pad(y[:, :, S:], pad_tail).reshape(B, C * S, *rest)
    pad_b = [(0, 0), (0, F)] + [(0, 0)] * len(rest)
    pad_t = [(0, 0), (S, 0)] + [(0, 0)] * len(rest)
    return jnp.pad(body, pad_b) + jnp.pad(tail, pad_t)[:, : C * S + F]


def band_dist(L: int, W: int, S: int) -> np.ndarray:
    """|i - j| over the scores representation, as a static int32 array:
    dense [L, L] or chunked [S, S+2W] (identical for every chunk)."""
    if S == 0:
        i = np.arange(L, dtype=np.int32)
        return np.abs(i[:, None] - i[None, :])
    s = np.arange(S, dtype=np.int32)
    k = np.arange(S + 2 * W, dtype=np.int32)
    return np.abs(s[:, None] + W - k[None, :])


def band_mask(
    keep: jnp.ndarray,
    valid: jnp.ndarray,
    w_eff: jnp.ndarray,
    W: int,
    S: int,
) -> jnp.ndarray:
    """The training-pair mask in scores representation.

    keep/valid/w_eff are [B, L]: center gate, context validity, per-center
    shrunk window (Word2Vec.cpp:282,285-287,332,335-337). Mask is
    keep_i & valid_j & 0 < |i-j| <= w_eff_i.
    """
    L = keep.shape[1]
    dist = jnp.asarray(band_dist(L, W, S))
    if S == 0:
        return (
            keep[:, :, None]
            & valid[:, None, :]
            & (dist[None] <= w_eff[:, :, None])
            & (dist[None] > 0)
        )
    C, P = _geom(L, W, S)
    keep_c = _pad_rows(keep, C * S).reshape(-1, C, S)
    w_c = _pad_rows(w_eff, C * S).reshape(-1, C, S)
    valid_k = _slabs(_pad_ctx(valid, W, P), C, S, 2 * W)  # [B, C, S+2W]
    return (
        keep_c[:, :, :, None]
        & valid_k[:, :, None, :]
        & (dist[None, None] <= w_c[:, :, :, None])
        & (dist[None, None] > 0)
    )


def band_qk(
    a: jnp.ndarray, b: jnp.ndarray, W: int, S: int, cdt, psum=None
) -> jnp.ndarray:
    """scores[i, j] = a_i . b_j over the band: [B,L,d] x [B,L,d] -> scores.

    cdt: MXU compute dtype; accumulation is always f32. psum: optional
    cross-shard reduction applied to the logits (tensor-parallel dim shards).
    """
    if S == 0:
        out = jnp.einsum(
            "bid,bjd->bij",
            a.astype(cdt),
            b.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    else:
        L = a.shape[1]
        C, P = _geom(L, W, S)
        a_c = _pad_rows(a, C * S).reshape(a.shape[0], C, S, a.shape[2])
        b_k = _slabs(_pad_ctx(b, W, P), C, S, 2 * W)  # [B, C, S+2W, d]
        out = jnp.einsum(
            "bcsd,bckd->bcsk",
            a_c.astype(cdt),
            b_k.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    return psum(out) if psum is not None else out


def band_sv(
    scores: jnp.ndarray, v: jnp.ndarray, W: int, S: int, cdt
) -> jnp.ndarray:
    """out_i = sum_j scores[i, j] * v_j : scores x [B,L,...last] -> [B,L,last].

    v may be [B, L, d] (row values) or [B, L, n] (e.g. collision indicators);
    the contraction is over the context axis either way.
    """
    if S == 0:
        return jnp.einsum(
            "bij,bjn->bin",
            scores.astype(cdt),
            v.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    L = v.shape[1]
    C, P = _geom(L, W, S)
    v_k = _slabs(_pad_ctx(v, W, P), C, S, 2 * W)
    out = jnp.einsum(
        "bcsk,bckn->bcsn",
        scores.astype(cdt),
        v_k.astype(cdt),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(out.shape[0], C * S, out.shape[3])[:, :L]


def band_vs(
    scores: jnp.ndarray, u: jnp.ndarray, W: int, S: int, cdt
) -> jnp.ndarray:
    """out_j = sum_i scores[i, j] * u_i : the transposed contraction
    (center-side values fan out to context positions), [B,L,d] -> [B,L,d]."""
    if S == 0:
        return jnp.einsum(
            "bij,bid->bjd",
            scores.astype(cdt),
            u.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    L = u.shape[1]
    C, P = _geom(L, W, S)
    u_c = _pad_rows(u, C * S).reshape(u.shape[0], C, S, u.shape[2])
    y = jnp.einsum(
        "bcsk,bcsd->bckd",
        scores.astype(cdt),
        u_c.astype(cdt),
        preferred_element_type=jnp.float32,
    )  # [B, C, S+2W, d]
    return _overlap_add(y, S, 2 * W)[:, W : W + L]


def band_vs_slab(
    scores: jnp.ndarray, u: jnp.ndarray, W: int, S: int, cdt
) -> jnp.ndarray:
    """band_vs WITHOUT the overlap-add: returns slab-space [B, C, S+2W, d].

    Intended for consumers that scatter-add by token id anyway — the
    scatter's duplicate-index summing performs the overlap-add for free
    (slab slots of adjacent chunks that alias the same position carry the
    same token id, see slab_token_ids). Skips the pad/add/slice chain whose
    layout copies dominate band_vs on TPU (benchmarks/exp_slab_scatter.py).
    Chunked representation only (S > 0).
    """
    if S == 0:
        raise ValueError("band_vs_slab requires the chunked representation")
    L = u.shape[1]
    C, _ = _geom(L, W, S)
    u_c = _pad_rows(u, C * S).reshape(u.shape[0], C, S, u.shape[2])
    return jnp.einsum(
        "bcsk,bcsd->bckd",
        scores.astype(cdt),
        u_c.astype(cdt),
        preferred_element_type=jnp.float32,
    )


def slab_token_ids(tok: jnp.ndarray, W: int, S: int) -> jnp.ndarray:
    """[B, L] token ids -> [B, C, S+2W] id per slab slot; -1 where the slot
    falls outside the row (left halo of chunk 0, beyond-row tail). A padded
    position aliased by two adjacent chunks' slabs gets the same id in both
    slots — scatter-adds over these ids therefore sum exactly the slots
    _overlap_add would have summed."""
    L = tok.shape[1]
    C, P = _geom(L, W, S)
    tok_pad = jnp.pad(tok, ((0, 0), (W, P - L - W)), constant_values=-1)
    return _slabs(tok_pad, C, S, 2 * W)


def band_col_sum_slab(scores: jnp.ndarray) -> jnp.ndarray:
    """Per-slab-slot column sum [B, C, S+2W] (the pre-overlap-add form of
    band_col_sum; pairs with slab_token_ids for by-id accumulation)."""
    return scores.sum(axis=2)


def band_row_sum(scores: jnp.ndarray, L: int) -> jnp.ndarray:
    """sum_j scores[i, j] -> [B, L] (e.g. contexts per center)."""
    if scores.ndim == 3:
        return scores.sum(axis=2)
    out = scores.sum(axis=3)  # [B, C, S]
    return out.reshape(out.shape[0], -1)[:, :L]


def band_col_sum(scores: jnp.ndarray, L: int, W: int, S: int) -> jnp.ndarray:
    """sum_i scores[i, j] -> [B, L] (e.g. centers per context position)."""
    if scores.ndim == 3:
        return scores.sum(axis=1)
    y = scores.sum(axis=2)  # [B, C, S+2W]
    return _overlap_add(y[..., None], S, 2 * W)[:, W : W + L, 0]


def band_loss_sum(masked_vals: jnp.ndarray) -> jnp.ndarray:
    """Global sum over the band plane — identical in both representations
    (each (center, in-window context) pair appears exactly once)."""
    return jnp.sum(masked_vals)
