"""Frequency-derived constant tables, resident in device HBM.

These are the device-side images of the reference's host structures:
  keep_probs   <- Word::sample_probability   (Word.h:14, Word2Vec.cpp:115-130)
  alias_*      <- the 1e8-slot unigram table (Word2Vec.cpp:81-113), replaced
                  by an exact O(V) alias table sampled on device
  hs_codes/points/len <- Word::codes/points  (Word.h:21-22, Word2Vec.cpp:52-78)

Built once per vocabulary and donated to the jit step as captured constants.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config import Word2VecConfig
from ..data.huffman import build_huffman
from ..data.negative import build_alias_table
from ..data.vocab import Vocab


@dataclass
class DeviceTables:
    keep_probs: jnp.ndarray            # [V] f32
    alias_accept: Optional[jnp.ndarray]  # [V] f32 (ns only)
    alias_idx: Optional[jnp.ndarray]     # [V] i32 (ns only)
    hs_codes: Optional[jnp.ndarray]      # [V, Lc] i8  (hs only)
    hs_points: Optional[jnp.ndarray]     # [V, Lc] i32 (hs only)
    hs_len: Optional[jnp.ndarray]        # [V] i32     (hs only)

    @property
    def vocab_size(self) -> int:
        return self.keep_probs.shape[0]

    @property
    def max_code_len(self) -> int:
        return 0 if self.hs_codes is None else self.hs_codes.shape[1]

    @classmethod
    def build(cls, vocab: Vocab, config: Word2VecConfig) -> "DeviceTables":
        keep = jnp.asarray(vocab.keep_probs(config.subsample_threshold))
        alias_accept = alias_idx = None
        hs_codes = hs_points = hs_len = None
        if config.use_ns:
            at = build_alias_table(vocab.unigram_probs(config.ns_power))
            alias_accept = jnp.asarray(at.accept)
            alias_idx = jnp.asarray(at.alias)
        if config.use_hs:
            hc = build_huffman(np.asarray(vocab.counts))
            hs_codes = jnp.asarray(hc.codes.astype(np.int8))
            hs_points = jnp.asarray(hc.points)
            hs_len = jnp.asarray(hc.code_len)
        return cls(keep, alias_accept, alias_idx, hs_codes, hs_points, hs_len)
