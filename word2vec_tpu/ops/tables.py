"""Frequency-derived constant tables, resident in device HBM.

These are the device-side images of the reference's host structures:
  keep_probs   <- Word::sample_probability   (Word.h:14, Word2Vec.cpp:115-130)
  alias_*      <- the 1e8-slot unigram table (Word2Vec.cpp:81-113), replaced
                  by an exact O(V) alias table sampled on device
  hs_codes/points/len <- Word::codes/points  (Word.h:21-22, Word2Vec.cpp:52-78)

Built once per vocabulary and donated to the jit step as captured constants.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config import Word2VecConfig
from ..data.huffman import build_huffman, split_dense_tier
from ..data.negative import build_alias_table
from ..data.vocab import Vocab


@dataclass
class DeviceTables:
    keep_probs: jnp.ndarray            # [V] f32
    alias_accept: Optional[jnp.ndarray]  # [V] f32 (ns only)
    alias_idx: Optional[jnp.ndarray]     # [V] i32 (ns only)
    hs_codes: Optional[jnp.ndarray]      # [V, Lc] i8  (hs only)
    hs_points: Optional[jnp.ndarray]     # [V, Lc] i32 (hs only)
    hs_len: Optional[jnp.ndarray]        # [V] i32     (hs only)
    # two-tier hs split (config.hs_dense_top > 0; data/huffman.py
    # split_dense_tier): signed multi-hot over the top-P node slice, padded
    # per-word path tails, and host-side tail-length stats for sizing
    # compacted tail buffers
    hs_msig: Optional[jnp.ndarray] = None         # [V, P] i8 in {-1,0,+1}
    hs_tail_codes: Optional[jnp.ndarray] = None   # [V, Ct] i8
    hs_tail_points: Optional[jnp.ndarray] = None  # [V, Ct] i32
    hs_tail_len: Optional[jnp.ndarray] = None     # [V] i32
    hs_tail_mean: float = 0.0
    hs_tail_var: float = 0.0
    hs_dense_coverage: float = 0.0

    @property
    def vocab_size(self) -> int:
        return self.keep_probs.shape[0]

    @property
    def max_code_len(self) -> int:
        return 0 if self.hs_codes is None else self.hs_codes.shape[1]

    @classmethod
    def build(cls, vocab: Vocab, config: Word2VecConfig) -> "DeviceTables":
        keep = jnp.asarray(vocab.keep_probs(config.subsample_threshold))
        alias_accept = alias_idx = None
        hs_codes = hs_points = hs_len = None
        if config.use_ns:
            at = build_alias_table(vocab.unigram_probs(config.ns_power))
            alias_accept = jnp.asarray(at.accept)
            alias_idx = jnp.asarray(at.alias)
        msig = tail_codes = tail_points = tail_len = None
        tail_mean = tail_var = coverage = 0.0
        if config.use_hs:
            hc = build_huffman(np.asarray(vocab.counts))
            hs_codes = jnp.asarray(hc.codes.astype(np.int8))
            hs_points = jnp.asarray(hc.points)
            hs_len = jnp.asarray(hc.code_len)
            if config.hs_dense_top > 0:
                split = split_dense_tier(
                    hc, np.asarray(vocab.counts), config.hs_dense_top
                )
                msig = jnp.asarray(split.msig)
                tail_codes = jnp.asarray(split.tail_codes.astype(np.int8))
                tail_points = jnp.asarray(split.tail_points)
                tail_len = jnp.asarray(split.tail_len)
                tail_mean = split.tail_mean
                tail_var = split.tail_var
                coverage = split.coverage
        return cls(
            keep, alias_accept, alias_idx, hs_codes, hs_points, hs_len,
            hs_msig=msig, hs_tail_codes=tail_codes,
            hs_tail_points=tail_points, hs_tail_len=tail_len,
            hs_tail_mean=tail_mean, hs_tail_var=tail_var,
            hs_dense_coverage=coverage,
        )
