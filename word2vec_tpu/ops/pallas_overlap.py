"""Pallas TPU overlap-add kernel: slab-space context gradients -> token order.

The XLA band chain realizes the context-gradient overlap-add
(ops/banded._overlap_add: the transpose of the slab extraction) as a
pad/reshape/add/slice composition over [B, C, S+2W, d]. XLA's layout
assignment inserts {0,2,1}<->{2,1,0} copies around that chain, and the r2
on-chip trace measured them as the LARGEST single component of the band step
— 2.14 ms of 7.97 ms, 26.9% (PERF.md "Step-time composition"), ~7x what the
raw bytes would cost at streaming bandwidth. The one attack tried before
this kernel, config.slab_scatter, deleted the copies by scattering from
slab space and LOST on chip (2.26M vs 3.64M words/sec): it traded the
copies for a scatter off the sorted-indices fast path, and v2's repair (a
second argsort over 1.33x the token count) pays the sort instead.

This kernel takes the third path PERF.md names ("accepting them or a Pallas
overlap-add"): perform the windowed overlap-add reduction itself, in VMEM,
one (batch row, band chunk) tile per grid step, and emit the context deltas
directly in TOKEN order — the order the sorted table scatter already has an
argsort for. The layout-copy chain never materializes in HBM, the scatter
keeps its sorted-indices fast path, and no extra sort is paid.

The reduction (chunk-coordinate invariant of ops/banded.py): slab slot k of
chunk c holds padded position p = c*S + k, token i sits at p = i + W, so
token block c (rows i in [c*S, c*S + S)) receives

    out[b, c, s] =            y[b, c,   s + W]            (own chunk)
                 + (s <  W) * y[b, c-1, s + W + S]        (left neighbor)
                 + (s >= S-W) * y[b, c+1, s + W - S]      (right neighbor)

Because the slab decomposition guarantees S >= 2W (ops/banded.resolve_chunk)
the two neighbor terms are disjoint: every token row sums exactly the <= 2
slab slots that alias its padded position — the same pairs _overlap_add
sums, so the result is bitwise identical in f32 (two-operand float addition
is commutative). Pinned against the XLA chain by tests/test_pallas_overlap.py.

The neighbor blocks arrive as two extra views of the SAME input array with
shifted (clamped) block index maps; boundary chunks zero their missing
neighbor by a program_id gate. Per grid step the working set is three
[S+2W, d] blocks plus one [S, d] output — a few hundred KB at the flagship
shape, far inside VMEM.

Scope: any consumer of slab-space [B, C, S+2W, d] f32 gradients. Wired as
config.band_backend='pallas_oa' (ops/band_step.py): the XLA band compute
path with this kernel replacing the _overlap_add chain — which keeps every
tail feature of the XLA step (fused_tables, bf16 tables +- stochastic
rounding, scatter_mean, clip, both negative scopes) available, unlike the
fully-fused 'pallas' backend. Single-chip only, same as every Pallas path
here (shard_map cannot host pallas_call — parallel/trainer._reject_pallas).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _oa_kernel(y_ref, yl_ref, yr_ref, out_ref, *, W: int, S: int, C: int):
    """One (batch row, chunk) tile of the token-space overlap-add.

    y_ref/yl_ref/yr_ref are three views of the same [B, C, S+2W, d] array:
    this chunk, its left neighbor, its right neighbor (block indices clamped
    at the edges; the gates below zero the out-of-range terms).
    """
    c = pl.program_id(1)
    body = y_ref[0, 0, W:S + W, :]            # own slots [W, S+W) -> rows 0..S
    lsl = yl_ref[0, 0, S + W:, :]             # left slots [S+W, S+2W) -> rows [0, W)
    rsl = yr_ref[0, 0, :W, :]                 # right slots [0, W) -> rows [S-W, S)
    d = body.shape[1]
    zeros = jnp.zeros((S - W, d), body.dtype)
    lpart = jnp.concatenate([lsl, zeros], axis=0)
    rpart = jnp.concatenate([zeros, rsl], axis=0)
    lgate = jnp.where(c > 0, 1.0, 0.0).astype(body.dtype)
    rgate = jnp.where(c < C - 1, 1.0, 0.0).astype(body.dtype)
    out_ref[0, 0] = body + lgate * lpart + rgate * rpart


@functools.partial(jax.jit, static_argnames=("W", "S", "interpret"))
def overlap_add_slabs(
    y: jnp.ndarray, *, W: int, S: int, interpret: bool = False
) -> jnp.ndarray:
    """[B, C, S+2W, d] slab-space values -> [B, C*S, d] token-space sums.

    Token row i = c*S + s of the output is the overlap-add of every slab
    slot aliasing padded position i + W (module docstring); rows past the
    caller's L (the C*S padding tail) carry the reduction of padding slots
    and must be sliced off (overlap_add_tokens does).
    """
    B, C, SK, d = y.shape
    if SK != S + 2 * W:
        raise ValueError(f"slab width {SK} != S + 2W = {S + 2 * W}")
    if S < 2 * W:
        # a slab would overlap beyond its immediate neighbors and the
        # two-term reduction above would drop contributions
        raise ValueError(f"S={S} < 2W={2 * W}: not a valid slab decomposition")

    def bc(i, j):
        return (i, j, 0, 0)

    def bl(i, j):
        return (i, jnp.maximum(j - 1, 0), 0, 0)

    def br(i, j):
        return (i, jnp.minimum(j + 1, C - 1), 0, 0)

    out = pl.pallas_call(
        functools.partial(_oa_kernel, W=W, S=S, C=C),
        grid=(B, C),
        in_specs=[
            pl.BlockSpec((1, 1, SK, d), bc),
            pl.BlockSpec((1, 1, SK, d), bl),
            pl.BlockSpec((1, 1, SK, d), br),
        ],
        out_specs=pl.BlockSpec((1, 1, S, d), bc),
        out_shape=jax.ShapeDtypeStruct((B, C, S, d), y.dtype),
        interpret=interpret,
    )(y, y, y)
    return out.reshape(B, C * S, d)


def overlap_add_tokens(
    y: jnp.ndarray, *, W: int, S: int, L: int, interpret: bool = False
) -> jnp.ndarray:
    """Drop-in for ops/banded.band_vs's overlap-add tail: slab-space
    [B, C, S+2W, d] -> per-token [B, L, d], via the Pallas kernel. The
    [:, :L] slice is a contiguous (layout-preserving) slice XLA fuses into
    the consumer — no transpose chain."""
    return overlap_add_slabs(y, W=W, S=S, interpret=interpret)[:, :L]
