"""Positional hierarchical-softmax training step: the fast path for hs.

The pair kernel (ops/train_step.py) enumerates (center, context) pairs and
gathers/scatters each context word's Huffman path rows once PER PAIR —
[P, C, d] traffic with P = B*L*2W. But a position's path is the same for
every center that predicts it, so this kernel:

  sg+hs   — gathers each position's path rows ONCE ([B, L, C, d], C = padded
            code length) and sweeps the window with 2W static shifted slices
            (the j-loop of Word2Vec.cpp:339-345 becomes a static offset
            loop over views of one padded tensor): per offset o,
            logit[b,i,c] = h_i . syn1[points[tok_{i+o}], c], with the
            reference's label 1-code and per-node mask. Path-row gradients
            accumulate positionally in the padded buffer, so the final
            scatter writes B*(L+2W)*C aggregated rows — 2W x fewer gather
            and scatter rows than the pair kernel.
  cbow+hs — no offset sweep at all: targets are the CENTER's own path
            (Word2Vec.cpp:304-309 with hs), so one gather, one [B, L, C]
            logit einsum, one scatter; the projection h is the banded
            context sum/mean exactly as in ops/band_step.py.

Update-rule semantics are reference-exact (same per-pair math as the pair
kernel, Word2Vec.cpp:232-249): only the gather/scatter aggregation is
restructured, so this kernel must agree with the pair kernel bitwise-modulo
f32 reassociation — pinned by tests/test_hs_step_golden.py, including
scatter_mean (the per-row contribution counts are identical sums).

RNG streams match the pair kernel exactly: same key split, same (B, L) draw
shapes for the subsample gate and window shrink, and hs draws no negatives —
which is what makes exact cross-kernel agreement possible at any window.

Mesh axes: tp_axis shards the embedding dim (logit einsums psum'd before the
sigmoid); dp_axis folds the PRNG key per shard. Sequence parallelism is not
implemented for hs (ShardedTrainer validates sp requires the ns band kernel).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import Word2VecConfig
from ..models.params import Params
from . import banded
from .tables import DeviceTables
from .train_step import (
    _cast_update, _dup_mean_scale, _row_clip_scale, _sr_streams,
)

Metrics = Dict[str, jnp.ndarray]


def make_hs_train_step(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
) -> Callable[[Params, jnp.ndarray, jax.Array, jnp.ndarray], Tuple[Params, Metrics]]:
    """step(params, tokens[B,L], key, alpha) -> (params, metrics).

    Same contract as train_step.make_train_step; hierarchical softmax only.
    """
    if not config.use_hs or config.use_ns:
        raise ValueError("hs kernel supports hierarchical softmax only")
    W = config.window
    is_cbow = config.model == "cbow"
    cbow_mean = config.cbow_mean
    scatter_mean = config.scatter_mean
    # per-row trust region (train_step._row_clip_scale). hs needs it even
    # more than ns: the Huffman ROOT node sits on EVERY word's path, so its
    # syn1 row accumulates the entire batch's path gradients in one scatter
    clip_tau = config.clip_row_update
    sr = config.stochastic_rounding
    cdt = jnp.dtype(config.compute_dtype)

    def psum(x):
        return jax.lax.psum(x, tp_axis) if tp_axis is not None else x

    def step(
        params: Params, tokens: jnp.ndarray, key: jax.Array, alpha: jnp.ndarray
    ) -> Tuple[Params, Metrics]:
        B, L = tokens.shape
        if dp_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
        k_sub, k_win, _ = jax.random.split(key, 3)
        k_sr = _sr_streams(key, sr)

        valid = tokens >= 0
        tok = jnp.where(valid, tokens, 0)
        keep = valid & (jax.random.uniform(k_sub, (B, L)) < tables.keep_probs[tok])
        w_eff = W - jax.random.randint(k_win, (B, L), 0, W, dtype=jnp.int32)

        emb_in = params["emb_in"]
        syn1 = params["emb_out_hs"]
        C = tables.hs_points.shape[1]
        clip_count = jnp.float32(0.0)  # rows the trust region engaged on

        if not is_cbow:
            # ---- skip-gram: h = center row; targets = each context's path.
            h = emb_in[tok]  # [B, L, d]
            # padded position axis: q = j + W for context position j
            tok_pad = jnp.pad(tokens, ((0, 0), (W, W)), constant_values=-1)
            vpad = tok_pad >= 0
            tpad = jnp.where(vpad, tok_pad, 0)
            paths = tables.hs_points[tpad]  # [B, L+2W, C]
            codes = tables.hs_codes[tpad]   # [B, L+2W, C]
            cmask = (
                jnp.arange(C, dtype=jnp.int32)[None, None, :]
                < tables.hs_len[tpad][:, :, None]
            ) & vpad[:, :, None]            # [B, L+2W, C]
            rows = syn1[paths]              # [B, L+2W, C, d] — ONE gather

            d_h = jnp.zeros(h.shape, jnp.float32)
            d_rows = jnp.zeros(rows.shape, jnp.float32)
            loss = jnp.float32(0.0)
            pairs = jnp.float32(0.0)
            ctx_hit = jnp.zeros((B, L), bool)  # any active pair per center
            out_touch = jnp.zeros((B, L + 2 * W, C), jnp.float32)
            for o in [o for o in range(-W, W + 1) if o != 0]:
                sl = slice(W + o, W + o + L)  # context j = i + o, padded coords
                pair_ok = keep & vpad[:, sl] & (abs(o) <= w_eff)  # [B, L]
                m = (pair_ok[:, :, None] & cmask[:, sl]).astype(jnp.float32)
                logit = psum(
                    jnp.einsum(
                        "bid,bicd->bic",
                        h.astype(cdt),
                        rows[:, sl].astype(cdt),
                        preferred_element_type=jnp.float32,
                    )
                )  # [B, L, C]
                # g = (1 - code - f) * alpha (Word2Vec.cpp:241-242)
                label = 1.0 - codes[:, sl].astype(jnp.float32)
                g = (label - jax.nn.sigmoid(logit)) * m * alpha
                d_h = d_h + jnp.einsum(
                    "bic,bicd->bid",
                    g.astype(cdt),
                    rows[:, sl].astype(cdt),
                    preferred_element_type=jnp.float32,
                )
                d_rows = d_rows.at[:, sl].add(
                    jnp.einsum(
                        "bic,bid->bicd",
                        g.astype(cdt),
                        h.astype(cdt),
                        preferred_element_type=jnp.float32,
                    )
                )
                ls = jax.nn.log_sigmoid(logit)
                loss += -jnp.sum(m * jnp.where(label > 0.5, ls, ls - logit))
                pairs += jnp.sum(m)
                ctx_hit = ctx_hit | pair_ok
                if scatter_mean:
                    out_touch = out_touch.at[:, sl].add(m)

            # center rows: W.row(center) += accumulated grad (:351)
            flat_c = tok.reshape(-1)
            vals = d_h.reshape(B * L, -1)
            if scatter_mean:
                vals = vals * _dup_mean_scale(
                    emb_in.shape[0], flat_c,
                    ctx_hit.reshape(-1).astype(jnp.float32),
                )[:, None]
            if clip_tau > 0.0:
                scale = _row_clip_scale(
                    emb_in.shape[0], clip_tau, (flat_c, vals),
                    tp_axis=tp_axis,
                )
                clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                vals = vals * scale[flat_c][:, None]
            new_in = emb_in.at[flat_c].add(
                _cast_update(
                    vals, emb_in.dtype, k_sr(0),
                    emb_in[flat_c] if sr else None,
                )
            )

            # path rows: one aggregated scatter over the padded positions
            flat_p = paths.reshape(-1)
            order = jnp.argsort(flat_p)
            d_rows_flat = d_rows.reshape(-1, d_rows.shape[-1])[order]
            if scatter_mean:
                d_rows_flat = d_rows_flat * _dup_mean_scale(
                    syn1.shape[0], flat_p[order], out_touch.reshape(-1)[order]
                )[:, None]
            if clip_tau > 0.0:
                scale = _row_clip_scale(
                    syn1.shape[0], clip_tau, (flat_p[order], d_rows_flat),
                    tp_axis=tp_axis,
                )
                clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                d_rows_flat = d_rows_flat * scale[flat_p[order]][:, None]
            new_out = syn1.at[flat_p[order]].add(
                _cast_update(
                    d_rows_flat, syn1.dtype, k_sr(1),
                    syn1[flat_p[order]] if sr else None,
                ),
                indices_are_sorted=True,
            )
        else:
            # ---- CBOW: h = (mean of) context rows; targets = center's path.
            # Band contractions use the window-blocked representation
            # (ops/banded.py) — cost L*(S+2W), not L^2.
            S = banded.resolve_chunk(L, W, config.band_chunk)
            band_f = banded.band_mask(keep, valid, w_eff, W, S).astype(
                jnp.float32
            )
            n_ctx = banded.band_row_sum(band_f, L)
            ein = emb_in[tok]  # [B, L, d]
            h = banded.band_sv(band_f, ein, W, S, cdt)
            if cbow_mean:
                h = h / jnp.maximum(n_ctx, 1.0)[:, :, None]

            paths = tables.hs_points[tok]  # [B, L, C]
            codes = tables.hs_codes[tok]
            active = keep & (n_ctx > 0)    # skip centers without context, :289
            cmask = (
                jnp.arange(C, dtype=jnp.int32)[None, None, :]
                < tables.hs_len[tok][:, :, None]
            ) & active[:, :, None]
            rows = syn1[paths]             # [B, L, C, d]
            logit = psum(
                jnp.einsum(
                    "bid,bicd->bic",
                    h.astype(cdt),
                    rows.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
            )
            m = cmask.astype(jnp.float32)
            label = 1.0 - codes.astype(jnp.float32)
            g = (label - jax.nn.sigmoid(logit)) * m * alpha
            d_h = jnp.einsum(
                "bic,bicd->bid",
                g.astype(cdt),
                rows.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            d_rows = jnp.einsum(
                "bic,bid->bicd",
                g.astype(cdt),
                h.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            ls = jax.nn.log_sigmoid(logit)
            loss = -jnp.sum(m * jnp.where(label > 0.5, ls, ls - logit))
            pairs = jnp.sum(m)

            # fan d_h to context rows (second /n under cbow_mean, :313-315)
            if cbow_mean:
                d_h = d_h / jnp.maximum(n_ctx, 1.0)[:, :, None]
            if config.slab_scatter and S > 0:
                # slab-space scatter: the table scatter's duplicate-index
                # summing performs the overlap-add (band_step.py, same knob).
                # v2: the slab ids get their own argsort so this scatter
                # keeps XLA's sorted fast path too (band_step.py rationale).
                d_in_slab = banded.band_vs_slab(band_f, d_h, W, S, cdt)
                slab_ids = banded.slab_token_ids(tok, W, S)
                ok = slab_ids >= 0
                slab_flat = jnp.where(ok, slab_ids, 0).reshape(-1)
                sorder = jnp.argsort(slab_flat)
                sflat = slab_flat[sorder]
                vals = jnp.where(ok[..., None], d_in_slab, 0.0).reshape(
                    -1, d_in_slab.shape[-1]
                )[sorder]
                if scatter_mean:
                    w = jnp.where(
                        ok, banded.band_col_sum_slab(band_f), 0.0
                    ).reshape(-1)[sorder]
                    vals = vals * _dup_mean_scale(
                        emb_in.shape[0], sflat, w
                    )[:, None]
                if clip_tau > 0.0:
                    scale = _row_clip_scale(
                        emb_in.shape[0], clip_tau, (sflat, vals),
                        tp_axis=tp_axis,
                    )
                    clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                    vals = vals * scale[sflat][:, None]
                new_in = emb_in.at[sflat].add(
                    _cast_update(
                        vals, emb_in.dtype, k_sr(0),
                        emb_in[sflat] if sr else None,
                    ),
                    indices_are_sorted=True,
                )
            else:
                d_in_pos = banded.band_vs(band_f, d_h, W, S, cdt)
                flat_c = tok.reshape(-1)
                order = jnp.argsort(flat_c)
                d_in_flat = d_in_pos.reshape(-1, d_in_pos.shape[-1])[order]
                if scatter_mean:
                    d_in_flat = d_in_flat * _dup_mean_scale(
                        emb_in.shape[0], flat_c[order],
                        banded.band_col_sum(band_f, L, W, S).reshape(-1)[order],
                    )[:, None]
                if clip_tau > 0.0:
                    scale = _row_clip_scale(
                        emb_in.shape[0], clip_tau, (flat_c[order], d_in_flat),
                        tp_axis=tp_axis,
                    )
                    clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                    d_in_flat = d_in_flat * scale[flat_c[order]][:, None]
                new_in = emb_in.at[flat_c[order]].add(
                    _cast_update(
                        d_in_flat, emb_in.dtype, k_sr(0),
                        emb_in[flat_c[order]] if sr else None,
                    ),
                    indices_are_sorted=True,
                )

            flat_p = paths.reshape(-1)
            porder = jnp.argsort(flat_p)
            d_rows_flat = d_rows.reshape(-1, d_rows.shape[-1])[porder]
            if scatter_mean:
                d_rows_flat = d_rows_flat * _dup_mean_scale(
                    syn1.shape[0], flat_p[porder], m.reshape(-1)[porder]
                )[:, None]
            if clip_tau > 0.0:
                scale = _row_clip_scale(
                    syn1.shape[0], clip_tau, (flat_p[porder], d_rows_flat),
                    tp_axis=tp_axis,
                )
                clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                d_rows_flat = d_rows_flat * scale[flat_p[porder]][:, None]
            new_out = syn1.at[flat_p[porder]].add(
                _cast_update(
                    d_rows_flat, syn1.dtype, k_sr(1),
                    syn1[flat_p[porder]] if sr else None,
                ),
                indices_are_sorted=True,
            )

        new_params = dict(params)
        new_params["emb_in"] = new_in
        new_params["emb_out_hs"] = new_out
        return new_params, {
            "loss_sum": loss,
            "pairs": pairs,
            "clip_engaged": clip_count,
        }

    return step
