"""Positional hierarchical-softmax training step: the fast path for hs.

The pair kernel (ops/train_step.py) enumerates (center, context) pairs and
gathers/scatters each context word's Huffman path rows once PER PAIR —
[P, C, d] traffic with P = B*L*2W. But a position's path is the same for
every center that predicts it, so this kernel:

  sg+hs   — gathers each position's path rows ONCE ([B, L, C, d], C = padded
            code length) and sweeps the window with 2W static shifted slices
            (the j-loop of Word2Vec.cpp:339-345 becomes a static offset
            loop over views of one padded tensor): per offset o,
            logit[b,i,c] = h_i . syn1[points[tok_{i+o}], c], with the
            reference's label 1-code and per-node mask. Path-row gradients
            accumulate positionally in the padded buffer, so the final
            scatter writes B*(L+2W)*C aggregated rows — 2W x fewer gather
            and scatter rows than the pair kernel.
  cbow+hs — no offset sweep at all: targets are the CENTER's own path
            (Word2Vec.cpp:304-309 with hs), so one gather, one [B, L, C]
            logit einsum, one scatter; the projection h is the banded
            context sum/mean exactly as in ops/band_step.py.

Two-tier update (config.hs_dense_top = P > 0): Huffman node ids decrease
monotonically along every root->leaf path (data/huffman.py), so the top-P
ids — the most-frequented top of the tree, ~73% of token-weighted path
entries at P=512 on a zipf-71k vocab — are simultaneously (a) a PREFIX of
every path and (b) a CONTIGUOUS top slice syn1[V-1-P:]. The kernel exploits
both:

  dense tier — all prefix entries collapse into matmuls. The per-pair-entry
    gradient g = (label - sigmoid(logit)) * alpha has a logit h_i . n_p that
    depends only on (center, node), so summing over the window/batch
    linearizes in the label: with F[b,i,p] = h_i . top_p (one matmul),
    A/N = window-summed counts of positive-label/any activations of node p
    around center i (two band matmuls over the per-word signed multi-hot
    tables.hs_msig), the SUMMED gradient is G = alpha * (A - sigmoid(F)*N).
    d_h and the tier's table update are two more matmuls, and the update
    lands as ONE contiguous slice add — the tier needs no gather, no
    scatter, and no per-offset work at all.
  tail tier — the short per-word remainders (tables.hs_tail_*, ~13 padded
    slots vs ~25 full-path) run through the SAME positional sweep/scatter
    machinery as the one-tier path (the helpers below are parameterized by
    the path tables), optionally compacting the scatter to the slots that
    actually received gradient (config.hs_tail_slots; overflow beyond the
    +6-sigma auto bound drops those slots' updates and reports
    hs_tail_dropped).

  The tiers PARTITION the rows of syn1 (a node id is either in the top
  slice or not), so the per-row trust region, scatter_mean normalization,
  and SR destination grids each see complete per-row updates in exactly
  one tier — semantics stay one-tier-exact, pinned by
  tests/test_hs_dense.py.

Update-rule semantics are reference-exact (same per-pair math as the pair
kernel, Word2Vec.cpp:232-249): only the gather/scatter aggregation is
restructured, so this kernel must agree with the pair kernel bitwise-modulo
f32 reassociation — pinned by tests/test_hs_step_golden.py, including
scatter_mean (the per-row contribution counts are identical sums).

RNG streams match the pair kernel exactly: same key split, same (B, L) draw
shapes for the subsample gate and window shrink, and hs draws no negatives —
which is what makes exact cross-kernel agreement possible at any window.

Mesh axes: tp_axis shards the embedding dim (logit einsums psum'd before the
sigmoid); dp_axis folds the PRNG key per shard. sp_axis adds sequence
(context) parallelism exactly like the ns band kernel (band_step.py): tokens
[B, L] are sharded along L, each shard halo-exchanges `window` edge tokens
with its neighbors over ICI (band_step._halo_exchange), and halo positions
are context-only (their center direction is owned by the neighboring shard),
so every directed (center, context) pair — and therefore every path-entry
update — is trained exactly once across shards. hs draws no negatives, so
with the window shrink and subsample pinned the sum of per-shard deltas
reproduces the single-chip update exactly (tests/test_hs_dense.py). Like ns,
the per-row trust region under sp sees shard-local contributions only.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import Word2VecConfig
from ..models.params import Params
from . import banded
from .band_step import _halo_exchange
from .tables import DeviceTables
from .train_step import (
    _cast_update, _dup_mean_scale, _row_clip_scale, _sr_streams,
)

Metrics = Dict[str, jnp.ndarray]


def resolve_tail_slots(
    config: Word2VecConfig, tables: DeviceTables, L: int, slots: int
) -> int:
    """Compacted tail-scatter bound T for a batch row of L positions with
    `slots` padded tail slots; 0 = compaction off (scatter every slot).

    Auto (-1): E[touched slots] + 6 sigma under the vocab's unigram
    tail-length stats — at most L positions contribute tail_len slots each,
    so mean L*mu and (independence approximation) variance L*var. The +Ct
    headroom covers tiny-L cases where the normal approximation is poor.

    The variance term assumes tail lengths are INDEPENDENT across a row's
    positions. Real corpora are bursty/topically correlated (a rare-word
    run inflates many positions' tails together), so overflow can occur
    more often than "statistically never" — and when it does, drops are
    deterministic in slot order, biasing against late positions. Two
    mitigations: the per-chunk hs_tail_dropped metric banks in every bench
    record and training log, and the training driver warns when it is
    persistently nonzero (Trainer._note_tail_dropped) — the fix then is a
    larger explicit hs_tail_slots or hs_tail_slots=0 (compaction off).
    """
    if config.hs_tail_slots == 0 or slots == 0:
        return 0
    if config.hs_tail_slots > 0:
        # a bound covering every slot can't drop anything — skip the
        # compaction sort/gather entirely, like the auto path below
        return 0 if config.hs_tail_slots >= slots else config.hs_tail_slots
    Ct = tables.hs_tail_codes.shape[1]
    exp = L * tables.hs_tail_mean
    sd = math.sqrt(max(L * tables.hs_tail_var, 0.0))
    T = int(math.ceil(exp + 6.0 * sd)) + Ct
    return 0 if T >= slots else T


def make_hs_train_step(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
    sp_axis: str | None = None,
) -> Callable[[Params, jnp.ndarray, jax.Array, jnp.ndarray], Tuple[Params, Metrics]]:
    """step(params, tokens[B,L], key, alpha) -> (params, metrics).

    Same contract as train_step.make_train_step; hierarchical softmax only.
    """
    if not config.use_hs or config.use_ns:
        raise ValueError("hs kernel supports hierarchical softmax only")
    if getattr(config, "table_layout", "split") == "unified":
        # defense in depth (config validation rejects this combination up
        # front): the unified [V, 2, d] slab holds {emb_in, emb_out_ns};
        # hs params are {emb_in, emb_out_hs} and emb_out_hs has V-1 rows —
        # there is no unified form to dispatch on
        raise ValueError(
            "table_layout='unified' applies to the ns band kernel only; "
            "hs params have no [V, 2, d] form (models/params.py)"
        )
    W = config.window
    is_cbow = config.model == "cbow"
    cbow_mean = config.cbow_mean
    scatter_mean = config.scatter_mean
    # per-row trust region (train_step._row_clip_scale). hs needs it even
    # more than ns: the Huffman ROOT node sits on EVERY word's path, so its
    # syn1 row accumulates the entire batch's path gradients in one scatter
    clip_tau = config.clip_row_update
    sr = config.stochastic_rounding
    cdt = jnp.dtype(config.compute_dtype)
    two_tier = tables.hs_msig is not None
    P = tables.hs_msig.shape[1] if two_tier else 0
    Ct = tables.hs_tail_codes.shape[1] if two_tier else 0

    def psum(x):
        return jax.lax.psum(x, tp_axis) if tp_axis is not None else x

    def dense_tier(h, A, N, syn1, alpha):
        """The top-slice tier: logits, gradients, loss — all matmuls.

        h [B,L,d] projections; A/N [B,L,P] summed positive-label/any
        activation counts of each top node over h's training pairs (already
        gated by keep/valid/window/active). Returns (d_h_dense [B,L,d],
        d_top [P,d] scaled by clip/scatter_mean, loss, pairs, clip_count).
        """
        top0 = syn1.shape[0] - P
        syn1_top = syn1[top0:]
        F = psum(
            jnp.einsum(
                "bid,pd->bip",
                h.astype(cdt),
                syn1_top.astype(cdt),
                preferred_element_type=jnp.float32,
            )
        )
        sigF = jax.nn.sigmoid(F)
        G = (A - sigF * N) * alpha
        d_h = jnp.einsum(
            "bip,pd->bid",
            G.astype(cdt),
            syn1_top.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        d_top = jnp.einsum(
            "bip,bid->pd",
            G.astype(cdt),
            h.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        lsF = jax.nn.log_sigmoid(F)
        loss = -(jnp.sum(A * lsF) + jnp.sum((N - A) * (lsF - F)))
        pairs = jnp.sum(N)
        clip_count = jnp.float32(0.0)
        mean_inv = None
        if scatter_mean:  # mean before clip, same order as the scatter paths
            cnt = jnp.sum(N, axis=(0, 1))  # contributions per top row
            mean_inv = 1.0 / jnp.maximum(cnt, 1.0)
            d_top = d_top * mean_inv[:, None]
        if clip_tau > 0.0:
            # triangle bound at PER-PAIR-ENTRY granularity (the pair
            # kernel's _row_clip_scale contribution set): S_p =
            # sum_entries ||g * h_i|| = sum |g| * ||h_i||, with
            # sum_entries |g| linearizing exactly like G does — label-1
            # entries contribute (1-sigF), label-0 entries sigF. The
            # positional one-tier kernel sums per SLOT (across-offset sums
            # taken before the norm), a coarser bound; the per-pair bound
            # is >= it, so the dense tier engages no later — differences
            # appear only when the trust region is actively reshaping a row
            hsq = jnp.sum(h.astype(jnp.float32) ** 2, axis=-1)
            if tp_axis is not None:
                hsq = jax.lax.psum(hsq, tp_axis)
            absg = (A * (1.0 - sigF) + (N - A) * sigF) * alpha
            s_p = jnp.einsum(
                "bip,bi->p", absg, jnp.sqrt(hsq),
                preferred_element_type=jnp.float32,
            )
            if mean_inv is not None:
                s_p = s_p * mean_inv
            scale = clip_tau / jnp.maximum(s_p, clip_tau)
            clip_count = jnp.sum((scale < 1.0).astype(jnp.float32))
            d_top = d_top * scale[:, None]
        return d_h, d_top, loss, pairs, clip_count

    def sg_sweep(h, tokens, keep, w_eff, syn1, alpha, pts, cds, lens, Cx):
        """The sg positional offset sweep over one set of path tables
        (full-path or tail-tier): per offset o, score/update every active
        (center i, context i+o) pair against the context's path entries.

        Returns (paths [B,Q,Cx], d_rows [B,Q,Cx,d], touched, out_touch,
        d_h [B,L,d], loss, pairs, ctx_hit [B,L]).
        """
        B, L = tokens.shape
        tok_pad = jnp.pad(tokens, ((0, 0), (W, W)), constant_values=-1)
        vpad = tok_pad >= 0
        tpad = jnp.where(vpad, tok_pad, 0)
        paths = pts[tpad]  # [B, Q, Cx]
        codes = cds[tpad]
        cmask = (
            jnp.arange(Cx, dtype=jnp.int32)[None, None, :]
            < lens[tpad][:, :, None]
        ) & vpad[:, :, None]
        rows = syn1[paths]  # [B, Q, Cx, d] — ONE gather

        d_h = jnp.zeros(h.shape, jnp.float32)
        d_rows = jnp.zeros(rows.shape, jnp.float32)
        loss = jnp.float32(0.0)
        pairs = jnp.float32(0.0)
        ctx_hit = jnp.zeros((B, L), bool)  # any active pair per center
        touched = jnp.zeros(paths.shape, bool)
        out_touch = jnp.zeros(paths.shape, jnp.float32)
        for o in [o for o in range(-W, W + 1) if o != 0]:
            sl = slice(W + o, W + o + L)  # context j = i + o, padded coords
            pair_ok = keep & vpad[:, sl] & (abs(o) <= w_eff)  # [B, L]
            m = (pair_ok[:, :, None] & cmask[:, sl]).astype(jnp.float32)
            logit = psum(
                jnp.einsum(
                    "bid,bicd->bic",
                    h.astype(cdt),
                    rows[:, sl].astype(cdt),
                    preferred_element_type=jnp.float32,
                )
            )  # [B, L, Cx]
            # g = (1 - code - f) * alpha (Word2Vec.cpp:241-242)
            label = 1.0 - codes[:, sl].astype(jnp.float32)
            g = (label - jax.nn.sigmoid(logit)) * m * alpha
            d_h = d_h + jnp.einsum(
                "bic,bicd->bid",
                g.astype(cdt),
                rows[:, sl].astype(cdt),
                preferred_element_type=jnp.float32,
            )
            d_rows = d_rows.at[:, sl].add(
                jnp.einsum(
                    "bic,bid->bicd",
                    g.astype(cdt),
                    h.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
            )
            ls = jax.nn.log_sigmoid(logit)
            loss += -jnp.sum(m * jnp.where(label > 0.5, ls, ls - logit))
            pairs += jnp.sum(m)
            ctx_hit = ctx_hit | pair_ok
            # unused outputs (touched in one-tier, out_touch without
            # scatter_mean) are dead code XLA eliminates under jit
            touched = touched.at[:, sl].set(touched[:, sl] | (m > 0))
            if scatter_mean:
                out_touch = out_touch.at[:, sl].add(m)
        return paths, d_rows, touched, out_touch, d_h, loss, pairs, ctx_hit

    def cbow_path_block(h, tok, gate, syn1, alpha, pts, cds, lens, Cx):
        """One cbow sigmoid-SGD block against one set of path tables:
        targets are the center's own path entries (no offset sweep).

        Returns (paths [B,L,Cx], d_rows, m, d_h_add, loss, pairs).
        """
        paths = pts[tok]  # [B, L, Cx]
        codes = cds[tok]
        cmask = (
            jnp.arange(Cx, dtype=jnp.int32)[None, None, :]
            < lens[tok][:, :, None]
        ) & gate[:, :, None]
        rows = syn1[paths]             # [B, L, Cx, d]
        logit = psum(
            jnp.einsum(
                "bid,bicd->bic",
                h.astype(cdt),
                rows.astype(cdt),
                preferred_element_type=jnp.float32,
            )
        )
        m = cmask.astype(jnp.float32)
        label = 1.0 - codes.astype(jnp.float32)
        g = (label - jax.nn.sigmoid(logit)) * m * alpha
        d_h_add = jnp.einsum(
            "bic,bicd->bid",
            g.astype(cdt),
            rows.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        d_rows = jnp.einsum(
            "bic,bid->bicd",
            g.astype(cdt),
            h.astype(cdt),
            preferred_element_type=jnp.float32,
        )
        ls = jax.nn.log_sigmoid(logit)
        loss = -jnp.sum(m * jnp.where(label > 0.5, ls, ls - logit))
        return paths, d_rows, m, d_h_add, loss, jnp.sum(m)

    def sorted_scatter(table, flat_idx, vals, weights, sr_key, clip_state):
        """THE table-update tail every scatter in this kernel shares:
        argsort by destination row (XLA's sorted-indices fast path), then
        scatter_mean normalization, per-row trust region, and the
        SR-aware accumulate. flat_idx [N], vals [N, d], weights [N] (only
        read under scatter_mean). Returns (new_table, clip_count).
        """
        order = jnp.argsort(flat_idx)
        flat_idx = flat_idx[order]
        vals = vals[order]
        if scatter_mean:
            vals = vals * _dup_mean_scale(
                table.shape[0], flat_idx, weights[order]
            )[:, None]
        clip_count = clip_state
        if clip_tau > 0.0:
            scale = _row_clip_scale(
                table.shape[0], clip_tau, (flat_idx, vals), tp_axis=tp_axis
            )
            clip_count = clip_count + jnp.sum(
                (scale < 1.0).astype(jnp.float32)
            )
            vals = vals * scale[flat_idx][:, None]
        new_table = table.at[flat_idx].add(
            _cast_update(
                vals, table.dtype, sr_key, table[flat_idx] if sr else None
            ),
            indices_are_sorted=True,
        )
        return new_table, clip_count

    def path_scatter(
        syn1, flat_p, vals, weights, touched, T, k_sr, clip_state
    ):
        """Path-row scatter, optionally compacted. flat_p/weights/touched
        are [B, Sl]-shaped (vals [B, Sl, d]); T = 0 scatters every slot
        (the one-tier path); T > 0 compacts each batch row to its first T
        touched slots (stable argsort keeps slot order), dropping any
        overflow — counted and returned so the quality impact is
        observable. Returns (new_syn1, clip_count, dropped).
        """
        B = flat_p.shape[0]
        dropped = jnp.float32(0.0)
        if T > 0:
            order = jnp.argsort(~touched, axis=1)[:, :T]
            bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
            flat_p = flat_p[bidx, order]
            vals = vals[bidx, order]
            if weights is not None:
                weights = weights[bidx, order]
            n_touched = jnp.sum(touched.astype(jnp.int32), axis=1)
            dropped = jnp.sum(
                jnp.maximum(n_touched - T, 0).astype(jnp.float32)
            )
        new_syn1, clip_count = sorted_scatter(
            syn1,
            flat_p.reshape(-1),
            vals.reshape(-1, vals.shape[-1]),
            weights.reshape(-1) if weights is not None else None,
            k_sr(1), clip_state,
        )
        return new_syn1, clip_count, dropped

    def dense_slice_add(new_out, d_top, k_sr):
        """The dense tier's table update: one contiguous slice add onto the
        top-P rows — disjoint from every tail id, and applied AFTER the tail
        scatter so the SR destination grid reads the latest table state."""
        top0 = new_out.shape[0] - P
        return new_out.at[top0:].add(
            _cast_update(
                d_top, new_out.dtype, k_sr(2),
                new_out[top0:] if sr else None,
            )
        )

    def center_scatter(emb_in, tok, d_h, ctx_weight, k_sr, clip_state):
        """sg center-row update: W.row(center) += accumulated grad (:351).

        Pre-sorted like every other table scatter in this kernel; the
        reorder only reassociates the f32 duplicate-row sums, inside the
        goldens' tolerance.
        """
        B, L = tok.shape
        return sorted_scatter(
            emb_in, tok.reshape(-1), d_h.reshape(B * L, -1),
            ctx_weight.reshape(-1), k_sr(0), clip_state,
        )

    def step(
        params: Params, tokens: jnp.ndarray, key: jax.Array, alpha: jnp.ndarray
    ) -> Tuple[Params, Metrics]:
        if dp_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
        center_zone = None
        if sp_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(sp_axis))
            Lloc = tokens.shape[1]
            tokens = _halo_exchange(tokens, W, sp_axis)
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            # halo positions are context-only: their center direction is
            # owned (and trained) by the neighboring shard
            center_zone = (pos >= W) & (pos < W + Lloc)
        B, L = tokens.shape
        k_sub, k_win, _ = jax.random.split(key, 3)
        k_sr = _sr_streams(key, sr)

        valid = tokens >= 0
        tok = jnp.where(valid, tokens, 0)
        keep = valid & (jax.random.uniform(k_sub, (B, L)) < tables.keep_probs[tok])
        if center_zone is not None:
            keep = keep & center_zone[None, :]
        w_eff = W - jax.random.randint(k_win, (B, L), 0, W, dtype=jnp.int32)

        emb_in = params["emb_in"]
        syn1 = params["emb_out_hs"]
        C = tables.hs_points.shape[1]
        clip_count = jnp.float32(0.0)  # rows the trust region engaged on
        dropped = jnp.float32(0.0)
        Q = L + 2 * W

        if not is_cbow:
            # ---- skip-gram: h = center row; targets = each context's path.
            h = emb_in[tok]  # [B, L, d]
            if two_tier:
                S = banded.resolve_chunk(L, W, config.band_chunk)
                # keep_i & valid_j & 0 < |i-j| <= w_eff_i: exactly the
                # pair_ok mask of the sg_sweep offset loop
                band_f = banded.band_mask(keep, valid, w_eff, W, S).astype(
                    jnp.float32
                )
                M = tables.hs_msig[tok]  # [B, L, P] i8
                # counts fit bf16's 8 mantissa bits exactly, and the einsum
                # accumulates in f32 — A/N are exact integers in any cdt
                A = banded.band_sv(
                    band_f, (M > 0).astype(jnp.float32), W, S, cdt
                )
                N = banded.band_sv(
                    band_f, (M != 0).astype(jnp.float32), W, S, cdt
                )
                d_h, d_top, loss, pairs, c_cnt = dense_tier(
                    h, A, N, syn1, alpha
                )
                clip_count += c_cnt
                if Ct:
                    (paths, d_rows, touched, out_touch, d_h_tail, t_loss,
                     t_pairs, ctx_hit) = sg_sweep(
                        h, tokens, keep, w_eff, syn1, alpha,
                        tables.hs_tail_points, tables.hs_tail_codes,
                        tables.hs_tail_len, Ct,
                    )
                    d_h = d_h + d_h_tail
                    loss += t_loss
                    pairs += t_pairs
                    T = resolve_tail_slots(config, tables, L, Q * Ct)
                    new_out, clip_count, dropped = path_scatter(
                        syn1,
                        paths.reshape(B, Q * Ct),
                        d_rows.reshape(B, Q * Ct, -1),
                        out_touch.reshape(B, Q * Ct) if scatter_mean else None,
                        touched.reshape(B, Q * Ct),
                        T, k_sr, clip_count,
                    )
                else:
                    # no tail tier: sg_sweep didn't run, so derive the
                    # center-activity mask from the band directly
                    ctx_hit = banded.band_row_sum(band_f, L) > 0
                    new_out = syn1
                new_out = dense_slice_add(new_out, d_top, k_sr)
            else:
                (paths, d_rows, _touched, out_touch, d_h, loss, pairs,
                 ctx_hit) = sg_sweep(
                    h, tokens, keep, w_eff, syn1, alpha,
                    tables.hs_points, tables.hs_codes, tables.hs_len, C,
                )
                new_out, clip_count, _ = path_scatter(
                    syn1,
                    paths.reshape(B, Q * C),
                    d_rows.reshape(B, Q * C, -1),
                    out_touch.reshape(B, Q * C) if scatter_mean else None,
                    None, 0, k_sr, clip_count,
                )

            new_in, clip_count = center_scatter(
                emb_in, tok, d_h, ctx_hit.astype(jnp.float32), k_sr,
                clip_count,
            )
        else:
            # ---- CBOW: h = (mean of) context rows; targets = center's path.
            # Band contractions use the window-blocked representation
            # (ops/banded.py) — cost L*(S+2W), not L^2.
            S = banded.resolve_chunk(L, W, config.band_chunk)
            band_f = banded.band_mask(keep, valid, w_eff, W, S).astype(
                jnp.float32
            )
            n_ctx = banded.band_row_sum(band_f, L)
            ein = emb_in[tok]  # [B, L, d]
            h = banded.band_sv(band_f, ein, W, S, cdt)
            if cbow_mean:
                h = h / jnp.maximum(n_ctx, 1.0)[:, :, None]
            active = keep & (n_ctx > 0)    # skip centers without context, :289

            if two_tier:
                # dense tier on the center's OWN path (no offset sweep)
                M = tables.hs_msig[tok]  # [B, L, P] i8
                act = active[:, :, None].astype(jnp.float32)
                A = (M > 0).astype(jnp.float32) * act
                N = (M != 0).astype(jnp.float32) * act
                d_h, d_top, loss, pairs, c_cnt = dense_tier(
                    h, A, N, syn1, alpha
                )
                clip_count += c_cnt
                if Ct:
                    paths, d_rows, m, d_h_add, t_loss, t_pairs = (
                        cbow_path_block(
                            h, tok, active, syn1, alpha,
                            tables.hs_tail_points, tables.hs_tail_codes,
                            tables.hs_tail_len, Ct,
                        )
                    )
                    d_h = d_h + d_h_add
                    loss += t_loss
                    pairs += t_pairs
                    T = resolve_tail_slots(config, tables, L, L * Ct)
                    new_out, clip_count, dropped = path_scatter(
                        syn1,
                        paths.reshape(B, L * Ct),
                        d_rows.reshape(B, L * Ct, -1),
                        m.reshape(B, L * Ct) if scatter_mean else None,
                        (m > 0).reshape(B, L * Ct),
                        T, k_sr, clip_count,
                    )
                else:
                    new_out = syn1
                new_out = dense_slice_add(new_out, d_top, k_sr)
            else:
                paths, d_rows, m, d_h, loss, pairs = cbow_path_block(
                    h, tok, active, syn1, alpha,
                    tables.hs_points, tables.hs_codes, tables.hs_len, C,
                )
                new_out, clip_count, _ = path_scatter(
                    syn1,
                    paths.reshape(B, L * C),
                    d_rows.reshape(B, L * C, -1),
                    m.reshape(B, L * C) if scatter_mean else None,
                    None, 0, k_sr, clip_count,
                )

            # fan d_h to context rows (second /n under cbow_mean, :313-315)
            if cbow_mean:
                d_h = d_h / jnp.maximum(n_ctx, 1.0)[:, :, None]
            if config.slab_scatter and S > 0:
                # slab-space scatter: the table scatter's duplicate-index
                # summing performs the overlap-add (band_step.py, same knob).
                # v2: the slab ids get their own argsort so this scatter
                # keeps XLA's sorted fast path too (band_step.py rationale).
                d_in_slab = banded.band_vs_slab(band_f, d_h, W, S, cdt)
                slab_ids = banded.slab_token_ids(tok, W, S)
                ok = slab_ids >= 0
                new_in, clip_count = sorted_scatter(
                    emb_in,
                    jnp.where(ok, slab_ids, 0).reshape(-1),
                    jnp.where(ok[..., None], d_in_slab, 0.0).reshape(
                        -1, d_in_slab.shape[-1]
                    ),
                    jnp.where(
                        ok, banded.band_col_sum_slab(band_f), 0.0
                    ).reshape(-1) if scatter_mean else None,
                    k_sr(0), clip_count,
                )
            else:
                d_in_pos = banded.band_vs(band_f, d_h, W, S, cdt)
                new_in, clip_count = sorted_scatter(
                    emb_in,
                    tok.reshape(-1),
                    d_in_pos.reshape(-1, d_in_pos.shape[-1]),
                    banded.band_col_sum(band_f, L, W, S).reshape(-1)
                    if scatter_mean else None,
                    k_sr(0), clip_count,
                )

        new_params = dict(params)
        new_params["emb_in"] = new_in
        new_params["emb_out_hs"] = new_out
        return new_params, {
            "loss_sum": loss,
            "pairs": pairs,
            "clip_engaged": clip_count,
            "hs_tail_dropped": dropped,
        }

    return step
