"""Banded-matmul training step: the MXU-shaped fast path for negative sampling.

The pair kernel (ops/train_step.py) enumerates (center, context) pairs
explicitly and scatters per-pair gradients — faithful, but its cost on TPU is
dominated by materializing [P, T, d] tensors and a P*(1+K)-row scatter-add.
This module re-expresses the same objective in the shapes the hardware wants
(measured on v5e: ~25-60x the pair kernel at dim=300):

  positives  — every (center, context) pair inside a [B, L] batch row is
               scored by window-blocked band matmuls (ops/banded.py):
               logits[b,i,j] = in_i . out_j masked to |i-j| <= w_eff(b,i),
               j != i (the j-loop of Word2Vec.cpp:339-341 becomes a band
               mask). Long rows are chunked into [S, S+2W] slabs so the
               positive-side cost scales with L*(S+2W) instead of L^2 —
               at the default 128-lane slab the step time is flat in L
               (benchmarks/ablate.py "band chunking" section). Both
               gradient sides are band matmuls too, so the update touches
               only B*L aggregated rows per table instead of B*L*2W
               per-pair rows.
  negatives  — drawn SHARED ([B, KP] per-row ids from the alias table, or
               with config.negative_scope="batch" one [KP] pool for the
               whole batch) instead of per pair, turning the negative
               score/update into dense [L, d] x [d, KP] matmuls (batch
               scope: one [B*L, d] x [d, KP] matmul) with no scatter at all
               for the score side and a (B*)KP-row scatter for the update.
               Each center i weights every shared draw by k_i / KP, where
               k_i is the number of draws the reference would have made for
               it (SG: n_ctx(i)*K per Word2Vec.cpp:339-349; CBOW: K per
               Word2Vec.cpp:304-311), so the expected update equals the
               reference's per-pair sampling; only the variance/correlation
               structure differs (draws are shared across the centers of a
               row, or of the batch). This is the standard batched-SGNS
               trade (e.g. candidate sampling) and is validated by the
               eval-parity gate plus the cross-scope expectation test
               (tests/test_negative_scope.py), not bitwise.
  scatter    — token-id scatters are pre-sorted (argsort once, reused for
               both tables) so XLA takes the sorted-indices fast path.

Semantics deltas vs the reference, all documented and eval-gated:
  * shared negatives (above);
  * a drawn negative colliding with the row's *center or active context set*
    is masked out for that center, approximating word2vec.c's per-pair
    "target == positive -> skip" (the reference instead relabels it to 1 via
    its dedup map, Word2Vec.cpp:253-257);
  * within-batch gradient staleness, as in the pair kernel (SURVEY §7(a));
  * scatter_mean normalizes by per-pair contribution counts like the pair
    kernel, but the within-row aggregation (one gradient per token position)
    is already summed before the scatter, and the emb_out count is joint
    across positive targets and shared negative draws (each draw counting
    its expected per-pair multiplicity k_i/KP summed over centers).

Hierarchical softmax has no shared-negative reformulation (per-word Huffman
paths), so config.kernel="auto" routes hs to the positional hs fast kernel
(ops/hs_step.py) instead of this one.

Mesh axes mirror the pair kernel: with tp_axis the embedding dim is sharded
and every logit matmul is psum'd over the axis before the sigmoid; all
gradients are then local to the dim shard. With dp_axis the PRNG key is
folded with the shard index.

sp_axis adds sequence (context) parallelism for long rows: tokens [B, L] are
sharded along L, and each shard halo-exchanges `window` edge tokens with its
neighbors over ICI (jax.lax.ppermute) so window pairs crossing the shard
boundary are preserved. Each shard then trains only the centers it OWNS
(halo positions stay context-only), which keeps every directed (center,
context) pair trained exactly once across the mesh: the i->j direction on
i's owner, j->i on j's owner. Updates land in the shard-local replica and
are reconciled by the same periodic averaging as the data axis
(parallel/trainer.py) — sequence parallelism here is data parallelism over
position slices plus the halo exchange that plain slicing would miss. The
pmean over dp+sp therefore applies 1/sp of the summed sp-shard delta per
sync (Hogwild-analog averaging, NOT single-chip equivalence — see the
sp_axis note in ops/train_step.make_pair_train_step and ADVICE r5 #1).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import Word2VecConfig
from ..models.params import Params
from . import banded
from .tables import DeviceTables
from .. import compat
from .train_step import (
    _cast_update, _draw_negatives, _dup_mean_scale, _row_clip_scale,
    _sr_streams,
)

Metrics = Dict[str, jnp.ndarray]


def _halo_exchange(tok: jnp.ndarray, w: int, axis: str) -> jnp.ndarray:
    """[B, Lloc] -> [B, w + Lloc + w]: fetch w edge tokens from each sequence
    neighbor over ICI. Outermost shards have no neighbor on one side; their
    halo is -1 (invalid), matching row-end padding semantics."""
    if tok.shape[1] < w:
        # the slice can't supply a full one-hop halo; multi-hop exchange is
        # deliberately unsupported (ShardedTrainer validates this upfront)
        raise ValueError(
            f"per-shard slice length {tok.shape[1]} < window {w}"
        )
    n = compat.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    # left halo = right edge of the left neighbor (shift right: i -> i+1)
    left = jax.lax.ppermute(
        tok[:, -w:], axis, [(i, i + 1) for i in range(n - 1)]
    )
    # right halo = left edge of the right neighbor (shift left: i+1 -> i)
    right = jax.lax.ppermute(
        tok[:, :w], axis, [(i + 1, i) for i in range(n - 1)]
    )
    # ppermute delivers zeros to shards with no source; zero is a real token
    # id, so explicitly invalidate the missing halos
    left = jnp.where(idx == 0, -1, left)
    right = jnp.where(idx == n - 1, -1, right)
    return jnp.concatenate([left, tok, right], axis=1)


# The fused [V, 2, d] layout machinery lives with the parameter layout
# itself (models/params.py) since table_layout="unified" made it a
# persistent storage format, not just a chunk-scoped restack; re-exported
# here for the existing importers (obs/health, tests).
from ..models.params import (  # noqa: F401  (re-exports)
    FUSED_KEY, FUSED_SUBTABLES, fuse_tables, unfuse_tables,
)


def make_band_train_step(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
    sp_axis: str | None = None,
    fused: bool = False,
) -> Callable[[Params, jnp.ndarray, jax.Array, jnp.ndarray], Tuple[Params, Metrics]]:
    """step(params, tokens[B,L], key, alpha) -> (params, metrics).

    Same contract as train_step.make_train_step; negative sampling only.
    With sp_axis, tokens is this shard's [B, Lloc] position slice of a longer
    row (see module docstring). With fused=True, params carry the two tables
    as one [V, 2, d] array under FUSED_KEY (models/params.fuse_tables —
    either the chunk runners' transient restack, config.fused_tables, or the
    persistent unified layout, config.table_layout) and the update runs as a
    single fused scatter; bitwise-identical trajectory in every dtype incl.
    bf16 ± SR (tests/test_fused.py, tests/test_unified.py).
    """
    if not config.use_ns or config.use_hs:
        raise ValueError(
            "band kernel supports negative sampling only "
            "(hs routes through ops/hs_step.make_hs_train_step)"
        )
    if fused and config.slab_scatter:
        raise ValueError(
            "fused_tables requires the sorted shared-index scatter "
            "(slab_scatter uses a different index set per table)"
        )
    pallas = config.band_backend == "pallas"
    pallas_oa = config.band_backend == "pallas_oa"
    pallas_fused = config.band_backend == "pallas_fused"
    if pallas or pallas_oa or pallas_fused:
        # Hard errors, not silent fallbacks: a bench A/B that silently ran
        # the XLA chain would bank a mislabeled measurement. Each rejection
        # names the specific incompatible lever AND the supported
        # alternative, so a failing config is actionable from the message
        # alone (the r12 error-message contract; tests/test_pallas_step.py
        # negative-parse-style pins).
        unsupported = [
            msg for cond, msg in [
                # fused_tables composes with pallas_oa (its context grads
                # come back in token order, same index set as the center
                # side) but not with the fully-fused kernel's slab scatter
                (fused and pallas,
                 "fused_tables (the fused [V, 2, d] restack has no split "
                 "gather for this kernel — use band_backend='pallas_oa', "
                 "which composes with fused_tables, or 'pallas_fused' on "
                 "table_layout='unified')"),
                (tp_axis is not None,
                 "tensor parallelism (tp mesh axis — use "
                 "band_backend='xla', the only backend shard_map can "
                 "host)"),
                (sp_axis is not None,
                 "sequence parallelism (sp mesh axis — use "
                 "band_backend='xla')"),
                # defense in depth: sharded trainers already reject pallas
                # up front (parallel/trainer._reject_pallas — shard_map
                # cannot host the kernel, see ops/pallas_band.py scope note)
                (dp_axis is not None,
                 "data-parallel sharding (dp mesh axis — use "
                 "band_backend='xla')"),
                # only the dtypes whose Mosaic tiling the kernel's block
                # specs were validated for
                (config.dtype not in ("float32", "bfloat16"),
                 f"table dtype {config.dtype} (supported: float32, "
                 "bfloat16)"),
            ] if cond
        ]
        if unsupported:
            raise ValueError(
                f"band_backend={config.band_backend!r} covers the sg/cbow "
                "ns single-chip step (ops/pallas_band.py, "
                "ops/pallas_overlap.py, ops/pallas_step.py); unsupported "
                "here: " + "; ".join(unsupported)
            )
    if pallas_fused and not fused:
        # config validation pins pallas_fused to table_layout='unified',
        # which routes every dispatch through fused=True — this is the
        # defense-in-depth for direct make_band_train_step callers
        raise ValueError(
            "band_backend='pallas_fused' needs the unified [V, 2, d] slab "
            "params (fused=True via table_layout='unified'); split tables "
            "have two index spaces the one-gather/one-scatter kernel "
            "cannot address — use band_backend='pallas_oa' for split "
            "tables"
        )
    W = config.window
    K = config.negative
    KP = config.shared_negatives
    per_row = config.negative_scope == "row"
    is_cbow = config.model == "cbow"
    cbow_mean = config.cbow_mean
    scatter_mean = config.scatter_mean
    clip_tau = config.clip_row_update
    slab_scatter = config.slab_scatter
    sr = config.stochastic_rounding
    cdt = jnp.dtype(config.compute_dtype)

    if pallas_oa:
        from . import pallas_overlap

        # interpret=True routes the kernel through the Pallas interpreter on
        # non-TPU backends (CPU tests / smoke); the same code compiles to
        # Mosaic on chip — the same gate as the fused kernel below
        oa_interpret = jax.devices()[0].platform != "tpu"

    def psum(x):
        return jax.lax.psum(x, tp_axis) if tp_axis is not None else x

    def step(
        params: Params, tokens: jnp.ndarray, key: jax.Array, alpha: jnp.ndarray
    ) -> Tuple[Params, Metrics]:
        if dp_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
        center_zone = None
        if sp_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(sp_axis))
            Lloc = tokens.shape[1]
            tokens = _halo_exchange(tokens, W, sp_axis)
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            # halo positions are context-only: their center direction is
            # owned (and trained) by the neighboring shard
            center_zone = (pos >= W) & (pos < W + Lloc)
        B, L = tokens.shape
        k_sub, k_win, k_neg = jax.random.split(key, 3)
        k_sr = _sr_streams(key, sr)

        valid = tokens >= 0
        tok = jnp.where(valid, tokens, 0)

        # Center-word subsample gate (Word2Vec.cpp:282,332) and per-center
        # window shrink w_eff in {1..W} (Word2Vec.cpp:285-287,335-337).
        keep = valid & (jax.random.uniform(k_sub, (B, L)) < tables.keep_probs[tok])
        if center_zone is not None:
            keep = keep & center_zone[None, :]
        w_eff = W - jax.random.randint(k_win, (B, L), 0, W, dtype=jnp.int32)

        # Band mask over the (center i, context j) pair plane, in the
        # window-blocked representation (ops/banded.py): dense [B, L, L] for
        # short rows, [B, C, S, S+2W] slabs for long — positive-side cost
        # scales with L*(S+2W), not L^2 (VERDICT r1 item 3).
        S = banded.resolve_chunk(L, W, config.band_chunk)
        if pallas_oa and S == 0:
            raise ValueError(
                f"band_backend='pallas_oa' needs the chunked band "
                f"representation (rows of length {L} resolved to the dense "
                f"path, which has no overlap-add to replace). Set "
                f"band_chunk to 2*window <= S < {L}, or use the XLA "
                f"backend for short rows"
            )

        def ctx_fan(scores, u):
            # band_vs — the context-side fan-out — with the overlap-add
            # reduced by the Pallas kernel on the pallas_oa backend, so the
            # XLA pad/add/slice chain and the layout copies around it
            # (2.14 ms = 26.9% of the r2 step, PERF.md) never materialize;
            # output is per-token order, so the sorted table scatter below
            # reuses the shared argsort unchanged
            if pallas_oa:
                return pallas_overlap.overlap_add_tokens(
                    banded.band_vs_slab(scores, u, W, S, cdt),
                    W=W, S=S, L=L, interpret=oa_interpret,
                )
            return banded.band_vs(scores, u, W, S, cdt)

        band_f = banded.band_mask(keep, valid, w_eff, W, S).astype(jnp.float32)
        n_ctx = banded.band_row_sum(band_f, L)  # [B, L] contexts per center
        # context-side gradients can stay in slab space and let the scatter
        # perform the overlap-add (config.slab_scatter; chunked repr only)
        use_slab = slab_scatter and S > 0
        d_ctx_slab = ctx_w_slab = None

        if fused:
            emb = params[FUSED_KEY]  # [V, 2, d]
            emb_in, emb_out = emb[:, 0], emb[:, 1]  # shape/dtype refs only
            g2 = emb[tok]  # one gather for both tables: [B, L, 2, d]
            ein, eout = g2[:, :, 0], g2[:, :, 1]
        else:
            emb_in = params["emb_in"]
            emb_out = params["emb_out_ns"]
            ein = emb_in[tok]   # [B, L, d]
            eout = emb_out[tok]  # [B, L, d]

        # Shared negatives (per row, or one batch-wide pool) + collision
        # mask vs each row's centers and active contexts (module docstring).
        negs = _draw_negatives(
            k_neg, (B, KP) if per_row else (KP,),
            tables.alias_accept, tables.alias_idx,
        )  # [B, KP] | [KP]
        en = emb[negs, 1] if fused else emb_out[negs]  # [B, KP, d] | [KP, d]
        if per_row:
            center_hit = tok[:, :, None] == negs[:, None, :]  # [B, L, KP]
        else:
            center_hit = tok[:, :, None] == negs[None, None, :]
        # context collision: neg n hits center i if any active context j of i
        # carries the same token id
        # 0/1 operands with row sums <= 2W, exactly representable in bf16, so
        # computing the mask matmul in cdt is bit-identical under "> 0"
        ctx_hit = (
            banded.band_sv(band_f, center_hit.astype(jnp.float32), W, S, cdt)
            > 0.0
        )
        neg_ok = ~(center_hit | ctx_hit)  # [B, L, KP]

        if not is_cbow:
            h = ein                       # projection = center row (W), :330
            k_i = n_ctx * K               # reference draws per center
        else:
            # projection = (mean of) context rows of emb_in (C), :300-302
            h = banded.band_sv(band_f, ein, W, S, cdt)
            if cbow_mean:
                h = h / jnp.maximum(n_ctx, 1.0)[:, :, None]
            k_i = jnp.where(n_ctx > 0, float(K), 0.0)  # ns once per center, :304

        # ---- negative side: dense matmuls against the shared draws.
        # batch scope turns the B batched [L,d]x[d,KP] contractions into one
        # [B*L, d] x [d, KP] matmul and the update into a [KP, d] reduction.
        en_spec = "bnd" if per_row else "nd"
        nlog = psum(
            jnp.einsum(
                f"bid,{en_spec}->bin",
                h.astype(cdt),
                en.astype(cdt),
                preferred_element_type=jnp.float32,
            )
        )  # [B, L, KP]
        w_neg = (k_i / KP)[:, :, None] * neg_ok  # [B, L, KP]
        gn = (0.0 - jax.nn.sigmoid(nlog)) * w_neg * alpha
        d_h = jnp.einsum(
            f"bin,{en_spec}->bid",
            gn.astype(cdt),
            en.astype(cdt),
            preferred_element_type=jnp.float32,
        )  # [B, L, d]
        d_neg = jnp.einsum(
            f"bin,bid->{en_spec}",
            gn.astype(cdt),
            h.astype(cdt),
            preferred_element_type=jnp.float32,
        )  # [B, KP, d] | [KP, d]

        # ---- positive side
        if not is_cbow:
            # logits over the band only (window-blocked slabs, ops/banded.py)
            plog = banded.band_qk(ein, eout, W, S, cdt, psum)
            gp = (1.0 - jax.nn.sigmoid(plog)) * band_f * alpha  # label 1
            d_h = d_h + banded.band_sv(gp, eout, W, S, cdt)
            # per-context-position grad (fans to the output matrix rows)
            if use_slab:
                d_ctx_slab = banded.band_vs_slab(gp, ein, W, S, cdt)
                ctx_w_slab = banded.band_col_sum_slab(band_f)
                d_out_pos = out_weight = None
            else:
                d_out_pos = ctx_fan(gp, ein)
                out_weight = banded.band_col_sum(band_f, L, W, S)
            d_in_pos = d_h  # accumulated on the center row (W.row += grad, :351)
            pos_loss = -banded.band_loss_sum(band_f * jax.nn.log_sigmoid(plog))
            pos_pairs = banded.band_loss_sum(band_f)
            # scatter_mean contribution weights, matching the pair kernel's
            # counting: a center with no active context gets no updates at all
            # in the reference (no ns calls run), so it contributes 0; a
            # context position contributes one unit per center predicting it
            in_weight = (keep & (n_ctx > 0)).astype(jnp.float32)
        else:
            # positive target = the center word on the output matrix, :304-311
            plog = psum(
                jnp.einsum(
                    "bid,bid->bi",
                    h.astype(cdt),
                    eout.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
            )  # [B, L]
            active = (keep & (n_ctx > 0)).astype(jnp.float32)
            gp = (1.0 - jax.nn.sigmoid(plog)) * active * alpha
            d_h = d_h + gp[:, :, None] * eout
            d_out_pos = gp[:, :, None] * h  # [B, L, d] on the center position
            # fan d_h back to contributing context rows (Word2Vec.cpp:313-315)
            if cbow_mean:
                d_h = d_h / jnp.maximum(n_ctx, 1.0)[:, :, None]
            if use_slab:
                d_ctx_slab = banded.band_vs_slab(band_f, d_h, W, S, cdt)
                ctx_w_slab = banded.band_col_sum_slab(band_f)
                d_in_pos = in_weight = None
            else:
                d_in_pos = ctx_fan(band_f, d_h)
                in_weight = banded.band_col_sum(band_f, L, W, S)
            pos_loss = -jnp.sum(active * jax.nn.log_sigmoid(plog))
            pos_pairs = jnp.sum(active)
            # scatter_mean weights (pair-kernel counting): each context row of
            # emb_in contributes one unit per center it serves; each center
            # contributes one unit on emb_out
            out_weight = active

        # ---- scatters: one shared sort of the row token ids; with
        # use_slab the context-side table instead takes its own sorted
        # scatter of slab-space values over slab token ids (whose
        # duplicate-index summing is the overlap-add,
        # banded.slab_token_ids). Round 2 measured the UNSORTED slab
        # scatter losing more than the skipped overlap-add layout copies
        # saved (2.26M vs 3.64M w/s end-to-end, PERF.md); v2 here pays a
        # second argsort (~1.33x the token count) to keep XLA's
        # sorted-indices scatter fast path on the slab side too.
        flat = tok.reshape(-1)
        order = jnp.argsort(flat)
        sorted_idx = flat[order]
        flat_negs = negs.reshape(-1)
        d_neg_flat = d_neg.reshape(-1, d_neg.shape[-1])
        if use_slab:
            slab_ids = banded.slab_token_ids(tok, W, S)  # [B, C, S+2W]
            slab_ok = slab_ids >= 0
            slab_flat = jnp.where(slab_ok, slab_ids, 0).reshape(-1)
            slab_order = jnp.argsort(slab_flat)
            slab_sorted = slab_flat[slab_order]
            d_ctx_flat = jnp.where(slab_ok[..., None], d_ctx_slab, 0.0).reshape(
                -1, d_ctx_slab.shape[-1]
            )[slab_order]
            ctx_w_flat = jnp.where(slab_ok, ctx_w_slab, 0.0).reshape(-1)[
                slab_order
            ]

        # emb_in side: dense center rows (sg) or context rows (cbow, slab-able)
        if d_in_pos is not None:
            in_idx, in_sorted = sorted_idx, True
            d_in_flat = d_in_pos.reshape(-1, d_in_pos.shape[-1])[order]
            if scatter_mean:
                # per-contribution counts, as in the pair kernel
                d_in_flat = d_in_flat * _dup_mean_scale(
                    emb_in.shape[0], sorted_idx,
                    in_weight.reshape(-1)[order],
                )[:, None]
        else:  # cbow + slab: context grads scatter from slab space
            in_idx, in_sorted = slab_sorted, True
            d_in_flat = d_ctx_flat
            if scatter_mean:
                d_in_flat = d_in_flat * _dup_mean_scale(
                    emb_in.shape[0], slab_sorted, ctx_w_flat
                )[:, None]

        # emb_out side: context rows (sg, slab-able) or center rows (cbow),
        # plus the shared-negative rows; under scatter_mean both share ONE
        # joint count so a word serving both roles is normalized by its total
        # contribution count (a drawn negative counts its expected per-pair
        # draws, w_neg summed over centers)
        if d_out_pos is not None:
            out_idx, out_sorted = sorted_idx, True
            d_out_flat = d_out_pos.reshape(-1, d_out_pos.shape[-1])[order]
            cnt_idx, cnt_w = flat, out_weight.reshape(-1)
        else:  # sg + slab
            out_idx, out_sorted = slab_sorted, True
            d_out_flat = d_ctx_flat
            cnt_idx, cnt_w = slab_sorted, ctx_w_flat
        if scatter_mean:
            cnt = (
                jnp.zeros((emb_out.shape[0],), jnp.float32)
                .at[cnt_idx].add(cnt_w)
                .at[flat_negs].add(
                    w_neg.sum(axis=1).reshape(-1) if per_row
                    else w_neg.sum(axis=(0, 1))
                )
            )
            inv = 1.0 / jnp.maximum(cnt, 1.0)
            d_out_flat = d_out_flat * inv[out_idx][:, None]
            d_neg_flat = d_neg_flat * inv[flat_negs][:, None]

        clip_count = jnp.float32(0.0)
        if clip_tau > 0.0:
            # per-row trust region (train_step._row_clip_scale): the out
            # table's positive-context and negative-draw contributions share
            # rows, so they share one budget
            in_scale = _row_clip_scale(
                emb_in.shape[0], clip_tau, (in_idx, d_in_flat),
                tp_axis=tp_axis,
            )
            out_scale = _row_clip_scale(
                emb_out.shape[0], clip_tau,
                (out_idx, d_out_flat), (flat_negs, d_neg_flat),
                tp_axis=tp_axis,
            )
            clip_count = jnp.sum((in_scale < 1.0).astype(jnp.float32)) + jnp.sum(
                (out_scale < 1.0).astype(jnp.float32)
            )
            d_in_flat = d_in_flat * in_scale[in_idx][:, None]
            d_out_flat = d_out_flat * out_scale[out_idx][:, None]
            d_neg_flat = d_neg_flat * out_scale[flat_negs][:, None]

        new_params = dict(params)
        if fused:
            # one [N, 2, d] scatter covers both tables (same sorted ids);
            # negative rows land on the out plane of the fused array.
            # SR quantizes each delta to the destination row's ulp grid
            # (dest rows re-gathered at the scatter indices, sr only) —
            # PER PLANE, with the same stream indices as the split step
            # (0=in, 1=out): the fused draws are then bit-identical to the
            # split layout's, which is what makes unified-vs-split bitwise
            # under bf16+SR too (tests/test_unified.py), not just in f32.
            vals2 = jnp.stack(
                [
                    _cast_update(
                        d_in_flat, emb.dtype, k_sr(0),
                        emb[sorted_idx, 0] if sr else None,
                    ),
                    _cast_update(
                        d_out_flat, emb.dtype, k_sr(1),
                        emb[sorted_idx, 1] if sr else None,
                    ),
                ],
                axis=1,
            )
            new_emb = emb.at[sorted_idx].add(vals2, indices_are_sorted=True)
            # SR dest rows come from NEW_emb: the positive scatter above may
            # have moved a shared row across a binade, and quantizing on the
            # stale pre-step grid would let the bf16 add re-round (or
            # swallow) the delta. Stream 2 = the split step's negative-row
            # stream (same parity contract as the planes above).
            new_emb = new_emb.at[flat_negs, 1].add(
                _cast_update(
                    d_neg_flat, emb.dtype, k_sr(2),
                    new_emb[flat_negs, 1] if sr else None,
                )
            )
            new_params[FUSED_KEY] = new_emb
        else:
            new_in = emb_in.at[in_idx].add(
                _cast_update(
                    d_in_flat, emb_in.dtype, k_sr(0),
                    emb_in[in_idx] if sr else None,
                ),
                indices_are_sorted=in_sorted,
            )
            new_out = emb_out.at[out_idx].add(
                _cast_update(
                    d_out_flat, emb_out.dtype, k_sr(1),
                    emb_out[out_idx] if sr else None,
                ),
                indices_are_sorted=out_sorted,
            )
            # negative-row scatter (KP rows per batch row; duplicates sum);
            # SR dest rows from NEW_out — see the fused branch's note
            new_out = new_out.at[flat_negs].add(
                _cast_update(
                    d_neg_flat, emb_out.dtype, k_sr(2),
                    new_out[flat_negs] if sr else None,
                )
            )
            new_params["emb_in"] = new_in
            new_params["emb_out_ns"] = new_out

        # masked BCE for metrics, matching the pair kernel's convention:
        # negatives contribute with their expectation weights
        neg_loss = -jnp.sum(w_neg * (jax.nn.log_sigmoid(nlog) - nlog))
        metrics = {
            "loss_sum": pos_loss + neg_loss,
            "pairs": pos_pairs + jnp.sum(w_neg),
            "clip_engaged": clip_count,
        }
        return new_params, metrics

    if pallas_fused:
        return _make_pallas_fused_step(config, tables)
    if not pallas:
        return step

    # ------------------------------------------------------------------
    # Fused-kernel path (ops/pallas_band.py): one VMEM-resident pass
    # computes everything between the gathers and the scatters. Kept as a
    # separate step function so the XLA path above stays untouched;
    # equivalence is pinned by tests/test_pallas_band.py.
    # ------------------------------------------------------------------
    from . import pallas_band

    # interpret=True runs the kernel through the Pallas interpreter so the
    # CPU test/virtual-device meshes exercise the identical code path
    interpret = jax.devices()[0].platform != "tpu"

    def step_pallas(
        params: Params, tokens: jnp.ndarray, key: jax.Array, alpha: jnp.ndarray
    ) -> Tuple[Params, Metrics]:
        B, L = tokens.shape
        k_sub, k_win, k_neg = jax.random.split(key, 3)
        # same stream indices as the XLA tail (0=in, 1=out, 2=negatives)
        k_sr = _sr_streams(key, sr)

        valid = tokens >= 0
        tok = jnp.where(valid, tokens, 0)
        keep = valid & (jax.random.uniform(k_sub, (B, L)) < tables.keep_probs[tok])
        w_eff = W - jax.random.randint(k_win, (B, L), 0, W, dtype=jnp.int32)

        S = banded.resolve_chunk(L, W, config.band_chunk)
        if S == 0:
            raise ValueError(
                f"band_backend='pallas' needs the chunked band "
                f"representation, but rows of length {L} resolved to the "
                f"dense path. Chunking requires 2*window <= band_chunk < "
                f"row length (window={W}); rows with L <= 2*window cannot "
                f"be chunked at all — use the XLA backend there"
            )
        C, P = banded._geom(L, W, S)
        d = params["emb_in"].shape[1]
        emb_in = params["emb_in"]
        emb_out = params["emb_out_ns"]

        negs = _draw_negatives(
            k_neg, (B, KP) if per_row else (KP,),
            tables.alias_accept, tables.alias_idx,
        )
        en = emb_out[negs]  # [B, KP, d] | [KP, d]

        # matrix roles (Word2Vec.cpp:300-315 vs :330-351): sg scores
        # emb_in centers against emb_out context slabs; cbow scores the
        # emb_in context projection against the center's emb_out row
        center_tbl, ctx_tbl = (
            (emb_out, emb_in) if is_cbow else (emb_in, emb_out)
        )
        pad_c = C * S - L
        a_c = jnp.pad(
            center_tbl[tok], ((0, 0), (0, pad_c), (0, 0))
        ).reshape(B, C, S, d)
        bk = banded._slabs(banded._pad_ctx(ctx_tbl[tok], W, P), C, S, 2 * W)
        tok_c = jnp.pad(
            tokens, ((0, 0), (0, pad_c)), constant_values=-1
        ).reshape(B, C, S)
        # raw ids with -1 preserved: the kernel derives context validity
        # from tok_k >= 0
        tok_k = banded.slab_token_ids(tokens, W, S)
        keep_c = jnp.pad(
            keep.astype(jnp.float32), ((0, 0), (0, pad_c))
        ).reshape(B, C, S)
        w_c = jnp.pad(
            w_eff.astype(jnp.float32), ((0, 0), (0, pad_c))
        ).reshape(B, C, S)

        d_h4, d_ctx_slab, d_neg_k, nctx_c, ctx_w_slab, wns, losses = (
            pallas_band.band_core(
                a_c, bk,
                en if per_row else en[None],
                tok_c, tok_k, keep_c, w_c,
                negs if per_row else negs[None],
                alpha,
                W=W, K=K, cdt=cdt, is_cbow=is_cbow, cbow_mean=cbow_mean,
                interpret=interpret,
            )
        )
        d_h = d_h4.reshape(B, C * S, d)[:, :L]
        n_ctx = nctx_c.reshape(B, C * S)[:, :L]
        d_neg_flat = (d_neg_k if per_row else d_neg_k[0]).reshape(-1, d)
        w_neg_flat = (wns if per_row else wns[0]).reshape(-1)
        flat_negs = negs.reshape(-1)

        # ---- scatters: same sorted discipline as the XLA step's
        # slab-scatter path above (centers by token id, contexts in slab
        # space). Deliberately a specialized copy, NOT shared code: the XLA
        # tail interleaves fused/cbow/sr variants this path can never take.
        # If you change the shared discipline (joint counts, clip budget,
        # sort order) in either place, tests/test_pallas_band.py pins the
        # two backends equal across every combination this path supports.
        flat = tok.reshape(-1)
        order = jnp.argsort(flat)
        sorted_idx = flat[order]
        d_in_flat = d_h.reshape(-1, d)[order]

        slab_ok = tok_k >= 0
        slab_flat = jnp.where(slab_ok, tok_k, 0).reshape(-1)
        slab_order = jnp.argsort(slab_flat)
        slab_sorted = slab_flat[slab_order]
        # the kernel already zeroes values/weights at invalid slots (their
        # mask column is zero), so no re-masking is needed here
        d_ctx_flat = d_ctx_slab.reshape(-1, d)[slab_order]
        ctx_w_flat = ctx_w_slab.reshape(-1)[slab_order]

        # Routing mirrors the gather roles, bound ONCE like the XLA tail:
        # sg puts center grads on emb_in and slab grads + negatives on
        # emb_out; cbow swaps the first two (negatives always live on
        # emb_out). active = per-center update gate, the XLA path's
        # (keep & n_ctx > 0). Each (idx, vals, weight) triple stays
        # aligned through scatter_mean / clip / the scatter itself.
        active_flat = (n_ctx > 0).astype(jnp.float32).reshape(-1)
        center_side = (sorted_idx, d_in_flat, active_flat[order])
        slab_side = (slab_sorted, d_ctx_flat, ctx_w_flat)
        if not is_cbow:
            (in_idx, in_vals, in_w) = center_side
            (out_idx, out_vals, out_w) = slab_side
            pos_pairs = jnp.sum(n_ctx)
        else:
            (in_idx, in_vals, in_w) = slab_side
            (out_idx, out_vals, out_w) = center_side
            pos_pairs = jnp.sum(active_flat)

        if scatter_mean:
            in_vals = in_vals * _dup_mean_scale(
                emb_in.shape[0], in_idx, in_w
            )[:, None]
            cnt = (
                jnp.zeros((emb_out.shape[0],), jnp.float32)
                .at[out_idx].add(out_w)
                .at[flat_negs].add(w_neg_flat)
            )
            inv = 1.0 / jnp.maximum(cnt, 1.0)
            out_vals = out_vals * inv[out_idx][:, None]
            d_neg_flat = d_neg_flat * inv[flat_negs][:, None]

        clip_count = jnp.float32(0.0)
        if clip_tau > 0.0:
            in_scale = _row_clip_scale(
                emb_in.shape[0], clip_tau, (in_idx, in_vals)
            )
            out_scale = _row_clip_scale(
                emb_out.shape[0], clip_tau,
                (out_idx, out_vals), (flat_negs, d_neg_flat),
            )
            clip_count = jnp.sum(
                (in_scale < 1.0).astype(jnp.float32)
            ) + jnp.sum((out_scale < 1.0).astype(jnp.float32))
            in_vals = in_vals * in_scale[in_idx][:, None]
            out_vals = out_vals * out_scale[out_idx][:, None]
            d_neg_flat = d_neg_flat * out_scale[flat_negs][:, None]

        new_params = dict(params)
        new_params["emb_in"] = emb_in.at[in_idx].add(
            _cast_update(
                in_vals, emb_in.dtype, k_sr(0),
                emb_in[in_idx] if sr else None,
            ),
            indices_are_sorted=True,
        )
        new_out = emb_out.at[out_idx].add(
            _cast_update(
                out_vals, emb_out.dtype, k_sr(1),
                emb_out[out_idx] if sr else None,
            ),
            indices_are_sorted=True,
        )
        # SR dest rows for the negative scatter come from NEW_out — the
        # scatter above may have moved a shared row across a binade
        # (band_step XLA tail, same note)
        new_params["emb_out_ns"] = new_out.at[flat_negs].add(
            _cast_update(
                d_neg_flat, emb_out.dtype, k_sr(2),
                new_out[flat_negs] if sr else None,
            )
        )
        metrics = {
            "loss_sum": losses[0, 0] + losses[0, 1],
            "pairs": pos_pairs + jnp.sum(w_neg_flat),
            "clip_engaged": clip_count,
        }
        return new_params, metrics

    return step_pallas


def _make_pallas_fused_step(config: Word2VecConfig, tables: DeviceTables):
    """band_backend='pallas_fused' (ops/pallas_step.py): the whole unified
    band step as gather->dot->grad->overlap-add in one Pallas kernel per
    band chunk plus the in-kernel doubled-width sorted scatter. The tail
    between the two kernels (argsort, scatter_mean counts, the clip trust
    region, bf16 SR casts on the split step's exact per-plane stream
    indices, and the unsorted negative-row scatter) is the XLA fused
    branch's code, shared value-for-value — which is what makes the f32
    trajectory bitwise and bf16 ± SR exact vs the XLA chain
    (tests/test_pallas_step.py)."""
    from . import pallas_step

    if config.negative_scope != "row":
        # d_neg under a batch-scope pool reduces over (batch, position)
        # jointly — no per-row kernel order reproduces that bitwise
        # (ops/pallas_step.py scope note)
        raise ValueError(
            "band_backend='pallas_fused' supports negative_scope='row' "
            "only (a batch-scope pool's negative gradient reduces over "
            "the whole batch at once); use band_backend='pallas_oa', "
            "which composes with negative_scope='batch'"
        )

    W = config.window
    K = config.negative
    KP = config.shared_negatives
    is_cbow = config.model == "cbow"
    cbow_mean = config.cbow_mean
    scatter_mean = config.scatter_mean
    clip_tau = config.clip_row_update
    sr = config.stochastic_rounding
    cdt = jnp.dtype(config.compute_dtype)

    # interpret=True routes through the Pallas interpreter on non-TPU
    # backends (CPU tests / smoke); the same code compiles to Mosaic on
    # chip — the pallas/pallas_oa gate
    interpret = jax.devices()[0].platform != "tpu"

    def step_fused(
        params: Params, tokens: jnp.ndarray, key: jax.Array, alpha: jnp.ndarray
    ) -> Tuple[Params, Metrics]:
        B, L = tokens.shape
        k_sub, k_win, k_neg = jax.random.split(key, 3)
        # same stream indices as the XLA tail (0=in, 1=out, 2=negatives)
        k_sr = _sr_streams(key, sr)

        valid = tokens >= 0
        tok = jnp.where(valid, tokens, 0)
        keep = valid & (jax.random.uniform(k_sub, (B, L)) < tables.keep_probs[tok])
        w_eff = W - jax.random.randint(k_win, (B, L), 0, W, dtype=jnp.int32)

        S = banded.resolve_chunk(L, W, config.band_chunk)
        if S == 0:
            raise ValueError(
                f"band_backend='pallas_fused' needs the chunked band "
                f"representation, but rows of length {L} resolved to the "
                f"dense path (band_chunk={config.band_chunk}, window={W}). "
                f"Set band_chunk to 2*window <= S < {L}, or use "
                f"band_backend='xla' for short rows"
            )
        C, _ = banded._geom(L, W, S)
        emb = params[FUSED_KEY]  # [V, 2, d]
        d = emb.shape[-1]

        negs = _draw_negatives(
            k_neg, (B, KP), tables.alias_accept, tables.alias_idx
        )  # [B, KP]

        pad_c = C * S - L
        tok_c = jnp.pad(tok, ((0, 0), (0, pad_c))).reshape(B, C, S)
        tok_k = banded.slab_token_ids(tokens, W, S)  # raw ids, -1 outside
        keep_c = jnp.pad(
            keep.astype(jnp.float32), ((0, 0), (0, pad_c))
        ).reshape(B, C, S)
        w_c = jnp.pad(
            w_eff.astype(jnp.float32), ((0, 0), (0, pad_c))
        ).reshape(B, C, S)

        d_ctr, d_ctx, nctx_c, ctxw_c, d_neg, wns, losses = (
            pallas_step.fused_grad_core(
                emb, tok_c, tok_k, keep_c, w_c, negs, alpha,
                W=W, K=K, L=L, cdt=cdt, is_cbow=is_cbow,
                cbow_mean=cbow_mean, interpret=interpret,
            )
        )
        d_ctr = d_ctr.reshape(B, C * S, d)[:, :L]
        d_ctx = d_ctx.reshape(B, C * S, d)[:, :L]
        n_ctx = nctx_c.reshape(B, C * S)[:, :L]
        ctx_w = ctxw_c.reshape(B, C * S)[:, :L]
        d_neg_flat = d_neg.reshape(-1, d)
        flat_negs = negs.reshape(-1)

        # routing mirrors the XLA fused branch (token-order context grads
        # share the center side's sorted index set)
        active = (keep & (n_ctx > 0)).astype(jnp.float32)
        if not is_cbow:
            d_in_pos, d_out_pos = d_ctr, d_ctx
            in_weight, out_weight = active, ctx_w
            pos_pairs = jnp.sum(n_ctx)
        else:
            d_in_pos, d_out_pos = d_ctx, d_ctr
            in_weight, out_weight = ctx_w, active
            pos_pairs = jnp.sum(active)

        # ---- the XLA fused tail, value-for-value (ops above): one shared
        # argsort of the row token ids, joint scatter_mean counts, one clip
        # budget per table, per-plane SR streams
        flat = tok.reshape(-1)
        order = jnp.argsort(flat)
        sorted_idx = flat[order]
        d_in_flat = d_in_pos.reshape(-1, d)[order]
        if scatter_mean:
            d_in_flat = d_in_flat * _dup_mean_scale(
                emb.shape[0], sorted_idx, in_weight.reshape(-1)[order]
            )[:, None]
        d_out_flat = d_out_pos.reshape(-1, d)[order]
        if scatter_mean:
            cnt = (
                jnp.zeros((emb.shape[0],), jnp.float32)
                .at[flat].add(out_weight.reshape(-1))
                .at[flat_negs].add(wns.reshape(-1))
            )
            inv = 1.0 / jnp.maximum(cnt, 1.0)
            d_out_flat = d_out_flat * inv[sorted_idx][:, None]
            d_neg_flat = d_neg_flat * inv[flat_negs][:, None]

        clip_count = jnp.float32(0.0)
        if clip_tau > 0.0:
            in_scale = _row_clip_scale(
                emb.shape[0], clip_tau, (sorted_idx, d_in_flat)
            )
            out_scale = _row_clip_scale(
                emb.shape[0], clip_tau,
                (sorted_idx, d_out_flat), (flat_negs, d_neg_flat),
            )
            clip_count = jnp.sum(
                (in_scale < 1.0).astype(jnp.float32)
            ) + jnp.sum((out_scale < 1.0).astype(jnp.float32))
            d_in_flat = d_in_flat * in_scale[sorted_idx][:, None]
            d_out_flat = d_out_flat * out_scale[sorted_idx][:, None]
            d_neg_flat = d_neg_flat * out_scale[flat_negs][:, None]

        vals2 = jnp.stack(
            [
                _cast_update(
                    d_in_flat, emb.dtype, k_sr(0),
                    emb[sorted_idx, 0] if sr else None,
                ),
                _cast_update(
                    d_out_flat, emb.dtype, k_sr(1),
                    emb[sorted_idx, 1] if sr else None,
                ),
            ],
            axis=1,
        )
        # the doubled-width sorted scatter runs INSIDE the kernel
        # (sequential RMW = XLA's sorted left-to-right duplicate order)
        new_emb = pallas_step.fused_slab_scatter(
            emb, sorted_idx, vals2, interpret=interpret
        )
        # negative rows: unsorted tail scatter, SR dest rows from NEW_emb
        # (the XLA fused branch's binade note)
        new_emb = new_emb.at[flat_negs, 1].add(
            _cast_update(
                d_neg_flat, emb.dtype, k_sr(2),
                new_emb[flat_negs, 1] if sr else None,
            )
        )
        new_params = dict(params)
        new_params[FUSED_KEY] = new_emb
        metrics = {
            "loss_sum": losses[0, 0] + losses[0, 1],
            "pairs": pos_pairs + jnp.sum(wns),
            "clip_engaged": clip_count,
        }
        return new_params, metrics

    return step_fused
