"""Fully-fused Pallas train step over the unified [V, 2, d] slab.

The unified-layout XLA band step (ops/band_step.py, table_layout="unified")
still materializes every intermediate in HBM: the [B, L, 2, d] gathered row
stack, four band-contraction planes, the overlap-add chain, and the sorted
doubled-width scatter each execute as separate XLA programs with the row
tensors round-tripping between them. The r12 lever (ROADMAP item 2) is to
delete those boundaries: the banked TPU best is dispatch-tail-bound
(tracediff attributes the kp16 win 100% to dispatch, PERF.md), and the
planner can only shrink the tail, not remove it.

This module is the whole band step as two Pallas kernels over the
HBM-resident slab (`band_backend='pallas_fused'`):

  * `fused_grad_core` — grid (B, C+1). Per (batch row, band chunk) it
    DMA-gathers the center rows (both planes at once — the unified layout's
    one-gather contract), the context slab rows and the shared-negative
    rows straight from the slab, computes the band mask, the positive and
    negative logits, sigmoid and every gradient contraction in VMEM, and
    performs the context-gradient overlap-add IN TOKEN ORDER with a
    one-chunk-lagged window reduction (the ops/pallas_overlap.py structure,
    inlined: chunk c's rows sum their own slab slots plus the <= W-wide
    left/right neighbor contributions, so the +1 grid step per row flushes
    the last chunk once its right neighbor can no longer exist). Outputs
    are exactly the tensors the unified scatter tail needs — per-token
    center/context gradients, n_ctx / context-weight counts, the per-row
    negative gradients and expectation weights — nothing else touches HBM.
  * `fused_slab_scatter` — the doubled-width sorted scatter back into the
    slab, input/output-aliased: sequential read-modify-write over the
    sorted (token id, [2, d] value) rows, so duplicate ids accumulate in
    exactly the left-to-right order XLA's sorted-indices scatter applies
    (pinned by tests/test_pallas_step.py) and the sorted order the r2
    "slab scatter lost" experiment destroyed is preserved inside the
    kernel. Padding ids are -1 and skipped.

Parity contract (the `pallas_oa` bar): the f32 trajectory vs the unified
XLA chain is BITWISE in interpret mode across sg/cbow x negative-scope-row
x scatter_mean x clip, and bf16 tables ± stochastic rounding match exactly
too (the SR cast runs in the shared band_step tail on the split step's
exact per-plane stream indices). That holds by construction, not by luck:

  * every contraction is a per-chunk `dot_general` whose per-element
    reduction XLA computes identically for the chunked and full shapes
    (same contraction length, same operand dtypes);
  * cross-position reductions (d_neg, w_neg sums) are NOT accumulated
    chunk-by-chunk — the per-chunk gn/h/w_neg rows are staged in VMEM
    scratch and reduced once per batch row at the flush step, over exactly
    the row's L positions, reproducing the XLA einsum's reduction shape;
  * the overlap-add sums the identical <= 2 slab slots per token row that
    banded._overlap_add sums (two-operand float addition is order-free);
  * the loss metrics are the one exception: they accumulate per chunk
    across the sequential grid (a reassociation), so `loss_sum` is pinned
    to rtol, not bitwise — parameters, the thing checkpoints and the
    quality gate read, stay exact.

cbow note: the center logit is a BATCHED row-dot (XLA's einsum
"bid,bid->bi"). Mosaic has no batched dot_general, so the compiled kernel
realizes it as multiply + row-sum on the VPU (one-ulp-class reassociation);
the interpreter path keeps the batched dot so the CPU parity pin stays
bitwise. Everything else is identical code on both paths.

Scope (config validation + ops/band_step.py): ns band kernel,
table_layout='unified' only (the kernel gathers and scatters the slab —
split tables have two index spaces), negative_scope='row' (a batch-scope
pool's d_neg reduces over (b, i) jointly, which no per-row kernel order
reproduces bitwise — 'pallas_oa' composes with batch scope instead),
chunked band representation, single chip (parallel/trainer._reject_pallas).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Sorted-scatter row block per grid step of fused_slab_scatter; the caller
# pads the flattened id/value rows up to a multiple with id -1 (skipped).
SCATTER_BLOCK = 512


def _gather_rows(emb_ref, dst, idx_fn, n, sem, plane=None):
    """DMA-gather n rows of the HBM slab into VMEM scratch.

    idx_fn(j) -> row id (already clamped to [0, V)). plane selects one
    [d] plane of the [V, 2, d] slab; None copies the whole [2, d] row —
    the unified layout's one-gather-for-both-tables contract.
    """

    def body(j, carry):
        i = idx_fn(j)
        src = emb_ref.at[i] if plane is None else emb_ref.at[i, plane]
        cp = pltpu.make_async_copy(src, dst.at[j], sem)
        cp.start()
        cp.wait()
        return carry

    jax.lax.fori_loop(0, n, body, 0)


def _grad_kernel(
    alpha_ref,   # [1, 1] SMEM
    emb_ref,     # [V, 2, d] ANY (HBM-resident slab)
    tokc_s,      # [1, 1, S, 1] SMEM int32 (clamped center ids, DMA source)
    tokk_s,      # [1, 1, SK, 1] SMEM int32 (raw slab ids, -1 outside)
    negs_s,      # [1, KP, 1] SMEM int32
    tokc_v,      # [1, 1, 1, S] int32
    tokk_v,      # [1, 1, 1, SK] int32
    keep_v,      # [1, 1, 1, S] f32
    wc_v,        # [1, 1, 1, S] f32
    negs_v,      # [1, 1, KP] int32
    d_ctr_ref,   # [1, 1, S, d] out (token order, one-chunk lag)
    d_ctx_ref,   # [1, 1, S, d] out (token order, one-chunk lag)
    nctx_ref,    # [1, 1, 1, S] out
    ctxw_ref,    # [1, 1, 1, S] out (token order, one-chunk lag)
    dneg_ref,    # [1, KP, d] out (per batch row)
    wns_ref,     # [1, 1, KP] out (per batch row)
    loss_ref,    # [1, 2] out (accumulated over the grid)
    g2,          # scratch [S, 2, d] emb dtype — gathered center rows
    bk,          # scratch [SK, d] emb dtype — gathered context-plane rows
    en,          # scratch [KP, d] emb dtype — gathered negative rows
    h_full,      # scratch [C*S, d] f32 — per-row hidden rows (flush input)
    gn_full,     # scratch [C*S, KP] f32
    wn_full,     # scratch [C*S, KP] f32
    y_scr,       # scratch [SK, d] f32 — this chunk's slab-space ctx grad
    cwy_scr,     # scratch [1, SK] f32 — this chunk's slab col sums
    dctr_scr,    # scratch [S, d] f32 — this chunk's center grad
    ctr_stash,   # scratch [S, d] f32 — previous chunk's center grad
    part_stash,  # scratch [S, d] f32 — prev chunk's ctx grad, body + left
    tail_stash,  # scratch [W, d] f32 — prev chunk's right-overhang slots
    cw_part,     # scratch [1, S] f32
    cw_tail,     # scratch [1, W] f32
    sem,         # DMA semaphore
    *,
    W: int,
    K: int,
    C: int,
    L: int,
    cdt,
    is_cbow: bool,
    cbow_mean: bool,
    interpret: bool,
):
    b = pl.program_id(0)
    c = pl.program_id(1)
    S = tokc_v.shape[3]
    SK = tokk_v.shape[3]
    KP = negs_v.shape[2]
    d = g2.shape[2]

    def dot(x, y, dims):
        return jax.lax.dot_general(
            x.astype(cdt), y.astype(cdt), (dims, ((), ())),
            preferred_element_type=jnp.float32,
        )

    alpha = alpha_ref[0, 0]
    # which slab plane each side lives on (Word2Vec.cpp:300-315 vs
    # :330-351 matrix roles): sg scores emb_in centers against emb_out
    # contexts; cbow swaps them. Negatives always live on the out plane.
    ctr_plane = 1 if is_cbow else 0
    ctx_plane = 0 if is_cbow else 1

    # ---------------------------------------------------- compute (c < C)
    @pl.when(c < C)
    def _compute():
        @pl.when(c == 0)
        def _():
            _gather_rows(
                emb_ref, en, lambda k: negs_s[0, k, 0], KP, sem, plane=1
            )

        # one DMA per center token fetches BOTH planes of its slab row
        _gather_rows(emb_ref, g2, lambda s: tokc_s[0, 0, s, 0], S, sem)
        _gather_rows(
            emb_ref, bk,
            lambda k: jnp.maximum(tokk_s[0, 0, k, 0], 0), SK, sem,
            plane=ctx_plane,
        )

        # band mask (banded.band_mask semantics; int32 iota — Mosaic
        # rejects float iota)
        s_iota = jax.lax.broadcasted_iota(jnp.int32, (S, SK), 0)
        k_iota = jax.lax.broadcasted_iota(jnp.int32, (S, SK), 1)
        dist = jnp.abs(s_iota + W - k_iota).astype(jnp.float32)
        valid_k = (tokk_v[0, 0, 0, :] >= 0).astype(jnp.float32)
        mask = (
            keep_v[0, 0, 0, :][:, None]
            * valid_k[None, :]
            * (dist <= wc_v[0, 0, 0, :][:, None]).astype(jnp.float32)
            * (dist > 0.0).astype(jnp.float32)
        )
        n_ctx = jnp.sum(mask, axis=1)  # [S]
        nctx_ref[0, 0, 0, :] = n_ctx
        cwy_scr[0, :] = jnp.sum(mask, axis=0)  # [SK] slab col sums

        a = g2[:, ctr_plane, :]         # center-side rows
        bk_rows = jnp.where(valid_k[:, None] > 0.0, bk[:], 0)

        # projection h and the reference draw count k_i per center
        if not is_cbow:
            h = a.astype(jnp.float32)
            k_i = n_ctx * float(K)
        else:
            h = dot(mask, bk_rows, ((1,), (0,)))
            if cbow_mean:
                h = h / jnp.maximum(n_ctx, 1.0)[:, None]
            k_i = jnp.where(n_ctx > 0.0, float(K), 0.0)
        h_full[pl.ds(c * S, S), :] = h

        # ---- negative side (per-row shared draws, collision-masked)
        negs = negs_v[0, 0, :]
        center_hit = (
            tokc_v[0, 0, 0, :][:, None] == negs[None, :]
        ).astype(jnp.float32)  # [S, KP]
        hit_k = (
            tokk_v[0, 0, 0, :][:, None] == negs[None, :]
        ).astype(jnp.float32)  # [SK, KP]
        ctx_hit = dot(mask, hit_k, ((1,), (0,)))
        neg_ok = 1.0 - jnp.clip(center_hit + ctx_hit, 0.0, 1.0)
        w_neg = (k_i / float(KP))[:, None] * neg_ok  # [S, KP]
        nlog = dot(h, en[:], ((1,), (1,)))  # [S, KP]
        gn = (0.0 - jax.nn.sigmoid(nlog)) * w_neg * alpha
        d_hid = dot(gn, en[:], ((1,), (0,)))  # [S, d]
        gn_full[pl.ds(c * S, S), :] = gn
        wn_full[pl.ds(c * S, S), :] = w_neg
        neg_loss = -jnp.sum(w_neg * (jax.nn.log_sigmoid(nlog) - nlog))

        # ---- positive side + gradient routing
        if not is_cbow:
            plog = dot(a, bk_rows, ((1,), (1,)))  # [S, SK] band logits
            gp = (1.0 - jax.nn.sigmoid(plog)) * mask * alpha
            dctr_scr[:] = d_hid + dot(gp, bk_rows, ((1,), (0,)))
            y_scr[:] = dot(gp, a, ((0,), (0,)))  # slab-space ctx grad
            pos_loss = -jnp.sum(mask * jax.nn.log_sigmoid(plog))
        else:
            # center logit = batched row-dot of h against the center's
            # out-plane row. The interpreter keeps XLA's batched-dot
            # reduction (the bitwise pin); Mosaic has no batched dot, so
            # on chip it is the VPU multiply + row-sum (module docstring).
            if interpret:
                plog_c = jax.lax.dot_general(
                    h.astype(cdt), a.astype(cdt),
                    (((1,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
            else:
                plog_c = jnp.sum(
                    h.astype(cdt).astype(jnp.float32)
                    * a.astype(cdt).astype(jnp.float32),
                    axis=1,
                )
            active = (n_ctx > 0.0).astype(jnp.float32)
            gp = (1.0 - jax.nn.sigmoid(plog_c)) * active * alpha  # [S]
            dctr_scr[:] = gp[:, None] * h  # center's emb_out update
            d_hid2 = d_hid + gp[:, None] * a.astype(jnp.float32)
            if cbow_mean:  # second divide (Word2Vec.cpp:313-315)
                d_hid2 = d_hid2 / jnp.maximum(n_ctx, 1.0)[:, None]
            y_scr[:] = dot(mask, d_hid2, ((0,), (0,)))
            pos_loss = -jnp.sum(active * jax.nn.log_sigmoid(plog_c))

        @pl.when(jnp.logical_and(b == 0, c == 0))
        def _():
            loss_ref[...] = jnp.zeros_like(loss_ref)

        loss_ref[0, :] = loss_ref[0, :] + jnp.stack([pos_loss, neg_loss])

    # ------------------------------------------------------ flush (c == C)
    @pl.when(c == C)
    def _flush():
        # no right neighbor exists for the last chunk
        y_scr[:] = jnp.zeros_like(y_scr)
        cwy_scr[:] = jnp.zeros_like(cwy_scr)
        # per-row reductions at FULL row granularity — the XLA einsum's
        # reduction shape, not a chunk-blocked reassociation (docstring)
        dneg_ref[0] = dot(gn_full[0:L, :], h_full[0:L, :], ((0,), (0,)))
        wns_ref[0, 0, :] = jnp.sum(wn_full[0:L, :], axis=0)

    # ------------------------------------- token-order outputs, lagged one
    # chunk: block (b, c-1) finalizes here, once chunk c's left-overhang
    # (this chunk's first W slab slots) is known. Same <= 2-slot sums as
    # banded._overlap_add (ops/pallas_overlap.py structure).
    d = g2.shape[2]
    zeros_tail = jnp.zeros((S - W, d), jnp.float32)
    rpad = jnp.concatenate([zeros_tail, y_scr[0:W, :]], axis=0)
    d_ctr_ref[0, 0] = ctr_stash[:]
    d_ctx_ref[0, 0] = part_stash[:] + rpad
    cw_rpad = jnp.concatenate(
        [jnp.zeros((1, S - W), jnp.float32), cwy_scr[:, 0:W]], axis=1
    )
    ctxw_ref[0, 0] = cw_part[:] + cw_rpad

    # ------------------------------------------------------- stash updates
    lpad = jnp.concatenate([tail_stash[:], zeros_tail], axis=0)
    # jnp.where (not a 0-gate multiply): the stash is uninitialized at
    # c == 0 and garbage * 0.0 would propagate NaN
    part_stash[:] = y_scr[W:S + W, :] + jnp.where(c > 0, lpad, 0.0)
    tail_stash[:] = y_scr[S + W:, :]
    ctr_stash[:] = dctr_scr[:]
    cw_lpad = jnp.concatenate(
        [cw_tail[:], jnp.zeros((1, S - W), jnp.float32)], axis=1
    )
    cw_part[:] = cwy_scr[:, W:S + W] + jnp.where(c > 0, cw_lpad, 0.0)
    cw_tail[:] = cwy_scr[:, S + W:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "W", "K", "L", "cdt", "is_cbow", "cbow_mean", "interpret",
    ),
)
def fused_grad_core(
    emb: jnp.ndarray,     # [V, 2, d] unified slab (any table dtype)
    tok_c: jnp.ndarray,   # [B, C, S] int32, clamped to [0, V)
    tok_k: jnp.ndarray,   # [B, C, SK] int32, -1 outside the row
    keep_c: jnp.ndarray,  # [B, C, S]
    w_c: jnp.ndarray,     # [B, C, S]
    negs: jnp.ndarray,    # [B, KP] int32 (negative_scope='row' only)
    alpha: jnp.ndarray,   # scalar
    *,
    W: int,
    K: int,
    L: int,
    cdt=jnp.bfloat16,
    is_cbow: bool = False,
    cbow_mean: bool = True,
    interpret: bool = False,
):
    """One fused gather->dot->grad->overlap-add pass; module docstring.

    Returns (d_ctr, d_ctx, n_ctx, ctx_w, d_neg, w_neg_sum, losses):
      d_ctr  [B, C, S, d]  center-side gradient, token order
      d_ctx  [B, C, S, d]  context-side gradient, token order (overlap-added)
      n_ctx  [B, C, S]     active contexts per center
      ctx_w  [B, C, S]     per-token context contribution counts
      d_neg  [B, KP, d]    negative-row gradient (reduced over the full row)
      w_neg_sum [B, KP]    per-draw expectation weight, summed over the row
      losses [1, 2]        (pos_loss, neg_loss), grid-accumulated (rtol-class)
    """
    B, C, S = tok_c.shape
    SK = tok_k.shape[2]
    _, KP = negs.shape
    d = emb.shape[2]

    def sds(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    def bc4(i, j):
        return (i, j, 0, 0)

    def bc4_clamp(i, j):
        return (i, jnp.minimum(j, C - 1), 0, 0)

    def bc3_clamp(i, j):
        return (i, jnp.minimum(j, C - 1), 0)

    def lag4(i, j):
        return (i, jnp.maximum(j - 1, 0), 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.ANY),
        # SMEM blocks carry a trailing singleton so the last two block
        # dims equal the array dims (the Mosaic SMEM tiling rule)
        pl.BlockSpec((1, 1, S, 1), bc4_clamp, memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, SK, 1), bc4_clamp, memory_space=pltpu.SMEM),
        pl.BlockSpec((1, KP, 1), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, 1, S), bc4_clamp),
        pl.BlockSpec((1, 1, 1, SK), bc4_clamp),
        pl.BlockSpec((1, 1, 1, S), bc4_clamp),
        pl.BlockSpec((1, 1, 1, S), bc4_clamp),
        pl.BlockSpec((1, 1, KP), lambda i, j: (i, 0, 0)),
    ]
    out_specs = [
        pl.BlockSpec((1, 1, S, d), lag4),
        pl.BlockSpec((1, 1, S, d), lag4),
        pl.BlockSpec((1, 1, 1, S), bc4_clamp),
        pl.BlockSpec((1, 1, 1, S), lag4),
        pl.BlockSpec((1, KP, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, 1, KP), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, 2), lambda i, j: (0, 0)),
    ]
    out_shape = [
        sds((B, C, S, d)),
        sds((B, C, S, d)),
        sds((B, C, 1, S)),
        sds((B, C, 1, S)),
        sds((B, KP, d)),
        sds((B, 1, KP)),
        sds((1, 2)),
    ]
    kernel = functools.partial(
        _grad_kernel, W=W, K=K, C=C, L=L, cdt=cdt, is_cbow=is_cbow,
        cbow_mean=cbow_mean, interpret=interpret,
    )
    outs = pl.pallas_call(
        kernel,
        grid=(B, C + 1),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((S, 2, d), emb.dtype),
            pltpu.VMEM((SK, d), emb.dtype),
            pltpu.VMEM((KP, d), emb.dtype),
            pltpu.VMEM((C * S, d), jnp.float32),
            pltpu.VMEM((C * S, KP), jnp.float32),
            pltpu.VMEM((C * S, KP), jnp.float32),
            pltpu.VMEM((SK, d), jnp.float32),
            pltpu.VMEM((1, SK), jnp.float32),
            pltpu.VMEM((S, d), jnp.float32),
            pltpu.VMEM((S, d), jnp.float32),
            pltpu.VMEM((S, d), jnp.float32),
            pltpu.VMEM((W, d), jnp.float32),
            pltpu.VMEM((1, S), jnp.float32),
            pltpu.VMEM((1, W), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(
        jnp.asarray(alpha, jnp.float32).reshape(1, 1),
        emb,
        tok_c[:, :, :, None], tok_k[:, :, :, None], negs[:, :, None],
        tok_c[:, :, None], tok_k[:, :, None],
        keep_c.astype(jnp.float32)[:, :, None],
        w_c.astype(jnp.float32)[:, :, None],
        negs[:, None],
    )
    d_ctr, d_ctx, nctx, ctxw, d_neg, wns, losses = outs
    return (
        d_ctr, d_ctx, nctx[:, :, 0], ctxw[:, :, 0], d_neg, wns[:, 0],
        losses,
    )


def _scatter_kernel(idx_ref, vals_ref, emb_in_ref, emb_ref, row, sem):
    """One SCATTER_BLOCK of the sorted doubled-width scatter: sequential
    read-modify-write per row, so duplicate ids accumulate left-to-right —
    the sorted-indices order XLA's scatter applies (emb_in_ref is the
    aliased input view of emb_ref; only emb_ref is touched)."""
    n = idx_ref.shape[0]

    def body(j, carry):
        i = idx_ref[j]

        @pl.when(i >= 0)
        def _():
            cp = pltpu.make_async_copy(emb_ref.at[i], row, sem)
            cp.start()
            cp.wait()
            row[:] = row[:] + vals_ref[j]
            cp2 = pltpu.make_async_copy(row, emb_ref.at[i], sem)
            cp2.start()
            cp2.wait()

        return carry

    jax.lax.fori_loop(0, n, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_slab_scatter(
    emb: jnp.ndarray,         # [V, 2, d]
    sorted_idx: jnp.ndarray,  # [N] int32, ascending; -1 = skip (padding)
    vals: jnp.ndarray,        # [N, 2, d] in emb's dtype
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """emb.at[sorted_idx].add(vals, indices_are_sorted=True), in-kernel:
    the slab is input/output-aliased and each sorted row is applied as one
    VMEM read-modify-write, preserving both the sorted order and XLA's
    left-to-right duplicate accumulation (bitwise in every table dtype —
    tests/test_pallas_step.py)."""
    n = sorted_idx.shape[0]
    d = emb.shape[2]
    blk = min(SCATTER_BLOCK, n)
    pad = (-n) % blk
    if pad:
        sorted_idx = jnp.concatenate(
            [sorted_idx, jnp.full((pad,), -1, sorted_idx.dtype)]
        )
        vals = jnp.concatenate(
            [vals, jnp.zeros((pad, 2, d), vals.dtype)]
        )
    return pl.pallas_call(
        _scatter_kernel,
        grid=((n + pad) // blk,),
        in_specs=[
            pl.BlockSpec((blk,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((blk, 2, d), lambda i: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(emb.shape, emb.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, d), emb.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={2: 0},
        interpret=interpret,
    )(sorted_idx, vals, emb)
