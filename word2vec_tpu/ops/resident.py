"""Device-resident corpus: batches assembled ON DEVICE inside the scanned chunk.

The host batcher (data/batcher.py) streams [S, B, L] token chunks over
host->device DMA every dispatch. For corpora that fit in HBM (text8 packed is
~68 MB against 16 GB; the gate is bytes, not design), the corpus can instead
live on device — the flat token stream plus the row table — and each step's
[B, L] batch is assembled by gathers inside the compiled program. A dispatch
then carries only scalars (key, step indices) plus one [R] int32 row-order
upload per EPOCH (~350 KB for text8), eliminating per-chunk token traffic
(6+ MB/chunk at the flagship geometry) and the host fill work with it.

The assembled batch is bit-identical to the host pipeline's
(native.fill_batch) on the same row order — pinned by tests/test_resident.py
— so the training trajectory is exactly the streaming path's: same rows per
step, same fold_in(key, step) stream, same alpha schedule.

Reference mapping: the host<->device split of SURVEY §3.2 moves one level up.
The per-epoch shuffle (Word2Vec.cpp:373) stays host-side as the [R]
permutation (a pure function of (seed, epoch), which is what mid-epoch resume
relies on); row fetch — the reference's `samples[idx]` read at
Word2Vec.cpp:377-390 — joins everything below it on device.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Word2VecConfig
# epoch_order re-exported: the row permutation is shared with BatchIterator
# so resident and streaming paths visit identical rows in identical order
from ..data.batcher import PAD, PackedCorpus, epoch_order  # noqa: F401
from .tables import DeviceTables
from .train_step import make_train_step

# Ceiling for the auto-mode resident gate when the backend cannot report
# real memory: 2 GiB leaves the [V, d] tables and step workspace ample HBM
# on any current chip; int32 row addressing holds to 2^31 tokens anyway.
RESIDENT_MAX_BYTES = 2 << 30


def resident_budget_bytes() -> int:
    """The packed-corpus HBM budget for auto mode.

    Prefers the device's real accounting (memory_stats: bytes_limit minus
    bytes_in_use, which already counts the tables and any donation
    double-buffers living on the chip — the corpus is replicated per device
    on sharded meshes, so per-device free memory is the right denominator)
    with a 2x headroom for step workspace, capped at RESIDENT_MAX_BYTES.
    Falls back to the constant where the backend reports nothing (CPU).

    Reads the first LOCAL device: on multi-process runs the global
    jax.devices()[0] is non-addressable on ranks != 0 and memory_stats
    raises there, which would silently put rank 0 on live stats and every
    other rank on the fallback constant. Because the resident-vs-streaming
    choice gates which program gets compiled, every process must gate on
    the SAME number — live per-host free-memory differences would otherwise
    compile mismatched programs whose collectives deadlock — so
    multi-process callers agree on the min budget across processes,
    mirroring the steps-per-epoch agreement (parallel/trainer.py). Note the
    shipped multi-host trainer currently STREAMS unconditionally
    (parallel/trainer.py _build_resident returns None when procs > 1, so
    its budget call never happens with procs > 1); the agreement branch
    makes this function safe for any direct caller and for future
    multi-host resident wiring, which must keep it.

    The stats read routes through obs/devmem.device_memory_stats — the ONE
    memory_stats funnel, shared with the HBM memory ledger — so the budget
    gate and the ledger can never disagree on what the device reported (and
    the statless-backend degrade is defined in exactly one place)."""
    from ..obs.devmem import device_memory_stats

    budget = RESIDENT_MAX_BYTES
    try:
        stats = device_memory_stats(jax.local_devices()[0]) or {}
        limit = stats.get("bytes_limit")
        if limit:
            free = int(limit) - int(stats.get("bytes_in_use", 0))
            budget = max(0, min(RESIDENT_MAX_BYTES, free // 2))
    except Exception:
        pass
    if jax.process_count() > 1:
        from ..parallel.multihost import global_agree_min

        budget = global_agree_min(budget)
    return budget


DeviceCorpus = Dict[str, jnp.ndarray]  # {"flat": [N], "starts": [R], "lens": [R]} i32


def corpus_fits(corpus: PackedCorpus, max_bytes: int | None = None) -> bool:
    if max_bytes is None:
        # live budget each call (testable via the module attrs)
        max_bytes = resident_budget_bytes()
    return (
        corpus.flat.nbytes + 8 * corpus.num_rows <= max_bytes
        and len(corpus.flat) < 2**31
    )


def corpus_arrays(corpus: PackedCorpus) -> Dict[str, np.ndarray]:
    """The packed corpus as int32 host arrays, ready for device placement."""
    if len(corpus.flat) >= 2**31:
        raise ValueError("corpus too large for int32 row addressing")
    return {
        "flat": np.asarray(corpus.flat, np.int32),
        "starts": corpus.row_starts.astype(np.int32),
        "lens": np.asarray(corpus.row_lens, np.int32),
    }


def device_corpus(corpus: PackedCorpus) -> DeviceCorpus:
    """Place the packed corpus in HBM (one transfer, reused every dispatch)."""
    return {k: jnp.asarray(v) for k, v in corpus_arrays(corpus).items()}


def assemble_batch(
    corpus: DeviceCorpus,
    order: jnp.ndarray,  # [R] int32 — this epoch's row permutation
    t: jnp.ndarray,      # batch index into the permuted row sequence
    batch_rows: int,
    max_len: int,
    col0: int | jnp.ndarray = 0,
) -> jnp.ndarray:
    """[B, max_len] token batch for batch index t; PAD(-1) outside rows.

    Matches native.fill_batch semantics exactly: batch t takes rows
    order[t*B : t*B+B]; positions past the end of the epoch (partial final
    batch, or no-op pad steps of a chunk) come out as all-PAD rows, which
    every kernel mask provably ignores.

    col0 selects a column window [col0, col0 + max_len) of each row — the
    sequence-parallel shard's position slice (a shard assembles only its own
    columns of the conceptual [B, L] batch).
    """
    n_rows = order.shape[0]
    pos = t * batch_rows + jnp.arange(batch_rows, dtype=jnp.int32)
    in_epoch = pos < n_rows
    rows = jnp.where(in_epoch, order[jnp.minimum(pos, n_rows - 1)], -1)
    ok = rows >= 0
    r = jnp.where(ok, rows, 0)
    starts = corpus["starts"][r]
    lens = jnp.where(ok, corpus["lens"][r], 0)
    cols = col0 + jnp.arange(max_len, dtype=jnp.int32)[None, :]
    within = cols < lens[:, None]
    idx = jnp.minimum(starts[:, None] + cols, corpus["flat"].shape[0] - 1)
    return jnp.where(within, corpus["flat"][idx], PAD)


def make_resident_chunk_runner(
    config: Word2VecConfig, tables: DeviceTables
):
    """S sequential optimizer steps as ONE device program, batches assembled
    on device (single-chip; sharded training keeps the streaming host path).

    chunk(params, corpus, order, base_key, step0, epoch_t0, alphas[S])
        -> (params, {"loss_sum": [S], "pairs": [S]})

    Identical trajectory contract to make_chunk_runner (step i uses
    fold_in(base_key, step0 + i) and alphas[i]); epoch_t0 is the within-epoch
    step index of the chunk's first step (skip + chunk offset on resume).
    Both step indices are traced scalars, so one compiled program serves
    every chunk of every epoch.
    """
    fused = config.fused_tables
    step = make_train_step(config, tables, fused=fused)
    B, L = config.batch_rows, config.max_sentence_len

    def chunk(params, corpus, order, base_key, step0, epoch_t0, alphas):
        if fused:
            from ..models.params import fuse_tables, unfuse_tables

            params = fuse_tables(params)

        def body(p, xs):
            i, a = xs
            tokens = assemble_batch(corpus, order, epoch_t0 + i, B, L)
            key = jax.random.fold_in(base_key, step0 + i)
            p, m = step(p, tokens, key, a)
            return p, m

        s = alphas.shape[0]
        idx = jnp.arange(s, dtype=jnp.int32)
        params, metrics = jax.lax.scan(body, params, (idx, alphas))
        if fused:
            params = unfuse_tables(params)
        return params, metrics

    return chunk


def jit_resident_chunk_runner(config: Word2VecConfig, tables: DeviceTables):
    """The resident runner jitted with params-buffer donation (the corpus and
    order arrays are NOT donated — they are reused across dispatches)."""
    return jax.jit(make_resident_chunk_runner(config, tables), donate_argnums=0)




def epoch_step_words(
    corpus: PackedCorpus, order: np.ndarray, batch_rows: int
) -> np.ndarray:
    """[steps_per_epoch] words consumed by each optimizer step (host-side
    alpha schedule + progress accounting; the device only needs tokens)."""
    lens = corpus.row_lens[order].astype(np.int64)
    n = len(lens)
    steps = -(-n // batch_rows)
    padded = np.zeros(steps * batch_rows, np.int64)
    padded[:n] = lens
    return padded.reshape(steps, batch_rows).sum(axis=1)
