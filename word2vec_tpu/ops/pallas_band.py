"""Fused Pallas TPU kernel for the banded sg+ns training step.

The XLA band chain (ops/band_step.py + ops/banded.py) materializes every
intermediate in HBM: the gathered [B, L, d] row tensors are re-read by four
band contractions, the [B, C, S, S+2W] logit/grad planes round-trip between
them, and XLA inserts layout copies around the overlap-add (measured 2.14 ms
= 27% of the round-2 step, PERF.md). This kernel is the flash-attention
treatment of the same math (SURVEY §7 step 8): one pass per (batch row,
chunk) that keeps the logit plane, the sigmoid, both positive-side gradient
contractions, and the whole shared-negative side in VMEM, reading each row
tensor from HBM exactly once and writing exactly the gradient tensors the
scatters need.

Same objective as band_step.py (Word2Vec.cpp:251-271,319-353 semantics with
the shared-negative reformulation documented there) — pinned against the
XLA kernel by tests/test_pallas_band.py.

Scope (config.band_backend="pallas"; band_step falls back to the XLA chain
otherwise): sg or cbow + negative sampling, per-row or batch negative scope,
unfused tables (f32 or bf16, with or without stochastic rounding — the SR
quantization happens in the caller's scatters, outside the kernel),
chunked band representation (S > 0), SINGLE-CHIP ONLY
(plain Trainer; sharded trainers reject it up front — pallas_call under
shard_map is unvalidatable here: the interpreter's internals are not
vma-aware, and no multi-chip hardware exists to compile the real thing;
parallel/trainer._reject_pallas). The context
gradient is emitted in SLAB space and flows through the sorted slab scatter
(band_step.py v2), so the overlap-add never exists anywhere on the pallas
path.

Layout contract (all pre-chunked by the caller with ops/banded helpers):
  a      [B, C, S, d]     center rows (ein chunks for sg, eout for cbow;
                          zero rows past L)
  bk     [B, C, S+2W, d]  context slabs (eout for sg, ein for cbow — the
                          matrix-role swap of Word2Vec.cpp:300-315 vs
                          :330-351; zero rows outside)
  en     [B, KP, d]       shared negative rows ([1, KP, d] batch scope)
  tok_c  [B, C, S]        center token ids, -1 past row end
  tok_k  [B, C, S+2W]     slab token ids, -1 outside (banded.slab_token_ids)
  keep_c [B, C, S]        center gate (subsample & valid), f32 0/1
  w_c    [B, C, S]        per-center shrunk window, f32
  negs   [B, KP]          negative ids ([1, KP] batch scope)
  alpha  scalar           learning rate

Outputs:
  d_h        [B, C, S, d]     center-row gradient (positives + negatives
                              for sg; the center's emb_out update for cbow)
  d_ctx      [B, C, S+2W, d]  context-row gradient, slab space (onto
                              emb_out for sg, emb_in for cbow)
  d_neg      [B, KP, d]       negative-row gradient (accumulated over C;
                              [1, KP, d] batch scope, accumulated over B too)
  n_ctx      [B, C, S]        active contexts per center (band row sums)
  ctx_w      [B, C, S+2W]     contribution weight per slab slot (col sums)
  w_neg_sum  [B, KP]          per-draw expectation weight, summed over rows
  losses     [1, 2]           (pos_loss, neg_loss) accumulated over the grid

The grid is (B, C) with C innermost; d_neg/w_neg_sum accumulate across the
C steps of one row (across the whole grid under batch scope), losses across
the whole grid — safe because the TPU grid executes sequentially.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _band_kernel(
    alpha_ref,  # [1, 1] SMEM
    a_ref,      # [1, 1, S, d]
    bk_ref,     # [1, 1, S+2W, d]
    en_ref,     # [1, KP, d]
    tokc_ref,   # [1, 1, 1, S] int32
    tokk_ref,   # [1, 1, 1, S+2W] int32
    keep_ref,   # [1, 1, 1, S] f32
    wc_ref,     # [1, 1, 1, S] f32
    negs_ref,   # [1, 1, KP] int32
    d_h_ref,    # [1, 1, S, d]
    d_ctx_ref,  # [1, 1, S+2W, d]
    d_neg_ref,  # [1, KP, d]
    nctx_ref,   # [1, 1, 1, S]
    ctxw_ref,   # [1, 1, 1, S+2W]
    wns_ref,    # [1, 1, KP]
    loss_ref,   # [1, 2]
    *,
    W: int,
    K: int,
    cdt,
    neg_shared: bool,
    is_cbow: bool,
    cbow_mean: bool,
):
    b = pl.program_id(0)
    c = pl.program_id(1)
    S = a_ref.shape[2]
    SK = bk_ref.shape[2]  # S + 2W

    def dot(x, y, dims):
        return jax.lax.dot_general(
            x.astype(cdt), y.astype(cdt), (dims, ((), ())),
            preferred_element_type=jnp.float32,
        )

    alpha = alpha_ref[0, 0]

    # ---- band mask [S, S+2W]: keep_i & valid_j & 0 < |i-j| <= w_eff_i
    # (Word2Vec.cpp:282,285-287,332,335-337 gates, as in banded.band_mask)
    # int32 iota (Mosaic rejects float iota), |i + W - j| exact in i32
    s_iota = jax.lax.broadcasted_iota(jnp.int32, (S, SK), 0)
    k_iota = jax.lax.broadcasted_iota(jnp.int32, (S, SK), 1)
    dist = jnp.abs(s_iota + W - k_iota).astype(jnp.float32)
    valid_k = (tokk_ref[0, 0, 0, :] >= 0).astype(jnp.float32)
    mask = (
        keep_ref[0, 0, 0, :][:, None]
        * valid_k[None, :]
        * (dist <= wc_ref[0, 0, 0, :][:, None]).astype(jnp.float32)
        * (dist > 0.0).astype(jnp.float32)
    )
    n_ctx = jnp.sum(mask, axis=1)  # [S]
    nctx_ref[0, 0, 0, :] = n_ctx
    ctxw_ref[0, 0, 0, :] = jnp.sum(mask, axis=0)

    a = a_ref[0, 0]
    bk = bk_ref[0, 0]

    # ---- projection h per center (Word2Vec.cpp:300-302 vs :330) and the
    # reference draw count k_i each shared draw stands in for
    if not is_cbow:
        h = a  # center row of emb_in
        k_i = n_ctx * float(K)
    else:
        h = dot(mask, bk, ((1,), (0,)))  # sum of context rows of emb_in
        if cbow_mean:
            h = h / jnp.maximum(n_ctx, 1.0)[:, None]
        k_i = jnp.where(n_ctx > 0.0, float(K), 0.0)

    # ---- negative side: shared draws, collision-masked per center
    # (center/context-collision semantics of band_step.py)
    en = en_ref[0]
    negs = negs_ref[0, 0, :]
    center_hit = (tokc_ref[0, 0, 0, :][:, None] == negs[None, :]).astype(
        jnp.float32
    )  # [S, KP]
    hit_k = (tokk_ref[0, 0, 0, :][:, None] == negs[None, :]).astype(
        jnp.float32
    )  # [S+2W, KP]
    ctx_hit = dot(mask, hit_k, ((1,), (0,)))  # [S, KP]
    neg_ok = 1.0 - jnp.clip(center_hit + ctx_hit, 0.0, 1.0)
    KP = neg_ok.shape[1]
    w_neg = (k_i / float(KP))[:, None] * neg_ok  # [S, KP]
    nlog = dot(h, en, ((1,), (1,)))  # [S, KP]
    gn = (0.0 - jax.nn.sigmoid(nlog)) * w_neg * alpha
    d_hid = dot(gn, en, ((1,), (0,)))  # [S, d] hidden grad, negatives
    d_neg_c = dot(gn, h, ((0,), (0,)))  # [KP, d]
    neg_loss = -jnp.sum(w_neg * (jax.nn.log_sigmoid(nlog) - nlog))

    # ---- positive side + gradient routing
    if not is_cbow:
        plog = dot(a, bk, ((1,), (1,)))  # [S, S+2W] band logits
        gp = (1.0 - jax.nn.sigmoid(plog)) * mask * alpha
        # center rows accumulate positive + negative hidden grads
        d_h_ref[0, 0] = d_hid + dot(gp, bk, ((1,), (0,)))
        # context rows of emb_out, slab space
        d_ctx_ref[0, 0] = dot(gp, a, ((0,), (0,)))
        pos_loss = -jnp.sum(mask * jax.nn.log_sigmoid(plog))
    else:
        # positive target = center word on the OUT matrix (a), scored
        # against the projection (Word2Vec.cpp:304-311). Operands round
        # to the compute dtype exactly like the XLA einsum (products and
        # accumulation stay f32 — MXU semantics).
        plog_c = jnp.sum(
            h.astype(cdt).astype(jnp.float32)
            * a.astype(cdt).astype(jnp.float32),
            axis=1,
        )  # [S]
        active = (n_ctx > 0.0).astype(jnp.float32)
        gp = (1.0 - jax.nn.sigmoid(plog_c)) * active * alpha  # [S]
        d_h_ref[0, 0] = gp[:, None] * h  # center's emb_out update
        d_hid = d_hid + gp[:, None] * a
        if cbow_mean:  # second divide (Word2Vec.cpp:313-315 semantics)
            d_hid = d_hid / jnp.maximum(n_ctx, 1.0)[:, None]
        # fan the hidden grad to contributing context rows of emb_in
        d_ctx_ref[0, 0] = dot(mask, d_hid, ((0,), (0,)))
        pos_loss = -jnp.sum(active * jax.nn.log_sigmoid(plog_c))

    # ---- accumulations across the sequential grid
    fresh = jnp.logical_and(b == 0, c == 0) if neg_shared else (c == 0)

    @pl.when(fresh)
    def _():
        d_neg_ref[...] = jnp.zeros_like(d_neg_ref)
        wns_ref[...] = jnp.zeros_like(wns_ref)

    d_neg_ref[0] += d_neg_c
    wns_ref[0, 0, :] += jnp.sum(w_neg, axis=0)

    @pl.when(jnp.logical_and(b == 0, c == 0))
    def _():
        loss_ref[...] = jnp.zeros_like(loss_ref)

    # vector store: Mosaic cannot store scalars to VMEM
    loss_ref[0, :] = loss_ref[0, :] + jnp.stack([pos_loss, neg_loss])


@functools.partial(
    jax.jit,
    static_argnames=("W", "K", "cdt", "is_cbow", "cbow_mean", "interpret"),
)
def band_core(
    a: jnp.ndarray,       # [B, C, S, d]
    bk: jnp.ndarray,      # [B, C, S+2W, d]
    en: jnp.ndarray,      # [B|1, KP, d]
    tok_c: jnp.ndarray,   # [B, C, S] int32
    tok_k: jnp.ndarray,   # [B, C, S+2W] int32
    keep_c: jnp.ndarray,  # [B, C, S]
    w_c: jnp.ndarray,     # [B, C, S]
    negs: jnp.ndarray,    # [B|1, KP] int32
    alpha: jnp.ndarray,   # scalar
    *,
    W: int,
    K: int,
    cdt=jnp.bfloat16,
    is_cbow: bool = False,
    cbow_mean: bool = True,
    interpret: bool = False,
):
    """One fused pass over the band; see the module docstring contract.

    en/negs with leading dim 1 (batch-scope negatives) are shared by every
    batch row; d_neg/w_neg_sum then come back [1, KP, d]/[1, KP] already
    summed over the batch.
    """
    B, C, S, d = a.shape
    SK = bk.shape[2]
    NB, KP = negs.shape
    neg_shared = NB == 1

    def sds(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    # Rank-3 payloads (tok/keep/w/n_ctx/ctx_w) are passed with a singleton
    # axis before their last dim so every block's trailing two dims equal
    # the array's (Mosaic tiling rule: last two block dims must divide
    # (8, 128) or equal the array dims).
    def bc4(i, j):
        return (i, j, 0, 0)

    def nb3(i, j):
        return (0 if neg_shared else i, 0, 0)

    grid_spec = pl.GridSpec(
        grid=(B, C),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, S, d), bc4),
            pl.BlockSpec((1, 1, SK, d), bc4),
            pl.BlockSpec((1, KP, d), nb3),
            pl.BlockSpec((1, 1, 1, S), bc4),
            pl.BlockSpec((1, 1, 1, SK), bc4),
            pl.BlockSpec((1, 1, 1, S), bc4),
            pl.BlockSpec((1, 1, 1, S), bc4),
            pl.BlockSpec((1, 1, KP), nb3),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, S, d), bc4),
            pl.BlockSpec((1, 1, SK, d), bc4),
            pl.BlockSpec((1, KP, d), nb3),
            pl.BlockSpec((1, 1, 1, S), bc4),
            pl.BlockSpec((1, 1, 1, SK), bc4),
            pl.BlockSpec((1, 1, KP), nb3),
            pl.BlockSpec((1, 2), lambda i, j: (0, 0)),
        ],
    )
    out_shape = [
        sds((B, C, S, d)),
        sds((B, C, SK, d)),
        sds((NB, KP, d)),
        sds((B, C, 1, S)),
        sds((B, C, 1, SK)),
        sds((NB, 1, KP)),
        sds((1, 2)),
    ]
    kernel = functools.partial(
        _band_kernel, W=W, K=K, cdt=cdt, neg_shared=neg_shared,
        is_cbow=is_cbow, cbow_mean=cbow_mean,
    )
    pl_call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )
    outs = pl_call(
        jnp.asarray(alpha, jnp.float32).reshape(1, 1),
        a, bk, en,
        tok_c[:, :, None], tok_k[:, :, None],
        keep_c.astype(jnp.float32)[:, :, None],
        w_c.astype(jnp.float32)[:, :, None],
        negs[:, None],
    )
    d_h, d_ctx, d_neg, nctx, ctxw, wns, losses = outs
    return (
        d_h, d_ctx, d_neg,
        nctx[:, :, 0], ctxw[:, :, 0], wns[:, 0], losses,
    )
