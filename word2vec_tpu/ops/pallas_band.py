"""Fused Pallas TPU kernel for the banded sg+ns training step.

The XLA band chain (ops/band_step.py + ops/banded.py) materializes every
intermediate in HBM: the gathered [B, L, d] row tensors are re-read by four
band contractions, the [B, C, S, S+2W] logit/grad planes round-trip between
them, and XLA inserts layout copies around the overlap-add (measured 2.14 ms
= 27% of the round-2 step, PERF.md). This kernel is the flash-attention
treatment of the same math (SURVEY §7 step 8): one pass per (batch row,
chunk) that keeps the logit plane, the sigmoid, both positive-side gradient
contractions, and the whole shared-negative side in VMEM, reading each row
tensor from HBM exactly once and writing exactly the gradient tensors the
scatters need.

Same objective as band_step.py (Word2Vec.cpp:251-271,319-353 semantics with
the shared-negative reformulation documented there) — pinned against the
XLA kernel by tests/test_pallas_band.py.

Scope (config.band_backend="pallas"; band_step falls back to the XLA chain
otherwise): skip-gram + negative sampling, per-row or batch negative scope,
unfused f32 tables, chunked band representation (S > 0), SINGLE-CHIP ONLY
(plain Trainer; sharded trainers reject it up front — pallas_call under
shard_map is unvalidatable here: the interpreter's internals are not
vma-aware, and no multi-chip hardware exists to compile the real thing;
parallel/trainer._reject_pallas). The context
gradient is emitted in SLAB space and flows through the sorted slab scatter
(band_step.py v2), so the overlap-add never exists anywhere on the pallas
path.

Layout contract (all pre-chunked by the caller with ops/banded helpers):
  a      [B, C, S, d]     center rows (ein chunks; zero rows past L)
  bk     [B, C, S+2W, d]  context slabs (eout; zero rows outside)
  en     [B, KP, d]       shared negative rows ([1, KP, d] batch scope)
  tok_c  [B, C, S]        center token ids, -1 past row end
  tok_k  [B, C, S+2W]     slab token ids, -1 outside (banded.slab_token_ids)
  keep_c [B, C, S]        center gate (subsample & valid), f32 0/1
  w_c    [B, C, S]        per-center shrunk window, f32
  negs   [B, KP]          negative ids ([1, KP] batch scope)
  alpha  scalar           learning rate

Outputs:
  d_h        [B, C, S, d]     center-row gradient (positives + negatives)
  d_ctx      [B, C, S+2W, d]  context-row gradient, slab space
  d_neg      [B, KP, d]       negative-row gradient (accumulated over C;
                              [1, KP, d] batch scope, accumulated over B too)
  n_ctx      [B, C, S]        active contexts per center (band row sums)
  ctx_w      [B, C, S+2W]     contribution weight per slab slot (col sums)
  w_neg_sum  [B, KP]          per-draw expectation weight, summed over rows
  losses     [1, 2]           (pos_loss, neg_loss) accumulated over the grid

The grid is (B, C) with C innermost; d_neg/w_neg_sum accumulate across the
C steps of one row (across the whole grid under batch scope), losses across
the whole grid — safe because the TPU grid executes sequentially.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _band_kernel(
    alpha_ref,  # [1, 1] SMEM
    a_ref,      # [1, 1, S, d]
    bk_ref,     # [1, 1, S+2W, d]
    en_ref,     # [1, KP, d]
    tokc_ref,   # [1, 1, S] int32
    tokk_ref,   # [1, 1, S+2W] int32
    keep_ref,   # [1, 1, S] f32
    wc_ref,     # [1, 1, S] f32
    negs_ref,   # [1, KP] int32
    d_h_ref,    # [1, 1, S, d]
    d_ctx_ref,  # [1, 1, S+2W, d]
    d_neg_ref,  # [1, KP, d]
    nctx_ref,   # [1, 1, S]
    ctxw_ref,   # [1, 1, S+2W]
    wns_ref,    # [1, KP]
    loss_ref,   # [1, 2]
    *,
    W: int,
    K: int,
    cdt,
    neg_shared: bool,
):
    b = pl.program_id(0)
    c = pl.program_id(1)
    S = a_ref.shape[2]
    SK = bk_ref.shape[2]  # S + 2W
    alpha = alpha_ref[0, 0]

    # ---- band mask [S, S+2W]: keep_i & valid_j & 0 < |i-j| <= w_eff_i
    # (Word2Vec.cpp:282,285-287,332,335-337 gates, as in banded.band_mask)
    s_iota = jax.lax.broadcasted_iota(jnp.float32, (S, SK), 0)
    k_iota = jax.lax.broadcasted_iota(jnp.float32, (S, SK), 1)
    dist = jnp.abs(s_iota + float(W) - k_iota)
    valid_k = (tokk_ref[0, 0, :] >= 0).astype(jnp.float32)
    mask = (
        keep_ref[0, 0, :][:, None]
        * valid_k[None, :]
        * (dist <= wc_ref[0, 0, :][:, None]).astype(jnp.float32)
        * (dist > 0.0).astype(jnp.float32)
    )
    n_ctx = jnp.sum(mask, axis=1)  # [S]
    nctx_ref[0, 0, :] = n_ctx
    ctxw_ref[0, 0, :] = jnp.sum(mask, axis=0)

    # ---- positive side: band logits + both gradient contractions, in VMEM
    a = a_ref[0, 0]
    bk = bk_ref[0, 0]
    plog = jax.lax.dot_general(
        a.astype(cdt), bk.astype(cdt),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [S, S+2W]
    gp = (1.0 - jax.nn.sigmoid(plog)) * mask * alpha
    d_h = jax.lax.dot_general(
        gp.astype(cdt), bk.astype(cdt),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [S, d]
    d_ctx_ref[0, 0] = jax.lax.dot_general(
        gp.astype(cdt), a.astype(cdt),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [S+2W, d]
    pos_loss = -jnp.sum(mask * jax.nn.log_sigmoid(plog))

    # ---- negative side: shared draws, collision-masked per center
    # (center/context-collision semantics of band_step.py lines 233-252)
    en = en_ref[0]
    negs = negs_ref[0, :]
    center_hit = (tokc_ref[0, 0, :][:, None] == negs[None, :]).astype(
        jnp.float32
    )  # [S, KP]
    hit_k = (tokk_ref[0, 0, :][:, None] == negs[None, :]).astype(
        jnp.float32
    )  # [S+2W, KP]
    ctx_hit = jax.lax.dot_general(
        mask.astype(cdt), hit_k.astype(cdt),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [S, KP]
    neg_ok = 1.0 - jnp.clip(center_hit + ctx_hit, 0.0, 1.0)
    KP = neg_ok.shape[1]
    w_neg = (n_ctx * (float(K) / float(KP)))[:, None] * neg_ok  # [S, KP]
    nlog = jax.lax.dot_general(
        a.astype(cdt), en.astype(cdt),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [S, KP]
    gn = (0.0 - jax.nn.sigmoid(nlog)) * w_neg * alpha
    d_h_ref[0, 0] = d_h + jax.lax.dot_general(
        gn.astype(cdt), en.astype(cdt),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    d_neg_c = jax.lax.dot_general(
        gn.astype(cdt), a.astype(cdt),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [KP, d]
    neg_loss = -jnp.sum(w_neg * (jax.nn.log_sigmoid(nlog) - nlog))

    # ---- accumulations across the sequential grid
    fresh = jnp.logical_and(b == 0, c == 0) if neg_shared else (c == 0)

    @pl.when(fresh)
    def _():
        d_neg_ref[...] = jnp.zeros_like(d_neg_ref)
        wns_ref[...] = jnp.zeros_like(wns_ref)

    d_neg_ref[0] += d_neg_c
    wns_ref[0, :] += jnp.sum(w_neg, axis=0)

    @pl.when(jnp.logical_and(b == 0, c == 0))
    def _():
        loss_ref[...] = jnp.zeros_like(loss_ref)

    loss_ref[0, 0] += pos_loss
    loss_ref[0, 1] += neg_loss


@functools.partial(
    jax.jit, static_argnames=("W", "K", "cdt", "interpret")
)
def band_core(
    a: jnp.ndarray,       # [B, C, S, d]
    bk: jnp.ndarray,      # [B, C, S+2W, d]
    en: jnp.ndarray,      # [B|1, KP, d]
    tok_c: jnp.ndarray,   # [B, C, S] int32
    tok_k: jnp.ndarray,   # [B, C, S+2W] int32
    keep_c: jnp.ndarray,  # [B, C, S]
    w_c: jnp.ndarray,     # [B, C, S]
    negs: jnp.ndarray,    # [B|1, KP] int32
    alpha: jnp.ndarray,   # scalar
    *,
    W: int,
    K: int,
    cdt=jnp.bfloat16,
    interpret: bool = False,
):
    """One fused pass over the band; see the module docstring contract.

    en/negs with leading dim 1 (batch-scope negatives) are shared by every
    batch row; d_neg/w_neg_sum then come back [1, KP, d]/[1, KP] already
    summed over the batch.
    """
    B, C, S, d = a.shape
    SK = bk.shape[2]
    NB, KP = negs.shape
    neg_shared = NB == 1

    def sds(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    def bc4(i, j):
        return (i, j, 0, 0)

    def bc3(i, j):
        return (i, j, 0)

    def nb3(i, j):
        return (0 if neg_shared else i, 0, 0)

    def nb2(i, j):
        return (0 if neg_shared else i, 0)

    grid_spec = pl.GridSpec(
        grid=(B, C),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, S, d), bc4),
            pl.BlockSpec((1, 1, SK, d), bc4),
            pl.BlockSpec((1, KP, d), nb3),
            pl.BlockSpec((1, 1, S), bc3),
            pl.BlockSpec((1, 1, SK), bc3),
            pl.BlockSpec((1, 1, S), bc3),
            pl.BlockSpec((1, 1, S), bc3),
            pl.BlockSpec((1, KP), nb2),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, S, d), bc4),
            pl.BlockSpec((1, 1, SK, d), bc4),
            pl.BlockSpec((1, KP, d), nb3),
            pl.BlockSpec((1, 1, S), bc3),
            pl.BlockSpec((1, 1, SK), bc3),
            pl.BlockSpec((1, KP), nb2),
            pl.BlockSpec((1, 2), lambda i, j: (0, 0)),
        ],
    )
    out_shape = [
        sds((B, C, S, d)),
        sds((B, C, SK, d)),
        sds((NB, KP, d)),
        sds((B, C, S)),
        sds((B, C, SK)),
        sds((NB, KP)),
        sds((1, 2)),
    ]
    kernel = functools.partial(
        _band_kernel, W=W, K=K, cdt=cdt, neg_shared=neg_shared
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        jnp.asarray(alpha, jnp.float32).reshape(1, 1),
        a, bk, en,
        tok_c, tok_k,
        keep_c.astype(jnp.float32), w_c.astype(jnp.float32),
        negs,
    )
