"""The fused device training step: the whole reference hot loop as one XLA program.

Everything under the reference's OpenMP `parallel for` (Word2Vec.cpp:375-394)
— subsample gate, window shrink, pair enumeration, negative draws /
Huffman-path lookup, sigmoid scoring, SGD updates — is re-expressed here as a
single jit-compiled, shape-static batched step over a [B, L] token matrix:

    pairs:   roll-free shifted gather builds [B, L, 2W] (center, context) pairs
             with a validity mask (replaces the j-loop at Word2Vec.cpp:339-341)
    score:   one einsum [P,d]x[P,T,d] -> [P,T] + sigmoid (replaces the per-row
             dot at Word2Vec.cpp:239-241 / :262-263)
    update:  dense scatter-add of rank-1 grads into the [V, d] tables
             (replaces the in-place += at Word2Vec.cpp:244-246 / :266-268)

Hogwild's benign races (SURVEY §2) disappear: duplicate indices inside a batch
sum deterministically in the scatter. The semantic delta vs the reference is
gradient staleness *within* one batch (all gathers read pre-update weights),
the standard minibatch trade-off (SURVEY §7 hard part (a)).

RNG note: all randomness (subsample gate, window shrink, negative draws) is
drawn on device from a threaded PRNG key — the counted-out replacement for the
reference's three mt19937 streams (Word2Vec.h:55-59). Bitwise parity with the
reference is impossible (it seeds from random_device); parity is statistical.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Word2VecConfig
from ..models.params import Params
from .tables import DeviceTables

Metrics = Dict[str, jnp.ndarray]


def _draw_negatives(
    key: jax.Array, shape: Tuple[int, ...], accept: jnp.ndarray, alias: jnp.ndarray
) -> jnp.ndarray:
    """Alias-method unigram^0.75 draws (replaces table lookup, Word2Vec.cpp:255)."""
    k_bucket, k_coin = jax.random.split(key)
    v = accept.shape[0]
    j = jax.random.randint(k_bucket, shape, 0, v, dtype=jnp.int32)
    u = jax.random.uniform(k_coin, shape)
    return jnp.where(u < accept[j], j, alias[j])


def _row_clip_scale(
    num_rows: int,
    tau: float,
    *contribs: Tuple[jnp.ndarray, jnp.ndarray],
    tp_axis: str | None = None,
) -> jnp.ndarray:
    """Per-row trust-region scale for batched duplicate-summed updates.

    contribs are (flat_idx, flat_vals[N, d]) pairs that all land in the same
    [num_rows, d] table this step. Returns scale[num_rows] in (0, 1]:
        scale_r = tau / max(S_r, tau),  S_r = sum_j ||vals_j||  over the
    row's contributions — the triangle-inequality bound on ||sum_j vals_j||,
    tight exactly in the dangerous case (aligned contributions on a hot row).

    Why: one batched scatter sums O(batch_tokens * word_freq) per-pair
    gradients into a frequent word's row with NO sequential feedback — the
    reference's one-at-a-time updates self-correct (sigmoid saturates, g->0,
    Word2Vec.cpp:239-268), a sum at stale weights cannot. At text8-scale
    geometry (~40k-token optimizer blocks) the hottest rows accumulate
    thousands of aligned updates and training diverges to NaN (measured:
    benchmarks/quality_full.py). Capping each row's summed step to L2 <= tau
    restores stability while leaving every row below the cap bitwise
    untouched — healthy updates are orders of magnitude under tau.

    Tensor parallelism: vals hold the local d/TP slice, so per-contribution
    squared norms are psum'd over tp_axis BEFORE the sqrt — every dim shard
    then applies the same scale computed from the row's GLOBAL norm (a [N]
    psum, same order as the logit psum the kernels already pay).
    """
    s = jnp.zeros((num_rows,), jnp.float32)
    for idx, vals in contribs:
        sq = jnp.sum(
            vals.astype(jnp.float32) * vals.astype(jnp.float32), axis=-1
        )
        if tp_axis is not None:
            sq = jax.lax.psum(sq, tp_axis)
        s = s.at[idx].add(jnp.sqrt(sq))
    return tau / jnp.maximum(s, tau)


def _cast_update(
    vals: jnp.ndarray,
    dtype: jnp.dtype,
    key: jax.Array | None = None,
    dest: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """f32 update values -> table storage dtype.

    Plain astype (round-to-nearest) unless a key is given and the target is
    bfloat16: then stochastic rounding AGAINST THE DESTINATION's ulp grid.

    Why the destination grid: an SGD table update is usually far smaller
    than bf16's ~2^-8 relative ulp of the weight it lands on, and the
    scatter-add accumulates in bf16 with round-to-nearest — so any delta
    below half that ulp would be swallowed by the ADD even if the delta
    itself were stochastically rounded on its own (much finer) binade grid.
    Quantizing each delta to an integer multiple of ulp(dest) with
    probability proportional to the remainder keeps E[delta] exact AND
    makes the subsequent bf16 accumulate exact (grid multiples add without
    rounding until a binade crossing, a second-order effect): tiny updates
    land as occasional whole-ulp steps instead of silently vanishing.
    `dest` must hold the bf16 rows being updated, gathered at the same
    indices the scatter uses — and gathered from the LATEST table state: a
    caller issuing two scatters onto the same table must gather the second
    scatter's dest rows from the first scatter's output (band_step does),
    or a row moved across a binade by scatter one leaves scatter two's
    delta on a stale ulp grid. Duplicate indices WITHIN one scatter still
    share a single pre-scatter dest row; like the binade crossing, that is
    a second-order effect (the duplicates' grid is right at the start of
    the add chain and only drifts if earlier duplicates cross a binade).
    Without `dest` no SR is possible — callers pass it whenever
    config.stochastic_rounding is on.

    The |dest| floor of 1e-7 keeps the grid math inside f32's normal/
    precision range (an unclamped ulp of a ZERO-initialized emb_out row
    underflows and the q division NaNs): below it the grid is ~2^-31,
    far finer than any SGD delta, so rounding there is effectively exact —
    which is also the correct limit, since accumulating onto weights that
    small is itself near-exact in bf16.
    """
    if key is None or jnp.dtype(dtype) != jnp.dtype(jnp.bfloat16):
        return vals.astype(dtype)
    assert dest is not None, "stochastic rounding needs the destination rows"
    w = jnp.abs(dest.astype(jnp.float32))
    # bf16 ulp(w) = 2^(exponent(w) - 7)
    ulp = jnp.exp2(jnp.floor(jnp.log2(jnp.maximum(w, 1e-7))) - 7.0)
    q = vals.astype(jnp.float32) / ulp
    qf = jnp.floor(q)
    u = jax.random.uniform(key, q.shape)
    return ((qf + (u < q - qf)) * ulp).astype(jnp.bfloat16)


def _sr_streams(key: jax.Array, sr: bool):
    """Per-update-site SR key streams: `k_sr(i)` for site i, or None when
    SR is off. fold_in (not a wider split) keeps every existing draw stream
    (subsample / window / negatives) bit-identical whether SR is on or off;
    0x5B domain-separates the SR streams from fold_in(key, step) uses."""
    if not sr:
        return lambda i: None
    base = jax.random.fold_in(key, 0x5B)
    return lambda i: jax.random.fold_in(base, i)


def _dup_mean_scale(
    num_rows: int, flat_idx: jnp.ndarray, flat_weight: jnp.ndarray
) -> jnp.ndarray:
    """1/duplicate-count scale per flattened index (see config.scatter_mean).

    Returns a [len(flat_idx)] factor that normalizes a scatter-add so each
    destination row receives the *mean* of its contributions. Rows contributed
    to exactly once get factor 1.0 — identical to plain sum.
    """
    cnt = jnp.zeros((num_rows,), jnp.float32).at[flat_idx].add(flat_weight)
    return (1.0 / jnp.maximum(cnt, 1.0))[flat_idx]


def _score_and_update(
    h: jnp.ndarray,          # [P, d] projection rows
    out: jnp.ndarray,        # [Vout, d] target-side matrix
    targets: jnp.ndarray,    # [P, T] int32 rows of `out`
    labels: jnp.ndarray,     # [P, T] f32 in {0, 1}
    tmask: jnp.ndarray,      # [P, T] f32 validity
    alpha: jnp.ndarray,      # scalar LR
    compute_dtype: jnp.dtype,
    scatter_mean: bool,
    tp_axis: str | None = None,
    clip_tau: float = 0.0,
    sr_key: jax.Array | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One sigmoid-SGD objective: returns (grad_h, new_out, loss_sum,
    pair_count, clip_count) — clip_count = rows of `out` whose summed update
    the trust region actually scaled this step (0 when clip_tau=0).

    Implements f = sigmoid(out[target] . h); g = (label - f) * alpha;
    grad_h += g * out[target]; out[target] += g * h
    — the shared kernel of hierarchical_softmax (Word2Vec.cpp:239-246) and
    negative_sampling (Word2Vec.cpp:262-268), batched over all P*T pairs.

    Tensor parallelism: with `tp_axis` set (inside shard_map), `h` and `out`
    hold the local d/TP slice of the embedding dim; the partial dot products
    are psum'd over the mesh axis so the sigmoid sees full logits, after which
    every gradient is purely local to the dim shard. The only communication
    per objective is the [P, T] logit psum — a few hundred KB over ICI, vs the
    [V, d] tables that never move.
    """
    d = h.shape[-1]
    t = out[targets]  # [P, T, d]
    logits = jnp.einsum(
        "pd,ptd->pt",
        h.astype(compute_dtype),
        t.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    if tp_axis is not None:
        logits = jax.lax.psum(logits, tp_axis)
    g = (labels - jax.nn.sigmoid(logits)) * tmask * alpha  # [P, T]
    grad_h = jnp.einsum(
        "pt,ptd->pd",
        g.astype(compute_dtype),
        t.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    grad_t = (g[:, :, None] * h[:, None, :]).astype(jnp.float32)  # [P, T, d]
    flat_t = targets.reshape(-1)
    vals = grad_t.reshape(-1, d)
    if scatter_mean:
        vals = vals * _dup_mean_scale(out.shape[0], flat_t, tmask.reshape(-1))[:, None]
    clip_count = jnp.float32(0.0)
    if clip_tau > 0.0:
        scale = _row_clip_scale(
            out.shape[0], clip_tau, (flat_t, vals), tp_axis=tp_axis
        )
        clip_count = jnp.sum((scale < 1.0).astype(jnp.float32))
        vals = vals * scale[flat_t][:, None]
    new_out = out.at[flat_t].add(
        _cast_update(
            vals, out.dtype, sr_key,
            out[flat_t] if sr_key is not None else None,
        )
    )
    # masked binary cross-entropy, for metrics only:
    # -[y log s(x) + (1-y) log s(-x)], with log s(-x) = log s(x) - x
    ls = jax.nn.log_sigmoid(logits)
    loss = -jnp.sum(tmask * jnp.where(labels > 0.5, ls, ls - logits))
    return grad_h, new_out, loss, jnp.sum(tmask), clip_count


def make_train_step(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
    sp_axis: str | None = None,
    fused: bool = False,
) -> Callable[[Params, jnp.ndarray, jax.Array, jnp.ndarray], Tuple[Params, Metrics]]:
    """Build the jittable step, dispatching on config.kernel.

    fused=True (chunk runners, config.fused_tables): params carry the two ns
    tables as one [V, 2, d] array (ops/band_step.fuse_tables) and the band
    step updates them with a single scatter; band+ns only.

    "band" selects the objective's fast path — banded-matmul ns
    (ops/band_step.py) or positional hs (ops/hs_step.py); "pair" is the
    reference-faithful enumeration below. sp_axis (sequence/context
    parallelism via halo exchange) is implemented by every kernel route:
    band, positional hs (both tiers), and — since r5 — the pair kernel
    (same halo + center-ownership contract).

    With config.micro_steps = k > 1 the step is wrapped in a sequential
    lax.fori_loop over k row sub-blocks of the dispatched batch: updates
    apply BETWEEN sub-blocks, so convergence behaves like k-times-smaller
    batches (the batched-sum staleness window shrinks k-fold) while the host
    still dispatches — and XLA still compiles — one fused program. This is
    what decouples device batch geometry from the ~70-optimizer-steps/epoch
    convergence threshold (config.auto_geometry).
    """
    base = _make_base_step(config, tables, tp_axis, dp_axis, sp_axis, fused)
    # Telemetry (obs/health.py): extend the metrics dict in-program — the
    # free non-finite-loss tripwire always, the full table-diff counters
    # under config.health_metrics. Applied UNDER the micro wrapper and the
    # chunk scans, so counters aggregate additively over every dispatch
    # granularity with zero extra dispatches or host syncs.
    from ..obs.health import instrument_step

    base = instrument_step(base, config, tp_axis)
    k = config.micro_steps
    if k <= 1:
        return base

    def micro(params, tokens, key, alpha):
        B, L = tokens.shape
        if B % k != 0:
            raise ValueError(
                f"batch_rows {B} not divisible by micro_steps {k}"
            )
        sub = tokens.reshape(k, B // k, L)

        def body(i, carry):
            p, acc = carry
            ki = jax.random.fold_in(key, i)
            p, m = base(p, sub[i], ki, alpha)
            return p, jax.tree.map(jnp.add, acc, m)

        # first sub-block peeled: under shard_map the metrics are varying
        # over the mesh axes, and a jnp.float32(0.0) initial carry would be
        # unvarying — a loop-carry type mismatch. Seeding the carry from a
        # real step gives it the right varying-axes type on any mesh.
        params, m0 = base(params, sub[0], jax.random.fold_in(key, 0), alpha)
        params, metrics = jax.lax.fori_loop(1, k, body, (params, m0))
        return params, metrics

    return micro


def _make_base_step(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
    sp_axis: str | None = None,
    fused: bool = False,
):
    # table_layout="unified": params persistently carry the [V, 2, d] slab
    # (models/params.py), so EVERY dispatch granularity takes the fused band
    # step — per-step included, since there is no chunk-boundary restack to
    # amortize. config validation pins unified to the ns band kernel, so the
    # hs/pair guards below stay unreachable for it.
    fused = fused or config.table_layout == "unified"
    if config.resolved_kernel == "band":
        if config.use_hs:
            if fused:
                raise ValueError("fused_tables applies to the ns band kernel only")
            from .hs_step import make_hs_train_step

            return make_hs_train_step(
                config, tables, tp_axis, dp_axis, sp_axis
            )
        from .band_step import make_band_train_step

        return make_band_train_step(
            config, tables, tp_axis, dp_axis, sp_axis, fused
        )
    if fused:
        raise ValueError("fused_tables applies to the ns band kernel only")
    return make_pair_train_step(config, tables, tp_axis, dp_axis, sp_axis)


def make_pair_train_step(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
    sp_axis: str | None = None,
) -> Callable[[Params, jnp.ndarray, jax.Array, jnp.ndarray], Tuple[Params, Metrics]]:
    """Build the jittable step(params, tokens[B,L], key, alpha) -> (params, metrics).

    All config values are closed over as static; `tables` arrays become
    captured device constants.

    Mesh axes (all None for single chip; set by parallel/ inside shard_map):
      tp_axis: embedding dim is sharded over this axis; logits are psum'd
               (see _score_and_update). All index/mask computation is
               replicated across tp shards (same key => same draws).
      dp_axis: each shard trains an independent replica on its own data;
               the PRNG key is folded with the shard index so negative/window
               draws decorrelate. Replicas are periodically averaged by
               parallel.sync_params (the TPU-native analog of Hogwild's shared
               memory, SURVEY §5 "distributed communication backend").
      sp_axis: each shard holds a [B, Lloc] column slice of the sequence and
               exchanges a W-token halo with its neighbors over ICI
               (band_step._halo_exchange — the same contract as the band/hs
               kernels, closing the last hole in the kernel x parallelism
               matrix, VERDICT r4 item 7). Halo positions are context-only:
               their center direction is owned by the neighboring shard, so
               every (center, context) pair is enumerated exactly once
               globally and the SUM of the per-shard table deltas equals the
               single-chip step's delta (pinned by the conservation tests,
               tests/test_parallel.py). NOTE the trainer's sync then pmeans
               replicas over dp AND sp (parallel/trainer.make_sync), so the
               cross-replica update it APPLIES is 1/sp of that single-chip
               sum — Hogwild-analog averaging semantics, an effective
               learning-rate scale vs single-chip, not an equivalence
               (ADVICE r5 #1; post-sync behavior pinned by
               test_sp_sync_applies_mean_of_shard_deltas).
    """
    W = config.window
    K = config.negative
    use_ns, use_hs = config.use_ns, config.use_hs
    is_cbow = config.model == "cbow"
    cbow_mean = config.cbow_mean
    scatter_mean = config.scatter_mean
    clip_tau = config.clip_row_update
    sr = config.stochastic_rounding
    cdt = jnp.dtype(config.compute_dtype)
    # Static offset vector o in {-W..-1, 1..W} — the unrolled j-loop of
    # Word2Vec.cpp:339 (j != i excluded by construction).
    offsets = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)]).astype(np.int32)
    abs_off = np.abs(offsets)

    def step(
        params: Params, tokens: jnp.ndarray, key: jax.Array, alpha: jnp.ndarray
    ) -> Tuple[Params, Metrics]:
        if dp_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
        center_zone = None
        if sp_axis is not None:
            from .band_step import _halo_exchange

            key = jax.random.fold_in(key, jax.lax.axis_index(sp_axis))
            Lloc = tokens.shape[1]
            tokens = _halo_exchange(tokens, W, sp_axis)
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            # halo positions are context-only: their center direction is
            # owned (and trained) by the neighboring shard
            center_zone = (pos >= W) & (pos < W + Lloc)
        B, L = tokens.shape
        k_sub, k_win, k_neg = jax.random.split(key, 3)
        k_sr = _sr_streams(key, sr)

        valid = tokens >= 0
        tok = jnp.where(valid, tokens, 0)

        # Subsample gate on the center word only (Word2Vec.cpp:282,332).
        keep = valid & (
            jax.random.uniform(k_sub, (B, L)) < tables.keep_probs[tok]
        )
        if center_zone is not None:
            keep = keep & center_zone[None, :]
        # Per-position window shrink: reduced ~ U{0..W-1}, effective half-width
        # w_eff = W - reduced in {1..W} (Word2Vec.cpp:285-287,335-337).
        w_eff = W - jax.random.randint(k_win, (B, L), 0, W, dtype=jnp.int32)

        # ctx[b, i, k] = tokens[b, i + offsets[k]] via padded gather.
        tok_pad = jnp.pad(tokens, ((0, 0), (W, W)), constant_values=-1)
        gidx = jnp.arange(L, dtype=jnp.int32)[:, None] + offsets[None, :] + W  # [L, 2W]
        ctx = tok_pad[:, gidx]  # [B, L, 2W]
        pair_mask = (
            keep[:, :, None]
            & (ctx >= 0)
            & (jnp.asarray(abs_off)[None, None, :] <= w_eff[:, :, None])
        )
        ctx = jnp.where(pair_mask, ctx, 0)

        new_params = dict(params)
        loss_sum = jnp.float32(0.0)
        pair_count = jnp.float32(0.0)
        clip_count = jnp.float32(0.0)  # rows the trust region engaged on

        if not is_cbow:
            # ---- skip-gram: input = center row of emb_in (W), predicted =
            # each context word (Word2Vec.cpp:319-353).
            P = B * L * 2 * W
            centers = jnp.broadcast_to(tok[:, :, None], (B, L, 2 * W)).reshape(P)
            pred = ctx.reshape(P)
            mask = pair_mask.reshape(P)
            h = params["emb_in"][centers]  # [P, d]
            grad_h = jnp.zeros_like(h, dtype=jnp.float32)

            if use_ns:
                negs = _draw_negatives(
                    k_neg, (P, K), tables.alias_accept, tables.alias_idx
                )
                targets = jnp.concatenate([pred[:, None], negs], axis=1)  # [P, 1+K]
                labels = jnp.zeros((P, 1 + K), jnp.float32).at[:, 0].set(1.0)
                # a drawn negative equal to the positive is skipped
                # (word2vec.c semantics; the reference instead relabels it 1
                # via its dedup map, Word2Vec.cpp:253-257)
                tmask = (
                    mask[:, None]
                    & jnp.concatenate(
                        [jnp.ones((P, 1), bool), negs != pred[:, None]], axis=1
                    )
                ).astype(jnp.float32)
                gh, new_out, ls, pc, cc = _score_and_update(
                    h, params["emb_out_ns"], targets, labels, tmask, alpha, cdt,
                    scatter_mean, tp_axis, clip_tau, k_sr(1),
                )
                grad_h += gh
                new_params["emb_out_ns"] = new_out
                loss_sum += ls
                pair_count += pc
                clip_count += cc

            if use_hs:
                targets = tables.hs_points[pred]  # [P, Lc]
                labels = (1 - tables.hs_codes[pred]).astype(jnp.float32)  # :242
                Lc = targets.shape[1]
                tmask = (
                    mask[:, None]
                    & (jnp.arange(Lc, dtype=jnp.int32)[None, :] < tables.hs_len[pred][:, None])
                ).astype(jnp.float32)
                gh, new_out, ls, pc, cc = _score_and_update(
                    h, params["emb_out_hs"], targets, labels, tmask, alpha, cdt,
                    scatter_mean, tp_axis, clip_tau, k_sr(2),
                )
                grad_h += gh
                new_params["emb_out_hs"] = new_out
                loss_sum += ls
                pair_count += pc
                clip_count += cc

            # W.row(center) += grad accumulated over the center's window
            # (Word2Vec.cpp:351). The per-position window sum is reference-
            # exact (neu1_grad accumulates across the j-loop); only the
            # scatter across positions sharing a center word is batched, with
            # optional duplicate-count normalization (config.scatter_mean).
            gh_pos = grad_h.reshape(B, L, 2 * W, -1).sum(axis=2)  # [B, L, d]
            flat_c = tok.reshape(-1)
            vals = gh_pos.reshape(B * L, -1)
            if scatter_mean:
                # a kept center with zero active contexts runs no kernels in
                # the reference (the j-loop is empty), so it must not count
                # toward the duplicate normalization either
                vals = vals * _dup_mean_scale(
                    params["emb_in"].shape[0],
                    flat_c,
                    pair_mask.any(axis=2).reshape(-1).astype(jnp.float32),
                )[:, None]
            if clip_tau > 0.0:
                scale = _row_clip_scale(
                    params["emb_in"].shape[0], clip_tau, (flat_c, vals),
                    tp_axis=tp_axis,
                )
                clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                vals = vals * scale[flat_c][:, None]
            new_params["emb_in"] = params["emb_in"].at[flat_c].add(
                _cast_update(
                    vals, params["emb_in"].dtype, k_sr(0),
                    params["emb_in"][flat_c] if sr else None,
                )
            )
        else:
            # ---- CBOW: projection = (mean of) context rows of emb_in (C),
            # predicted = center word (Word2Vec.cpp:273-317). Duplicate context
            # words are NOT deduped (the reference dedups via set<size_t> at
            # :293-298; duplicates here contribute multiplicity-weighted, as in
            # word2vec.c/gensim).
            P = B * L
            ctx_rows = params["emb_in"][ctx]  # [B, L, 2W, d]
            fmask = pair_mask.astype(ctx_rows.dtype)[..., None]
            h_bl = jnp.sum(ctx_rows * fmask, axis=2)  # [B, L, d]
            n_ctx = jnp.sum(pair_mask, axis=2).astype(jnp.float32)  # neu1_num, :288
            center_ok = keep & (n_ctx > 0)  # skip if no context, :289
            if cbow_mean:
                h_bl = h_bl / jnp.maximum(n_ctx, 1.0)[:, :, None]  # :301-302
            h = h_bl.reshape(P, -1)
            pred = tok.reshape(P)
            mask = center_ok.reshape(P)
            grad_h = jnp.zeros_like(h, dtype=jnp.float32)

            if use_ns:
                negs = _draw_negatives(
                    k_neg, (P, K), tables.alias_accept, tables.alias_idx
                )
                targets = jnp.concatenate([pred[:, None], negs], axis=1)
                labels = jnp.zeros((P, 1 + K), jnp.float32).at[:, 0].set(1.0)
                tmask = (
                    mask[:, None]
                    & jnp.concatenate(
                        [jnp.ones((P, 1), bool), negs != pred[:, None]], axis=1
                    )
                ).astype(jnp.float32)
                gh, new_out, ls, pc, cc = _score_and_update(
                    h, params["emb_out_ns"], targets, labels, tmask, alpha, cdt,
                    scatter_mean, tp_axis, clip_tau, k_sr(1),
                )
                grad_h += gh
                new_params["emb_out_ns"] = new_out
                loss_sum += ls
                pair_count += pc
                clip_count += cc

            if use_hs:
                targets = tables.hs_points[pred]
                labels = (1 - tables.hs_codes[pred]).astype(jnp.float32)
                Lc = targets.shape[1]
                tmask = (
                    mask[:, None]
                    & (jnp.arange(Lc, dtype=jnp.int32)[None, :] < tables.hs_len[pred][:, None])
                ).astype(jnp.float32)
                gh, new_out, ls, pc, cc = _score_and_update(
                    h, params["emb_out_hs"], targets, labels, tmask, alpha, cdt,
                    scatter_mean, tp_axis, clip_tau, k_sr(2),
                )
                grad_h += gh
                new_params["emb_out_hs"] = new_out
                loss_sum += ls
                pair_count += pc
                clip_count += cc

            # Fan the projection grad back to every contributing context row
            # (Word2Vec.cpp:313-315), with the second /neu1_num under cbow_mean.
            g_bl = grad_h.reshape(B, L, -1)
            if cbow_mean:
                g_bl = g_bl / jnp.maximum(n_ctx, 1.0)[:, :, None]
            g_ctx = (g_bl[:, :, None, :] * fmask).reshape(B * L * 2 * W, -1)
            flat_ctx = ctx.reshape(-1)
            if scatter_mean:
                g_ctx = g_ctx * _dup_mean_scale(
                    params["emb_in"].shape[0],
                    flat_ctx,
                    pair_mask.reshape(-1).astype(jnp.float32),
                )[:, None]
            if clip_tau > 0.0:
                scale = _row_clip_scale(
                    params["emb_in"].shape[0], clip_tau, (flat_ctx, g_ctx),
                    tp_axis=tp_axis,
                )
                clip_count += jnp.sum((scale < 1.0).astype(jnp.float32))
                g_ctx = g_ctx * scale[flat_ctx][:, None]
            new_params["emb_in"] = params["emb_in"].at[flat_ctx].add(
                _cast_update(
                    g_ctx, params["emb_in"].dtype, k_sr(0),
                    params["emb_in"][flat_ctx] if sr else None,
                )
            )

        metrics = {
            "loss_sum": loss_sum,
            "pairs": pair_count,
            "clip_engaged": clip_count,
        }
        return new_params, metrics

    return step


def jit_train_step(config: Word2VecConfig, tables: DeviceTables):
    """The step jitted with params-buffer donation (in-place table updates)."""
    return jax.jit(make_train_step(config, tables), donate_argnums=0)


def make_chunk_runner(
    config: Word2VecConfig,
    tables: DeviceTables,
    tp_axis: str | None = None,
    dp_axis: str | None = None,
    sp_axis: str | None = None,
):
    """S sequential optimizer steps as ONE device program (lax.scan).

    chunk(params, tokens[S, B, L], base_key, step0, alphas[S])
        -> (params, {"loss_sum": [S], "pairs": [S]})

    Step i applies make_train_step with key = fold_in(base_key, step0 + i)
    and LR alphas[i] — the exact per-step driver sequence (train.Trainer),
    so chunked and per-step training produce identical parameter trajectories
    (pinned by tests/test_chunk_runner.py). The point is dispatch economics:
    one host->device round trip per S steps instead of per step. Through a
    remote-dispatch link (the axon tunnel) per-step dispatch costs ~4-5x the
    8 ms device step; chunked, the overhead amortizes to noise.

    A batch whose rows are all padding (-1) is a provable no-op (every mask
    derives from token validity), which is how the trailing partial chunk of
    an epoch is padded to the compiled shape without a second XLA program.

    With config.fused_tables the ns tables are restacked to [V, 2, d] for
    the chunk's lifetime (models/params.fuse_tables) — the restack amortizes
    over the S steps, and the public params layout is untouched outside.
    (table_layout="unified" needs no restack: the params ARE the slab, and
    make_train_step routes to the fused step by itself.)
    """
    fused = config.fused_tables
    step = make_train_step(config, tables, tp_axis, dp_axis, sp_axis, fused)

    def chunk(params, tokens, base_key, step0, alphas):
        if fused:
            from ..models.params import fuse_tables, unfuse_tables

            params = fuse_tables(params)

        def body(p, xs):
            toks, i, a = xs
            key = jax.random.fold_in(base_key, step0 + i)
            p, m = step(p, toks, key, a)
            return p, m

        s = tokens.shape[0]
        idx = jnp.arange(s, dtype=jnp.int32)
        # scan stacks each metric key to [S]; keys are whatever the kernel
        # emits (loss_sum / pairs / clip_engaged / ...)
        params, metrics = jax.lax.scan(body, params, (tokens, idx, alphas))
        if fused:
            params = unfuse_tables(params)
        return params, metrics

    return chunk


def jit_chunk_runner(config: Word2VecConfig, tables: DeviceTables):
    """The chunk runner jitted with params-buffer donation."""
    return jax.jit(make_chunk_runner(config, tables), donate_argnums=0)
