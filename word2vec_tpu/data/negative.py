"""Alias-method negative sampler: exact unigram^0.75 draws in O(1) on device.

The reference quantizes the distorted unigram distribution into a 1e8-entry
int array and samples it with a uniform index (reference: Word2Vec.cpp:81-113
`make_table`, draw at :255). That costs 800MB of host RAM and is approximate.
The TPU-native replacement is Vose's alias method: two [V] arrays built once
on host in O(V), then each draw on device is

    j ~ UniformInt(V);  u ~ Uniform(0,1)
    sample = j        if u < accept[j]
             alias[j] otherwise

which vectorizes to two gathers + a select — exact, O(1) per draw, and shape-
static for XLA.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class AliasTable:
    accept: np.ndarray  # [V] float32 — acceptance threshold per bucket
    alias: np.ndarray   # [V] int32   — fallback outcome per bucket

    @property
    def n(self) -> int:
        return len(self.accept)

    def sample_np(self, rng: np.random.Generator, shape) -> np.ndarray:
        """NumPy sampling (host fallback / golden tests)."""
        j = rng.integers(0, self.n, size=shape)
        u = rng.random(size=shape)
        return np.where(u < self.accept[j], j, self.alias[j]).astype(np.int32)


def build_alias_table(probs: np.ndarray) -> AliasTable:
    """Vose's alias method over an arbitrary probability vector."""
    p = np.asarray(probs, dtype=np.float64)
    if p.ndim != 1 or len(p) == 0:
        raise ValueError("probs must be a non-empty 1-D array")
    p = p / p.sum()
    n = len(p)
    scaled = p * n
    accept = np.ones(n, dtype=np.float64)
    alias = np.arange(n, dtype=np.int32)

    small = [i for i in range(n) if scaled[i] < 1.0]
    large = [i for i in range(n) if scaled[i] >= 1.0]
    while small and large:
        s = small.pop()
        l = large.pop()
        accept[s] = scaled[s]
        alias[s] = l
        scaled[l] = scaled[l] - (1.0 - scaled[s])
        (small if scaled[l] < 1.0 else large).append(l)
    # leftovers are 1.0 up to float error
    for i in small + large:
        accept[i] = 1.0
        alias[i] = i
    return AliasTable(accept=accept.astype(np.float32), alias=alias)
