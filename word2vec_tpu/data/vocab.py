"""Vocabulary construction and frequency-derived precomputes.

Host-side, array-oriented replacement for the reference's pointer-based vocab
(reference: Word.h:11-31 `class Word`, Word2Vec.cpp:132-169 `build_vocab`,
Word2Vec.cpp:115-130 `precalc_sampling`). Instead of one heap object per word,
the vocabulary is a struct-of-arrays: `counts[V]`, `words[V]`, plus derived
float32 arrays that ship to the device once and stay in HBM.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np


class Vocab:
    """Sorted vocabulary with frequency-derived device arrays.

    Words are sorted by descending count and indexed 0..V-1
    (reference: Word2Vec.cpp:153-160; comparator at Word2Vec.cpp:3-6).
    Ties are broken lexicographically, which is deterministic — unlike the
    reference, whose tie order depends on unordered_map iteration.
    """

    def __init__(self, words: Sequence[str], counts: np.ndarray):
        if len(words) != len(counts):
            raise ValueError("words and counts length mismatch")
        self.words: List[str] = list(words)
        self.counts: np.ndarray = np.asarray(counts, dtype=np.int64)
        self.word2id: Dict[str, int] = {w: i for i, w in enumerate(self.words)}
        self.total_words: int = int(self.counts.sum())

    # ------------------------------------------------------------------ build
    @classmethod
    def build(
        cls,
        sentences: Iterable[Sequence[str]],
        min_count: int = 5,
        max_vocab: int = 0,
    ) -> "Vocab":
        """Count tokens, drop count < min_count, sort by descending count.

        Reference: Word2Vec.cpp:134-160 (count loop, min_count filter at :145,
        sort at :155).
        """
        counter: Counter = Counter()
        for sentence in sentences:
            counter.update(sentence)
        return cls.from_counter(counter, min_count, max_vocab)

    @classmethod
    def from_counter(
        cls, counter: Dict[str, int], min_count: int = 5, max_vocab: int = 0
    ) -> "Vocab":
        """max_vocab > 0 caps the vocabulary to the top-N words by count
        (ties lexicographic, same order as the sort). This supplies the
        intent of the reference's `reduce_vocab` — declared at Word2Vec.h:69
        to bound vocab memory on huge corpora, but never defined (SURVEY §2
        dead code) — as a post-count cap rather than word2vec.c's lossy
        mid-count eviction, so the kept words' counts stay exact."""
        items = [(w, c) for w, c in counter.items() if c >= min_count]
        # descending count, ties lexicographic: deterministic regardless of
        # counter iteration order (dict vs the native C++ hash table), where
        # the reference inherits unordered_map's arbitrary tie order
        items.sort(key=lambda wc: (-wc[1], wc[0]))
        if max_vocab > 0:
            items = items[:max_vocab]
        words = [w for w, _ in items]
        counts = np.array([c for _, c in items], dtype=np.int64)
        return cls(words, counts)

    # ------------------------------------------------------------- properties
    def __len__(self) -> int:
        return len(self.words)

    def __contains__(self, word: str) -> bool:
        return word in self.word2id

    def __getitem__(self, word: str) -> int:
        return self.word2id[word]

    # ------------------------------------------------------------- precompute
    def keep_probs(self, subsample_threshold: float) -> np.ndarray:
        """Per-word keep probability for frequent-word subsampling.

        word2vec.c formula, reference Word2Vec.cpp:115-130:
            tc = threshold * total_words
            p_keep = min((sqrt(count/tc) + 1) * tc / count, 1.0)
        threshold <= 0 disables subsampling (all ones, Word2Vec.cpp:127-129).
        """
        if subsample_threshold <= 0:
            return np.ones(len(self), dtype=np.float32)
        tc = subsample_threshold * self.total_words
        c = self.counts.astype(np.float64)
        p = (np.sqrt(c / tc) + 1.0) * tc / c
        return np.minimum(p, 1.0).astype(np.float32)

    def unigram_probs(self, power: float = 0.75) -> np.ndarray:
        """Normalized count^power negative-sampling distribution.

        Replaces the reference's 1e8-entry quantized table
        (Word2Vec.cpp:81-113) with the exact distribution; sampling uses an
        alias table on device (see data/negative.py).
        """
        p = self.counts.astype(np.float64) ** power
        p /= p.sum()
        return p.astype(np.float64)

    # -------------------------------------------------------------- encoding
    def encode(self, sentence: Sequence[str]) -> np.ndarray:
        """Token strings -> int32 ids, silently dropping OOV.

        Reference: Word2Vec.cpp:212-230 `build_sample` (OOV drop at :223).
        """
        w2i = self.word2id
        ids = [w2i[t] for t in sentence if t in w2i]
        return np.asarray(ids, dtype=np.int32)

    def encode_corpus(self, sentences: Iterable[Sequence[str]]) -> Iterator[np.ndarray]:
        for sentence in sentences:
            yield self.encode(sentence)

    def content_hash(self, limit: Optional[int] = None) -> str:
        """sha256 over the ordered (index, word, count) content.

        The resume-compatibility fingerprint: two Vocab objects hash equal
        iff they assign the same words to the same rows with the same
        counts — exactly the condition under which a checkpoint's embedding
        rows keep their meaning and the deterministic corpus encoding is
        identical. Stored in every checkpoint's integrity.json metadata
        (io/checkpoint.py) and compared by the CLI's --resume guard against
        the vocabulary the current corpus rebuilds to.

        `limit` hashes only the first `limit` rows — the compatible-superset
        check: a vocabulary GROWN online (stream/driver.py admits new words
        into reserved rows, never touching existing ones) satisfies
        grown.content_hash(limit=len(base)) == base.content_hash(), so a
        grown checkpoint still resumes against the original corpus."""
        import hashlib

        n = len(self.words) if limit is None else min(int(limit), len(self.words))
        h = hashlib.sha256()
        for i in range(n):
            h.update(
                f"{i}\t{self.words[i]}\t{int(self.counts[i])}\n".encode("utf-8")
            )
        return h.hexdigest()

    def is_compatible_superset(self, base: "Vocab") -> bool:
        """True iff this vocabulary extends `base` without disturbing it:
        same words at the same rows with the same counts for base's full
        index range (the online-growth invariant — the --resume guard
        accepts a grown checkpoint against the original corpus on this)."""
        return len(self) >= len(base) and (
            self.content_hash(limit=len(base)) == base.content_hash()
        )

    # ------------------------------------------------------------- growth
    def admit(self, items: Sequence[tuple]) -> List[int]:
        """Admit `(word, count)` pairs IN PLACE at the next free ids
        (deterministic: callers pass an already-ordered admission list —
        stream/driver.admission_order). Existing rows are untouched: ids,
        words and counts 0..V-1 keep their exact values, so embedding-table
        rows keep their meaning and content_hash(limit=V) is invariant.
        Returns the assigned ids. Duplicate or already-present words are
        rejected loudly (silent re-admission would alias two rows)."""
        ids: List[int] = []
        new_counts: List[int] = []
        for w, c in items:
            if w in self.word2id:
                raise ValueError(f"cannot admit {w!r}: already in vocabulary")
            i = len(self.words)
            self.words.append(w)
            self.word2id[w] = i
            ids.append(i)
            new_counts.append(int(c))
        if new_counts:
            self.counts = np.concatenate(
                [self.counts, np.asarray(new_counts, dtype=np.int64)]
            )
            self.total_words = int(self.counts.sum())
        return ids

    # ------------------------------------------------------------ persistence
    def save(self, path: str) -> None:
        """Write `index count word` lines (reference: Word2Vec.cpp:171-177)."""
        with open(path, "w", encoding="utf-8") as f:
            for i, (w, c) in enumerate(zip(self.words, self.counts)):
                f.write(f"{i} {int(c)} {w}\n")

    @classmethod
    def load(cls, path: str) -> "Vocab":
        """Read the `index count word` format (reference: Word2Vec.cpp:179-196).

        Unlike the reference's read_vocab (which trusts file order and is never
        called by its own CLI), rows are placed at their recorded index.
        """
        idx: List[int] = []
        cnt: List[int] = []
        wrd: List[str] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                idx.append(int(parts[0]))
                cnt.append(int(parts[1]))
                wrd.append(parts[2])
        order = np.argsort(np.asarray(idx))
        words = [wrd[i] for i in order]
        counts = np.asarray(cnt, dtype=np.int64)[order]
        return cls(words, counts)
