"""Huffman coding for hierarchical softmax, as padded dense arrays.

The reference builds a pointer tree with a std heap and walks it with an
explicit stack (reference: Word2Vec.cpp:32-79 `create_huffman_tree`). The
TPU-native representation is three dense arrays sized for one device gather:

    codes  [V, L] uint8  — binary code of each word, 0=left / 1=right
                           (reference: Word2Vec.cpp:69-70), padded with 0
    points [V, L] int32  — internal-node index along the root->leaf path
                           (reference: Word2Vec.cpp:72-73), padded with 0
    code_len [V] int32   — true path length; positions >= code_len are masked

L = max code length (~log2 V for Zipfian corpora). Internal nodes are numbered
0..V-2 in merge order, matching the reference's `index - vocab_size`
(Word2Vec.cpp:73), so `points` rows index straight into the [V-1, d] hs output
matrix (reference `synapses1`, Word2Vec.cpp:207).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

import numpy as np


@dataclass
class HuffmanCoding:
    codes: np.ndarray      # [V, L] uint8
    points: np.ndarray     # [V, L] int32
    code_len: np.ndarray   # [V] int32

    @property
    def max_code_len(self) -> int:
        return self.codes.shape[1]

    @property
    def num_internal(self) -> int:
        return self.codes.shape[0] - 1


@dataclass
class DenseTierSplit:
    """Two-tier split of a Huffman coding around the top-P internal nodes.

    Internal-node ids are assigned in merge order (build_huffman), so ids
    along every root->leaf path strictly DECREASE — membership of the top-P
    ids (the last-created, highest-frequency region of the tree) is a true
    PREFIX of every path. That yields two disjoint row sets of the [V-1, d]
    hs output matrix:

      dense tier — the CONTIGUOUS top slice out[V-1-P:], touched by ~3/4 of
        all token-weighted path entries (measured: top-512 covers 73% on a
        zipf-71k vocab). Represented as a per-word signed multi-hot
        msig[V, P] int8: +1 where the word's path visits node (V-1-P)+p with
        code bit 0 (label 1, Word2Vec.cpp:241), -1 for code bit 1, else 0.
        A kernel can therefore score/update the whole tier with dense
        matmuls and a contiguous slice add — no gather/scatter at all.
      tail tier — the per-word path REMAINDER below the top slice, as padded
        arrays tail_codes/tail_points[V, Ct] (Ct = max tail length, ~13 vs
        the full C ~ 25 at zipf-71k/P=512) for the usual positional
        gather/scatter path, now over ~4x fewer padded slots.

    coverage / tail_mean / tail_var are count-weighted corpus expectations
    used for reporting and for sizing compacted tail buffers
    (E[slots per position] = tail_mean, var for the +6-sigma bound).
    """

    msig: np.ndarray         # [V, P] int8 in {-1, 0, +1}
    tail_codes: np.ndarray   # [V, Ct] uint8
    tail_points: np.ndarray  # [V, Ct] int32
    tail_len: np.ndarray     # [V] int32
    coverage: float          # token-weighted share of path entries in dense tier
    tail_mean: float         # E[tail_len] under the unigram distribution
    tail_var: float          # Var[tail_len] under the unigram distribution


def split_dense_tier(
    hc: HuffmanCoding, counts: np.ndarray, top_p: int
) -> DenseTierSplit:
    """Split `hc` into dense/tail tiers around the top_p largest node ids.

    top_p is clamped to the internal-node count (then the whole tree is
    dense and every tail is empty, Ct = 0).
    """
    if top_p < 1:
        raise ValueError(f"top_p must be >= 1, got {top_p}")
    V, C = hc.points.shape
    n_internal = V - 1
    P = min(top_p, n_internal)
    thresh = n_internal - P

    cmask = np.arange(C, dtype=np.int32)[None, :] < hc.code_len[:, None]
    in_dense = (hc.points >= thresh) & cmask
    plen = in_dense.sum(axis=1).astype(np.int32)
    # the monotone-id property makes in_dense a per-row prefix; the whole
    # tier split is unsound if that ever breaks, so verify at build time
    prefix = (np.arange(C, dtype=np.int32)[None, :] < plen[:, None]) & cmask
    if not np.array_equal(in_dense, prefix):
        raise AssertionError(
            "path node ids are not monotone decreasing; dense-tier prefix "
            "split is invalid for this tree"
        )
    tail_len = (hc.code_len - plen).astype(np.int32)
    Ct = int(tail_len.max()) if V else 0

    msig = np.zeros((V, P), dtype=np.int8)
    w_idx, c_idx = np.nonzero(in_dense)
    p_idx = hc.points[w_idx, c_idx] - thresh
    msig[w_idx, p_idx] = np.where(
        hc.codes[w_idx, c_idx] == 0, 1, -1
    ).astype(np.int8)

    tail_codes = np.zeros((V, max(Ct, 1)), dtype=np.uint8)[:, :Ct]
    tail_points = np.zeros((V, max(Ct, 1)), dtype=np.int32)[:, :Ct]
    if Ct:
        rows = np.arange(V)[:, None]
        src = np.minimum(plen[:, None] + np.arange(Ct)[None, :], C - 1)
        tmask = np.arange(Ct, dtype=np.int32)[None, :] < tail_len[:, None]
        tail_codes = np.where(tmask, hc.codes[rows, src], 0).astype(np.uint8)
        tail_points = np.where(tmask, hc.points[rows, src], 0).astype(np.int32)

    w = counts.astype(np.float64)
    w = w / max(w.sum(), 1.0)
    total_len = float((w * hc.code_len).sum())
    tail_mean = float((w * tail_len).sum())
    tail_var = float((w * tail_len.astype(np.float64) ** 2).sum()) - tail_mean**2
    return DenseTierSplit(
        msig=msig,
        tail_codes=tail_codes,
        tail_points=tail_points,
        tail_len=tail_len,
        coverage=1.0 - tail_mean / max(total_len, 1e-12),
        tail_mean=tail_mean,
        tail_var=max(tail_var, 0.0),
    )


def build_huffman(counts: np.ndarray) -> HuffmanCoding:
    """Build Huffman codes from word counts (descending-sorted vocab order).

    Merge semantics match the reference (Word2Vec.cpp:39-49): repeatedly pop
    the two lowest-count nodes; the first popped becomes the left child
    (code bit 0), the second the right child (code bit 1); the merged node's
    internal index is the merge step i, i.e. reference node index i+V minus V.
    Heap ties are broken by node creation order (deterministic), where the
    reference inherits std::make_heap's unspecified tie order — codes can
    differ on ties but are equally optimal.
    """
    V = len(counts)
    if V < 2:
        raise ValueError("Huffman tree needs at least 2 words")

    # heap entries: (count, creation_order, node_id)
    # node ids: 0..V-1 leaves, V..2V-2 internal (merge step i -> id V+i)
    heap = [(int(counts[i]), i, i) for i in range(V)]
    heapq.heapify(heap)
    left = np.empty(V - 1, dtype=np.int64)
    right = np.empty(V - 1, dtype=np.int64)
    for i in range(V - 1):
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        left[i] = n1
        right[i] = n2
        heapq.heappush(heap, (c1 + c2, V + i, V + i))

    # Iterative root->leaf walk assigning codes/points
    # (reference: Word2Vec.cpp:52-78; points hold internal indices from root).
    code_len = np.zeros(V, dtype=np.int32)
    codes_list: list = [None] * V
    points_list: list = [None] * V
    root = 2 * V - 2
    stack = [(root, [], [])]
    while stack:
        node, code, points = stack.pop()
        if node < V:
            codes_list[node] = code
            points_list[node] = points
            code_len[node] = len(code)
        else:
            k = node - V
            child_points = points + [k]
            stack.append((int(left[k]), code + [0], child_points))
            stack.append((int(right[k]), code + [1], child_points))

    L = int(code_len.max())
    codes = np.zeros((V, L), dtype=np.uint8)
    pts = np.zeros((V, L), dtype=np.int32)
    for w in range(V):
        n = code_len[w]
        codes[w, :n] = codes_list[w]
        pts[w, :n] = points_list[w]
    return HuffmanCoding(codes=codes, points=pts, code_len=code_len)
