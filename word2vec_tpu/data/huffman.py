"""Huffman coding for hierarchical softmax, as padded dense arrays.

The reference builds a pointer tree with a std heap and walks it with an
explicit stack (reference: Word2Vec.cpp:32-79 `create_huffman_tree`). The
TPU-native representation is three dense arrays sized for one device gather:

    codes  [V, L] uint8  — binary code of each word, 0=left / 1=right
                           (reference: Word2Vec.cpp:69-70), padded with 0
    points [V, L] int32  — internal-node index along the root->leaf path
                           (reference: Word2Vec.cpp:72-73), padded with 0
    code_len [V] int32   — true path length; positions >= code_len are masked

L = max code length (~log2 V for Zipfian corpora). Internal nodes are numbered
0..V-2 in merge order, matching the reference's `index - vocab_size`
(Word2Vec.cpp:73), so `points` rows index straight into the [V-1, d] hs output
matrix (reference `synapses1`, Word2Vec.cpp:207).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

import numpy as np


@dataclass
class HuffmanCoding:
    codes: np.ndarray      # [V, L] uint8
    points: np.ndarray     # [V, L] int32
    code_len: np.ndarray   # [V] int32

    @property
    def max_code_len(self) -> int:
        return self.codes.shape[1]

    @property
    def num_internal(self) -> int:
        return self.codes.shape[0] - 1


def build_huffman(counts: np.ndarray) -> HuffmanCoding:
    """Build Huffman codes from word counts (descending-sorted vocab order).

    Merge semantics match the reference (Word2Vec.cpp:39-49): repeatedly pop
    the two lowest-count nodes; the first popped becomes the left child
    (code bit 0), the second the right child (code bit 1); the merged node's
    internal index is the merge step i, i.e. reference node index i+V minus V.
    Heap ties are broken by node creation order (deterministic), where the
    reference inherits std::make_heap's unspecified tie order — codes can
    differ on ties but are equally optimal.
    """
    V = len(counts)
    if V < 2:
        raise ValueError("Huffman tree needs at least 2 words")

    # heap entries: (count, creation_order, node_id)
    # node ids: 0..V-1 leaves, V..2V-2 internal (merge step i -> id V+i)
    heap = [(int(counts[i]), i, i) for i in range(V)]
    heapq.heapify(heap)
    left = np.empty(V - 1, dtype=np.int64)
    right = np.empty(V - 1, dtype=np.int64)
    for i in range(V - 1):
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        left[i] = n1
        right[i] = n2
        heapq.heappush(heap, (c1 + c2, V + i, V + i))

    # Iterative root->leaf walk assigning codes/points
    # (reference: Word2Vec.cpp:52-78; points hold internal indices from root).
    code_len = np.zeros(V, dtype=np.int32)
    codes_list: list = [None] * V
    points_list: list = [None] * V
    root = 2 * V - 2
    stack = [(root, [], [])]
    while stack:
        node, code, points = stack.pop()
        if node < V:
            codes_list[node] = code
            points_list[node] = points
            code_len[node] = len(code)
        else:
            k = node - V
            child_points = points + [k]
            stack.append((int(left[k]), code + [0], child_points))
            stack.append((int(right[k]), code + [1], child_points))

    L = int(code_len.max())
    codes = np.zeros((V, L), dtype=np.uint8)
    pts = np.zeros((V, L), dtype=np.int32)
    for w in range(V):
        n = code_len[w]
        codes[w, :n] = codes_list[w]
        pts[w, :n] = points_list[w]
    return HuffmanCoding(codes=codes, points=pts, code_len=code_len)
