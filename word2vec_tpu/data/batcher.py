"""Fixed-shape batch pipeline: encoded corpus -> [B, L] token-id matrices.

TPU-first design (SURVEY §7 step 2): the host does *no* pair generation.
Sentences are packed into fixed-shape int32 rows (pad = -1); subsampling,
window shrink, pair enumeration and negative sampling all happen inside the
jit-compiled device step (ops/). This keeps the host loop at O(tokens) memcpy
— essential on a 1-core host — and makes device cost shape-static.

The per-epoch sentence shuffle (reference: Word2Vec.cpp:373 std::shuffle)
becomes a per-epoch row permutation. Sentences longer than max_len are wrapped
into multiple rows; context windows do not cross row boundaries, which differs
from the reference only for the ~2*window/max_len fraction of positions at
wrap points.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

PAD = -1


class PackedCorpus:
    """Flat token-id array + row table; the in-memory corpus representation."""

    def __init__(self, flat: np.ndarray, row_starts: np.ndarray, row_lens: np.ndarray):
        self.flat = flat
        self.row_starts = row_starts
        self.row_lens = row_lens

    @property
    def num_rows(self) -> int:
        return len(self.row_starts)

    @property
    def num_tokens(self) -> int:
        return int(self.row_lens.sum())

    @classmethod
    def from_flat(cls, flat: np.ndarray, max_len: int) -> "PackedCorpus":
        """Pack a flat id stream (from native.encode_file).

        Runs of ids between -1 separators are sentences (MODE_LINES); a stream
        with no separators (MODE_STREAM / text8) is one giant sentence whose
        rows are cut every max_len tokens — the same boundaries the reference's
        1000-word chunking would produce after re-wrapping.
        """
        flat = np.asarray(flat, dtype=np.int32)
        if len(flat) == 0:
            raise ValueError("empty corpus")
        if not (flat == PAD).any():
            n = len(flat)
            starts = np.arange(0, n, max_len, dtype=np.int64)
            lens = np.minimum(n - starts, max_len).astype(np.int32)
            return cls(flat, starts, lens)
        # split at separators, then wrap each sentence
        sep = np.flatnonzero(flat == PAD)
        bounds = np.concatenate([[-1], sep, [len(flat)]])
        starts: List[int] = []
        lens: List[int] = []
        for s, e in zip(bounds[:-1] + 1, bounds[1:]):
            n = e - s
            for ofs in range(0, n, max_len):
                starts.append(s + ofs)
                lens.append(min(max_len, n - ofs))
        return cls(
            flat,
            np.asarray(starts, dtype=np.int64),
            np.asarray(lens, dtype=np.int32),
        )

    @classmethod
    def pack(cls, sentences: Iterable[np.ndarray], max_len: int) -> "PackedCorpus":
        """Pack encoded sentences, wrapping rows longer than max_len."""
        chunks: List[np.ndarray] = []
        starts: List[int] = []
        lens: List[int] = []
        pos = 0
        for ids in sentences:
            n = len(ids)
            if n == 0:
                continue
            chunks.append(np.asarray(ids, dtype=np.int32))
            for ofs in range(0, n, max_len):
                ln = min(max_len, n - ofs)
                starts.append(pos + ofs)
                lens.append(ln)
            pos += n
        if not chunks:
            raise ValueError("empty corpus")
        flat = np.concatenate(chunks)
        return cls(flat, np.asarray(starts, dtype=np.int64), np.asarray(lens, dtype=np.int32))


def epoch_order(seed: int, epoch_index: int, num_rows: int) -> np.ndarray:
    """The per-epoch row permutation — a pure function of (seed, epoch), the
    property mid-epoch resume and the device-resident path both rely on
    (reference shuffle: Word2Vec.cpp:373). Single source of truth for
    BatchIterator and ops/resident.py."""
    order = np.arange(num_rows, dtype=np.int64)
    np.random.default_rng((seed, epoch_index)).shuffle(order)
    return order


class BatchIterator:
    """Yields [B, L] int32 batches (pad = -1) in per-epoch shuffled row order.

    The final partial batch of an epoch is padded with empty rows so every
    device step has the same shape (no recompilation).

    Each epoch's permutation is a pure function of (seed, epoch index) —
    epoch k can be regenerated in isolation, which is what makes mid-epoch
    checkpoint resume possible (epoch(k, skip=n) re-enters epoch k at batch
    n without replaying batches 0..n-1). Calling epoch() with no index keeps
    an internal counter, so sequential use shuffles every pass as before
    (Word2Vec.cpp:373).
    """

    def __init__(
        self,
        corpus: PackedCorpus,
        batch_rows: int,
        max_len: int,
        seed: int = 0,
        shuffle: bool = True,
    ):
        self.corpus = corpus
        self.B = batch_rows
        self.L = max_len
        self.seed = seed
        self.shuffle = shuffle
        self._epoch_counter = 0

    def steps_per_epoch(self) -> int:
        return -(-self.corpus.num_rows // self.B)

    def epoch(
        self, epoch_index: Optional[int] = None, skip: int = 0
    ) -> Iterator[Tuple[np.ndarray, int]]:
        """Yield (tokens [B, L], words_in_batch) for one pass over the corpus,
        starting `skip` batches in.

        Batch assembly goes through the native fill (native.fill_batch) when
        the C++ layer is available; the Python fallback is identical.
        """
        from .. import native

        if epoch_index is None:
            epoch_index = self._epoch_counter
            self._epoch_counter += 1
        if self.shuffle:
            order = epoch_order(self.seed, epoch_index, self.corpus.num_rows)
        else:
            order = np.arange(self.corpus.num_rows, dtype=np.int64)
        flat = self.corpus.flat
        starts = self.corpus.row_starts
        lens = self.corpus.row_lens
        B, L = self.B, self.L
        for i in range(skip * B, len(order), B):
            batch = np.empty((B, L), dtype=np.int32)
            words = native.fill_batch(flat, starts, lens, order, i, batch)
            yield batch, words


def chunk_batches(
    epoch_iter: Iterator[Tuple[np.ndarray, int]], s: int
) -> Iterator[Tuple[np.ndarray, List[int]]]:
    """Group an epoch's [B, L] batches into [S, B, L] chunks for the chunked
    dispatch runner (ops/train_step.make_chunk_runner). The trailing partial
    chunk is padded with all-(-1) batches — provable no-op steps — so one
    compiled shape covers every chunk. Yields (tokens, per-batch word counts:
    len(words) < S exactly when the chunk is padded)."""
    buf: List[np.ndarray] = []
    words: List[int] = []
    for tokens, w in epoch_iter:
        buf.append(tokens)
        words.append(w)
        if len(buf) == s:
            yield np.stack(buf), words
            buf, words = [], []
    if buf:
        dead = np.full_like(buf[0], PAD)
        yield np.stack(buf + [dead] * (s - len(buf))), words


def placed_prefetch(
    stream: Iterator[Tuple], place, depth: int = 1
) -> Iterator[Tuple]:
    """prefetch() with device placement of each item's first element done in
    the PRODUCER thread: the host->device copy of chunk i+1 (jax.device_put is
    async, and the transfer releases the GIL) overlaps chunk i's dispatched
    compute — through a remote-tunneled device that copy costs tens of ms.

    depth defaults to 1 (not prefetch's 2): every in-flight item pins a device
    buffer — the consumer's, the queued one, and the one the producer holds
    while blocked on the full queue, so depth=1 already keeps up to two chunks
    ahead alive — and one chunk of copy overlap is all the latency hiding
    needs.
    """
    placed = ((place(item[0]), *item[1:]) for item in stream)
    return prefetch(placed, depth=depth)


def prefetch(iterator: Iterator, depth: int = 2) -> Iterator:
    """Background-thread prefetch so host batch assembly overlaps device compute.

    The device step releases the GIL while executing, so even on a 1-core host
    this hides most of the batch-assembly latency. If the consumer abandons the
    generator early (exception in the training loop, GeneratorExit), the
    producer thread is signalled to stop rather than blocking forever on the
    bounded queue.

    Producer-failure contract (pinned by tests/test_batcher.py): an
    exception anywhere in the producer — the underlying iterator, batch
    assembly, or a placed_prefetch device put — RE-RAISES in the consumer
    after the items produced before it drain; it never hangs the consumer
    or silently ends the epoch short. The consumer's queue wait is
    additionally guarded against the producer dying without its sentinel
    (interpreter teardown killing the daemon thread): a dead producer with
    an empty queue raises RuntimeError instead of blocking forever.
    """
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    sentinel = object()
    err: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in iterator:
                if not _put(item):
                    return
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            _put(sentinel)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            try:
                item = q.get(timeout=1.0)
            except queue.Empty:
                if not t.is_alive() and q.empty():
                    # sentinel never arrived: the producer was torn down
                    # without running its finally (daemon-thread kill)
                    if err:
                        raise err[0]
                    raise RuntimeError(
                        "prefetch producer thread died without a sentinel"
                    )
                continue
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
