"""Corpus readers.

Reference equivalents:
  - text8_corpus: whitespace token stream chunked into 1000-word
    pseudo-sentences (reference: main.cpp:63-92). Here the path is a real
    parameter — the reference hardcodes "text8" (main.cpp:68) and ignores its
    own -train flag; that bug is not replicated.
  - line_docs: one sentence per line (reference: Word2Vec.cpp:19-30).

Readers are generators: the corpus streams through vocab counting and encoding
without materializing a vector<vector<string>> like the reference does, which
matters at enwik9 scale (~124M tokens).

When the native C++ host library is available (word2vec_tpu.native), the
tokenize/encode hot path is done there; these pure-Python readers are the
always-available fallback and the reference semantics definition.
"""

from __future__ import annotations

from typing import Iterator, List

DEFAULT_CHUNK_WORDS = 1000  # reference: main.cpp:66 max_sentence_len


def text8_corpus(path: str, chunk_words: int = DEFAULT_CHUNK_WORDS) -> Iterator[List[str]]:
    """Whitespace tokens chunked into fixed-size pseudo-sentences.

    Reference: main.cpp:63-92 (chunk boundary at :80-85, trailing partial
    sentence kept at :88-89).
    """
    sentence: List[str] = []
    remainder = ""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            block = remainder + block
            parts = block.split()
            # A token can straddle the block boundary: hold back the tail
            # unless the block ends in whitespace.
            if parts and not block[-1].isspace():
                remainder = parts.pop()
            else:
                remainder = ""
            for tok in parts:
                sentence.append(tok)
                if len(sentence) == chunk_words:
                    yield sentence
                    sentence = []
    if remainder:
        sentence.append(remainder)
    if sentence:
        yield sentence


def line_docs(path: str) -> Iterator[List[str]]:
    """One whitespace-tokenized sentence per line (reference: Word2Vec.cpp:19-30)."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            toks = line.split()
            if toks:
                yield toks


def load_corpus(
    path: str, fmt: str = "text8", min_count: int = 5, max_vocab: int = 0
):
    """One-shot corpus load: (Vocab, flat int32 id stream).

    Uses the native C++ layer (word2vec_tpu.native) for the two host-side
    O(corpus) passes — word counting and id encoding — falling back to Python
    transparently. `fmt` selects the reference reader semantics: "text8" is a
    whitespace stream (main.cpp:63-92), "lines" treats each line as a sentence
    (Word2Vec.cpp:19-30; sentence breaks become -1 separators in the stream).
    max_vocab > 0 caps the vocabulary to the top-N by count (the working
    replacement for the reference's declared-but-undefined reduce_vocab,
    Word2Vec.h:69); capped-out words encode as OOV and are dropped.

    Pack the result with PackedCorpus.from_flat(flat, max_sentence_len).
    """
    from .. import native
    from .vocab import Vocab

    mode = native.MODE_STREAM if fmt == "text8" else native.MODE_LINES
    counts, total = native.count_file(path)
    vocab = Vocab.from_counter(counts, min_count=min_count, max_vocab=max_vocab)
    flat = native.encode_file(path, vocab, mode, max_tokens=total)
    return vocab, flat
