"""word2vec.c-compatible command line, plus TPU-native extensions.

Flag names and defaults mirror the reference CLI (main.cpp:94-205) so a
reference user can switch by changing only the binary name:

    word2vec-tpu -train text8 -output vec.txt -size 200 -window 5 \
        -negative 5 -model sg -train_method ns -iter 3 -binary 0

Reference divergences (deliberate, each a reference bug or gap):
  - `-train <file>` is honored. The reference parses it but hardcodes
    ./text8 (main.cpp:125-126 vs :188; SURVEY §2 dead code).
  - `-binary` works. The reference's parse line is commented out
    (main.cpp:131).
  - `-alpha` is honored for skip-gram. The reference unconditionally
    overwrites init_alpha with 0.05 because its cbow_mean flag is hardcoded
    true (main.cpp:117,180-181) — even for -model sg. Here the 0.05
    cbow-mean default applies only when model=cbow and -alpha was not given
    (word2vec.c behavior).
  - `-threads` is accepted for compatibility and ignored: parallelism is
    --dp/--sp/--tp over the device mesh, not host threads.

TPU extensions: --backend {tpu,cpu}, --dp/--sp/--tp mesh shape, --corpus-format,
--checkpoint-dir/--checkpoint-every, --eval-ws353/--eval-analogy.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="word2vec-tpu",
        description="TPU-native word2vec (reference-compatible flags)",
        allow_abbrev=False,
    )
    # reference flags (main.cpp:123-151); single-dash long names as upstream
    p.add_argument("-train", dest="train", metavar="FILE", help="training corpus")
    p.add_argument("-output", dest="output", metavar="FILE",
                   default="text8-sgns.txt", help="output vectors (main.cpp:106)")
    p.add_argument("-size", dest="size", type=int, default=200,
                   help="embedding dim (default 200, main.cpp:112)")
    p.add_argument("-window", dest="window", type=int, default=5)
    p.add_argument("-subsample", dest="subsample", type=float, default=1e-4)
    p.add_argument("-train_method", dest="train_method", default="ns",
                   choices=["ns", "hs"])
    p.add_argument("-negative", dest="negative", type=int, default=0,
                   help="negative samples (reference default 0, main.cpp:118)")
    p.add_argument("-threads", dest="threads", type=int, default=1,
                   help="accepted for compatibility; ignored (use --dp/--sp/--tp)")
    p.add_argument("-iter", dest="iter", type=int, default=1)
    p.add_argument("-min-count", dest="min_count", type=int, default=5)
    p.add_argument("--max-vocab", type=int, default=0,
                   help="cap the vocabulary to the top-N words by count "
                        "(0 = unlimited); the working version of the "
                        "reference's declared-but-undefined reduce_vocab "
                        "(Word2Vec.h:69)")
    p.add_argument("-alpha", dest="alpha", type=float, default=None)
    p.add_argument("-model", dest="model", default="sg", choices=["sg", "cbow"])
    p.add_argument("-save-vocab", dest="save_vocab", metavar="FILE")
    p.add_argument("-read-vocab", dest="read_vocab", metavar="FILE")
    p.add_argument("-binary", dest="binary", type=int, default=0)
    p.add_argument("-cbow-mean", dest="cbow_mean", type=int, default=1,
                   help="cbow projection: 1=mean (reference default), 0=sum")
    # TPU-native extensions
    p.add_argument("--backend", choices=["tpu", "cpu"], default="tpu",
                   help="device backend (BASELINE.json north star)")
    p.add_argument("--prng", choices=["threefry", "rbg"], default="threefry",
                   help="jax PRNG impl for the device draw streams "
                        "(subsample gate / window shrink / negatives); rbg "
                        "is cheaper on TPU, statistically equivalent, but a "
                        "different stream. Persisted in checkpoints "
                        "(config.prng_impl): a resumed run keeps the "
                        "checkpoint's impl and warns if this flag differs")
    p.add_argument("--dp", type=int, default=1, help="data-parallel mesh axis")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel mesh axis")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel mesh axis (halo-exchange context "
                        "parallelism for long rows; band-route kernels — "
                        "ns band or positional hs)")
    p.add_argument("--dp-sync-every", type=int, default=64)
    p.add_argument("--sync-mode", choices=["mean", "delta"], default="mean",
                   help="replica reconciliation: mean = full-table pmean; "
                        "delta = delta-psum with bf16 wire compression "
                        "(half the ICI bytes; parallel/trainer.py)")
    p.add_argument("--multihost", action="store_true",
                   help="multi-process mode: jax.distributed.initialize from "
                        "the W2V_COORDINATOR/W2V_NUM_PROCS/W2V_PROC_ID env "
                        "contract, mesh over the global device set with the "
                        "data axis spanning slices/DCN (parallel/multihost.py);"
                        " pass each process its own corpus shard via -train")
    p.add_argument("--micro-steps", type=int, default=0,
                   help="sequential optimizer sub-steps per dispatched batch "
                        "(0 = auto with --batch-rows 0, else 1); decouples "
                        "convergence from dispatch size (config.auto_geometry)")
    p.add_argument("--chunk-steps", type=int, default=0,
                   help="optimizer steps fused into one dispatched device "
                        "program (lax.scan); 0 = auto, 1 = per-step dispatch. "
                        "Identical trajectory either way — purely dispatch "
                        "economics (sharded: capped to divide the sync "
                        "interval)")
    p.add_argument("--batch-rows", type=int, default=0,
                   help="sentence rows per device step; 0 = auto-size so an "
                        "epoch has enough optimizer steps to learn (see "
                        "config.scatter_mean notes)")
    p.add_argument("--clip-row-update", type=float, default=1.0,
                   help="per-row trust region: max L2 norm of one row's "
                        "summed update per optimizer step (0 = off). "
                        "Prevents hot-row divergence of batched-sum updates "
                        "at scale; a no-op below the cap "
                        "(config.clip_row_update)")
    p.add_argument("--scatter-mean", type=int, default=0, choices=[0, 1],
                   help="normalize duplicate-row updates by count (hot-row "
                        "stabilizer; 0 = reference-faithful sum)")
    p.add_argument("--kernel", choices=["auto", "band", "pair"], default="auto",
                   help="device kernel: band = MXU fast path (ns only), "
                        "pair = reference-faithful per-pair enumeration")
    p.add_argument("--compute-dtype", choices=["bfloat16", "float32"],
                   default="bfloat16",
                   help="dot-product dtype; float32 for reference-exact scores")
    p.add_argument("--table-dtype", choices=["float32", "bfloat16"],
                   default="float32",
                   help="storage dtype of the [V, d] embedding tables; "
                        "bfloat16 halves their HBM bytes (pair with "
                        "--stochastic-rounding: SGD updates are usually "
                        "below bf16's ulp and nearest-rounding drops them)")
    p.add_argument("--stochastic-rounding", type=int, default=0, choices=[0, 1],
                   help="unbiased stochastic rounding of table updates "
                        "(bfloat16 tables, ns band route; "
                        "config.stochastic_rounding)")
    p.add_argument("--table-layout", choices=["split", "unified"],
                   default="split",
                   help="how the two ns tables are stored "
                        "(config.table_layout): split = two [V, d] arrays; "
                        "unified = one [V, 2, d] slab, scattered ONCE per "
                        "step at doubled width over the shared sorted "
                        "token ids (~half the table-update tail; trajectory "
                        "bitwise identical incl. bf16±SR). ns band kernel "
                        "only; composes with pallas_oa but not pallas/"
                        "slab-scatter. Also an --autotune candidate "
                        "arbitrated per device")
    p.add_argument("--shared-negatives", type=int, default=64,
                   help="shared negative draws per batch row (band kernel)")
    p.add_argument("--negative-scope", choices=["row", "batch"], default="row",
                   help="share the negative pool per row, or one pool for "
                        "the whole batch (one dense matmul + KP-row update; "
                        "raise --shared-negatives with 'batch'; "
                        "config.negative_scope)")
    p.add_argument("--band-backend",
                   choices=["xla", "pallas", "pallas_oa", "pallas_fused"],
                   default="xla",
                   help="band step compute: XLA chain; the fused Pallas "
                        "kernel; the XLA chain with the Pallas overlap-add "
                        "kernel deleting the layout-copy chain (pallas_oa); "
                        "or the fully-fused step — in-kernel gather, "
                        "compute, overlap-add and the doubled-width sorted "
                        "scatter over the unified [V, 2, d] slab "
                        "(pallas_fused; requires --table-layout unified "
                        "and row negative scope). config.band_backend; "
                        "sg/cbow + ns, f32 or bf16 tables, single-chip; "
                        "'pallas' is additionally unfused-only")
    p.add_argument("--slab-scatter", type=int, default=0, choices=[0, 1],
                   help="band kernel: scatter context grads from slab space "
                        "(skips the overlap-add; config.slab_scatter)")
    p.add_argument("--hs-dense-top", type=int, default=0, metavar="P",
                   help="two-tier hs update: handle the top-P Huffman nodes "
                        "(a contiguous slice + per-path prefix) with dense "
                        "matmuls, gather/scatter only the short path tails "
                        "(config.hs_dense_top; 0 = single-tier)")
    p.add_argument("--hs-tail-slots", type=int, default=-1, metavar="T",
                   help="two-tier hs tail-scatter compaction bound per batch "
                        "row: -1 auto (+6 sigma), 0 off, >0 explicit "
                        "(config.hs_tail_slots)")
    p.add_argument("--autotune", choices=["off", "probe", "cached"],
                   default="off",
                   help="autotuned execution planner (tune/): probe = search "
                        "the step-shape space (cost-model-pruned grid, short "
                        "timed probes) and persist the winner; cached = "
                        "start from the persisted plan for this (device, "
                        "kernel, vocab, dim) with zero probe cost (falls "
                        "back to probe on a miss)")
    p.add_argument("--plan-cache", dest="plan_cache", metavar="FILE",
                   default="",
                   help="plan-cache JSON path (default: $W2V_PLAN_CACHE or "
                        "~/.cache/word2vec_tpu/plan_cache.json; the packaged "
                        "seed plans back every lookup)")
    p.add_argument("--resident", choices=["auto", "on", "off"], default="auto",
                   help="device-resident corpus: keep the packed corpus in "
                        "HBM and assemble batches on device (single-chip "
                        "chunked path; ops/resident.py)")
    p.add_argument("--corpus-mode", choices=["resident", "streaming"],
                   default="resident",
                   help="how the corpus reaches the device (stream/): "
                        "resident = read+pack the whole corpus up front "
                        "(the historical path; requires corpus-fits-in-"
                        "RAM); streaming = consume it in bounded segments "
                        "from a file set / comma list / directory / glob "
                        "(-train accepts all of those) or a pipe "
                        "(-train -), with host read/pack/copy overlapping "
                        "device compute, mid-stream cursor checkpoints "
                        "(byte-for-byte SIGTERM resume), and online vocab "
                        "growth into --vocab-reserve rows. A streaming "
                        "checkpoint resumes streaming automatically")
    p.add_argument("--segment-tokens", type=int, default=0, metavar="N",
                   help="streaming segment size in raw corpus tokens "
                        "(config.segment_tokens; 0 = auto, 4M). The "
                        "segment is the growth/swap/resume boundary unit "
                        "and the per-'epoch' alpha-schedule horizon")
    p.add_argument("--vocab-reserve", type=int, default=0, metavar="N",
                   help="reserve N embedding rows for online vocabulary "
                        "growth (streaming only): new words seen in a "
                        "consumed segment are admitted into reserved rows "
                        "at the next segment boundary, deterministically, "
                        "leaving existing rows bitwise untouched; a grown "
                        "vocab resumes through the compatible-superset "
                        "content-hash guard (0 = fixed vocabulary)")
    p.add_argument("--stream-spool", metavar="DIR", default="",
                   help="pipe-ingest spool directory (-train - only): "
                        "segments read from the pipe are spooled here so "
                        "a mid-stream resume can replay them (default: "
                        "<--checkpoint-dir>/stream_spool, else a temp dir "
                        "— resumable only while it survives)")
    p.add_argument("--max-sentence-len", type=int, default=192)
    p.add_argument("--corpus-format", choices=["text8", "lines"], default="text8",
                   help="text8: 1000-word chunks (main.cpp:63-92); "
                        "lines: one sentence per line (Word2Vec.cpp:19-30)")
    p.add_argument("--binary-layout", choices=["reference", "google"],
                   default="reference")
    p.add_argument("--export-int8", metavar="FILE",
                   help="also export the table as the int8 "
                        "symmetric-quantized container (per-row scale "
                        "header, io/embeddings.save_embeddings_int8): "
                        "4x smaller than f32, loads straight into "
                        "`python -m word2vec_tpu.serve --format int8`")
    p.add_argument("--export-side", choices=["auto", "input", "output"],
                   default="auto",
                   help="which table -output saves: auto = the reference's "
                   "choice (main.cpp:196-202); input = the gather-side "
                   "table (gensim wv); output = the ns prediction table "
                   "(gensim syn1neg). The reference's auto choice for "
                   "cbow+ns saves the output matrix, which its own "
                   "training leaves anticorrelated with fine-grained "
                   "similarity (benchmarks/CBOW_GRADED_CALIB_r5.jsonl)")
    p.add_argument("--checkpoint-dir", metavar="DIR")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="STEPS")
    p.add_argument("--checkpoint-keep", type=int, default=1, metavar="N",
                   help="previous checkpoints retained as rollback targets "
                        "(<dir>.old ... .old{N}); --auto-recover depends on "
                        "N >= 1 (io/checkpoint.py retention)")
    p.add_argument("--resume", metavar="DIR", help="resume from checkpoint "
                   "dir (integrity-checked; a corrupt checkpoint is "
                   "quarantined as .corrupt and the .old backup loads "
                   "instead)")
    p.add_argument("--auto-recover", type=int, default=0, metavar="N",
                   help="supervised divergence recovery: on DivergenceError "
                        "roll back to the last-good checkpoint (integrity + "
                        "finite-params validated, .old fallback), rescale "
                        "alpha (--recover-alpha-scale), advance the shuffle "
                        "seed, and retry up to N times before exiting rc=2 "
                        "(resilience/supervisor.py; needs --checkpoint-dir "
                        "+ --checkpoint-every for rollback targets)")
    p.add_argument("--recover-alpha-scale", type=float, default=0.5,
                   metavar="S",
                   help="multiply init_alpha by S on every auto-recovery "
                        "(1.0 = keep the schedule)")
    p.add_argument("--faults", metavar="SPEC", default="",
                   help="fault-injection plan for chaos testing "
                        "(resilience/faults.py): comma-separated "
                        "kind[@step][:key=val], e.g. 'nan@40,sigterm@80', "
                        "'hang@10:secs=300', 'peer_dead@25', or "
                        "'ckpt_oserror:times=2,stall@10:secs=0.5'; or a "
                        ".json plan file")
    p.add_argument("--step-deadline", type=float, default=0.0, metavar="SECS",
                   help="step-deadline watchdog (resilience/watchdog.py; "
                        "0 = off): if no step/chunk boundary lands within "
                        "max(SECS, 4x rolling-p90 boundary time) — first "
                        "compile covered by a grace window — dump all "
                        "thread stacks + the wedged phase to --metrics-dir, "
                        "mark the manifest 'shutdown: stalled', and exit "
                        "76 (EXIT_STALLED) so schedulers requeue with "
                        "--resume. Set SECS above your worst checkpoint + "
                        "mid-run compile wall")
    p.add_argument("--sync-deadline", type=float, default=0.0, metavar="SECS",
                   help="deadline on cross-process collectives (multihost "
                        "agree/heartbeat + replica sync + the sharded "
                        "metrics drain; 0 = off/unbounded): a dead peer "
                        "turns the infinite collective hang into a "
                        "coordinated abort — survivors checkpoint where "
                        "safe and exit 75 (EXIT_PREEMPTED) for requeue "
                        "with --resume — or, with --elastic, into a "
                        "shrink-remesh that keeps training")
    p.add_argument("--elastic", choices=["off", "shrink", "shrink+grow"],
                   default="off",
                   help="elastic multi-host training "
                        "(resilience/elastic.py): off = PR 5 semantics (a "
                        "dead peer aborts the fleet to requeue, exit "
                        "75/76); shrink = on SyncTimeout the survivors "
                        "agree on membership through the elastic "
                        "rendezvous (W2V_ELASTIC_COORD; hosted by rank 0), "
                        "re-form the mesh at N-1 in place, re-shard from "
                        "the last integrity-verified checkpoint, and keep "
                        "training — no scheduler round-trip; shrink+grow "
                        "additionally admits a restarted host back at the "
                        "next sync boundary. Requires --sync-deadline and "
                        "--checkpoint-dir/--checkpoint-every (validated); "
                        "single-process runs ignore it with a warning")
    p.add_argument("--elastic-policy", metavar="RULES", default="",
                   help="signal-driven autoscale policy "
                        "(resilience/policy.py; needs --elastic): "
                        "comma-separated "
                        "'<signal><op><thr>[:for=N][:act=shrink|grow]' "
                        "clauses over the derived signals (same clause "
                        "core as --slo), e.g. "
                        "'throughput_wps<0.6*baseline:for=2:act=shrink,"
                        "throughput_wps>0.8*baseline:for=2:act=grow,"
                        "cooldown=3'. A sustained shrink breach evicts "
                        "the attributed straggler at the next sync "
                        "boundary (trigger=policy, zero failures); a "
                        "sustained grow breach opens the admission gate "
                        "for parked rejoiners. Global options: cooldown=N "
                        "windows per fresh generation, min_world=/"
                        "max_world= bounds. Implies the signal plane on")
    p.add_argument("--rejoin-window", type=int, default=0, metavar="N",
                   help="rejoin re-announce bound (resilience/elastic.py; "
                        "0 = the default 6): how many times a parked "
                        "rejoiner re-announces after the rendezvous drops "
                        "its connection (one generation turnover each) "
                        "before giving up — the exhaustion error prints "
                        "the total bounded wait N implies")
    p.add_argument("--compile-cache", metavar="DIR", default="",
                   help="warm-restart compile cache root "
                        "(tune/compile_cache.py): exec'd elastic "
                        "generations (W2V_ELASTIC_GEN > 0) point jax's "
                        "persistent compilation cache at DIR/<topology-"
                        "plan-key> so a generation switch that revisits a "
                        "compiled topology skips the recompile blackout. "
                        "FENCED to next-generation processes only: the "
                        "launch process (gen 0) and every non-elastic run "
                        "always fresh-compile (the PR 1 warm-cache "
                        "segfault scenario; tests pin the fence), and an "
                        "operator-set JAX_COMPILATION_CACHE_DIR is never "
                        "overridden")
    p.add_argument("--allow-vocab-mismatch", action="store_true",
                   help="skip the --resume vocabulary-compatibility guard "
                        "(by default a resume whose corpus rebuilds to a "
                        "DIFFERENT vocabulary than the checkpoint's — "
                        "content-hash compared — is an error: training "
                        "would silently re-attribute embedding rows)")
    p.add_argument("--eval-ws353", metavar="FILE",
                   help="WordSim-353 csv/tsv for post-train eval")
    p.add_argument("--eval-analogy", metavar="FILE",
                   help="google questions-words.txt for post-train eval")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=100)
    p.add_argument("--log-jsonl", metavar="FILE",
                   help="append machine-readable JSONL log records to FILE")
    p.add_argument("--metrics-dir", metavar="DIR",
                   help="telemetry directory (obs/): writes manifest.json "
                        "(realized plan/backend, device, versions, git sha), "
                        "metrics.jsonl, and metrics.prom there, and enables "
                        "the full on-device health counters unless "
                        "--health-metrics 0")
    p.add_argument("--trace", metavar="DIR",
                   help="export this run's step-scoped span timeline as "
                        "Chrome-trace/Perfetto JSON into DIR (obs/trace.py: "
                        "one trace_p<i>.json per process, merged into "
                        "trace.json on process 0 by step index). Open in "
                        "ui.perfetto.dev or chrome://tracing; diff two runs "
                        "with python -m word2vec_tpu.obs.tracediff. The "
                        "flight recorder itself is always on — this flag "
                        "only controls the export")
    p.add_argument("--prom-textfile", metavar="FILE",
                   help="maintain a Prometheus-format textfile of the "
                        "latest metrics at FILE (node-exporter textfile "
                        "collector style; obs/export.py)")
    p.add_argument("--health-metrics", type=int, choices=[0, 1], default=None,
                   help="full on-device health counters (grad-norm, "
                        "per-table update magnitudes, non-finite counts) in "
                        "the step metrics (config.health_metrics; default: "
                        "on when --metrics-dir is set, else off — they cost "
                        "one extra table read per step)")
    p.add_argument("--quality-probe-every", type=int, default=None,
                   metavar="STEPS",
                   help="in-training embedding-quality probe cadence "
                        "(obs/quality.py): every STEPS optimizer steps, "
                        "score a read-only view of the live tables "
                        "(planted Spearman + analogy accuracy, Jaccard@k "
                        "neighbor drift, row-norm/effective-rank health) "
                        "through the serve query kernel and emit "
                        "w2v_quality_* telemetry. Default: 100 when "
                        "--metrics-dir or a --probe-* file is set, else "
                        "off; 0 disables. Non-probe steps add zero device "
                        "syncs")
    p.add_argument("--probe-pairs", metavar="FILE",
                   help="held-out word-pair golds for the quality probe "
                        "(WS-353-shaped word1,word2,score lines); default: "
                        "synthesized from planted-structure vocabularies "
                        "(utils/synthetic.planted_probe_golds), stats-only "
                        "otherwise")
    p.add_argument("--probe-analogies", metavar="FILE",
                   help="held-out analogy questions for the quality probe "
                        "(questions-words.txt format)")
    p.add_argument("--quality-budget", type=int, default=0, metavar="N",
                   help="degeneracy-sentinel escalation budget "
                        "(obs/quality.QualitySentinel): N consecutive "
                        "degraded probes -> checkpoint-and-continue, 2N -> "
                        "abort rc=3 with a QualityAlert in flight.json "
                        "(mirrors the DivergenceError contract). 0 = warn "
                        "only (default)")
    p.add_argument("--quality-floor", type=float, default=0.1, metavar="F",
                   help="sentinel absolute floor on the watched planted "
                        "score (analogy accuracy, else Spearman); probes "
                        "below it count as degraded")
    p.add_argument("--quality-drop", type=float, default=0.5, metavar="F",
                   help="sentinel relative-drop fraction: a probe below "
                        "(1-F) x the score's own peak counts as degraded "
                        "(the learn-then-collapse signature; needs a peak "
                        ">= the floor first)")
    p.add_argument("--quality-grace", type=int, default=2, metavar="N",
                   help="scored probes ignored by the sentinel's absolute "
                        "floor before it arms (early training legitimately "
                        "scores low; the relative-drop check is always "
                        "armed since it needs an established peak)")
    p.add_argument("--slo", metavar="RULES", default="",
                   help="declarative SLO rules over the derived signals "
                        "(obs/slo.py): comma-separated "
                        "'<signal><op><threshold>[:for=N][:baseline=N]' "
                        "clauses, e.g. "
                        "'throughput_wps<0.8*baseline:for=5', or a path to "
                        "a .json rule list. Evaluated per signal window, "
                        "escalating ok -> warn -> breach with structured "
                        "SloEvents on the metrics stream + flight ring and "
                        "a w2v_slo_breaches_total counter. A breach is a "
                        "log + event, NEVER an exit (observe, don't "
                        "actuate). Implies the signal plane on")
    p.add_argument("--signal-window", type=int, default=0, metavar="STEPS",
                   help="optimizer steps per derived-signal window "
                        "(obs/signals.py; 0 = auto: 50). Each closed "
                        "window emits one w2v_signal_* row (throughput, "
                        "step-time p50/p90, input-bound ratio, straggler "
                        "skew, quality) into the metrics stream and "
                        "signals_p<rank>.jsonl; rank 0 merges all hosts' "
                        "rows by window id into fleet.json + w2v_fleet_* "
                        "gauges. On by default with --metrics-dir or "
                        "--prom-textfile; windows add zero device fetches")
    p.add_argument("--divergence-budget", type=int, default=8,
                   help="consecutive non-finite-loss steps before the run "
                        "aborts with a structured DivergenceError instead "
                        "of training on NaN parameters (0 = warn only; "
                        "config.divergence_budget; observed every step via "
                        "the lagged metrics drain, even with --log-every 0)")
    p.add_argument("--inject-nan", action="store_true", help=argparse.SUPPRESS)
    # ^ legacy alias for `--faults nan@0` (poison the initial params), kept
    #   so existing CI invocations of the divergence tripwire don't break
    p.add_argument("--tensorboard", metavar="DIR",
                   help="write TensorBoard scalar summaries to DIR "
                        "(loss/alpha/words_per_sec/progress + health "
                        "counters; degrades to a warning without "
                        "tensorboardX)")
    p.add_argument("--profile", metavar="DIR",
                   help="capture a jax.profiler trace of training into DIR "
                        "(view with tensorboard/xprof). This traces the "
                        "WHOLE run; for bounded windows use "
                        "--profile-steps / --profile-on-breach")
    p.add_argument("--profile-steps", metavar="A:B", default="",
                   help="bounded profiler window (obs/profiler.py): arm "
                        "jax.profiler at step A, stop at step B, and write "
                        "a schema-checked capture manifest "
                        "(capture_<n>.json) next to flight.json in "
                        "--metrics-dir (required)")
    p.add_argument("--profile-on-breach", type=int, default=0, metavar="N",
                   help="breach-triggered profiler capture "
                        "(obs/profiler.py): when an --slo rule enters "
                        "breach, arm jax.profiler for N step boundaries — "
                        "one bounded capture per breach episode, "
                        "cooldown-gated — and dump a capture manifest next "
                        "to flight.json. Needs --metrics-dir and --slo; "
                        "SIGUSR2 requests the same bounded window on "
                        "demand (plus a memory-ledger dump) without "
                        "stopping the run")
    p.add_argument("--mem-sample-every", type=int, default=0, metavar="N",
                   help="HBM memory-ledger cadence (obs/devmem.py; 0 = "
                        "auto: 50): sample device.memory_stats() every N "
                        "step boundaries into w2v_mem_* gauges, the "
                        "mem_headroom_frac derived signal (SLO-able), "
                        "flight.json, and the manifest's per-phase "
                        "watermarks + growth-headroom forecast. Non-sample "
                        "boundaries add zero device dispatches; backends "
                        "without memory stats (CPU) degrade to "
                        "present-from-zero gauges")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans (SURVEY §5: the batched-update "
                        "analog of a race detector/sanitizer)")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--emit-device", action="store_true",
                   help="after training, print one machine-readable "
                        "'device: <platform> <kind>' line to stderr even "
                        "under --quiet (harnesses use it to prove where a "
                        "run actually executed — a silent CPU fallback must "
                        "not bank as an on-chip result)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    parser = build_parser()
    if not argv:
        parser.print_help()  # reference: help on no args (main.cpp:99-103)
        return 0
    args = parser.parse_args(argv)

    if args.backend == "cpu":
        # before the multihost init: the coordination handshake must see the
        # cpu platform, not the tunnel backend the sitecustomize pins
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    # Elastic mode: validated and connected BEFORE the first jax touch — a
    # rejoining host must be parked at the rendezvous instead of hanging on
    # a coordination service the fleet has already moved past.
    elastic_ctl = None
    if args.rejoin_window < 0:
        print("error: --rejoin-window must be >= 0", file=sys.stderr)
        return 1
    if args.elastic_policy and args.elastic == "off":
        print(
            "error: --elastic-policy requires --elastic shrink or "
            "shrink+grow: the policy actuates through the elastic "
            "rendezvous/remesh machinery",
            file=sys.stderr,
        )
        return 1
    if args.elastic_policy:
        # fail-in-milliseconds: a typo'd policy spec dies before the
        # corpus scan (clause + offset in the message, the --faults/--slo
        # contract)
        from .resilience.policy import PolicyError, parse_policy

        try:
            parse_policy(args.elastic_policy)
        except PolicyError as e:
            print(f"error: bad --elastic-policy spec: {e}", file=sys.stderr)
            return 1
    if args.elastic != "off":
        if args.sync_deadline <= 0:
            print(
                "error: --elastic requires --sync-deadline > 0: peer loss "
                "is detected by the deadline-bounded collectives, and "
                "without a deadline a dead peer is an unbounded hang, not "
                "a recoverable SyncTimeout",
                file=sys.stderr,
            )
            return 1
        if not (args.checkpoint_dir and args.checkpoint_every):
            print(
                "error: --elastic requires --checkpoint-dir and "
                "--checkpoint-every (on a filesystem every host shares): "
                "survivors re-shard from the last integrity-verified "
                "checkpoint, and without one there is no agreed state to "
                "re-form from",
                file=sys.stderr,
            )
            return 1
        from .resilience.elastic import ElasticController, ElasticError

        elastic_ctl = ElasticController.from_env(
            mode=args.elastic, argv=list(argv), dp=args.dp,
            ckpt_dir=args.checkpoint_dir, sync_deadline=args.sync_deadline,
            step_deadline=args.step_deadline,
            max_reannounce=args.rejoin_window,
        )
        if elastic_ctl is None:
            if not args.quiet:
                print(
                    "warning: --elastic set but the W2V_COORDINATOR/"
                    "W2V_NUM_PROCS multi-process contract is not "
                    "configured; a single-process run has no fleet to "
                    "shrink or grow — continuing non-elastic",
                    file=sys.stderr,
                )
        else:
            try:
                # rank 0 binds the rendezvous; other ranks hello — and an
                # admitted rejoiner EXECS into the grown generation here
                elastic_ctl.startup()
            except ElasticError as e:
                print(f"error: elastic startup: {e}", file=sys.stderr)
                return 1

    if args.multihost:
        # must run before any backend use on every host. Elastic fleets
        # defuse the coordination service's fatal error poller: its
        # default callback SIGABRTs survivors when the coordinator host
        # dies — the one loss the rank-0 election exists to survive.
        from .parallel.multihost import initialize_from_env

        if not initialize_from_env(
            defuse_fatal=elastic_ctl is not None
        ) and not args.quiet:
            print(
                "warning: --multihost set but W2V_COORDINATOR/W2V_NUM_PROCS "
                "not configured; continuing single-process",
                file=sys.stderr,
            )
    import jax

    from .config import Word2VecConfig
    from .data.batcher import PackedCorpus
    from .data.vocab import Vocab
    from .io.checkpoint import (
        CheckpointError, load_checkpoint_with_path, read_stream_cursor,
        save_checkpoint,
    )
    from .io.embeddings import save_word2vec
    from .models.params import export_matrix
    from .resilience.faults import Fault, FaultPlan
    from .train import Trainer
    from .utils.logging import progress_logger

    # Fault plan + resilience knobs: validated before any expensive work
    # (a chaos run with a typo'd spec must fail in milliseconds, not after
    # the corpus scan).
    try:
        fault_plan = FaultPlan.parse(args.faults)
        if args.inject_nan:  # legacy alias
            fault_plan.faults.append(Fault("nan", step=0))
    except (ValueError, OSError) as e:
        print(f"error: bad --faults spec: {e}", file=sys.stderr)
        return 1
    # SLO rules: same fail-in-milliseconds contract as the fault spec (the
    # parse errors name clause + offset, obs/slo.py)
    from .obs.slo import SloError, parse_slo

    try:
        slo_rules = parse_slo(args.slo)
    except SloError as e:
        print(f"error: bad --slo spec: {e}", file=sys.stderr)
        return 1
    if args.signal_window < 0:
        print("error: --signal-window must be >= 0", file=sys.stderr)
        return 1
    if args.checkpoint_keep < 0:
        print("error: --checkpoint-keep must be >= 0", file=sys.stderr)
        return 1
    if args.auto_recover < 0:
        print("error: --auto-recover must be >= 0", file=sys.stderr)
        return 1
    if args.auto_recover and not (0.0 < args.recover_alpha_scale <= 1.0):
        print("error: --recover-alpha-scale must be in (0, 1]", file=sys.stderr)
        return 1
    if args.step_deadline < 0:
        print("error: --step-deadline must be >= 0", file=sys.stderr)
        return 1
    if args.sync_deadline < 0:
        print("error: --sync-deadline must be >= 0", file=sys.stderr)
        return 1
    if args.quality_budget < 0:
        print("error: --quality-budget must be >= 0", file=sys.stderr)
        return 1
    if args.quality_grace < 0:
        print("error: --quality-grace must be >= 0", file=sys.stderr)
        return 1
    if args.quality_probe_every is not None and args.quality_probe_every < 0:
        print("error: --quality-probe-every must be >= 0", file=sys.stderr)
        return 1
    if args.mem_sample_every < 0:
        print("error: --mem-sample-every must be >= 0", file=sys.stderr)
        return 1
    if args.profile_on_breach < 0:
        print("error: --profile-on-breach must be >= 0", file=sys.stderr)
        return 1
    # bounded profiler windows need a manifest destination; parse A:B
    # before the corpus scan (the --faults/--slo fail-fast contract)
    profile_window = None
    if args.profile_steps:
        try:
            a_s, _, b_s = args.profile_steps.partition(":")
            profile_window = (int(a_s), int(b_s))
        except ValueError:
            print(
                f"error: bad --profile-steps {args.profile_steps!r} "
                "(want A:B, two integer steps)",
                file=sys.stderr,
            )
            return 1
        if profile_window[1] <= profile_window[0]:
            print(
                f"error: --profile-steps window is empty: "
                f"{args.profile_steps!r}",
                file=sys.stderr,
            )
            return 1
    if (args.profile_steps or args.profile_on_breach) and not args.metrics_dir:
        print(
            "error: --profile-steps/--profile-on-breach write their capture "
            "manifests into --metrics-dir; set it",
            file=sys.stderr,
        )
        return 1
    if args.profile_on_breach and not slo_rules:
        print(
            "error: --profile-on-breach triggers on --slo breaches; set "
            "--slo rules (SIGUSR2 windows work without any)",
            file=sys.stderr,
        )
        return 1
    # quality-probe cadence: on by default for instrumented runs
    # (--metrics-dir) and whenever the user supplies probe material
    q_every = args.quality_probe_every
    if q_every is None:
        q_every = 100 if (
            args.metrics_dir or args.probe_pairs or args.probe_analogies
        ) else 0

    # Resume: the checkpoint's config and vocab are authoritative — resuming
    # against a rebuilt vocab would silently re-attribute embedding rows; and
    # the flag-derived config is never even validated (default flags need not
    # form a valid config to resume from one that does).
    state = None
    ck_cfg = None
    ck_vocab = None
    stream_doc = None
    if args.resume:
        try:
            state, ck_cfg, ck_vocab, ck_dir = load_checkpoint_with_path(
                args.resume
            )
        except CheckpointError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        # streaming checkpoints carry their replay cursor NEXT TO the
        # params (same integrity manifest, same backup rotation) — read it
        # from the dir that actually loaded, which may be a .old fallback
        stream_doc = read_stream_cursor(ck_dir)
        if not args.quiet:
            print(
                f"resumed from {args.resume} at step {state.step}"
                + (
                    f" (stream segment {stream_doc.get('segment')}, "
                    f"vocab generation {stream_doc.get('vocab_generation')})"
                    if stream_doc else ""
                )
            )

    # validation mirrors main.cpp:164-181 (raised by Word2VecConfig)
    alpha = args.alpha
    if alpha is None:
        # word2vec.c-style default: 0.05 for cbow(+mean), 0.025 for sg
        alpha = 0.05 if (args.model == "cbow" and args.cbow_mean) else 0.025
    # One kwargs dict serves both the fresh-run constructor and the resume
    # flag-diff notice below, so the notice can never silently fall out of
    # sync with the set of flags the constructor honors (ADVICE r3: levers
    # like --table-dtype/--sr/--negative-scope were invisible to the old
    # subset comparison).
    flag_kwargs = dict(
        iters=args.iter,
        window=args.window,
        min_count=args.min_count,
        word_dim=args.size,
        negative=args.negative,
        subsample_threshold=args.subsample,
        init_alpha=alpha,
        cbow_mean=bool(args.cbow_mean),
        train_method=args.train_method,
        model=args.model,
        batch_rows=args.batch_rows or 32,  # placeholder; auto-sized below
        # with auto batch sizing the real (rows, micro) pair is set
        # below; constructing with micro here would trip the
        # divisibility check against the placeholder
        micro_steps=max(1, args.micro_steps) if args.batch_rows else 1,
        chunk_steps=args.chunk_steps,
        max_sentence_len=args.max_sentence_len,
        seed=args.seed,
        dp_sync_every=args.dp_sync_every,
        sync_mode=args.sync_mode,
        kernel=args.kernel,
        compute_dtype=args.compute_dtype,
        shared_negatives=args.shared_negatives,
        negative_scope=args.negative_scope,
        scatter_mean=bool(args.scatter_mean),
        slab_scatter=bool(args.slab_scatter),
        band_backend=args.band_backend,
        table_layout=args.table_layout,
        hs_dense_top=args.hs_dense_top,
        hs_tail_slots=args.hs_tail_slots,
        resident=args.resident,
        corpus_mode=args.corpus_mode,
        segment_tokens=args.segment_tokens,
        vocab_reserve=args.vocab_reserve,
        autotune=args.autotune,
        plan_cache=args.plan_cache,
        clip_row_update=args.clip_row_update,
        prng_impl=args.prng,
        dtype=args.table_dtype,
        stochastic_rounding=bool(args.stochastic_rounding),
        # telemetry: --metrics-dir implies the full health counters unless
        # the user explicitly opted out
        health_metrics=bool(
            args.health_metrics
            if args.health_metrics is not None
            else args.metrics_dir
        ),
        divergence_budget=args.divergence_budget,
        quality_probe_every=q_every,
        elastic=args.elastic,
        elastic_policy=args.elastic_policy,
    )
    try:
        cfg = ck_cfg if ck_cfg is not None else Word2VecConfig(**flag_kwargs)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if cfg.elastic != args.elastic or cfg.elastic_policy != args.elastic_policy:
        # elasticity (and its policy) is runtime wiring, like
        # --sync-deadline: the flag is authoritative on resume (a
        # checkpoint from a non-elastic generation must not pin recovery
        # off — every elastic generation IS such a resume)
        import dataclasses as _dc

        cfg = _dc.replace(
            cfg, elastic=args.elastic, elastic_policy=args.elastic_policy
        )

    if args.export_side == "output" and cfg.use_hs:
        # fail BEFORE training, not at the export step after a long run —
        # and on the EFFECTIVE config (a resumed checkpoint overrides the
        # -train_method flag): the hs output table rows are Huffman
        # internal nodes, not words
        print("error: --export-side output requires negative sampling "
              "(the hs output table holds internal nodes, not word rows)",
              file=sys.stderr)
        return 1

    if ck_cfg is not None and args.prng != ck_cfg.prng_impl:
        # unconditional (even under --quiet): silently switching the draw
        # streams mid-run is exactly the hazard the persisted field prevents
        print(
            f"resume: checkpoint pins prng_impl={ck_cfg.prng_impl!r}; "
            f"ignoring --prng {args.prng} (the draw streams stay on the "
            f"checkpoint's impl)",
            file=sys.stderr,
        )

    if not args.train:
        print("error: -train <file> is required", file=sys.stderr)
        return 1

    from . import native
    from .data.corpus import load_corpus
    from .train import TrainState

    # In multi-host mode only process 0 writes shared artifacts (vectors,
    # vocab, checkpoints): every process reaching the save paths with the
    # same -output on a shared filesystem would interleave writes.
    is_primary = jax.process_index() == 0

    if ck_cfg is not None:
        # Notice about flags the checkpoint config overrides. Unconditional
        # (even under --quiet), like the prng warning above: a lever flag
        # passed at resume time being silently ignored is exactly how an A/B
        # run ends up measuring the wrong configuration. Built from the SAME
        # kwargs as the fresh-run constructor so every honored flag is
        # compared (the combo itself may not be constructible — fine).
        try:
            flag_cfg = Word2VecConfig(**flag_kwargs)
        except ValueError:
            flag_cfg = None
        if flag_cfg is not None:
            import dataclasses as _dc

            # Only flags the user actually typed can be "ignored": the
            # checkpoint legitimately differs from parser defaults all the
            # time, and reporting untyped fields would bury real mismatches
            # in false alarms. Presence is detected by scanning argv for the
            # parser's own option strings (covers every alias and the
            # --flag=value form, and catches a flag explicitly passed AT its
            # default — which IS overridden when the checkpoint differs).
            argv_tokens = list(sys.argv[1:] if argv is None else argv)
            opts_by_dest = {
                a.dest: a.option_strings for a in parser._actions
            }
            # config fields whose argparse dest is spelled differently; any
            # field not listed here uses its own name as the dest, so a new
            # lever added with matching names is covered automatically
            dest_overrides = {
                "iters": "iter", "word_dim": "size",
                "subsample_threshold": "subsample", "init_alpha": "alpha",
                "dtype": "table_dtype",
            }

            def user_set(field: str) -> bool:
                opts = opts_by_dest.get(dest_overrides.get(field, field))
                if opts is None:
                    # No parser action for this field. If the constructor
                    # kwargs don't carry it either, there is no CLI flag at
                    # all (min_alpha, band_chunk, ...) — it can never be
                    # user-typed, and a checkpoint written via the Python
                    # API with a non-default value would otherwise trigger
                    # a false notice naming a flag that does not exist.
                    # A field that IS constructor-fed but has no resolvable
                    # dest (spelling drift) still fails OPEN — a spurious
                    # notice beats silently re-opening the ADVICE-r3 hole.
                    return field in flag_kwargs
                return any(
                    t == o or t.startswith(o + "=")
                    for t in argv_tokens
                    for o in opts
                )

            def flag_value(field: str):
                # without --batch-rows, flag_kwargs carries geometry
                # PLACEHOLDERS (32, 1); a typed --micro-steps must still be
                # compared by the value the user typed, or its silent
                # override on resume goes unreported (batch_rows untyped is
                # already filtered by user_set)
                if field == "micro_steps" and not args.batch_rows:
                    return max(1, args.micro_steps)
                return getattr(flag_cfg, field)

            diffs = sorted(
                f.name
                for f in _dc.fields(flag_cfg)
                # prng_impl warned separately above; elastic and its
                # policy are runtime wiring the flag overrides on resume
                # (never ignored)
                if f.name not in ("prng_impl", "elastic", "elastic_policy")
                and user_set(f.name)
                and flag_value(f.name) != getattr(ck_cfg, f.name)
            )
            if diffs:
                print(
                    "resume: using checkpoint config; ignoring differing "
                    f"flags {diffs}", file=sys.stderr,
                )

    t0 = time.perf_counter()
    mode = native.MODE_STREAM if args.corpus_format == "text8" else native.MODE_LINES
    if args.max_vocab and (ck_vocab is not None or args.read_vocab):
        print(
            "warning: --max-vocab applies only when the vocabulary is built "
            "from the corpus; the loaded vocabulary (checkpoint/-read-vocab) "
            "is used as-is", file=sys.stderr,
        )
    streaming = cfg.corpus_mode == "streaming"
    stream_source = None
    stream_cursor = None
    stream_run = None  # set after the trainer exists; save sites read it lazily
    if args.train == "-" and not streaming:
        print(
            "error: -train - (pipe ingestion) requires --corpus-mode "
            "streaming: a pipe cannot be packed resident",
            file=sys.stderr,
        )
        return 1
    if streaming:
        import numpy as _np

        from .stream import DEFAULT_SEGMENT_TOKENS, StreamCursor, make_source
        from .stream.driver import encode_segment

        seg_tokens = cfg.segment_tokens or DEFAULT_SEGMENT_TOKENS
        spool = args.stream_spool
        if not spool and args.train == "-":
            import tempfile

            spool = (
                os.path.join(args.checkpoint_dir, "stream_spool")
                if args.checkpoint_dir
                else os.path.join(
                    tempfile.gettempdir(), f"w2v_stream_spool_{os.getpid()}"
                )
            )
            if args.checkpoint_dir and jax.process_count() > 1:
                spool += f"_p{jax.process_index()}"
        try:
            stream_source = make_source(
                args.train, fmt=args.corpus_format,
                segment_tokens=seg_tokens, spool_dir=spool,
            )
        except (FileNotFoundError, ValueError, OSError) as e:
            print(f"error: bad streaming corpus spec: {e}", file=sys.stderr)
            return 1
        stream_cursor = (
            StreamCursor.from_json(stream_doc) if stream_doc
            else StreamCursor()
        )
        if args.resume and stream_doc is None and not args.quiet:
            print(
                "warning: resuming a non-streaming checkpoint into "
                "--corpus-mode streaming: the stream starts from its "
                "beginning (no cursor to replay)",
                file=sys.stderr,
            )
        # Vocabulary bootstrap: checkpoint > -read-vocab > first segment.
        # The streaming resume skips the full-corpus rebuild guard (a
        # stream cannot be re-counted mid-flight); identity is pinned by
        # the cursor + the checkpoint's own vocab instead.
        boot = None
        if ck_vocab is not None:
            vocab = ck_vocab
        elif args.read_vocab:
            vocab = Vocab.load(args.read_vocab)
        else:
            boot = stream_source.read_segment(
                stream_cursor.segment, stream_cursor.shard,
                stream_cursor.offset, vocab=None,
            )
            if boot.raw_tokens == 0:
                print(
                    "error: the streaming corpus produced no tokens "
                    "(empty stream at the start cursor)", file=sys.stderr,
                )
                return 1
            vocab = Vocab.from_counter(
                boot.counts or {}, min_count=cfg.min_count,
                max_vocab=args.max_vocab,
            )
            if len(vocab) == 0:
                print(
                    "error: the first streaming segment built an empty "
                    "vocabulary (every word under -min-count "
                    f"{cfg.min_count}); lower -min-count or enlarge "
                    "--segment-tokens", file=sys.stderr,
                )
                return 1
        if boot is None:
            boot = stream_source.read_segment(
                stream_cursor.segment, stream_cursor.shard,
                stream_cursor.offset, vocab=vocab,
            )
        # bootstrap corpus: feeds plan shapes / auto geometry / hazard
        # warnings at construction; the driver replaces it per segment
        flat = encode_segment(
            boot, vocab, getattr(stream_source, "fmt", "text8")
        )
        if flat.size == 0 or not (flat >= 0).any():
            flat = _np.zeros(1, dtype=_np.int32)
    elif ck_vocab is not None:
        vocab = ck_vocab
        if args.read_vocab and Vocab.load(
            args.read_vocab
        ).content_hash() != vocab.content_hash() and not args.allow_vocab_mismatch:
            print(
                f"error: -read-vocab {args.read_vocab} holds a different "
                f"vocabulary than the checkpoint at {args.resume} "
                "(content-hash mismatch); resuming would re-attribute "
                "embedding rows. Drop -read-vocab (the checkpoint's vocab "
                "is authoritative) or pass --allow-vocab-mismatch.",
                file=sys.stderr,
            )
            return 1
        if not args.read_vocab and not args.allow_vocab_mismatch:
            # Resume-compatibility guard: rebuild the vocabulary this corpus
            # + the checkpoint's min_count produce and compare content
            # hashes. A different corpus used to train SILENTLY against the
            # checkpoint's vocab — every row's meaning drifts while the loss
            # looks healthy. Hash-equal vocabularies encode identically
            # (deterministic sort), so the rebuilt ids are reused — the
            # guard costs one vocab count pass, not a second encode.
            rb_vocab, rb_flat = load_corpus(
                args.train, fmt=args.corpus_format, min_count=cfg.min_count,
                max_vocab=args.max_vocab,
            )
            if rb_vocab.content_hash() == vocab.content_hash():
                flat = rb_flat
            elif vocab.is_compatible_superset(rb_vocab):
                # Compatible superset: the checkpoint's vocabulary extends
                # what this corpus rebuilds to — exactly what online vocab
                # growth produces (stream/driver.py admits new words into
                # reserved rows without disturbing existing ones). The
                # grown vocabulary stays authoritative; re-encode with it
                # so any grown word present in the corpus keeps its row.
                print(
                    f"resume: checkpoint vocabulary ({len(vocab)} words) is "
                    f"a compatible superset of the corpus rebuild "
                    f"({len(rb_vocab)} words) — an online-growth "
                    "checkpoint; resuming with the grown vocabulary",
                    file=sys.stderr,
                )
                flat = native.encode_file(args.train, vocab, mode)
            else:
                print(
                    f"error: the corpus at {args.train} rebuilds to a "
                    f"different vocabulary ({len(rb_vocab)} words) than the "
                    f"checkpoint at {args.resume} pins ({len(vocab)} words, "
                    "content-hash mismatch, not a compatible superset): "
                    "this is not the corpus the "
                    "checkpoint was trained on (or -min-count/--max-vocab "
                    "differ from the original run). Resuming would silently "
                    "re-attribute embedding rows; pass "
                    "--allow-vocab-mismatch to train the checkpoint's "
                    "vocab against this corpus anyway.",
                    file=sys.stderr,
                )
                return 1
        else:
            flat = native.encode_file(args.train, vocab, mode)
    elif args.read_vocab:
        vocab = Vocab.load(args.read_vocab)  # Word2Vec.cpp:179-196
        flat = native.encode_file(args.train, vocab, mode)
    else:
        vocab, flat = load_corpus(
            args.train, fmt=args.corpus_format, min_count=cfg.min_count,
            max_vocab=args.max_vocab,
        )
    if not args.quiet:
        impl = "native" if native.available() else "python"
        print(f"vocab: {len(vocab)} words, {vocab.total_words} total "
              f"({time.perf_counter() - t0:.1f}s, {impl} data layer)")
    corpus = PackedCorpus.from_flat(flat, cfg.max_sentence_len)
    if args.save_vocab and is_primary:
        vocab.save(args.save_vocab)  # Word2Vec.cpp:171-177

    if args.batch_rows == 0 and not args.resume:
        import dataclasses as _dc

        # multi-host: size from the GLOBAL token count (sum over shards) so
        # every process derives the same batch_rows and global array shapes
        auto_tokens = corpus.num_tokens
        if jax.process_count() > 1:
            from .parallel.multihost import global_agree_sum

            auto_tokens = global_agree_sum(auto_tokens)
        auto_rows, auto_micro = Word2VecConfig.auto_geometry(
            auto_tokens, cfg.max_sentence_len, dp=args.dp,
            vocab_size=len(vocab),
        )
        if args.micro_steps:
            # explicit micro with auto rows: keep the auto-sized OPTIMIZER
            # block (the convergence/hot-row unit) and scale the dispatch to
            # block * micro — carrying auto_rows over would silently multiply
            # the per-block token count past the hot-row cap
            block = max(1, auto_rows // auto_micro)
            auto_micro = args.micro_steps
            auto_rows = block * auto_micro
        cfg = _dc.replace(cfg, batch_rows=auto_rows, micro_steps=auto_micro)
        if not args.quiet:
            steps = max(
                1,
                auto_tokens
                * auto_micro
                // (auto_rows * cfg.max_sentence_len * args.dp),
            )
            print(
                f"batch geometry auto: {auto_rows} rows x {auto_micro} "
                f"micro-steps (~{steps} optimizer steps/epoch)"
            )

    if args.multihost and jax.process_count() > 1 and args.dp * args.tp * args.sp <= 1:
        print(
            "error: --multihost with a 1-device mesh: every process would "
            "train a redundant full model; set --dp (and optionally "
            "--tp/--sp) to span the global device set",
            file=sys.stderr,
        )
        return 1

    # One MetricsHub fans every log record out to the enabled sinks and is
    # the single close point for their file handles (obs/export.py replaces
    # the old ad-hoc tee(...) wiring).
    from .obs.export import MetricsHub, prometheus_textfile

    hub = MetricsHub()
    if not args.quiet:
        hub.add(progress_logger())
    metrics_dir = args.metrics_dir if is_primary else None
    if metrics_dir:
        os.makedirs(metrics_dir, exist_ok=True)
    jsonl_path = args.log_jsonl or (
        os.path.join(metrics_dir, "metrics.jsonl") if metrics_dir else None
    )
    prom_path = args.prom_textfile or (
        os.path.join(metrics_dir, "metrics.prom") if metrics_dir else None
    )
    if jsonl_path or prom_path or args.tensorboard:
        from .utils.logging import jsonl_logger, tensorboard_logger

        if jsonl_path:
            hub.add(jsonl_logger(jsonl_path))
        if prom_path:
            hub.add(prometheus_textfile(prom_path))
        if args.tensorboard:
            hub.add(tensorboard_logger(args.tensorboard))
    log_fn = hub if hub.sinks else None
    if elastic_ctl is not None:
        # the rendezvous decisions and announces land on the run's sinks
        elastic_ctl.log_fn = log_fn
        if elastic_ctl.server is not None:
            elastic_ctl.server.log_fn = log_fn
    if args.dp * args.tp * args.sp > 1:
        from .parallel import ShardedTrainer

        mesh = None
        if args.multihost:
            from .parallel.multihost import make_global_mesh

            mesh = make_global_mesh(args.dp, args.tp, args.sp)
        trainer = ShardedTrainer(
            cfg, vocab, corpus, dp=args.dp, tp=args.tp, sp=args.sp,
            mesh=mesh, log_fn=log_fn,
        )
    else:
        trainer = Trainer(cfg, vocab, corpus, log_fn=log_fn)

    if trainer.plan_resolution is not None:
        cfg = trainer.config  # the plan-applied config (checkpoints pin it)
        if not args.quiet:
            pr = trainer.plan_resolution
            hit = "cache hit" if pr.source == "cache" else "probed"
            print(f"autotune ({hit}, key {pr.key}): {pr.plan.to_json()}")

    # Device-truth observability (obs/devmem.py + obs/harvest.py +
    # obs/profiler.py), on for the same instrumented runs the signal plane
    # covers: the HBM memory ledger (per-phase watermarks, w2v_mem_*
    # gauges, the mem_headroom_frac derived signal, the growth-headroom
    # forecast), the compiled-program cost harvest (banked into the
    # manifest at run end), and the bounded profiler capture (armed by SLO
    # breaches / --profile-steps / SIGUSR2). Constructed BEFORE the
    # manifest write so the manifest's start block carries the init
    # watermark; installed process-wide so serve swap_table and the
    # SIGUSR2 handler find the live ledger (obs/devmem.activate).
    mem_ledger = None
    cost_harvest = None
    prof_capture = None
    prev_ledger = None
    if slo_rules or args.metrics_dir or args.prom_textfile:
        from .obs import devmem as devmem_mod
        from .obs.devmem import MemoryLedger, table_row_bytes
        from .obs.harvest import CostHarvest

        mem_ledger = MemoryLedger(
            sample_every=args.mem_sample_every or 50,
            # the hub directly (not the log_fn gate): the SignalEngine is
            # itself a hub sink, and the mem rows must reach it even when
            # no console/file sink is attached (--slo alone, --quiet)
            log_fn=hub,
            flight=trainer.flight,
            host=jax.process_index(),
            row_bytes=table_row_bytes(trainer.config),
            vocab_reserve=trainer.config.vocab_reserve,
        )
        trainer.devmem = mem_ledger
        prev_ledger = devmem_mod.activate(mem_ledger)
        # pre-training watermark: whatever init/compile already allocated
        mem_ledger.sample("init")
        cost_harvest = CostHarvest(host=jax.process_index())
        trainer.harvest = cost_harvest
    if args.metrics_dir and is_primary:
        from .obs.profiler import ProfilerCapture

        prof_capture = ProfilerCapture(
            metrics_dir,
            steps=args.profile_on_breach or 8,
            log_fn=hub,
            flight=trainer.flight,
        )
        trainer.profiler = prof_capture
        if profile_window is not None:
            prof_capture.schedule(*profile_window)

    elastic_gen = int(os.environ.get("W2V_ELASTIC_GEN", "0") or 0)
    # Warm-restart compile cache: ONLY an exec'd next-generation elastic
    # process may point jax's persistent compilation cache at the
    # per-(topology, plan) directory — enable_warm_cache refuses for gen 0
    # (the PR 1 warm-cache segfault fence) and for operator-owned
    # JAX_COMPILATION_CACHE_DIR. Enabled after plan resolution (the plan
    # is part of the key) and before the first train-step compile.
    warm_cache_dir = None
    if args.compile_cache:
        from .tune.compile_cache import enable_warm_cache, topology_key

        warm_cache_dir = enable_warm_cache(
            args.compile_cache,
            topology_key(
                jax.process_count(), args.dp, args.tp, args.sp,
                trainer.config,
                plan_key=getattr(trainer.plan_resolution, "key", None),
            ),
            elastic_gen,
        )
        if warm_cache_dir and not args.quiet:
            print(
                f"compile cache: generation {elastic_gen} warm-restarts "
                f"from {warm_cache_dir}"
            )
    if metrics_dir:
        # the manifest carries the REALIZED config (plan applied) so every
        # record in this directory can be traced to what actually ran
        import json as _json

        from .obs.manifest import write_manifest

        man_path0 = os.path.join(metrics_dir, "manifest.json")
        extra = {
            "corpus_tokens": corpus.num_tokens,
            "corpus_rows": corpus.num_rows,
            # the data plane: resident (corpus_tokens = the whole corpus)
            # or streaming (corpus_tokens = the bootstrap segment; the
            # stream record below carries the live cursor)
            "corpus_mode": cfg.corpus_mode,
            "resumed_from": args.resume or None,
            # the kernel auto-selection record, when the degeneracy
            # domain re-routed a kernel='auto' run to 'pair' (the
            # manifest's "kernel" field already carries the realized
            # choice; this is the WHY)
            "kernel_decision": trainer.kernel_decision,
            "mesh_size": args.dp * args.tp * args.sp,
            "elastic": args.elastic,
            "elastic_policy": args.elastic_policy or None,
            "elastic_generation": elastic_gen,
            "compile_cache": warm_cache_dir,
            # the device-memory view at run start: availability, the init
            # watermark, and the growth-headroom forecast (rows-remaining
            # before table growth exhausts the budget) — the end-of-run
            # update rewrites this with the full per-phase ledger
            "device_memory": (
                mem_ledger.summary() if mem_ledger is not None else None
            ),
        }
        if streaming:
            extra["stream"] = {
                "segment_tokens": cfg.segment_tokens or DEFAULT_SEGMENT_TOKENS,
                "vocab_reserve": cfg.vocab_reserve,
                "source": stream_source.describe(),
                "resume_cursor": stream_doc,
            }
        if args.elastic != "off":
            # mesh_events survive the exec between generations: carry the
            # prior generations' rows forward before this rewrite, and
            # append this generation's start (with the exec->here wall when
            # we were re-formed rather than launched)
            prior_events = []
            if os.path.exists(man_path0):
                try:
                    with open(man_path0) as f:
                        prior_events = _json.load(f).get("mesh_events") or []
                except (OSError, ValueError):
                    prior_events = []
            exec_t = os.environ.get("W2V_ELASTIC_EXEC_T")
            elected_env = os.environ.get("W2V_ELASTIC_ELECTED")
            election = None
            if elected_env:
                er, _, ea = elected_env.partition(":")
                try:
                    election = {"elected_rank": int(er), "rendezvous": ea}
                except ValueError:
                    election = None
            extra["mesh_events"] = list(prior_events) + ([{
                "event": "rendezvous_election", "gen": elastic_gen,
                **election,
            }] if election else []) + [{
                "event": "generation_start",
                "gen": elastic_gen,
                "world": jax.process_count(),
                "mesh_size": args.dp * args.tp * args.sp,
                "dp": args.dp, "tp": args.tp, "sp": args.sp,
                "resumed_from": args.resume or None,
                # per-generation audit: which rendezvous decided this
                # topology (moves after a rank-0 election) and WHY the
                # remesh happened (failure | policy | rejoin; launch for
                # gen 0)
                "rendezvous": os.environ.get("W2V_ELASTIC_COORD"),
                "trigger": (
                    os.environ.get("W2V_ELASTIC_TRIGGER")
                    or ("launch" if elastic_gen == 0 else None)
                ),
                "startup_wall_s": (
                    round(time.monotonic() - float(exec_t), 3)
                    if exec_t and elastic_gen > 0 else None
                ),
            }]
        write_manifest(
            man_path0,
            trainer.config,
            vocab_size=len(vocab),
            plan_resolution=trainer.plan_resolution,
            extra=extra,
        )
    if log_fn is not None:
        # the mesh-topology gauges (obs/export.GAUGE_EVENTS): one record
        # per generation — w2v_mesh_size is the live fleet-shape signal
        # the elastic drill (and a dashboard) watches across remeshes
        log_fn({
            "event": "mesh",
            "mesh_size": args.dp * args.tp * args.sp,
            "mesh_processes": jax.process_count(),
            "elastic_generation": elastic_gen,
        })
        elected_env = os.environ.get("W2V_ELASTIC_ELECTED")
        if elected_env and elastic_gen > 0:
            # the generation we exec'd FROM ran the rendezvous election;
            # count it here, where this process has its metrics sinks
            # (w2v_rendezvous_elections_total, present from zero)
            er, _, ea = elected_env.partition(":")
            log_fn({
                "event": "rendezvous_election", "gen": elastic_gen,
                "elected_rank": er, "rendezvous": ea,
            })

    if state is not None and hasattr(trainer, "import_params"):
        # checkpoints always hold unreplicated [V, d] tables; re-shard them
        trainer.import_params(state.params, state)

    def unreplicated(s: TrainState) -> TrainState:
        if hasattr(trainer, "export_params"):
            return TrainState(
                params=trainer.export_params(s),
                step=s.step, words_done=s.words_done, epoch=s.epoch,
            )
        return s

    def _stream_meta():
        # read lazily at save time: the driver's cursor advances per
        # segment, and every checkpoint must carry the cursor of the
        # segment it was taken IN (None on resident runs)
        return stream_run.cursor_meta() if stream_run is not None else None

    def _save_ckpt(snap):
        save_checkpoint(
            args.checkpoint_dir, snap, trainer.config, vocab,
            keep=args.checkpoint_keep, stream=_stream_meta(),
        )

    ckpt_cb = None
    if args.checkpoint_dir and args.checkpoint_every:
        def ckpt_cb(s):
            # unreplicated() may run the pmean sync — a collective — so ALL
            # processes must enter it; only the file write is primary-gated.
            # trainer.config (not the captured cfg): a supervisor recovery
            # may have rescaled alpha / advanced the seed, and the
            # checkpoint must pin what the run is ACTUALLY using.
            snap = unreplicated(s)
            if is_primary:
                _save_ckpt(snap)

    # Quality-probe wiring: the CLI's flags are authoritative over the
    # trainer's config-built default (telemetry is runtime wiring, like
    # --metrics-dir — a resumed checkpoint must not pin it off). The probe
    # logs through the run's hub, rides the trainer's flight recorder, and
    # the sentinel escalates per --quality-budget; checkpoint-and-continue
    # reuses the run's checkpoint callback.
    from .obs.quality import (
        EXIT_QUALITY, ProbeSet, QualityAlert, QualityProbe, QualitySentinel,
    )

    if q_every > 0:
        from .tune.planner import degeneracy_domain

        try:
            pset = (
                ProbeSet.from_files(
                    vocab, args.probe_pairs, args.probe_analogies
                )
                if (args.probe_pairs or args.probe_analogies)
                else ProbeSet.synthesize(vocab)
            )
        except (OSError, ValueError) as e:
            print(f"error: bad probe file: {e}", file=sys.stderr)
            return 1
        trainer.quality_probe = QualityProbe(
            vocab, pset, every=q_every, log_fn=log_fn,
            flight=trainer.flight,
            sentinel=QualitySentinel(
                budget=args.quality_budget,
                floor=args.quality_floor,
                drop=args.quality_drop,
                grace=args.quality_grace,
                in_domain=degeneracy_domain(
                    trainer.config, len(vocab), corpus.num_tokens
                ),
            ),
        )
        if ckpt_cb is not None:
            trainer.quality_probe.checkpoint_fn = (
                lambda: ckpt_cb(trainer.last_state)
            )
        if not args.quiet:
            print(
                f"quality probe: every {q_every} steps, "
                f"{len(pset.pairs)} pairs + {len(pset.analogies)} "
                f"analogies ({pset.source}), sentinel budget "
                f"{args.quality_budget}"
            )
    else:
        trainer.quality_probe = None

    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)

    import contextlib

    from .utils.profiling import trace

    from .obs.health import DivergenceError
    from .obs.manifest import update_manifest
    from .resilience import faults as _faults
    from .resilience import watchdog as _watchdog
    from .resilience.elastic import GrowRequested, PolicyShrinkRequested
    from .resilience.shutdown import EXIT_PREEMPTED, ShutdownHandler
    from .resilience.watchdog import SyncTimeout

    manifest_path = (
        os.path.join(metrics_dir, "manifest.json") if metrics_dir else None
    )

    # Preemption-safe shutdown: SIGTERM/SIGINT request a cooperative stop at
    # the next step boundary (multihost-agreed); the run then checkpoints
    # and exits EXIT_PREEMPTED so a scheduler can requeue with --resume.
    handler = ShutdownHandler().install()

    # Step-deadline watchdog: a run that stops reaching step boundaries is
    # shot (EXIT_STALLED) with stacks + the wedged phase + the flight
    # recorder's timeline in the metrics dir instead of burning chip time
    # invisibly. Installed BEFORE install_shutdown so the multihost stop
    # check's heartbeat can read the watchdog's step-time p50. flush_fn
    # counts the stall in the Prometheus sinks and closes them — the fire
    # path os._exits, skipping every atexit hook.
    if args.step_deadline:
        def _stall_flush(rec):
            hub({"event": "stalled", "step": rec.get("step")})
            if elastic_ctl is None:
                # os._exit skips atexit, so close now; the elastic path
                # instead keeps the sinks open for the remesh records (its
                # execve also skips atexit, but the jsonl sink is
                # line-buffered and the prom textfile rewrites per record —
                # nothing is buffered to lose)
                hub.close()

        # Elastic shrink detection, leg 2: on a CPU/gloo backend the step
        # DISPATCH itself blocks synchronously on the collective, so a dead
        # peer wedges the main thread before any bounded channel runs — the
        # watchdog is the only detector that still fires. With --elastic,
        # its fire path attempts the shrink-remesh FROM THE MONITOR THREAD
        # (execve replaces the whole process, wedged main thread included)
        # and only falls back to the EXIT_STALLED shot when the rendezvous
        # fails. The stall artifacts (stacks, stall.json, flight) are still
        # written first — a recovered wedge should leave evidence too.
        elastic_on_fire = None
        if elastic_ctl is not None:
            def elastic_on_fire(rec):
                try:
                    elastic_ctl.remesh_and_exec(
                        "shrink", rec.get("step"),
                        manifest_path=manifest_path, hub=hub,
                        flight=trainer.flight, metrics_dir=metrics_dir,
                    )
                except Exception as e:  # noqa: BLE001 — fall through to 76
                    print(f"elastic: stall recovery failed: {e}",
                          file=sys.stderr)
                os._exit(_watchdog.EXIT_STALLED)

        trainer.watchdog = _watchdog.StepWatchdog(
            deadline=args.step_deadline,
            phases=trainer.phases,
            metrics_dir=metrics_dir,
            manifest_path=manifest_path,
            flight=trainer.flight,
            flush_fn=_stall_flush,
            on_fire=elastic_on_fire,
        )
    elif elastic_ctl is not None and not args.quiet:
        print(
            "warning: --elastic without --step-deadline: a dead peer that "
            "wedges the step dispatch itself (synchronous collectives, "
            "e.g. the CPU/gloo backend) is only detected by the step "
            "watchdog — set --step-deadline to bound that leg",
            file=sys.stderr,
        )
    # Deadline-bounded collectives: process-wide, consumed by
    # parallel/multihost's agree/heartbeat allgathers and the sharded
    # trainer's replica-sync wait. Restored in the finally below — main()
    # runs in-process under tests, and a leaked deadline would bound some
    # other run's collectives.
    prev_sync_deadline = _watchdog.set_sync_deadline(
        args.sync_deadline or None
    )
    if elastic_ctl is not None and args.elastic == "shrink+grow":
        # the grow channel: rank 0's pending-rejoin poll rides the
        # PeerAgreement heartbeat row install_shutdown wires below, so the
        # whole fleet admits a rejoiner at the same sync boundary
        trainer.elastic_poll = elastic_ctl.grow_pending
    # Derived-signal plane (obs/signals.py): on for instrumented runs
    # (--metrics-dir / --prom-textfile) and whenever SLO rules are set.
    # EVERY rank writes its per-window row file into args.metrics_dir
    # (distinct signals_p<rank>.jsonl names — the trace_p<i>.json
    # discipline); rank 0 additionally merges the fleet view. Wired BEFORE
    # install_shutdown so the PeerAgreement heartbeat can feed the
    # straggler_skew signal; registered on the hub so the quality probe's
    # gauge records feed quality_planted with zero new plumbing.
    sig_engine = None
    if (
        slo_rules or args.metrics_dir or args.prom_textfile
        or args.elastic_policy
    ):
        from .obs.fleet import FleetAggregator
        from .obs.signals import SignalEngine
        from .obs.slo import SloEvaluator

        sig_window = args.signal_window or 50
        sig_engine = SignalEngine(
            window=sig_window,
            phases=trainer.phases,
            flight=trainer.flight,
            log_fn=hub,
            metrics_dir=args.metrics_dir,
            host=jax.process_index(),
            slo=SloEvaluator(slo_rules) if slo_rules else None,
            aggregator=(
                FleetAggregator(args.metrics_dir, window_steps=sig_window)
                if args.metrics_dir and is_primary else None
            ),
        )
        trainer.signals = sig_engine
        hub.add(sig_engine)  # hub.close() also closes the row file
        if prof_capture is not None and args.profile_on_breach:
            # the third SignalBus consumer (after FleetHealth and
            # ElasticPolicy): an SLO breach requests one bounded profiler
            # window, armed at the next step boundary (obs/profiler.py)
            prof_capture.attach(sig_engine.bus)
        if not args.quiet and slo_rules:
            print(
                f"slo: {len(slo_rules)} rule(s) over {sig_window}-step "
                f"windows: {[str(r) for r in slo_rules]}"
            )
    # Elastic policy (resilience/policy.py): the control loop over the
    # signal plane. Only the rendezvous-hosting rank evaluates and
    # requests; every other rank reads the verdict from the heartbeat
    # rows. Wired BEFORE install_shutdown so PeerAgreement carries both
    # the policy column and the (now policy-gated) grow column.
    elastic_policy = None
    if args.elastic_policy and elastic_ctl is not None:
        from .resilience.policy import parse_policy

        if elastic_ctl.server is not None:
            elastic_policy = parse_policy(args.elastic_policy)
            elastic_policy.world = jax.process_count()
            elastic_policy.log_fn = log_fn
            if sig_engine is not None:
                elastic_policy.attach(sig_engine.bus)
            trainer.policy_poll = elastic_policy.poll
            if trainer.elastic_poll is not None:
                grow_src = trainer.elastic_poll
                trainer.elastic_poll = lambda: (
                    grow_src() if elastic_policy.grow_gate() else 0.0
                )
            if not args.quiet:
                print(
                    f"elastic policy: {len(elastic_policy.rules)} rule(s), "
                    f"cooldown {elastic_policy.cooldown} windows, world "
                    f"[{elastic_policy.min_world}, "
                    f"{elastic_policy.max_world or 'unbounded'}]: "
                    f"{[str(r) for r in elastic_policy.rules]}"
                )
    elif args.elastic_policy and elastic_ctl is None and not args.quiet:
        print(
            "warning: --elastic-policy set but no elastic fleet is "
            "configured; a single-process run has nothing to shrink or "
            "grow — the policy is inert",
            file=sys.stderr,
        )
    trainer.install_shutdown(handler)

    # On-demand diagnostics: SIGUSR1 dumps the flight recorder + all-thread
    # stacks into the metrics dir without stopping the run; SIGUSR2 is the
    # device-side mirror — a bounded profiler window + the memory ledger.
    from .resilience.shutdown import install_usr1_dump, install_usr2_profile

    uninstall_usr1 = (
        install_usr1_dump(metrics_dir, trainer.flight)
        if metrics_dir else (lambda: None)
    )
    uninstall_usr2 = (
        install_usr2_profile(metrics_dir, prof_capture, mem_ledger)
        if metrics_dir else (lambda: None)
    )

    def dump_flight(reason: str, failure_step=None) -> None:
        """Flight-recorder dump into the metrics dir (every failure path —
        the stall path dumps from the watchdog's own fire thread instead)."""
        if metrics_dir and trainer.flight is not None:
            trainer.flight.dump(
                metrics_dir, reason=reason,
                extra={"failure_step": failure_step},
            )

    def export_trace() -> None:
        """--trace DIR: Chrome-trace export of the run's span timeline.
        Best-effort on every exit path — a failed export must not change
        the run's exit code or eat its artifacts."""
        if not args.trace or trainer.flight is None:
            return
        try:
            import glob

            from .obs.trace import (
                chrome_trace_doc, load_trace, merge_traces, write_trace,
            )

            os.makedirs(args.trace, exist_ok=True)
            idx = jax.process_index()
            write_trace(
                os.path.join(args.trace, f"trace_p{idx}.json"),
                chrome_trace_doc(
                    trainer.flight.ring.events(), process_index=idx
                ),
            )
            if is_primary:
                # merge whatever per-process tracks share this directory
                # (single-process: just our own) into the canonical file
                docs = [
                    load_trace(p) for p in sorted(
                        glob.glob(os.path.join(args.trace, "trace_p*.json"))
                    )
                ]
                write_trace(
                    os.path.join(args.trace, "trace.json"),
                    merge_traces(docs),
                )
        except Exception as e:  # noqa: BLE001 — best-effort export
            print(f"warning: trace export failed: {e}", file=sys.stderr)

    # Supervised auto-recovery: DivergenceError rolls back to the last-good
    # checkpoint and retries instead of killing the run.
    run_train = trainer.train
    if streaming:
        # the continuous-training driver (stream/): segments in, the same
        # (state, report) contract out — everything below (preemption,
        # divergence, manifest, export) works unchanged, and every
        # checkpoint the run writes carries the stream cursor (_save_ckpt)
        from .stream import StreamRun

        try:
            stream_run = StreamRun(
                trainer, stream_source, cursor=stream_cursor,
                fault_plan=fault_plan if fault_plan else None,
                log_fn=log_fn,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            hub.close()
            return 1
        run_train = stream_run.train
    supervisor = None
    if args.auto_recover and streaming:
        print(
            "warning: --auto-recover is not supported with --corpus-mode "
            "streaming yet (the supervisor's rollback replays a resident "
            "epoch, not a stream cursor); continuing without it",
            file=sys.stderr,
        )
    elif args.auto_recover:
        from .resilience.supervisor import Supervisor

        if not (args.checkpoint_dir and args.checkpoint_every) and not args.quiet:
            print(
                "warning: --auto-recover without --checkpoint-dir/"
                "--checkpoint-every has no rollback target; recovery "
                "restarts from a fresh init",
                file=sys.stderr,
            )
        supervisor = Supervisor(
            trainer,
            checkpoint_dir=args.checkpoint_dir,
            max_retries=args.auto_recover,
            alpha_scale=args.recover_alpha_scale,
            log_fn=log_fn,
        )
        run_train = supervisor.run

    prev_plan = None
    if fault_plan:
        trainer.fault_plan = fault_plan
        prev_plan = _faults.activate(fault_plan)

    profile_ctx = trace(args.profile) if args.profile else contextlib.nullcontext()
    if elastic_ctl is not None:
        # from here on, a hello claiming membership of this generation is a
        # crashed member coming back, not a late starter
        elastic_ctl.mark_running()
    try:
        with profile_ctx:
            try:
                state, report = run_train(
                    state=state,
                    log_every=args.log_every,
                    checkpoint_cb=ckpt_cb,
                    checkpoint_every=args.checkpoint_every,
                )
            except Exception as e:
                # A lost peer has TWO faces: the silent hang the bounded
                # collectives turn into SyncTimeout — and an immediate
                # runtime ERROR when the peer died mid-transfer (gloo
                # connection reset, coordination heartbeat timeout), which
                # can surface from ANY device interaction, bounded or not.
                # Route the second face into the same SyncTimeout handling
                # (elastic shrink, or abort-to-requeue) instead of crashing
                # with a raw XlaRuntimeError whose teardown then wedges in
                # the distributed shutdown barrier.
                if (
                    not isinstance(e, (SyncTimeout, DivergenceError))
                    and jax.process_count() > 1
                    and _watchdog.is_peer_failure(e)
                ):
                    raise SyncTimeout(
                        "distributed runtime peer failure "
                        f"({type(e).__name__}: "
                        f"{str(e).splitlines()[0][:160]})",
                        args.sync_deadline or 0.0,
                    ) from e
                raise
    except DivergenceError as e:
        # structured abort: the step/counters/checkpoint hint are in the
        # message; the flight dump carries the timeline of the steps that
        # led here, and the metrics sinks are flushed so the JSONL/prom
        # tail shows the run's last healthy records
        print(f"error: DivergenceError: {e}", file=sys.stderr)
        if manifest_path:
            update_manifest(manifest_path, {
                "shutdown": "diverged",
                "divergence": e.record(),
                "recoveries": supervisor.recoveries if supervisor else [],
            })
        # failure_step = where the loop ABORTED (the lagged drain detects
        # the poisoned observation one boundary later; e.step names the
        # observation itself and is in the manifest's divergence record)
        dump_flight(
            "diverged",
            failure_step=getattr(trainer.last_state, "step", None) or e.step,
        )
        export_trace()
        hub.close()
        return 2
    except QualityAlert as e:
        # the degeneracy sentinel escalated past 2x its budget: structured
        # abort, mirroring DivergenceError — manifest records why, the
        # flight dump carries the probe rows that led here (the quality
        # ring rides every snapshot), rc=3 (EXIT_QUALITY)
        print(f"error: QualityAlert: {e}", file=sys.stderr)
        if manifest_path:
            update_manifest(manifest_path, {
                "shutdown": "quality_degraded",
                "quality_alert": e.record(),
            })
        dump_flight(
            "quality_alert",
            failure_step=getattr(trainer.last_state, "step", None) or e.step,
        )
        export_trace()
        hub.close()
        return EXIT_QUALITY
    except GrowRequested as e:
        # Elastic grow: a restarted host waits at the rendezvous, and every
        # fleet member raised this at the SAME sync boundary (the verdict
        # rides one allgather). The fleet is intact, so write a collective
        # checkpoint — the admission snapshot's source — then re-form at
        # N+rejoiners. remesh_and_exec replaces the process image; it only
        # RETURNS on failure, in which case requeue like a preemption (the
        # checkpoint just landed, nothing is lost).
        print(f"elastic: {e}", file=sys.stderr)
        last = getattr(trainer, "last_state", None)
        grow_saved = False
        if last is not None:
            try:
                snap = unreplicated(last)  # collective: all ranks enter
                if is_primary:
                    _save_ckpt(snap)
                grow_saved = True
            except Exception as ce:  # noqa: BLE001 — degrade to last periodic
                print(
                    f"warning: grow-boundary checkpoint failed ({ce}); the "
                    "generation snapshot falls back to the last periodic "
                    "checkpoint",
                    file=sys.stderr,
                )
        if elastic_ctl is not None:
            elastic_ctl.remesh_and_exec(
                "grow", getattr(last, "step", None),
                manifest_path=manifest_path, hub=hub,
                flight=trainer.flight, metrics_dir=metrics_dir,
                # a policy-gated admission is a policy decision; the plain
                # PR 10 waiter-pending admission is a rejoin
                trigger="policy" if args.elastic_policy else "rejoin",
            )
        # unreachable after a successful exec — this is the failure path
        if manifest_path:
            update_manifest(manifest_path, {
                "shutdown": "elastic_failed",
                "grow_checkpoint": grow_saved,
            })
        dump_flight("elastic_failed", failure_step=getattr(last, "step", None))
        export_trace()
        hub.close()
        return EXIT_PREEMPTED
    except PolicyShrinkRequested as e:
        # Elastic policy shrink: the rendezvous host's policy latched an
        # eviction and every rank read the same heartbeat row, so the
        # whole fleet lands here at one sync boundary with ZERO failures.
        # The fleet is intact: write the collective checkpoint (the
        # generation snapshot's source), then split — the victim leaves
        # (announce-only exec in shrink+grow, clean rc=0 exit in shrink),
        # the survivors join a policy_shrink round that closes at world-1.
        print(f"elastic: {e}", file=sys.stderr)
        last = getattr(trainer, "last_state", None)
        if last is not None:
            try:
                snap = unreplicated(last)  # collective: all ranks enter
                if is_primary:
                    _save_ckpt(snap)
            except Exception as ce:  # noqa: BLE001 — degrade to last periodic
                print(
                    f"warning: policy-shrink checkpoint failed ({ce}); the "
                    "generation snapshot falls back to the last periodic "
                    "checkpoint",
                    file=sys.stderr,
                )
        if elastic_ctl is not None and jax.process_index() == e.victim:
            # the evicted host: record how this run ended, then leave
            if manifest_path:
                update_manifest(manifest_path, {
                    "shutdown": "policy_evicted",
                    "policy_evict": {"step": e.step, "victim": e.victim},
                })
            dump_flight("policy_evicted", failure_step=e.step)
            export_trace()
            hub({"event": "policy_evicted", "step": e.step})
            if args.elastic == "shrink+grow":
                hub.close()
                elastic_ctl.exec_announce()  # never returns: parks + rejoins
            print(
                f"policy shrink: this host (rank {e.victim}) was evicted "
                "at a sync boundary; exiting 0 (shrink mode does not "
                "readmit)",
                file=sys.stderr,
            )
            hub.close()
            return 0
        if elastic_ctl is not None:
            elastic_ctl.remesh_and_exec(
                "policy_shrink", e.step,
                manifest_path=manifest_path, hub=hub,
                flight=trainer.flight, metrics_dir=metrics_dir,
                trigger="policy", victim=e.victim,
            )
        # unreachable after a successful exec — this is the failure path
        if manifest_path:
            update_manifest(manifest_path, {"shutdown": "elastic_failed"})
        dump_flight(
            "elastic_failed", failure_step=getattr(last, "step", None)
        )
        export_trace()
        hub.close()
        return EXIT_PREEMPTED
    except SyncTimeout as e:
        if jax.process_count() <= 1:
            # Latent single-host hole: a SyncTimeout with no peers (an
            # injected sync_timeout fault, or a --sync-deadline bounding a
            # local operation that wedged) must NOT run the peer-loss
            # protocol — there is no fleet to agree with, no membership to
            # shrink, and calling it "peer_lost" would send an operator
            # hunting for a host that never existed. Fail fast, named.
            print(
                f"error: {e}\n"
                "error: SyncTimeout with num_processes == 1: no peer "
                "exists to lose or agree with. This is a misconfiguration "
                "(a --sync-deadline bounding single-host work, or an "
                "injected sync_timeout fault outside a fleet) or a wedged "
                "local device/host operation — use --step-deadline for "
                "single-host hang detection.",
                file=sys.stderr,
            )
            if manifest_path:
                update_manifest(manifest_path, {
                    "shutdown": "sync_timeout_single_host",
                    "sync_timeout": {"what": e.what, "deadline_s": e.deadline},
                })
            dump_flight(
                "sync_timeout_single_host",
                failure_step=getattr(
                    getattr(trainer, "last_state", None), "step", None
                ),
            )
            export_trace()
            hub.close()
            return 1
        if elastic_ctl is not None:
            # Elastic shrink: survivors re-form at N-1 instead of aborting.
            # remesh_and_exec replaces the process image on success; on
            # failure (rendezvous unreachable, declared late, no verified
            # checkpoint) it returns and we fall through to the PR 5
            # abort-to-requeue below — elasticity degrades, never regresses.
            print(
                f"elastic: {e}; attempting shrink-remesh instead of abort",
                file=sys.stderr,
            )
            elastic_ctl.remesh_and_exec(
                "shrink",
                getattr(getattr(trainer, "last_state", None), "step", None),
                manifest_path=manifest_path, hub=hub,
                flight=trainer.flight, metrics_dir=metrics_dir,
            )
        # Coordinated abort-to-requeue: a peer died or wedged and a bounded
        # collective timed out on THIS host. Every survivor takes this same
        # path (their collectives time out too), so nobody is stranded.
        # Checkpoint where safe — the last boundary-consistent state, via a
        # bounded save, since a sharded export itself runs collectives that
        # may hang against the dead peer — then exit the requeue rc.
        print(f"error: {e}", file=sys.stderr)
        last = getattr(trainer, "last_state", None)
        saved = False
        if args.checkpoint_dir and last is not None:
            def _final_save():
                # unreplicated() may run mesh collectives — against a dead
                # peer those can hang too, hence the bounded wrapper
                snap = unreplicated(last)
                if is_primary:
                    _save_ckpt(snap)

            try:
                _watchdog.bounded_call(
                    _final_save,
                    what="final checkpoint after peer loss",
                    deadline=args.sync_deadline or 30.0,
                )
                saved = True
            except Exception as ce:  # noqa: BLE001 — best-effort abort path
                print(
                    f"warning: final checkpoint not written ({ce}); the "
                    "last periodic checkpoint is the resume point",
                    file=sys.stderr,
                )
        if manifest_path:
            update_manifest(manifest_path, {
                "shutdown": "peer_lost",
                "sync_timeout": {"what": e.what, "deadline_s": e.deadline},
                "final_checkpoint": saved,
            })
        dump_flight("peer_lost", failure_step=getattr(last, "step", None))
        export_trace()
        # counted by the Prometheus sink's peer_lost_total before the close
        hub({"event": "peer_lost", "what": e.what})
        print(
            f"peer lost: aborting at step "
            f"{getattr(last, 'step', '?')} for requeue"
            + (
                f"; requeue with --resume {args.checkpoint_dir}"
                if args.checkpoint_dir else
                "; WARNING: no --checkpoint-dir, progress rides on the "
                "last periodic checkpoint only"
            ),
            file=sys.stderr,
        )
        hub.close()
        return EXIT_PREEMPTED
    finally:
        # restore signal dispositions (incl. the SIGUSR1 dump), the
        # process-wide fault plan, and the process-wide sync deadline on
        # every exit path — main() runs in-process under tests, and a
        # leaked SIGTERM handler or deadline would outlive the run it
        # protects
        handler.uninstall()
        uninstall_usr1()
        uninstall_usr2()
        _watchdog.set_sync_deadline(prev_sync_deadline)
        if fault_plan:
            _faults.activate(prev_plan)
        if mem_ledger is not None:
            from .obs import devmem as devmem_mod

            devmem_mod.activate(prev_ledger)
    if report.health is not None or report.phases is not None:
        # final-summary event record: the run's verdict lands in the JSONL
        # tail (and the console, one line) without re-deriving it from logs
        summary = {
            "event": "train_report",
            "steps": report.steps,
            "words_per_sec": round(report.words_per_sec, 1),
            "final_loss": report.final_loss,
        }
        if report.health is not None:
            summary.update(
                nonfinite_loss_steps=report.health.get("nonfinite_loss_steps"),
                health_observations=report.health.get("observations"),
            )
        if report.phases is not None:
            summary.update(
                verdict=report.phases.get("verdict"),
                input_fraction=report.phases.get("input_fraction"),
            )
        if report.interrupted:
            summary["interrupted"] = report.interrupted
        if report.recoveries:
            summary["recoveries"] = len(report.recoveries)
        if report.stream:
            summary.update(
                stream_segments=report.stream.get("segments"),
                vocab_size=report.stream.get("vocab_size"),
                table_swaps=report.stream.get("swaps"),
            )
        if report.signals:
            # the signal plane's one-line verdict: did the run stay inside
            # its SLOs, and who lagged (obs/signals.FleetHealth)
            fh = report.signals.get("fleet_health") or {}
            summary["fleet_health"] = fh.get("verdict")
            if fh.get("straggler_host") is not None:
                summary["straggler_host"] = fh.get("straggler_host")
            slo_rep = report.signals.get("slo")
            if slo_rep:
                summary["slo_state"] = slo_rep.get("state")
                summary["slo_breaches"] = slo_rep.get("breaches_total")
        if report.device_memory and report.device_memory.get("available"):
            # the device-memory one-liner: worst headroom seen this run
            summary["mem_headroom_frac_min"] = report.device_memory.get(
                "headroom_frac_min"
            )
            summary["mem_peak_bytes"] = report.device_memory.get(
                "peak_bytes"
            )
        if log_fn is not None:
            log_fn(summary)

    # Compiled-program cost harvest: analyze the captured executables NOW,
    # after the measured loop (obs/harvest.py), and land the totals as
    # w2v_cost_harvest_* gauges + a manifest block.
    harvest_report = None
    if cost_harvest is not None:
        harvest_report = cost_harvest.finalize()
        if log_fn is not None:
            _hrec = cost_harvest.gauge_record()
            if _hrec:
                log_fn(_hrec)

    # How the run ended, recorded where how it started already is: the
    # manifest distinguishes a clean completion from a preempted one, and
    # carries any auto-recovery history.
    preempted = report.interrupted == "preempted"
    if manifest_path:
        end_fields = {
            "shutdown": "preempted" if preempted else "clean",
            "final_step": state.step,
            "recoveries": report.recoveries or [],
        }
        if report.device_memory is not None:
            # the full per-phase ledger replaces the start block's
            # init-only watermark (same key, one manifest read answers
            # "where did the HBM go")
            end_fields["device_memory"] = report.device_memory
        if harvest_report is not None:
            end_fields["cost_harvest"] = harvest_report
        if prof_capture is not None:
            end_fields["profiler"] = prof_capture.summary()
        if trainer.flight is not None and cost_harvest is not None:
            # anchor-drift verdict (tune/cost_model.cost_calibrate): the
            # run's own measured device time inverted against the three
            # hand anchors — banked so a stale constant is visible from
            # the manifest alone
            try:
                from .obs import tracediff as _tracediff
                from .tune import cost_model as _cm

                _dev = jax.devices()[0]
                _est = _cm.predict(
                    trainer.config, len(vocab), _dev.device_kind,
                    _dev.platform,
                )
                end_fields["cost_calibrate"] = _cm.cost_calibrate(
                    _est,
                    _cm.measured_device_ms(
                        _tracediff.summarize(trainer.flight.ring.events())
                    ),
                )
            except Exception as _ce:  # noqa: BLE001 — advisory, never fatal
                end_fields["cost_calibrate"] = {"error": str(_ce)}
        if getattr(trainer, "resume_fallback", None):
            # an out-of-range checkpointed step counter fell back to epoch
            # restart (train._resume_skip) — recorded so the manifest shows
            # data was re-trained, not resumed
            end_fields["resume_fallback"] = trainer.resume_fallback
        if report.stream:
            # the continuous-training verdict: segments consumed, final
            # cursor, vocab generation, growth/swap counts — one manifest
            # read answers "where did the stream stop"
            end_fields["stream"] = report.stream
        if report.signals:
            # the SLO summary + fleet-health verdict land where how the run
            # started already is — one manifest read answers "did it hold
            # its SLOs" (obs/slo.SloEvaluator.summary)
            if report.signals.get("slo"):
                end_fields["slo"] = report.signals["slo"]
            end_fields["fleet_health"] = report.signals.get("fleet_health")
        update_manifest(manifest_path, end_fields)

    if preempted:
        # Preemption-safe exit: checkpoint the stopped-at-boundary state,
        # skip export/eval (the run is not finished — a scheduler requeues
        # it with --resume), exit with the distinct requeue rc.
        if args.checkpoint_dir:
            snap = unreplicated(state)  # collective-capable: all processes
            if is_primary:
                _save_ckpt(snap)
        sig = handler.signum
        dump_flight("preempted", failure_step=state.step)
        export_trace()
        print(
            f"preempted (signal {sig}): stopped at step {state.step}; "
            + (
                f"checkpoint saved to {args.checkpoint_dir}; requeue with "
                f"--resume {args.checkpoint_dir}"
                if args.checkpoint_dir
                else "WARNING: no --checkpoint-dir, progress not persisted"
            ),
            file=sys.stderr,
        )
        if args.emit_device:
            dev = jax.devices()[0]
            print(f"device: {dev.platform} {dev.device_kind}", file=sys.stderr)
        hub.close()
        return EXIT_PREEMPTED

    if args.emit_device:
        dev = jax.devices()[0]
        print(f"device: {dev.platform} {dev.device_kind}", file=sys.stderr)
    if not args.quiet and is_primary:
        print(f"\ntrained {report.total_words} words in {report.wall_time:.1f}s "
              f"({report.words_per_sec:,.0f} words/sec), final loss "
              f"{report.final_loss:.4f}")

    if args.checkpoint_dir:
        snap = unreplicated(state)  # collective-capable: all processes enter
        if is_primary:
            _save_ckpt(snap)

    # matrix choice per main.cpp:196-202
    if hasattr(trainer, "export_params"):
        params = trainer.export_params(state)
    else:
        params = {k: v for k, v in state.params.items()}
    matrix = export_matrix(params, cfg, side=args.export_side)
    if matrix.shape[0] > len(vocab):
        # unadmitted online-growth reserve rows are not words
        matrix = matrix[: len(vocab)]
    if args.output and is_primary:
        save_word2vec(
            args.output, vocab, matrix,
            binary=bool(args.binary), layout=args.binary_layout,
        )
        if not args.quiet:
            print(f"saved {'binary' if args.binary else 'text'} vectors to "
                  f"{args.output}")
    if args.export_int8 and is_primary:
        from .io.embeddings import save_embeddings_int8

        import numpy as np

        save_embeddings_int8(args.export_int8, vocab.words,
                             np.asarray(matrix, dtype=np.float32))
        if not args.quiet:
            print(f"saved int8-quantized vectors to {args.export_int8}")

    export_trace()

    if (args.eval_ws353 or args.eval_analogy) and is_primary:
        from .eval.similarity import evaluate_ws353
        from .eval.analogy import evaluate_analogies

        import numpy as np

        W = np.asarray(matrix)
        if args.eval_ws353:
            r = evaluate_ws353(W, vocab, args.eval_ws353)
            print(f"WS-353 spearman: {r.spearman:.4f} ({r.pairs_used}/{r.pairs_total} pairs)")
        if args.eval_analogy:
            r = evaluate_analogies(W, vocab, args.eval_analogy)
            # skip counts are part of the verdict: a probe set full of
            # OOV/degenerate rows must not read as a clean 0-question pass
            print(
                f"analogy accuracy: {r.accuracy:.4f} ({r.correct}/{r.total}"
                f", {r.skipped_oov} oov-skipped, {r.skipped_degenerate} "
                f"degenerate-skipped)"
            )
    hub.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
