"""Checkpoint / resume, with integrity manifests and last-good retention.

The reference has only dormant partial persistence (vocab + embedding files,
never reloaded by its CLI — SURVEY §5). Here checkpointing is first-class:
a checkpoint captures the full training state {params, step, words_done,
epoch, config} plus the vocabulary, so an interrupted run resumes exactly on
the alpha schedule (Word2Vec.cpp:379-380 depends only on words_done).

Format: one directory per checkpoint —
    state.npz       all embedding tables + integer counters
    config.json     the Word2VecConfig
    vocab.txt       `index count word` lines (reference format, Word2Vec.cpp:171)
    integrity.json  sha256 of every other file, written last

Durability contract (the resilience subsystem builds on all three):
  * writes are atomic (tmp dir + rename) AND retried with bounded backoff
    on OSError — a flaky network filesystem gets a few chances before the
    failure surfaces;
  * the previous checkpoint is RETAINED as `<path>.old` (and `.old2`, ...,
    up to `keep`) instead of deleted after a successful write, so a
    rollback target always exists — the divergence supervisor
    (resilience/supervisor.py) depends on this;
  * the loader verifies the sha256 manifest and the parse itself; a
    truncated/corrupt checkpoint is QUARANTINED (renamed `<dir>.corrupt`)
    and the loader falls back along the backup chain instead of crashing
    the resume. Checkpoints without an integrity manifest (pre-manifest
    writers) load with parse-level checking only.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
import time
import zipfile
from typing import Callable, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..config import Word2VecConfig
from ..data.vocab import Vocab
from ..resilience import faults as _faults
from ..train import TrainState

INTEGRITY_FILE = "integrity.json"


class CheckpointError(RuntimeError):
    """A checkpoint failed integrity/parse validation — or, out of
    load_checkpoint, every candidate did (the message lists what was
    tried)."""


#: everything a torn/corrupt checkpoint can raise out of the parse
#: (BadZipFile: truncated npz; ValueError: short buffers / bad json /
#: bad config fields; KeyError: missing arrays; OSError: unreadable files)
_CORRUPT_ERRORS = (
    CheckpointError,
    zipfile.BadZipFile,
    ValueError,
    KeyError,
    OSError,
)


# --------------------------------------------------------------- integrity
def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def write_integrity(dirpath: str, meta: Optional[dict] = None) -> dict:
    """Hash every regular file in `dirpath` into its integrity manifest.
    Called LAST during a save, so a manifest's presence certifies that every
    named file was completely written when the hash was taken. `meta`
    carries content-level fingerprints (today: the vocabulary's
    content_hash) that external tools can read without parsing the
    checkpoint itself; verification ignores it."""
    files = {
        e.name: _sha256(e.path)
        for e in sorted(os.scandir(dirpath), key=lambda e: e.name)
        if e.is_file() and e.name != INTEGRITY_FILE
    }
    man = {"schema": 1, "algo": "sha256", "files": files}
    if meta:
        man["meta"] = dict(meta)
    with open(os.path.join(dirpath, INTEGRITY_FILE), "w") as f:
        json.dump(man, f, indent=2)
        f.write("\n")
    return man


def read_integrity_meta(path: str) -> dict:
    """The `meta` block of a checkpoint's integrity manifest ({} when the
    manifest or the block is missing/unreadable — metadata reads must never
    fail a resume)."""
    try:
        with open(os.path.join(path, INTEGRITY_FILE)) as f:
            return dict(json.load(f).get("meta") or {})
    except (OSError, ValueError, TypeError, AttributeError):
        return {}


def verify_checkpoint(path: str) -> None:
    """Validate `path` against its integrity manifest; raises CheckpointError
    on a missing or mismatched file. A checkpoint without a manifest (older
    writer) passes — the parse-level checks in the loader still apply."""
    man_path = os.path.join(path, INTEGRITY_FILE)
    if not os.path.exists(man_path):
        return
    try:
        with open(man_path) as f:
            man = json.load(f)
        files = dict(man["files"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise CheckpointError(f"{path}: unreadable integrity manifest: {e}")
    for name, want in files.items():
        fp = os.path.join(path, name)
        if not os.path.exists(fp):
            raise CheckpointError(f"{path}: missing file {name!r} named by "
                                  "the integrity manifest")
        got = _sha256(fp)
        if got != want:
            raise CheckpointError(
                f"{path}: sha256 mismatch on {name!r} "
                f"(manifest {want[:12]}…, file {got[:12]}…)"
            )


# ------------------------------------------------------------ backup chain
def backup_name(path: str, k: int) -> str:
    """k-th retained backup: `.old` (most recent previous), `.old2`, ..."""
    return path + ".old" + ("" if k == 1 else str(k))


#: how far the candidate scan looks for backups (far above any sane
#: --checkpoint-keep; quarantine can leave gaps, so the scan doesn't stop
#: at the first missing index)
_SCAN_LIMIT = 16


def checkpoint_candidates(path: str) -> Iterator[str]:
    """The load order: the checkpoint itself, then its backups newest-first."""
    yield path
    for k in range(1, _SCAN_LIMIT + 1):
        b = backup_name(path, k)
        if os.path.isdir(b):
            yield b


def _quarantine(path: str) -> Optional[str]:
    """Rename a corrupt checkpoint dir aside (never clobbering an earlier
    quarantine); returns the new name, or None when the rename itself fails
    (the load fallback must proceed regardless)."""
    base = path + ".corrupt"
    dst = base
    n = 2
    while os.path.exists(dst):
        dst = base + str(n)
        n += 1
    try:
        os.replace(path, dst)
        return dst
    except OSError:
        return None


# ------------------------------------------------------------------- save
STREAM_FILE = "stream.json"


def save_checkpoint(path: str, state: TrainState, config: Word2VecConfig,
                    vocab: Optional[Vocab] = None, keep: int = 1,
                    retries: int = 3, backoff: float = 0.05,
                    stream: Optional[dict] = None) -> None:
    """Atomic checkpoint write with integrity manifest and retention.

    `keep` previous checkpoints are retained (`.old` ... `.old{keep}`);
    keep=0 restores the old delete-after-success behavior. OSError during
    the write (full disk hiccup, flaky NFS, an injected `ckpt_oserror`
    fault) is retried up to `retries` times with exponential backoff before
    surfacing — a checkpoint that fails to land must be loud, but not
    because of one transient error.

    `stream` (corpus_mode="streaming" runs) is the stream cursor document
    — segment index, shard, in-shard offset, vocab generation, global
    counters (stream/source.StreamCursor.to_json) — written as
    `stream.json` INSIDE the checkpoint dir before the integrity manifest,
    so the cursor is covered by the same sha256 manifest, rotates with the
    same backup chain, and can never describe a different checkpoint than
    the params next to it. Read it back with `read_stream_cursor`.
    """
    if keep < 0:
        raise ValueError(f"keep must be >= 0, got {keep}")
    last: Optional[OSError] = None
    for attempt in range(retries + 1):
        if attempt:
            import warnings

            warnings.warn(
                f"checkpoint write to {path!r} failed ({last}); "
                f"retry {attempt}/{retries}",
                stacklevel=2,
            )
            time.sleep(backoff * (2 ** (attempt - 1)))
        try:
            _save_once(path, state, config, vocab, keep, stream)
            return
        except OSError as e:
            last = e
    raise last  # type: ignore[misc]


def read_stream_cursor(path: str) -> Optional[dict]:
    """The stream-cursor document of the checkpoint dir `path` (None for
    non-streaming checkpoints). `path` must be the dir that actually
    LOADED — use load_checkpoint_with_path, not the nominal path, or a
    fallback to `.old` would pair new params with a stale cursor."""
    fp = os.path.join(path, STREAM_FILE)
    if not os.path.exists(fp):
        return None
    with open(fp) as f:
        return json.load(f)


def _save_once(path: str, state: TrainState, config: Word2VecConfig,
               vocab: Optional[Vocab], keep: int,
               stream: Optional[dict] = None) -> None:
    _faults.raise_if_active("ckpt_oserror", where=path)
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=parent, prefix=".ckpt_tmp_")
    try:
        # bfloat16 tables (config.dtype="bfloat16") are an ml_dtypes dtype
        # numpy's npz format cannot represent: savez silently stores them as
        # raw 2-byte void ("|V2") and the LOAD then hands jnp.asarray an
        # invalid dtype. Store such arrays as their uint16 bit pattern plus
        # a dtype manifest, and view them back on load.
        arrays = {}
        nonnative = {}
        for k, v in state.params.items():
            a = np.asarray(v)
            if a.dtype == np.dtype(jnp.bfloat16):
                nonnative[k] = "bfloat16"
                a = a.view(np.uint16)
            arrays[k] = a
        np.savez(
            os.path.join(tmp, "state.npz"),
            __step=np.int64(state.step),
            __words_done=np.int64(state.words_done),
            __epoch=np.int64(state.epoch),
            __dtypes=np.str_(json.dumps(nonnative)),
            **arrays,
        )
        with open(os.path.join(tmp, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(config), f, indent=2)
        if vocab is not None:
            vocab.save(os.path.join(tmp, "vocab.txt"))
        if stream is not None:
            # the mid-stream cursor rides inside the dir so the integrity
            # manifest below covers it (a torn cursor quarantines the whole
            # candidate, exactly like a torn state.npz)
            with open(os.path.join(tmp, STREAM_FILE), "w") as f:
                json.dump(dict(stream), f, indent=2)
                f.write("\n")
        from ..models.params import params_layout

        # the realized table layout (split [V, d] pair vs unified [V, 2, d]
        # slab, models/params.py) rides in the meta so external tooling can
        # tell what the state.npz rows MEAN without parsing it; loaders
        # convert cross-layout losslessly (convert_params_layout) or fail
        # loudly naming both layouts
        meta = {"table_layout": params_layout(state.params)}
        if vocab is not None:
            meta["vocab_hash"] = vocab.content_hash()
            # the live vocab size, so external tools can run the
            # compatible-superset check (content_hash(limit=...)) without
            # parsing vocab.txt
            meta["vocab_size"] = len(vocab)
        # written last: its presence certifies a complete write; the meta
        # block carries the vocab fingerprint for the --resume corpus guard
        write_integrity(tmp, meta=meta)
        # Atomic overwrite with retention: rotate the backup chain, move the
        # old checkpoint to `.old`, land the new one. A crash at any point
        # leaves either the old or the new checkpoint recoverable (the
        # loader walks path, .old, .old2, ...).
        if os.path.isdir(path):
            for k in range(max(keep, 1), 1, -1):
                src = backup_name(path, k - 1)
                if os.path.isdir(src):
                    dst = backup_name(path, k)
                    if os.path.isdir(dst):
                        shutil.rmtree(dst)
                    os.replace(src, dst)
            first = backup_name(path, 1)
            if os.path.isdir(first):
                shutil.rmtree(first)
            os.replace(path, first)
        os.replace(tmp, path)
        # prune beyond the retention window (keep=0: drop `.old` too, the
        # pre-retention behavior — rollback-dependent callers keep >= 1)
        for k in range(keep + 1, _SCAN_LIMIT + 1):
            b = backup_name(path, k)
            if os.path.isdir(b):
                shutil.rmtree(b, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


# ------------------------------------------------------------------- load
def _load_dir(path: str) -> Tuple[TrainState, Word2VecConfig, Optional[Vocab]]:
    """Parse one specific checkpoint dir (no fallback, no quarantine)."""
    with np.load(os.path.join(path, "state.npz")) as z:
        nonnative = (
            json.loads(str(z["__dtypes"])) if "__dtypes" in z.files else {}
        )

        def restore(k: str) -> jnp.ndarray:
            a = z[k]
            if nonnative.get(k) == "bfloat16":
                a = a.view(np.dtype(jnp.bfloat16))
            return jnp.asarray(a)

        params = {
            k: restore(k) for k in z.files if not k.startswith("__")
        }
        state = TrainState(
            params=params,
            step=int(z["__step"]),
            words_done=int(z["__words_done"]),
            epoch=int(z["__epoch"]),
        )
    with open(os.path.join(path, "config.json")) as f:
        raw = json.load(f)
    known = {f.name for f in dataclasses.fields(Word2VecConfig)}
    config = Word2VecConfig(**{k: v for k, v in raw.items() if k in known})
    vocab_path = os.path.join(path, "vocab.txt")
    vocab = Vocab.load(vocab_path) if os.path.exists(vocab_path) else None
    return state, config, vocab


def load_checkpoint(
    path: str,
    fallback: bool = True,
    quarantine: bool = True,
    validate: Optional[
        Callable[[TrainState, Word2VecConfig, Optional[Vocab]], None]
    ] = None,
) -> Tuple[TrainState, Word2VecConfig, Optional[Vocab]]:
    """Load the newest GOOD checkpoint at `path`.

    Candidates are tried newest-first (`path`, `.old`, `.old2`, ...). A
    candidate fails on integrity mismatch (verify_checkpoint), any parse
    error of a truncated/torn dir, or a caller-supplied `validate(state,
    config, vocab)` raising (the supervisor validates params are finite —
    a checkpoint saved after divergence is not a rollback target). Failed
    candidates are quarantined (renamed `.corrupt*`) so the next save's
    rotation never resurrects them; `fallback=False` restricts the search
    to `path` itself. Raises CheckpointError when nothing loads.
    """
    state, config, vocab, _ = load_checkpoint_with_path(
        path, fallback=fallback, quarantine=quarantine, validate=validate
    )
    return state, config, vocab


def load_checkpoint_with_path(
    path: str,
    fallback: bool = True,
    quarantine: bool = True,
    validate: Optional[
        Callable[[TrainState, Word2VecConfig, Optional[Vocab]], None]
    ] = None,
) -> Tuple[TrainState, Word2VecConfig, Optional[Vocab], str]:
    """load_checkpoint, additionally returning the DIRECTORY that loaded
    (`path` itself, or the `.old*` backup the fallback walked to) — the
    streaming resume reads its cursor sidecar (read_stream_cursor) from
    this dir, never the nominal path, so params and cursor always come
    from the same write."""
    tried: List[str] = []
    for cand in checkpoint_candidates(path):
        if not os.path.exists(os.path.join(cand, "state.npz")):
            tried.append(f"{cand}: missing state.npz")
            if not fallback:
                break
            continue
        try:
            verify_checkpoint(cand)
            out = _load_dir(cand)
            if validate is not None:
                validate(*out)
            return out + (cand,)
        except _CORRUPT_ERRORS as e:
            import warnings

            tried.append(f"{cand}: {type(e).__name__}: {e}")
            moved = _quarantine(cand) if quarantine else None
            warnings.warn(
                f"corrupt checkpoint {cand!r} ({type(e).__name__}: {e})"
                + (f"; quarantined as {moved!r}" if moved else "")
                + ("; falling back" if fallback else ""),
                stacklevel=2,
            )
        if not fallback:
            break
    raise CheckpointError(
        f"no loadable checkpoint at {path!r}; tried: " + "; ".join(tried)
    )
