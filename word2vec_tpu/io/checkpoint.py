"""Checkpoint / resume.

The reference has only dormant partial persistence (vocab + embedding files,
never reloaded by its CLI — SURVEY §5). Here checkpointing is first-class:
a checkpoint captures the full training state {params, step, words_done,
epoch, config} plus the vocabulary, so an interrupted run resumes exactly on
the alpha schedule (Word2Vec.cpp:379-380 depends only on words_done).

Format: one directory per checkpoint —
    state.npz     all embedding tables + integer counters
    config.json   the Word2VecConfig
    vocab.txt     `index count word` lines (reference format, Word2Vec.cpp:171)
Writes are atomic (tmp dir + rename), so a crash mid-save never corrupts the
latest checkpoint.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..config import Word2VecConfig
from ..data.vocab import Vocab
from ..train import TrainState


def save_checkpoint(path: str, state: TrainState, config: Word2VecConfig,
                    vocab: Optional[Vocab] = None) -> None:
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=parent, prefix=".ckpt_tmp_")
    try:
        # bfloat16 tables (config.dtype="bfloat16") are an ml_dtypes dtype
        # numpy's npz format cannot represent: savez silently stores them as
        # raw 2-byte void ("|V2") and the LOAD then hands jnp.asarray an
        # invalid dtype. Store such arrays as their uint16 bit pattern plus
        # a dtype manifest, and view them back on load.
        arrays = {}
        nonnative = {}
        for k, v in state.params.items():
            a = np.asarray(v)
            if a.dtype == np.dtype(jnp.bfloat16):
                nonnative[k] = "bfloat16"
                a = a.view(np.uint16)
            arrays[k] = a
        np.savez(
            os.path.join(tmp, "state.npz"),
            __step=np.int64(state.step),
            __words_done=np.int64(state.words_done),
            __epoch=np.int64(state.epoch),
            __dtypes=np.str_(json.dumps(nonnative)),
            **arrays,
        )
        with open(os.path.join(tmp, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(config), f, indent=2)
        if vocab is not None:
            vocab.save(os.path.join(tmp, "vocab.txt"))
        # Atomic overwrite: move the old checkpoint aside first so a crash at
        # any point leaves either the old or the new checkpoint recoverable
        # (the loader falls back to `<path>.old`).
        backup = path + ".old"
        if os.path.isdir(path):
            if os.path.isdir(backup):
                shutil.rmtree(backup)
            os.replace(path, backup)
        os.replace(tmp, path)
        shutil.rmtree(backup, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(path: str) -> Tuple[TrainState, Word2VecConfig, Optional[Vocab]]:
    if not os.path.exists(os.path.join(path, "state.npz")):
        backup = path + ".old"
        if os.path.exists(os.path.join(backup, "state.npz")):
            path = backup  # crash landed between move-aside and replace
    with np.load(os.path.join(path, "state.npz")) as z:
        nonnative = (
            json.loads(str(z["__dtypes"])) if "__dtypes" in z.files else {}
        )

        def restore(k: str) -> jnp.ndarray:
            a = z[k]
            if nonnative.get(k) == "bfloat16":
                a = a.view(np.dtype(jnp.bfloat16))
            return jnp.asarray(a)

        params = {
            k: restore(k) for k in z.files if not k.startswith("__")
        }
        state = TrainState(
            params=params,
            step=int(z["__step"]),
            words_done=int(z["__words_done"]),
            epoch=int(z["__epoch"]),
        )
    with open(os.path.join(path, "config.json")) as f:
        raw = json.load(f)
    known = {f.name for f in dataclasses.fields(Word2VecConfig)}
    config = Word2VecConfig(**{k: v for k, v in raw.items() if k in known})
    vocab_path = os.path.join(path, "vocab.txt")
    vocab = Vocab.load(vocab_path) if os.path.exists(vocab_path) else None
    return state, config, vocab
